"""Declarative latency SLOs with multi-window multi-burn-rate alerts.

The SRE Workbook (Beyer et al., 2018, ch. 5) shape: an SLO is "P
(target_fraction) of requests complete under T (threshold_ms)", the
error budget is ``1 - target_fraction``, and alerting is on *burn
rate* — the ratio of the observed bad fraction to the budget — over
paired short/long windows (fast-burn: 5m + 1h above 14.4x; slow-burn:
6h + 3d above 1x), so a sudden regression pages within minutes while a
slow leak still trips before the budget is gone, and neither flaps.

Samples are NOT double-recorded: the monitor reads the existing
latency histograms (``paddle_serving_latency_ms``,
``paddle_fleet_request_ms``, ``paddle_decode_inter_token_ms``, any
registry histogram) by snapshotting cumulative bucket counts at each
``evaluate()`` and differencing snapshots across rolling windows.
Good = samples at or under the largest bucket bound <= threshold
(declare thresholds on bucket bounds for exact accounting; the
effective bound is reported). Because serving/fleet warmup and
readiness traffic never reaches those histograms
(``record_traffic=False`` batches, structurally untraced warmup — the
PR 9 exclusion), SLO windows inherit the exclusion; the direct-feed
``observe()`` path takes an explicit ``warmup=`` flag and drops (and
counts) excluded samples for the same reason.

Surfaces:

- ``paddle_slo_burn_rate{slo,window}`` and
  ``paddle_slo_budget_remaining{slo}`` gauges;
- ``/sloz`` on the observability httpd and replica workers; the fleet
  router serves a fleet-aggregated ``/sloz`` (summed window counts
  across replicas) the way ``/tracez`` stitches spans;
- registered alert sinks — callables receiving every alert transition
  (fire/resolve) with the burn numbers and an exemplar trace id from
  the PR 9 exemplar store, so a page links to a concrete trace. This
  is the surface ``ReplicaSupervisor.scale_to`` autoscaling (ROADMAP
  item 4) will subscribe to.

The clock is injected; every window is deterministic under test.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .registry import MetricRegistry, default_registry

__all__ = [
    "BurnRule", "LatencySLO", "SLOMonitor",
    "default_monitor", "set_default_monitor", "latency_slo",
    "add_alert_sink", "remove_alert_sink", "sloz_payload",
    "DEFAULT_BURN_RULES", "merge_sloz_payloads",
]


class BurnRule:
    """One multi-window burn-rate alert rule: fires when the burn rate
    exceeds ``factor`` over BOTH the short and the long window (the
    short window gives fast detection+reset, the long one suppresses
    flapping on blips)."""

    __slots__ = ("name", "short_s", "long_s", "factor", "severity")

    def __init__(self, name: str, short_s: float, long_s: float,
                 factor: float, severity: str = "page"):
        self.name = name
        self.short_s = float(short_s)
        self.long_s = float(long_s)
        self.factor = float(factor)
        self.severity = severity

    def to_dict(self) -> dict:
        return {"name": self.name, "short_s": self.short_s,
                "long_s": self.long_s, "factor": self.factor,
                "severity": self.severity}


# The SRE Workbook's recommended pairs (ch. 5, "6: Multiwindow,
# Multi-Burn-Rate Alerts"): 14.4x over 5m/1h pages, 1x over 6h/3d
# tickets.
DEFAULT_BURN_RULES = (
    BurnRule("fast_burn", 300.0, 3600.0, 14.4, severity="page"),
    BurnRule("slow_burn", 6 * 3600.0, 3 * 86400.0, 1.0,
             severity="ticket"),
)


class LatencySLO:
    """Declarative latency objective over one registry histogram.

    ``labels`` filters the family's children (subset match:
    ``{"server": "default"}`` selects that server's slice; empty =
    every child summed). ``windows`` are the rolling spans evaluated
    and exported; they default to the union of the burn rules'
    windows."""

    def __init__(self, name: str, metric: str, threshold_ms: float,
                 target_fraction: float,
                 labels: Optional[dict] = None,
                 windows: Optional[Sequence[float]] = None,
                 burn_rules: Optional[Sequence[BurnRule]] = None):
        if not 0.0 < float(target_fraction) < 1.0:
            raise ValueError(
                "target_fraction must be in (0, 1) — an SLO of 1.0 "
                "has no error budget to burn")
        self.name = str(name)
        self.metric = str(metric)
        self.threshold_ms = float(threshold_ms)
        self.target_fraction = float(target_fraction)
        self.labels = dict(labels or {})
        self.burn_rules = tuple(burn_rules if burn_rules is not None
                                else DEFAULT_BURN_RULES)
        if windows is None:
            windows = sorted({w for r in self.burn_rules
                              for w in (r.short_s, r.long_s)})
        self.windows = tuple(float(w) for w in windows)

    @property
    def error_budget(self) -> float:
        return 1.0 - self.target_fraction

    def to_dict(self) -> dict:
        return {"name": self.name, "metric": self.metric,
                "threshold_ms": self.threshold_ms,
                "target_fraction": self.target_fraction,
                "labels": dict(self.labels),
                "windows_s": list(self.windows),
                "burn_rules": [r.to_dict() for r in self.burn_rules]}


class _SLOState:
    """Monitor-side state for one SLO: the snapshot ring of
    ``(t, good, total)`` cumulative counts and per-rule firing
    state."""

    __slots__ = ("slo", "snaps", "firing", "effective_bound",
                 "direct_good", "direct_total")

    def __init__(self, slo: LatencySLO, maxlen: int):
        self.slo = slo
        self.snaps: deque = deque(maxlen=maxlen)
        self.firing: Dict[str, bool] = {r.name: False
                                        for r in slo.burn_rules}
        self.effective_bound: Optional[float] = None
        self.direct_good = 0     # direct-feed path (no histogram)
        self.direct_total = 0


class SLOMonitor:
    """Evaluates registered SLOs over deterministic rolling windows
    and drives the alert sinks + gauges."""

    def __init__(self, registry: Optional[MetricRegistry] = None,
                 now: Callable[[], float] = time.monotonic,
                 max_snapshots: int = 4096):
        self._reg = registry or default_registry()
        self._now = now
        self._lock = threading.Lock()
        self._states: "Dict[str, _SLOState]" = {}
        self._sinks: Dict[str, Callable] = {}
        self._max_snapshots = int(max_snapshots)
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._g_burn = self._reg.gauge(
            "paddle_slo_burn_rate",
            "error-budget burn rate per SLO and rolling window "
            "(1.0 = burning exactly the budget)", ("slo", "window"))
        self._g_budget = self._reg.gauge(
            "paddle_slo_budget_remaining",
            "fraction of the error budget left over the longest "
            "configured window (negative = overspent)", ("slo",))
        self._c_excluded = self._reg.counter(
            "paddle_slo_samples_excluded_total",
            "direct-feed samples dropped from SLO windows because "
            "they were warmup/readiness traffic", ("slo",))

    # ------------------------------------------------------- registry
    def add(self, slo: LatencySLO) -> LatencySLO:
        with self._lock:
            if slo.name in self._states:
                raise ValueError(f"SLO {slo.name!r} already declared")
            self._states[slo.name] = _SLOState(slo,
                                               self._max_snapshots)
        return slo

    def remove(self, name: str):
        with self._lock:
            self._states.pop(name, None)
        self._g_burn.clear(slo=name)
        self._g_budget.clear(slo=name)

    def slos(self) -> List[LatencySLO]:
        with self._lock:
            return [s.slo for s in self._states.values()]

    def clear(self):
        with self._lock:
            names = list(self._states)
            self._states.clear()
        for n in names:
            self._g_burn.clear(slo=n)
            self._g_budget.clear(slo=n)

    # ------------------------------------------------------- sinks
    def add_alert_sink(self, name: str, fn: Callable):
        """Register ``fn(alert: dict)``; called on every firing
        transition (``alert["firing"]`` True on fire, False on
        resolve). A raising sink is isolated, never fatal."""
        with self._lock:
            self._sinks[name] = fn

    def remove_alert_sink(self, name: str):
        with self._lock:
            self._sinks.pop(name, None)

    # ------------------------------------------------------- sampling
    def observe(self, name: str, latency_ms: float,
                warmup: bool = False):
        """Direct-feed path for SLOs without a backing histogram:
        count one sample against the threshold. Warmup/readiness
        samples are dropped (and counted) — the same exclusion the
        histogram path inherits from ``record_traffic=False``."""
        with self._lock:
            st = self._states.get(name)
        if st is None:
            raise KeyError(f"unknown SLO {name!r}")
        if warmup:
            self._c_excluded.labels(slo=name).inc()
            return
        with self._lock:
            st.direct_total += 1
            if float(latency_ms) <= st.slo.threshold_ms:
                st.direct_good += 1

    def _histogram_counts(self, st: _SLOState
                          ) -> Optional[Tuple[int, int]]:
        """(good, total) cumulative counts from the SLO's histogram
        family, summed over label-matching children; None when the
        family does not exist (yet)."""
        fam = self._reg.get(st.slo.metric)
        if fam is None or fam.kind != "histogram":
            return None
        good = total = 0
        matched = False
        for labels, child in fam.collect():
            if any(labels.get(k) != str(v)
                   for k, v in st.slo.labels.items()):
                continue
            matched = True
            bound_le = None
            for ub, cum in child.buckets():
                if ub <= st.slo.threshold_ms:
                    bound_le = ub
                    good_here = cum
                else:
                    break
            if bound_le is not None:
                st.effective_bound = bound_le
                good += good_here
            total += child.count
        if not matched:
            return (0, 0)
        return (good, total)

    def _snapshot(self, st: _SLOState, t: float):
        counts = self._histogram_counts(st)
        with self._lock:
            dg, dt = st.direct_good, st.direct_total
        if counts is None:
            good, total = dg, dt
        else:
            good, total = counts[0] + dg, counts[1] + dt
        st.snaps.append((t, good, total))

    @staticmethod
    def _window_delta(snaps, t: float, window_s: float) -> dict:
        """Counts over ``[t - window_s, t]`` by differencing the
        newest snapshot against the latest one at or before the window
        start (partial coverage uses the oldest snapshot and says
        so)."""
        if not snaps:
            return {"good": 0, "total": 0, "bad_fraction": 0.0,
                    "covered": False}
        t_now, good_now, total_now = snaps[-1]
        base = None
        for s in snaps:
            if s[0] <= t - window_s:
                base = s
            else:
                break
        covered = base is not None
        if base is None:
            base = snaps[0]
        d_total = max(0, total_now - base[2])
        d_good = max(0, good_now - base[1])
        bad = (d_total - d_good) / d_total if d_total > 0 else 0.0
        return {"good": d_good, "total": d_total,
                "bad_fraction": bad, "covered": covered}

    # ------------------------------------------------------- evaluate
    def evaluate(self, t: Optional[float] = None) -> dict:
        """One evaluation pass: snapshot every SLO's counts, compute
        window deltas + burn rates, update gauges, run the alert
        rules, notify sinks on transitions. Returns the ``/sloz``
        payload."""
        t = self._now() if t is None else float(t)
        with self._lock:
            states = list(self._states.values())
            sinks = list(self._sinks.items())
        out = []
        transitions = []
        for st in states:
            self._snapshot(st, t)
            slo = st.slo
            windows = {}
            for w in slo.windows:
                d = self._window_delta(st.snaps, t, w)
                d["burn_rate"] = round(
                    d["bad_fraction"] / slo.error_budget, 4)
                windows[self._wlabel(w)] = d
                self._g_burn.labels(slo=slo.name,
                                    window=self._wlabel(w)).set(
                    d["burn_rate"])
            longest = self._wlabel(max(slo.windows))
            budget_remaining = round(
                1.0 - windows[longest]["burn_rate"], 4)
            self._g_budget.labels(slo=slo.name).set(budget_remaining)
            alerts = []
            for rule in slo.burn_rules:
                short = windows.get(self._wlabel(rule.short_s)) or \
                    self._window_delta(st.snaps, t, rule.short_s)
                long = windows.get(self._wlabel(rule.long_s)) or \
                    self._window_delta(st.snaps, t, rule.long_s)
                b_short = short["bad_fraction"] / slo.error_budget
                b_long = long["bad_fraction"] / slo.error_budget
                firing = b_short > rule.factor and \
                    b_long > rule.factor
                alert = {
                    "slo": slo.name, "rule": rule.name,
                    "severity": rule.severity,
                    "firing": firing,
                    "factor": rule.factor,
                    "burn_short": round(b_short, 4),
                    "burn_long": round(b_long, 4),
                    "short_s": rule.short_s, "long_s": rule.long_s,
                    "threshold_ms": slo.threshold_ms,
                    "target_fraction": slo.target_fraction,
                    "exemplar_trace_id": self._exemplar(slo),
                }
                alerts.append(alert)
                if firing != st.firing[rule.name]:
                    st.firing[rule.name] = firing
                    transitions.append(alert)
            out.append({
                "slo": slo.to_dict(),
                "effective_threshold_ms": st.effective_bound,
                "windows": windows,
                "budget_remaining": budget_remaining,
                "alerts": alerts,
                "firing": [a["rule"] for a in alerts if a["firing"]],
            })
        for alert in transitions:
            for _, fn in sinks:
                try:
                    fn(dict(alert))
                except Exception:  # noqa: BLE001 - a broken sink must
                    pass           # not stop evaluation or its peers
        return {"t": t, "slos": out}

    @staticmethod
    def _wlabel(w: float) -> str:
        w = float(w)
        if w >= 86400 and w % 86400 == 0:
            return f"{int(w // 86400)}d"
        if w >= 3600 and w % 3600 == 0:
            return f"{int(w // 3600)}h"
        if w >= 60 and w % 60 == 0:
            return f"{int(w // 60)}m"
        return f"{w:g}s"

    def _exemplar(self, slo: LatencySLO) -> Optional[str]:
        """The PR 9 exemplar link: the latest trace id seen in the
        worst bucket above the threshold (the request an operator
        should look at), else the slowest recorded one."""
        try:
            from . import tracing
            table = tracing.exemplars(slo.metric)
        except Exception:  # noqa: BLE001
            return None
        if not table:
            return None
        over = [(e["value_ms"], e["trace_id"])
                for e in table.values()
                if e["value_ms"] > slo.threshold_ms]
        pool = over or [(e["value_ms"], e["trace_id"])
                        for e in table.values()]
        return max(pool)[1] if pool else None

    # ------------------------------------------------------- evaluator
    def start(self, interval_s: Optional[float] = None
              ) -> "SLOMonitor":
        """Periodic evaluation on a daemon thread
        (``FLAGS_slo_eval_interval_s`` default)."""
        if interval_s is None:
            try:
                from ..framework.flags import flag_value
                interval_s = float(
                    flag_value("FLAGS_slo_eval_interval_s"))
            except Exception:  # noqa: BLE001
                interval_s = 10.0
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, args=(float(interval_s),),
                name="slo-evaluator", daemon=True)
            self._thread.start()
        return self

    def _loop(self, interval_s: float):
        while not self._stop.wait(interval_s):
            try:
                self.evaluate()
            except Exception:  # noqa: BLE001 - the evaluator must
                pass           # survive any single bad pass

    def stop(self):
        self._stop.set()
        with self._lock:
            t, self._thread = self._thread, None
        if t is not None and t.is_alive():
            t.join(timeout=5)

    # ------------------------------------------------------- payload
    def sloz_payload(self, evaluate: bool = True) -> dict:
        """The ``/sloz`` JSON document (evaluates by default so a
        scrape is always current)."""
        from .tracing import process_name
        doc = self.evaluate() if evaluate else {"t": self._now(),
                                                "slos": []}
        doc["process"] = process_name()
        return doc


def merge_sloz_payloads(own: dict, remotes: Dict[str, dict]) -> dict:
    """Fleet aggregation: sum window good/total counts across
    processes per (slo name, window label) and recompute bad
    fraction + burn rate — the router's ``/sloz`` view, shaped like
    the per-process document plus per-replica sub-documents."""
    merged: Dict[str, dict] = {}
    for entry in own.get("slos", []):
        merged[entry["slo"]["name"]] = _copy_entry(entry)
    for rid, doc in sorted(remotes.items()):
        for entry in doc.get("slos", []):
            name = entry["slo"]["name"]
            if name not in merged:
                merged[name] = _copy_entry(entry)
                continue
            tgt = merged[name]
            budget = 1.0 - tgt["slo"]["target_fraction"]
            for wl, d in entry.get("windows", {}).items():
                td = tgt["windows"].setdefault(
                    wl, {"good": 0, "total": 0, "bad_fraction": 0.0,
                         "covered": d.get("covered", False),
                         "burn_rate": 0.0})
                td["good"] += d.get("good", 0)
                td["total"] += d.get("total", 0)
                total = td["total"]
                bad = (total - td["good"]) / total if total else 0.0
                td["bad_fraction"] = round(bad, 6)
                td["burn_rate"] = round(bad / budget, 4)
                td["covered"] = td["covered"] and d.get("covered",
                                                        False)
    return {"process": own.get("process"),
            "replicas": sorted(remotes),
            "slos": list(merged.values())}


def _copy_entry(entry: dict) -> dict:
    out = dict(entry)
    out["windows"] = {k: dict(v)
                      for k, v in entry.get("windows", {}).items()}
    return out


# ------------------------------------------------------------- default
_default_lock = threading.Lock()
_default: Optional[SLOMonitor] = None


def default_monitor() -> SLOMonitor:
    """The process-wide monitor ``/sloz`` serves."""
    global _default
    with _default_lock:
        if _default is None:
            _default = SLOMonitor()
        return _default


def set_default_monitor(mon: Optional[SLOMonitor]
                        ) -> Optional[SLOMonitor]:
    """Swap the process-wide monitor (tests; ``None`` resets to a
    fresh one on next use). Returns the previous monitor."""
    global _default
    with _default_lock:
        prev, _default = _default, mon
    return prev


def latency_slo(name: str, threshold_ms: float,
                target_fraction: float, *,
                metric: str = "paddle_serving_latency_ms",
                labels: Optional[dict] = None,
                windows: Optional[Sequence[float]] = None,
                burn_rules: Optional[Sequence[BurnRule]] = None
                ) -> LatencySLO:
    """Declare a latency SLO on the default monitor::

        latency_slo("serving_p99", threshold_ms=100.0,
                    target_fraction=0.99)
    """
    slo = LatencySLO(name, metric, threshold_ms, target_fraction,
                     labels=labels, windows=windows,
                     burn_rules=burn_rules)
    return default_monitor().add(slo)


def add_alert_sink(name: str, fn: Callable):
    default_monitor().add_alert_sink(name, fn)


def remove_alert_sink(name: str):
    default_monitor().remove_alert_sink(name)


def sloz_payload() -> dict:
    return default_monitor().sloz_payload()
