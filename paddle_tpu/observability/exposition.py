"""Render a MetricRegistry for scraping: Prometheus text format 0.0.4
and a JSON mirror of the same samples (the JSON additionally carries the
bounded-window percentiles that Prometheus histograms cannot express)."""
from __future__ import annotations

import json
import math
from typing import Optional

from .registry import (Counter, Gauge, Histogram, MetricRegistry,
                       default_registry)

__all__ = ["prometheus_text", "json_snapshot", "PROMETHEUS_CONTENT_TYPE"]

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s: str) -> str:
    return (s.replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _fmt_value(v) -> str:
    try:
        f = float(v)
    except (TypeError, ValueError):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if math.isnan(f):
        return "NaN"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _labels_str(labels: dict, extra: Optional[dict] = None) -> str:
    merged = dict(labels)
    if extra:
        merged.update(extra)
    if not merged:
        return ""
    inner = ",".join(f'{k}="{_escape_label(str(v))}"'
                     for k, v in sorted(merged.items()))
    return "{" + inner + "}"


def prometheus_text(registry: Optional[MetricRegistry] = None) -> str:
    registry = registry or default_registry()
    lines = []
    for fam in registry.collect():
        children = fam.collect()
        if not children:
            continue
        if fam.help:
            lines.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
        lines.append(f"# TYPE {fam.name} {fam.kind}")
        if isinstance(fam, Histogram):
            for labels, child in children:
                for ub, cum in child.buckets():
                    lines.append(
                        f"{fam.name}_bucket"
                        f"{_labels_str(labels, {'le': _fmt_value(ub)})} "
                        f"{cum}")
                lines.append(f"{fam.name}_sum{_labels_str(labels)} "
                             f"{_fmt_value(child.sum)}")
                lines.append(f"{fam.name}_count{_labels_str(labels)} "
                             f"{child.count}")
        elif isinstance(fam, (Counter, Gauge)):
            for labels, child in children:
                lines.append(f"{fam.name}{_labels_str(labels)} "
                             f"{_fmt_value(child.value)}")
    return "\n".join(lines) + ("\n" if lines else "")


def json_snapshot(registry: Optional[MetricRegistry] = None) -> dict:
    registry = registry or default_registry()
    out = {}
    for fam in registry.collect():
        samples = []
        if isinstance(fam, Histogram):
            for labels, child in fam.collect():
                samples.append({
                    "labels": labels,
                    "count": child.count,
                    "sum": child.sum,
                    "buckets": {_fmt_value(ub): cum
                                for ub, cum in child.buckets()},
                    "window": child.window_snapshot(),
                })
        else:
            for labels, child in fam.collect():
                v = child.value
                if isinstance(v, float) and (math.isnan(v)
                                             or math.isinf(v)):
                    v = None
                samples.append({"labels": labels, "value": v})
        out[fam.name] = {"type": fam.kind, "help": fam.help,
                         "samples": samples}
    return out


def json_text(registry: Optional[MetricRegistry] = None,
              indent: Optional[int] = None) -> str:
    return json.dumps(json_snapshot(registry), indent=indent,
                      sort_keys=True)
