"""Telemetry HTTP endpoint — stdlib ``http.server``, zero dependencies.

Serves four paths off a daemon thread:

- ``/metrics``  — Prometheus text format (0.0.4); ``?format=json`` or
  an ``Accept: application/json`` header switches to the JSON mirror;
- ``/healthz``  — runs the registered health checks, 200 when all pass,
  503 otherwise, JSON body either way (LIVENESS: the process is up and
  its workers have not died);
- ``/readyz``   — runs the registered readiness checks, same contract
  (READINESS: the process may be handed traffic — e.g. a serving
  replica flips ready only once warmup completed, so a fleet router
  never routes to a cold replica; distinct from liveness: a warming
  replica is alive but not ready);
- ``/statusz``  — process/runtime status page (pid, uptime, backend,
  live serving servers, metric family count);
- ``/goodputz`` — the goodput ledger's accounting report plus the
  continuous step profiler summary;
- ``/sloz``     — declared SLOs with rolling-window attainment, burn
  rates, and firing alerts (evaluated at scrape time);
- ``/schedz``   — multi-tenant admission control + autoscaler state:
  per-tenant token buckets, shed counts, and the last autoscaling
  decisions;
- ``/execz``    — the executable cost & roofline registry: every
  compile site's signatures with XLA FLOPs / bytes / memory, cache
  provenance, live per-kind MFU and bandwidth utilization;
- ``/profilez`` — the device-profile capture ring;
  ``?duration_ms=`` runs one bounded, rate-limited ``jax.profiler``
  capture and returns the chrome-trace document;
- ``/numericsz`` — the correctness plane: NaN/Inf tripwire health,
  shadow-verification divergence, int8 scale drift, device canary
  state, and the numerics anomaly ledger.

``InferenceServer`` attaches via ``FLAGS_serving_telemetry_port``
(-1 disabled, 0 ephemeral, >0 fixed); standalone training scripts call
``start_telemetry_server()`` explicitly. One shared server per process
— the registry is process-wide, so one scrape endpoint serves every
subsystem.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple

from .exposition import (PROMETHEUS_CONTENT_TYPE, json_text,
                         prometheus_text)
from .registry import MetricRegistry, default_registry

__all__ = [
    "TelemetryServer", "start_telemetry_server", "get_telemetry_server",
    "stop_telemetry_server", "add_health_check", "remove_health_check",
    "healthz", "add_readiness_check", "remove_readiness_check",
    "readyz", "execz_text", "profilez_response", "numericsz_text",
]

_start_time = time.time()

# ---------------------------------------------------------------- health
_health_lock = threading.Lock()
_health_checks: Dict[str, Callable] = {}
_readiness_checks: Dict[str, Callable] = {}


def add_health_check(name: str, fn: Callable):
    """Register ``fn() -> bool | (bool, info)``; raising counts as
    unhealthy. All checks must pass for /healthz to return 200."""
    with _health_lock:
        _health_checks[name] = fn


def remove_health_check(name: str):
    with _health_lock:
        _health_checks.pop(name, None)


def add_readiness_check(name: str, fn: Callable):
    """Register a READINESS probe (same ``fn() -> bool | (bool, info)``
    contract as health checks): all must pass for /readyz to return
    200. Readiness means "send me traffic" — a serving replica
    registers one that flips true only after warmup completes —
    whereas health means "the process is alive". A router routes on
    readiness; a supervisor restarts on (lack of) liveness."""
    with _health_lock:
        _readiness_checks[name] = fn


def remove_readiness_check(name: str):
    with _health_lock:
        _readiness_checks.pop(name, None)


def _run_checks(checks: Dict[str, Callable],
                unhealthy: str) -> Tuple[bool, dict]:
    ok, detail = True, {}
    for name, fn in checks.items():
        try:
            res = fn()
            if isinstance(res, tuple):
                c_ok, info = bool(res[0]), res[1]
            else:
                c_ok, info = bool(res), None
        except Exception as e:  # noqa: BLE001 - a raising probe is a
            c_ok, info = False, repr(e)  # failing probe, not a crash
        detail[name] = {"ok": c_ok}
        if info is not None:
            detail[name]["info"] = info
        ok = ok and c_ok
    return ok, {"status": "ok" if ok else unhealthy, "checks": detail}


def healthz() -> Tuple[bool, dict]:
    with _health_lock:
        checks = dict(_health_checks)
    return _run_checks(checks, "unhealthy")


def readyz() -> Tuple[bool, dict]:
    """Run the registered readiness checks. With none registered the
    process is vacuously ready (mirrors /healthz semantics)."""
    with _health_lock:
        checks = dict(_readiness_checks)
    return _run_checks(checks, "not ready")


def _statusz() -> dict:
    out = {
        "pid": os.getpid(),
        "uptime_s": round(time.time() - _start_time, 3),
        "python": sys.version.split()[0],
        "argv": list(sys.argv),
    }
    try:
        reg = default_registry()
        out["metric_families"] = len(reg.collect())
    except Exception:  # noqa: BLE001
        pass
    try:  # live serving servers (lazy — serving may not be imported)
        serving_metrics = sys.modules.get("paddle_tpu.serving.metrics")
        if serving_metrics is not None:
            out["serving_servers"] = sorted(
                serving_metrics.all_snapshots())
    except Exception:  # noqa: BLE001
        pass
    try:  # live decode engines: prefix-cache + page-accounting state
        # (incl. the refcount-leak check), lazy like the above
        gen_engine = sys.modules.get(
            "paddle_tpu.serving.generation.engine")
        if gen_engine is not None:
            engines = gen_engine.engines_statusz()
            if engines:
                out["decode_engines"] = engines
    except Exception:  # noqa: BLE001
        pass
    try:
        jax = sys.modules.get("jax")
        if jax is not None:
            out["jax"] = {"version": jax.__version__,
                          "backend": jax.default_backend(),
                          "device_count": jax.device_count()}
    except Exception:  # noqa: BLE001
        pass
    try:  # persistent compile-cache health (hits/misses/fallbacks/
        # entries/bytes) without scraping /metrics — lazy like the
        # other sections; absent until the cache package is imported
        cc = sys.modules.get("paddle_tpu.compile_cache")
        if cc is not None:
            section = dict(cc.stats())
            try:
                from ..framework.flags import flag_value
                section["dir"] = str(
                    flag_value("FLAGS_compile_cache_dir") or "")
                section["enabled"] = bool(section["dir"])
            except Exception:  # noqa: BLE001
                pass
            out["compile_cache"] = section
    except Exception:  # noqa: BLE001
        pass
    try:  # numerics health (lazy — absent until the numerics layer
        # has something to say; importing observability pulls it in)
        num = sys.modules.get("paddle_tpu.observability.numerics")
        if num is not None:
            out["numerics"] = num.numericsz_payload()
    except Exception:  # noqa: BLE001
        pass
    try:  # what sharding this process runs (lazy — shard may be absent)
        shard_mod = sys.modules.get("paddle_tpu.distributed.shard")
        mesh_mod = sys.modules.get("paddle_tpu.distributed.mesh_utils")
        if shard_mod is not None:
            reg = default_registry()
            fam = reg.gauge(
                "paddle_shard_spec_tree_info",
                "Spec-tree identity of the live process's sharding "
                "(value 1; the hash label identifies the tree)",
                labelnames=("hash",))
            hashes = [labels.get("hash", "") for labels, child
                      in fam.collect() if child.value]
            sharding = {"specs_generation":
                        shard_mod.specs_generation(),
                        "spec_tree_hash": hashes[0] if hashes else None}
            mesh = mesh_mod.get_global_mesh() \
                if mesh_mod is not None else None
            if mesh is not None:
                sharding["mesh_axes"] = {
                    str(k): int(v) for k, v in dict(mesh.shape).items()}
            out["sharding"] = sharding
    except Exception:  # noqa: BLE001
        pass
    try:  # serving tensor-parallel mesh: replica mesh shape + the
        # per-chip projected KV-pool bytes of every live decode engine
        # (lazy — absent until a mesh-attached engine exists)
        gen_engine = sys.modules.get(
            "paddle_tpu.serving.generation.engine")
        if gen_engine is not None:
            meshes = {}
            for name, snap in (out.get("decode_engines") or {}).items():
                sm = snap.get("serving_mesh")
                if sm:
                    meshes[name] = sm
            if meshes:
                sharding = out.setdefault("sharding", {})
                sharding["serving_mesh"] = meshes
    except Exception:  # noqa: BLE001
        pass
    return out


def tracez_text(query: str) -> str:
    """The ``/tracez`` body: the flight recorder's recent traces as
    JSON. Query params: ``trace_id=<32hex>`` (one trace),
    ``min_ms=<float>`` (only traces at least that long),
    ``limit=<n>`` (newest-first cap, default 100), and
    ``format=chrome`` for a chrome-trace document of the selected
    spans instead of the tracez schema. Shared by the telemetry
    endpoint, replica workers, and the fleet router (which merges
    replica payloads into its own)."""
    from urllib.parse import parse_qs

    from . import tracing
    q = {k: v[-1] for k, v in parse_qs(query).items()}
    trace_id = q.get("trace_id") or None
    min_ms = float(q["min_ms"]) if q.get("min_ms") else None
    limit = int(q.get("limit", 100))
    payload = tracing.tracez_payload(trace_id=trace_id,
                                     min_duration_ms=min_ms,
                                     limit=limit)
    if q.get("format") == "chrome":
        spans = [s for t in payload["traces"] for s in t["spans"]]
        return json.dumps(
            {"traceEvents": tracing.chrome_trace_events(spans)})
    return json.dumps(payload, indent=1, sort_keys=True)


def execz_text(query: str = "") -> str:
    """The ``/execz`` body: the executable registry with cost/memory
    analysis materialized, per-site rollups, and the per-kind MFU /
    roofline join state. ``?compute=0`` skips lazy analysis (pure
    registry dump). Shared by the telemetry endpoint and replica
    workers; the router aggregates replica payloads."""
    from . import xstats
    compute = "compute=0" not in (query or "")
    return json.dumps(xstats.execz_payload(compute=compute),
                      indent=1, sort_keys=True, default=str)


def numericsz_text(query: str = "") -> str:
    """The ``/numericsz`` body: tripwire/shadow/canary health from
    the numerics layer (see ``numerics.numericsz_payload``). Shared by
    the telemetry endpoint and replica workers; the router merges
    replica payloads into a fleet view."""
    del query  # no parameters yet; the signature matches its siblings
    from . import numerics
    return json.dumps(numerics.numericsz_payload(), indent=1,
                      sort_keys=True, default=str)


def profilez_response(query: str = "") -> Tuple[int, str]:
    """The ``/profilez`` behavior shared by every HTTP surface:
    without ``duration_ms`` — list the capture ring; with it — run one
    bounded, rate-limited capture and return the chrome-trace document
    (429 when the rate limit refuses). Returns ``(status, body)``;
    the body is JSON either way."""
    from urllib.parse import parse_qs

    from . import xstats
    q = {k: v[-1] for k, v in parse_qs(query or "").items()}
    if not q.get("duration_ms"):
        return 200, json.dumps(xstats.profilez_payload(), indent=1,
                               sort_keys=True)
    got = xstats.capture_profile(float(q["duration_ms"]),
                                 reason=q.get("reason", "manual"))
    if got is None:
        return 429, json.dumps(
            {"error": "capture rate-limited or already in flight",
             "ring": xstats.profilez_payload()}, indent=1,
            sort_keys=True)
    meta, doc = got
    return 200, json.dumps(doc)


# ---------------------------------------------------------------- server
class _Handler(BaseHTTPRequestHandler):
    server_version = "paddle-tpu-telemetry/1.0"

    def _send(self, code: int, body: str, ctype: str):
        data = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler ABI
        path, _, query = self.path.partition("?")
        registry = self.server.registry  # type: ignore[attr-defined]
        try:
            if path == "/metrics":
                want_json = ("format=json" in query or "application/json"
                             in (self.headers.get("Accept") or ""))
                if want_json:
                    self._send(200, json_text(registry, indent=1),
                               "application/json")
                else:
                    self._send(200, prometheus_text(registry),
                               PROMETHEUS_CONTENT_TYPE)
            elif path == "/healthz":
                ok, detail = healthz()
                self._send(200 if ok else 503,
                           json.dumps(detail, indent=1, sort_keys=True),
                           "application/json")
            elif path == "/readyz":
                ok, detail = readyz()
                self._send(200 if ok else 503,
                           json.dumps(detail, indent=1, sort_keys=True),
                           "application/json")
            elif path == "/statusz":
                self._send(200, json.dumps(_statusz(), indent=1,
                                           sort_keys=True, default=str),
                           "application/json")
            elif path == "/tracez":
                self._send(200, tracez_text(query), "application/json")
            elif path == "/goodputz":
                from .goodput import goodputz_payload
                self._send(200, json.dumps(goodputz_payload(),
                                           indent=1, sort_keys=True),
                           "application/json")
            elif path == "/sloz":
                from .slo import sloz_payload
                self._send(200, json.dumps(sloz_payload(), indent=1,
                                           sort_keys=True),
                           "application/json")
            elif path == "/schedz":
                from ..serving.scheduling.schedz import schedz_payload
                self._send(200, json.dumps(schedz_payload(), indent=1,
                                           sort_keys=True),
                           "application/json")
            elif path == "/execz":
                self._send(200, execz_text(query), "application/json")
            elif path == "/profilez":
                code, body = profilez_response(query)
                self._send(code, body, "application/json")
            elif path == "/numericsz":
                self._send(200, numericsz_text(query),
                           "application/json")
            elif path == "/":
                self._send(200, "paddle-tpu telemetry\n"
                                "/metrics  /healthz  /readyz  "
                                "/statusz  /tracez  /goodputz  "
                                "/sloz  /schedz  /execz  /profilez  "
                                "/numericsz\n",
                           "text/plain; charset=utf-8")
            else:
                self._send(404, "not found\n",
                           "text/plain; charset=utf-8")
        except Exception as e:  # noqa: BLE001 - a scrape bug must never
            try:                # kill the handler thread
                self._send(500, f"internal error: {e!r}\n",
                           "text/plain; charset=utf-8")
            except Exception:  # noqa: BLE001
                pass

    def log_message(self, *args):  # silence per-request stderr noise
        pass


class TelemetryServer:
    """Owns one ThreadingHTTPServer on a daemon thread. ``port=0`` binds
    an ephemeral port; read the actual one back from ``.port``."""

    def __init__(self, port: int = 0, host: str = "0.0.0.0",
                 registry: Optional[MetricRegistry] = None):
        self._requested_port = int(port)
        self.host = host
        self.registry = registry or default_registry()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def running(self) -> bool:
        return self._httpd is not None

    @property
    def port(self) -> Optional[int]:
        return self._httpd.server_address[1] if self._httpd else None

    def url(self, path: str = "/metrics") -> str:
        host = "127.0.0.1" if self.host in ("0.0.0.0", "::") else self.host
        return f"http://{host}:{self.port}{path}"

    def start(self) -> "TelemetryServer":
        if self._httpd is not None:
            return self
        httpd = ThreadingHTTPServer((self.host, self._requested_port),
                                    _Handler)
        httpd.daemon_threads = True
        httpd.registry = self.registry  # type: ignore[attr-defined]
        self._httpd = httpd
        self._thread = threading.Thread(
            target=httpd.serve_forever, name="telemetry-http",
            daemon=True)
        self._thread.start()
        return self

    def stop(self):
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        t, self._thread = self._thread, None
        if t is not None and t.is_alive():
            t.join(timeout=5)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


_singleton_lock = threading.Lock()
_singleton: Optional[TelemetryServer] = None


def start_telemetry_server(port: Optional[int] = None,
                           host: str = "0.0.0.0",
                           registry: Optional[MetricRegistry] = None,
                           install_collectors: bool = True
                           ) -> TelemetryServer:
    """Start (or return) the shared process-wide telemetry endpoint.
    Default collectors — device memory, JAX compile events, profiler
    span mirroring when ``FLAGS_profiler_span_metrics`` is on — are
    installed on first start so a bare scrape already carries runtime
    gauges."""
    global _singleton
    with _singleton_lock:
        if _singleton is not None and _singleton.running:
            return _singleton
        srv = TelemetryServer(port=0 if port is None else int(port),
                              host=host, registry=registry)
        srv.start()
        _singleton = srv
    if install_collectors:
        try:
            from . import runtime
            runtime.install_all(registry)
        except Exception:  # noqa: BLE001 - collectors are best-effort;
            pass           # the endpoint itself must come up regardless
    return _singleton


def get_telemetry_server() -> Optional[TelemetryServer]:
    with _singleton_lock:
        return _singleton


def stop_telemetry_server():
    global _singleton
    with _singleton_lock:
        srv, _singleton = _singleton, None
    if srv is not None:
        srv.stop()
