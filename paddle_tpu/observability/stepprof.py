"""Continuous step profiler — an always-on bounded ring of per-step
timing envelopes with straggler detection.

The profiler package answers "where did time go" for a session someone
deliberately recorded; this module answers "was step 48213 slow last
night" without anyone having pressed record. Every training step
(``TrainStep.__call__``, the hapi fit callback) and every decode
iteration (``GenerationEngine``) drops one fixed-size envelope into a
bounded ring:

    {step, kind, unix_ms, wall_ms, host_ms?, device_ms?,
     occupancy?, kv_pages_used?, device_peak_bytes?}

``device_peak_bytes`` is read from the existing
``paddle_device_memory_bytes`` gauge (set by the PR 3 scrape
collector) — a dict lookup, never a runtime call — so the steady-state
cost of an envelope is a deque append plus a handful of float ops.

**Anomaly detection** is EWMA + MAD per step kind: the detector keeps
an exponentially-weighted mean of step wall time and a bounded window
for the median-absolute-deviation scale estimate; a step slower than
``ewma + k * 1.4826 * MAD`` (``FLAGS_stepprof_anomaly_k``) after
``FLAGS_stepprof_min_samples`` warm-up samples is a straggler. A
straggler is not just a counter bump: it is recorded as an
error-status span (``stepprof::straggler``) through the PR 9 tracing
layer, which tail-promotes it into the flight recorder — so a slow
step becomes a retrievable, attributable event in ``/tracez``, not a
lost statistic.

Deterministic under test: ``now``/``wall_ns`` are injected.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, Optional

from .registry import MetricRegistry, default_registry

__all__ = ["StepProfiler", "default_profiler", "set_default_profiler",
           "record_step"]


def _flag(name, default):
    from ..framework.flags import flag_value
    try:
        return flag_value(name)
    except KeyError:
        return default


class _KindStats:
    """EWMA + MAD detector state for one step kind (train / decode).
    The MAD (a sort of the deviation window) is refreshed every
    ``_MAD_REFRESH`` samples, not per step — the scale estimate moves
    slowly and the hot path stays a deque append."""

    _MAD_REFRESH = 16

    __slots__ = ("ewma", "n", "devs", "anomalies", "hist_child",
                 "_mad", "_mad_age")

    def __init__(self, mad_window: int = 256):
        self.ewma: Optional[float] = None
        self.n = 0
        self.devs: deque = deque(maxlen=mad_window)
        self.anomalies = 0
        self.hist_child = None      # cached histogram label child
        self._mad = 0.0
        self._mad_age = 0

    def mad(self) -> float:
        if self._mad_age >= self._MAD_REFRESH or \
                (self._mad == 0.0 and self.devs):
            vals = sorted(self.devs)
            self._mad = vals[len(vals) // 2] if vals else 0.0
            self._mad_age = 0
        return self._mad


class StepProfiler:
    """Bounded envelope ring + per-kind straggler detector."""

    def __init__(self, window: Optional[int] = None,
                 alpha: float = 0.1,
                 anomaly_k: Optional[float] = None,
                 min_samples: Optional[int] = None,
                 registry: Optional[MetricRegistry] = None,
                 now: Callable[[], float] = time.monotonic,
                 wall_ns: Callable[[], int] = time.time_ns):
        self._window = int(window if window is not None
                           else _flag("FLAGS_stepprof_window", 512))
        self._alpha = float(alpha)
        self._k = float(anomaly_k if anomaly_k is not None
                        else _flag("FLAGS_stepprof_anomaly_k", 6.0))
        self._min = int(min_samples if min_samples is not None
                        else _flag("FLAGS_stepprof_min_samples", 32))
        self._now = now
        self._wall_ns = wall_ns
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self._window)
        self._kinds: Dict[str, _KindStats] = {}
        self._total = 0
        reg = registry or default_registry()
        self._c_anomalies = reg.counter(
            "paddle_step_anomalies_total",
            "steps flagged as stragglers by the EWMA+MAD detector",
            ("kind",))
        self._h_wall = reg.histogram(
            "paddle_step_wall_ms",
            "per-step wall time from the continuous step profiler",
            ("kind",))

    # ------------------------------------------------------- recording
    def record_step(self, wall_ms: float, *, kind: str = "train",
                    step: Optional[int] = None,
                    host_ms: Optional[float] = None,
                    device_ms: Optional[float] = None,
                    occupancy: Optional[int] = None,
                    kv_pages_used: Optional[int] = None,
                    attrs: Optional[dict] = None) -> dict:
        """Drop one envelope; runs the detector; returns the envelope
        (with ``anomaly`` set when flagged)."""
        wall_ms = float(wall_ms)
        env = {"kind": kind, "unix_ms": self._wall_ns() // 1_000_000,
               "wall_ms": round(wall_ms, 4)}
        if step is not None:
            env["step"] = int(step)
        if host_ms is not None:
            env["host_ms"] = round(float(host_ms), 4)
        if device_ms is not None:
            env["device_ms"] = round(float(device_ms), 4)
        if occupancy is not None:
            env["occupancy"] = int(occupancy)
        if kv_pages_used is not None:
            env["kv_pages_used"] = int(kv_pages_used)
        peak = self._device_peak_bytes()
        if peak is not None:
            env["device_peak_bytes"] = peak
        if attrs:
            env.update(attrs)
        anomaly = None
        with self._lock:
            st = self._kinds.get(kind)
            if st is None:
                st = self._kinds[kind] = _KindStats()
            if st.ewma is not None and st.n >= self._min:
                scale = 1.4826 * st.mad()
                threshold = st.ewma + self._k * max(scale, 1e-9)
                if wall_ms > threshold:
                    anomaly = {"ewma_ms": round(st.ewma, 4),
                               "mad_ms": round(st.mad(), 4),
                               "threshold_ms": round(threshold, 4)}
                    st.anomalies += 1
            if st.ewma is None:
                st.ewma = wall_ms
            elif anomaly is None:
                # anomalous samples do not drag the baseline: a burst
                # of stragglers stays anomalous instead of becoming
                # the new normal
                st.ewma += self._alpha * (wall_ms - st.ewma)
            if anomaly is None:
                st.devs.append(abs(wall_ms - st.ewma))
                st._mad_age += 1
            st.n += 1
            if anomaly is not None:
                env["anomaly"] = anomaly
            self._ring.append(env)
            self._total += 1
            child = st.hist_child
            if child is None:
                child = st.hist_child = self._h_wall.labels(kind=kind)
        child.observe(wall_ms)
        try:
            # xstats join: the envelope's kind meets the live
            # executable's cost model — paddle_mfu{kind=} and the
            # bandwidth gauge move here (dict lookups + gauge sets;
            # analysis is never computed on this path)
            from . import xstats
            xstats.on_step_envelope(env)
        except Exception:  # noqa: BLE001 - garnish on the hot path
            pass
        if anomaly is not None:
            self._c_anomalies.labels(kind=kind).inc()
            trace_id = self._emit_anomaly_span(env, anomaly)
            try:
                # armed via FLAGS_profile_on_anomaly: the straggler
                # kicks off one rate-limited background device-profile
                # capture linked to the promoted span's trace id
                from . import xstats
                xstats.on_anomaly(env, trace_id)
            except Exception:  # noqa: BLE001 - never break a step
                pass
        return env

    _PEAK_PROBE_EVERY = 64

    def _device_peak_bytes(self) -> Optional[int]:
        """Cheap watermark: the max ``peak_bytes_in_use`` child of the
        existing device-memory gauge, if the collector ever ran. No
        runtime call is made here, and the family scan is amortized —
        the cached value is refreshed every ``_PEAK_PROBE_EVERY``
        envelopes (the watermark is a scrape-cadence signal, not a
        per-step one)."""
        age = getattr(self, "_peak_age", None)
        if age is not None and age < self._PEAK_PROBE_EVERY:
            self._peak_age = age + 1
            return self._peak_cache
        self._peak_age = 1
        self._peak_cache = None
        try:
            fam = default_registry().get("paddle_device_memory_bytes")
            if fam is not None:
                peaks = [child.value for labels, child in fam.collect()
                         if labels.get("stat") == "peak_bytes_in_use"]
                if peaks:
                    self._peak_cache = int(max(peaks))
        except Exception:  # noqa: BLE001 - the envelope must never fail
            pass
        return self._peak_cache

    def _emit_anomaly_span(self, env: dict, anomaly: dict):
        """A straggler becomes a traceable event: an error-status span
        recorded under a fresh sampled context rides the PR 9
        tail-promotion path into the flight recorder. Returns the
        span's trace id so the anomaly-capture artifact can link back
        to it."""
        try:
            from . import tracing
            ctx = tracing.new_context(sampled=True)
            attrs = {"kind": env["kind"],
                     "wall_ms": env["wall_ms"],
                     "error": "step straggler: "
                              f"{env['wall_ms']}ms vs threshold "
                              f"{anomaly['threshold_ms']}ms"}
            attrs.update(anomaly)
            if "step" in env:
                attrs["step"] = env["step"]
            tracing.record_span(
                ctx, "stepprof::straggler", stage="anomaly",
                start_unix_ns=env["unix_ms"] * 1_000_000
                - int(env["wall_ms"] * 1e6),
                duration_ms=env["wall_ms"], status="error",
                attrs=attrs, root=True)
            return ctx.trace_id
        except Exception:  # noqa: BLE001 - detection is garnish on the
            return None    # hot path; never let it break a step

    # ------------------------------------------------------- views
    def envelopes(self, kind: Optional[str] = None, limit: int = 100
                  ) -> list:
        with self._lock:
            envs = list(self._ring)
        if kind is not None:
            envs = [e for e in envs if e["kind"] == kind]
        return envs[-int(limit):]

    def summary(self) -> dict:
        """Per-kind live stats for ``/goodputz``: EWMA, MAD, sample and
        anomaly counts, plus the most recent anomalous envelopes."""
        with self._lock:
            kinds = {
                k: {"ewma_ms": round(st.ewma, 4)
                    if st.ewma is not None else None,
                    "mad_ms": round(st.mad(), 4),
                    "samples": st.n,
                    "anomalies": st.anomalies}
                for k, st in self._kinds.items()}
            recent_anomalies = [e for e in self._ring if "anomaly" in e]
            n_ring = len(self._ring)
            total = self._total
        return {"window": self._window, "ring": n_ring,
                "total_steps": total, "kinds": kinds,
                "recent_anomalies": recent_anomalies[-20:]}

    def clear(self):
        with self._lock:
            self._ring.clear()
            self._kinds.clear()
            self._total = 0


_default_lock = threading.Lock()
_default: Optional[StepProfiler] = None


def default_profiler() -> StepProfiler:
    """The process-wide profiler every step recorder reports into."""
    global _default
    with _default_lock:
        if _default is None:
            _default = StepProfiler()
        return _default


def set_default_profiler(prof: Optional[StepProfiler]
                         ) -> Optional[StepProfiler]:
    """Swap the process-wide profiler (tests; ``None`` resets to a
    fresh one on next use). Returns the previous profiler."""
    global _default
    with _default_lock:
        prev, _default = _default, prof
    return prev


def record_step(wall_ms: float, **kw) -> dict:
    """Module-level convenience onto the default profiler."""
    return default_profiler().record_step(wall_ms, **kw)
