"""Typed metric registry — the single surface every subsystem reports into.

Reference analog: paddle/fluid/platform/monitor.cc keeps a process-wide
map of named int64 stats behind STAT_ADD/STAT_RESET macros; serving
frameworks around the reference engine layer Prometheus-style families
on top. This module is both: three metric families (Counter, Gauge,
Histogram) with Prometheus-style label sets, a bounded-window
percentile estimator (``PercentileWindow``, shared with
``paddle_tpu.serving.metrics``), and a ``MetricRegistry`` that owns
families plus scrape-time collectors. Exposition (Prometheus text,
JSON, HTTP) lives in exposition.py / httpd.py so this module stays
stdlib-only and import-light — ``framework.monitor`` imports it before
most of the package exists.

Time is *injected*: ``PercentileWindow`` and ``Histogram`` take a
``now`` callable (default ``time.monotonic``) so age-bounded windows
are deterministic under test and immune to wall-clock jumps.
"""
from __future__ import annotations

import bisect
import math
import re
import threading
import time
from collections import OrderedDict, deque
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricRegistry", "PercentileWindow",
    "default_registry", "sanitize_metric_name", "DEFAULT_MS_BUCKETS",
]

# Millisecond-scaled default buckets (the stack's latencies are ms-sized;
# Prometheus' stock seconds buckets would collapse everything into one).
DEFAULT_MS_BUCKETS = (0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
                      250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0)

_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_INVALID_CHARS = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize_metric_name(name: str) -> str:
    """Coerce an arbitrary string into a legal Prometheus metric name."""
    name = _INVALID_CHARS.sub("_", str(name))
    if not name or not _NAME_RE.match(name):
        name = "_" + name
    return name


def _nearest_rank(sorted_vals: Sequence[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted sample — the same
    estimator serving.metrics shipped with, hoisted here so serving and
    the registry agree on every quantile."""
    if not sorted_vals:
        return 0.0
    k = max(0, min(len(sorted_vals) - 1,
                   math.ceil(q / 100.0 * len(sorted_vals)) - 1))
    return float(sorted_vals[k])


class PercentileWindow:
    """Bounded window of recent observations with nearest-rank
    percentiles. Bounded two ways: at most ``maxlen`` samples, and (when
    ``max_age_s`` is set) only samples younger than that — so a
    long-running server's p99 tracks current behavior, not its whole
    life. ``now`` is injected for deterministic tests.

    Not internally locked: callers (Histogram children, ServingMetrics)
    synchronize around it, matching the deques it replaces."""

    __slots__ = ("_dq", "_now", "max_age_s")

    def __init__(self, maxlen: int = 2048, max_age_s: Optional[float] = None,
                 now: Callable[[], float] = time.monotonic):
        self._dq = deque(maxlen=int(maxlen))
        self._now = now
        self.max_age_s = max_age_s

    def _prune(self):
        if self.max_age_s is None:
            return
        cutoff = self._now() - self.max_age_s
        dq = self._dq
        while dq and dq[0][0] < cutoff:
            dq.popleft()

    def observe(self, value: float):
        self._dq.append((self._now(), float(value)))
        self._prune()

    def extend(self, values: Iterable[float]):
        t = self._now()
        self._dq.extend((t, float(v)) for v in values)
        self._prune()

    def values(self) -> List[float]:
        self._prune()
        return [v for _, v in self._dq]

    def __len__(self):
        self._prune()
        return len(self._dq)

    def sum(self) -> float:
        return float(sum(self.values()))

    def max(self) -> float:
        vals = self.values()
        return float(max(vals)) if vals else 0.0

    def percentile(self, q: float) -> float:
        return _nearest_rank(sorted(self.values()), q)

    def snapshot(self, qs: Sequence[float] = (50, 95, 99)) -> dict:
        vals = sorted(self.values())
        out = {"count": len(vals)}
        for q in qs:
            out[f"p{int(q)}"] = _nearest_rank(vals, q)
        out["max"] = vals[-1] if vals else 0.0
        return out

    def clear(self):
        self._dq.clear()


# --------------------------------------------------------------- families
class _Family:
    """A named metric with a fixed label-name set; each distinct label
    value tuple is one child. With an empty label set the family proxies
    to its single anonymous child (``Counter("x").inc()``)."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "",  # noqa: A002
                 labelnames: Sequence[str] = ()):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: "OrderedDict[Tuple, object]" = OrderedDict()

    # -- child construction (subclass hook)
    def _new_child(self):
        raise NotImplementedError

    def _key(self, args, kwargs) -> Tuple:
        if args and kwargs:
            raise ValueError("pass labels positionally or by name, not both")
        if kwargs:
            if set(kwargs) != set(self.labelnames):
                raise ValueError(
                    f"{self.name} expects labels {self.labelnames}, "
                    f"got {tuple(kwargs)}")
            return tuple(str(kwargs[ln]) for ln in self.labelnames)
        if len(args) != len(self.labelnames):
            raise ValueError(
                f"{self.name} expects {len(self.labelnames)} label "
                f"values, got {len(args)}")
        return tuple(str(a) for a in args)

    def labels(self, *args, **kwargs):
        key = self._key(args, kwargs)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._new_child()
            return child

    def get(self, *args, **kwargs):
        """Child for these labels, or None — never creates (so read-only
        probes like monitor.stat_get don't mint empty series)."""
        key = self._key(args, kwargs)
        with self._lock:
            return self._children.get(key)

    def remove(self, *args, **kwargs):
        key = self._key(args, kwargs)
        with self._lock:
            self._children.pop(key, None)

    def clear(self, **label_filter):
        """Drop children; with kwargs, only those matching the partial
        label set (``family.clear(server="x")`` wipes one server's
        slice)."""
        with self._lock:
            if not label_filter:
                self._children.clear()
                return
            idx = {ln: i for i, ln in enumerate(self.labelnames)}
            for ln in label_filter:
                if ln not in idx:
                    raise ValueError(f"unknown label {ln!r}")
            dead = [k for k in self._children
                    if all(k[idx[ln]] == str(v)
                           for ln, v in label_filter.items())]
            for k in dead:
                del self._children[k]

    def items(self) -> List[Tuple[Tuple, object]]:
        with self._lock:
            return list(self._children.items())

    def collect(self) -> List[Tuple[Dict[str, str], object]]:
        """(labels_dict, child) pairs for exposition."""
        return [(dict(zip(self.labelnames, key)), child)
                for key, child in self.items()]

    def label_values(self) -> List[Tuple]:
        with self._lock:
            return list(self._children)


class _CounterChild:
    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n=1):
        with self._lock:
            self._value += n
            return self._value

    @property
    def value(self):
        return self._value

    def reset(self):
        with self._lock:
            self._value = 0


class Counter(_Family):
    """Monotonic count. ``inc`` tolerates any numeric delta because it
    also backs ``framework.monitor``'s permissive STAT_ADD view."""

    kind = "counter"

    def _new_child(self):
        return _CounterChild()

    def inc(self, n=1):
        return self.labels().inc(n)

    @property
    def value(self):
        return self.labels().value


class _GaugeChild:
    __slots__ = ("_lock", "_value", "_fn")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0
        self._fn = None

    def set(self, v):
        with self._lock:
            self._fn = None
            self._value = v

    def inc(self, n=1):
        with self._lock:
            self._value += n

    def dec(self, n=1):
        with self._lock:
            self._value -= n

    def set_function(self, fn: Callable[[], float]):
        """Value is computed at read time (scrape) instead of pushed."""
        with self._lock:
            self._fn = fn

    @property
    def value(self):
        fn = self._fn
        if fn is not None:
            try:
                return fn()
            except Exception:  # noqa: BLE001 - a broken probe must not
                return float("nan")  # take down the whole scrape
        return self._value


class Gauge(_Family):
    kind = "gauge"

    def _new_child(self):
        return _GaugeChild()

    def set(self, v):
        self.labels().set(v)

    def inc(self, n=1):
        self.labels().inc(n)

    def dec(self, n=1):
        self.labels().dec(n)

    def set_function(self, fn):
        self.labels().set_function(fn)

    @property
    def value(self):
        return self.labels().value


class _HistogramChild:
    __slots__ = ("_lock", "_bounds", "_counts", "_sum", "_count", "window")

    def __init__(self, bounds, window_len, max_age_s, now):
        self._lock = threading.Lock()
        self._bounds = bounds
        self._counts = [0] * (len(bounds) + 1)   # +1 = the +Inf bucket
        self._sum = 0.0
        self._count = 0
        self.window = PercentileWindow(window_len, max_age_s, now)

    def observe(self, v):
        v = float(v)
        with self._lock:
            self._counts[bisect.bisect_left(self._bounds, v)] += 1
            self._sum += v
            self._count += 1
            self.window.observe(v)

    def observe_many(self, vals):
        with self._lock:
            for v in vals:
                v = float(v)
                self._counts[bisect.bisect_left(self._bounds, v)] += 1
                self._sum += v
                self._count += 1
                self.window.observe(v)

    @property
    def sum(self):
        return self._sum

    @property
    def count(self):
        return self._count

    def buckets(self) -> List[Tuple[float, int]]:
        """Cumulative (upper_bound, count) pairs, +Inf last — the
        Prometheus histogram wire shape."""
        with self._lock:
            out, running = [], 0
            for ub, c in zip(self._bounds, self._counts):
                running += c
                out.append((ub, running))
            out.append((float("inf"), running + self._counts[-1]))
            return out

    def percentile(self, q: float) -> float:
        with self._lock:
            return self.window.percentile(q)

    def window_snapshot(self, qs=(50, 95, 99)) -> dict:
        with self._lock:
            return self.window.snapshot(qs)

    def window_sum(self) -> float:
        with self._lock:
            return self.window.sum()

    def window_count(self) -> int:
        with self._lock:
            return len(self.window)

    def reset(self):
        with self._lock:
            self._counts = [0] * len(self._counts)
            self._sum = 0.0
            self._count = 0
            self.window.clear()


class Histogram(_Family):
    """Cumulative buckets (Prometheus exposition) plus a bounded
    ``PercentileWindow`` per child (live p50/p95/p99, the serving
    snapshot schema)."""

    kind = "histogram"

    def __init__(self, name, help="", labelnames=(),  # noqa: A002
                 buckets: Sequence[float] = DEFAULT_MS_BUCKETS,
                 window: int = 2048, max_age_s: Optional[float] = None,
                 now: Callable[[], float] = time.monotonic):
        super().__init__(name, help, labelnames)
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self._bounds = tuple(b for b in bounds if not math.isinf(b))
        self._window_len = int(window)
        self._max_age_s = max_age_s
        self._now = now

    def _new_child(self):
        return _HistogramChild(self._bounds, self._window_len,
                               self._max_age_s, self._now)

    def observe(self, v):
        self.labels().observe(v)


# --------------------------------------------------------------- registry
class MetricRegistry:
    """Owns metric families (creation is get-or-create and idempotent)
    plus scrape-time collectors — callables invoked at ``collect()`` to
    refresh pull-style gauges (device memory, queue depths) just before
    exposition."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: "OrderedDict[str, _Family]" = OrderedDict()
        self._collectors: List[Callable[["MetricRegistry"], None]] = []

    # -- family management
    def register(self, family: _Family) -> _Family:
        with self._lock:
            existing = self._families.get(family.name)
            if existing is not None:
                if type(existing) is not type(family):
                    raise ValueError(
                        f"metric {family.name!r} already registered as "
                        f"{existing.kind}, not {family.kind}")
                return existing
            self._families[family.name] = family
            return family

    def unregister(self, name: str):
        with self._lock:
            self._families.pop(name, None)

    def get(self, name: str) -> Optional[_Family]:
        with self._lock:
            return self._families.get(name)

    def _get_or_create(self, cls, name, help, labelnames, **kw):  # noqa: A002
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if not isinstance(fam, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.kind}, not {cls.kind}")
                if tuple(labelnames) != fam.labelnames:
                    raise ValueError(
                        f"metric {name!r} already registered with labels "
                        f"{fam.labelnames}, not {tuple(labelnames)}")
                return fam
            fam = cls(name, help, labelnames, **kw)
            self._families[name] = fam
            return fam

    def counter(self, name, help="", labelnames=()) -> Counter:  # noqa: A002
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name, help="", labelnames=()) -> Gauge:  # noqa: A002
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name, help="", labelnames=(),  # noqa: A002
                  buckets=DEFAULT_MS_BUCKETS, window=2048,
                  max_age_s=None, now=time.monotonic) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets, window=window,
                                   max_age_s=max_age_s, now=now)

    # -- collectors
    def register_collector(self, fn, name: Optional[str] = None):
        """Idempotent by ``name`` (default: the function's qualname), so
        installers can run on every telemetry-server start."""
        key = name or getattr(fn, "__qualname__", repr(fn))
        with self._lock:
            if any(k == key for k, _ in self._collectors):
                return fn
            self._collectors.append((key, fn))
        return fn

    def unregister_collector(self, name: str):
        with self._lock:
            self._collectors = [(k, f) for k, f in self._collectors
                                if k != name]

    def collect(self) -> List[_Family]:
        """Run collectors (a broken one is skipped, never fatal) and
        return families in registration order."""
        with self._lock:
            collectors = list(self._collectors)
            families = list(self._families.values())
        for _, fn in collectors:
            try:
                fn(self)
            except Exception:  # noqa: BLE001 - scrape must survive any
                pass           # single broken probe
        return families


_default_lock = threading.Lock()
_default: Optional[MetricRegistry] = None


def default_registry() -> MetricRegistry:
    """The process-wide registry every built-in subsystem reports into
    (framework.monitor, serving, training, JAX runtime probes)."""
    global _default
    with _default_lock:
        if _default is None:
            _default = MetricRegistry()
        return _default
