"""Runtime instrumentation feeding the registry: JAX compile events,
live device memory, and profiler-span mirroring.

Everything here is install-on-demand and idempotent — importing this
module touches nothing heavy; ``install_all()`` (run by
``start_telemetry_server``) wires the probes:

- ``install_jax_monitoring``: a ``jax.monitoring`` listener pair
  counting runtime events (``paddle_jax_events_total{event=}``) and
  timing the durated ones — compilation first among them —
  (``paddle_jax_event_duration_seconds{event=}``), the scrapeable
  version of "how often and how long are we compiling";
- ``install_device_memory_collector``: a scrape-time collector setting
  ``paddle_device_memory_bytes{device=,stat=}`` from PJRT
  ``memory_stats()`` where the runtime exposes it, falling back to the
  live ``jax.Array`` set (framework.memory's estimator) on backends
  that don't (CPU);
- ``mirror_profiler_spans``: hooks the profiler's RecordEvent sink so
  every host span ALSO lands in
  ``paddle_profiler_span_ms{span=}`` — span timing in chrome traces and
  scraped histograms then agree by construction;
- ``install_build_info``: the ``paddle_build_info`` info-gauge
  (package/jax/jaxlib versions, backend, python as labels on a
  constant 1) so every scraped record is attributable to the exact
  build that produced it.
"""
from __future__ import annotations

from typing import Optional

from .registry import MetricRegistry, default_registry

__all__ = [
    "install_jax_monitoring", "install_device_memory_collector",
    "mirror_profiler_spans", "install_build_info", "install_all",
]

_jax_monitoring_installed = False


def install_jax_monitoring(registry: Optional[MetricRegistry] = None
                           ) -> bool:
    """Register jax.monitoring listeners (once per process). Returns
    True when listeners are live, False when this jax build has no
    monitoring hooks."""
    global _jax_monitoring_installed
    if _jax_monitoring_installed:
        return True
    reg = registry or default_registry()
    try:
        from jax import monitoring
    except Exception:  # noqa: BLE001 - no monitoring in this jax build
        return False
    events = reg.counter(
        "paddle_jax_events_total",
        "jax.monitoring events by name (compilation cache activity, "
        "backend init, ...)", ("event",))
    durations = reg.histogram(
        "paddle_jax_event_duration_seconds",
        "durations of timed jax.monitoring events (jit compile/trace "
        "time lives here)", ("event",),
        buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0,
                 30.0, 60.0, 120.0))

    def _on_event(name, **kw):
        try:
            events.labels(event=str(name)).inc()
        except Exception:  # noqa: BLE001
            pass

    def _on_duration(name, secs, **kw):
        try:
            events.labels(event=str(name)).inc()
            durations.labels(event=str(name)).observe(float(secs))
            # compile time is badput: feed the goodput ledger (the
            # ledger's frame accounting subtracts it from any
            # enclosing step frame, so nothing double-counts)
            if "compil" in str(name).lower():
                from .goodput import default_ledger
                default_ledger().record("compile", float(secs))
        except Exception:  # noqa: BLE001
            pass

    try:
        monitoring.register_event_listener(_on_event)
        monitoring.register_event_duration_secs_listener(_on_duration)
    except Exception:  # noqa: BLE001
        return False
    _jax_monitoring_installed = True
    return True


def _device_label(dev) -> str:
    return f"{getattr(dev, 'platform', 'unknown')}:{getattr(dev, 'id', 0)}"


def install_device_memory_collector(
        registry: Optional[MetricRegistry] = None) -> bool:
    """Scrape-time gauge of live device memory per device. PJRT stats
    where available; the framework.memory live-array estimator (exact
    current usage, observed peak) on backends without them."""
    reg = registry or default_registry()
    gauge = reg.gauge(
        "paddle_device_memory_bytes",
        "device memory by device and stat (bytes_in_use / "
        "peak_bytes_in_use; live-array estimate on backends without "
        "PJRT memory_stats)", ("device", "stat"))

    def _collect(_reg):
        import jax

        from ..framework import memory as fmem
        for dev in jax.devices():
            label = _device_label(dev)
            stats = dev.memory_stats() if hasattr(dev, "memory_stats") \
                else None
            if stats:
                for key in ("bytes_in_use", "peak_bytes_in_use",
                            "bytes_limit"):
                    if key in stats:
                        gauge.labels(device=label, stat=key).set(
                            int(stats[key]))
            else:
                cur = fmem._live_bytes(dev)
                gauge.labels(device=label, stat="bytes_in_use").set(cur)
                peak = gauge.labels(device=label,
                                    stat="peak_bytes_in_use")
                peak.set(max(int(peak.value or 0), cur))

    reg.register_collector(_collect, name="device_memory")
    return True


_span_histogram = None


def mirror_profiler_spans(enable: bool = True,
                          registry: Optional[MetricRegistry] = None
                          ) -> bool:
    """Route every profiler ``RecordEvent`` span duration into
    ``paddle_profiler_span_ms{span=}`` so chrome-trace spans and scraped
    metrics report the same timings. Spans mirror regardless of whether
    a profiler session is recording — the sink is the registry, not the
    tracer."""
    global _span_histogram
    from .. import profiler
    if not enable:
        profiler.set_span_sink(None)
        return False
    reg = registry or default_registry()
    _span_histogram = reg.histogram(
        "paddle_profiler_span_ms",
        "host-tracer RecordEvent span durations (serving::assemble, "
        "serving::dispatch, user spans, ...)", ("span",))

    def _sink(name, dur_ms):
        try:
            _span_histogram.labels(span=str(name)).observe(dur_ms)
        except Exception:  # noqa: BLE001
            pass

    profiler.set_span_sink(_sink)
    return True


def install_build_info(registry: Optional[MetricRegistry] = None):
    """``paddle_build_info`` info-gauge (value 1; the labels carry the
    payload): package version, jax/jaxlib versions, backend, python.
    Scraped records from different hosts/rounds become attributable —
    the PERF.md r04/r05 wedged-round confusion was partly scrape
    provenance nobody could reconstruct after the fact."""
    import platform

    reg = registry or default_registry()
    labels = {"version": "unknown", "jax": "unknown",
              "jaxlib": "unknown", "backend": "unknown",
              "python": platform.python_version()}
    try:
        from .. import __version__
        labels["version"] = str(__version__)
    except Exception:  # noqa: BLE001 - partial info beats no info
        pass
    try:
        import jax
        labels["jax"] = str(jax.__version__)
        labels["backend"] = str(jax.default_backend())
    except Exception:  # noqa: BLE001
        pass
    try:
        import jaxlib
        labels["jaxlib"] = str(getattr(jaxlib, "__version__", "unknown"))
    except Exception:  # noqa: BLE001
        pass
    gauge = reg.gauge(
        "paddle_build_info",
        "build/runtime identity of this process (value 1; version, "
        "jax, jaxlib, backend, python ride the labels)",
        ("version", "jax", "jaxlib", "backend", "python"))
    gauge.clear()  # one identity per process: never two live children
    gauge.labels(**labels).set(1)
    return labels


def install_all(registry: Optional[MetricRegistry] = None):
    """Everything a telemetry endpoint should carry by default.
    Profiler-span mirroring is opt-in via FLAGS_profiler_span_metrics
    (every RecordEvent takes the histogram path once enabled)."""
    install_jax_monitoring(registry)
    install_device_memory_collector(registry)
    install_build_info(registry)
    try:
        from ..framework.flags import flag_value
        if flag_value("FLAGS_profiler_span_metrics"):
            mirror_profiler_spans(True, registry)
    except Exception:  # noqa: BLE001
        pass
