"""Goodput ledger — wall-clock accounting of a training run.

MegaScale (Jiang et al., 2024) makes the case that what keeps 10k-chip
training operable is not a profiler trace but an *accounting identity*:
every second of wall-clock is either productive step time or a typed
category of badput, and the categories must sum to elapsed time. This
module is that accountant for one process:

- **Categories** (``CATEGORIES``): ``step`` (productive device step
  time), ``compile`` (jit trace + XLA compile, fed by the
  ``install_jax_monitoring`` duration listeners and the compile-cache
  miss path), ``ckpt_save`` / ``ckpt_restore`` (the synchronous part of
  ``CheckpointManager`` saves — the async writer is off the critical
  path and deliberately NOT badput — and restores), ``data_stall``
  (input-pipeline waits, fed by ``TrainingTelemetryCallback``'s
  inter-batch gap), ``recovery`` (steps re-run after a preemption
  restore, armed by ``CheckpointManager.restore_latest``'s steps-lost
  witness), and derived ``idle`` (elapsed minus everything attributed).

- **Frames, not raw adds**: attribution nests. A ``timed("step")``
  frame that contains a compile event (the jax listener fires inside
  the first step) records only ``elapsed - claimed`` to its own
  category — the compile seconds land in ``compile``, the remainder in
  ``step``, and the identity holds with no double counting. Frames are
  per-thread; cross-thread recordings (the jax listener thread) fall
  back to plain adds.

- **Exposure**: ``paddle_goodput_seconds_total{category=}`` counters
  (idle synced monotonically at scrape/report time so the scraped
  categories also sum to elapsed), a ``paddle_goodput_fraction``
  gauge, ``report()`` (the ``/goodputz`` JSON, with an ``accounting``
  block asserting the sum-to-elapsed identity within
  ``FLAGS_goodput_tolerance``), and ``goodputz_payload()`` which adds
  the continuous step profiler's summary.

Time is injected (``now=``) so the accounting identity is testable
with a deterministic clock, like every window in this package.
"""
from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Callable, Optional

from .registry import MetricRegistry, default_registry

__all__ = [
    "GoodputLedger", "default_ledger", "set_default_ledger",
    "record", "timed", "goodput_report", "goodputz_payload",
    "CATEGORIES",
]

# "idle" is derived (elapsed - attributed), never recorded directly.
CATEGORIES = ("step", "compile", "ckpt_save", "ckpt_restore",
              "data_stall", "recovery")
IDLE = "idle"


def _tolerance() -> float:
    try:
        from ..framework.flags import flag_value
        return float(flag_value("FLAGS_goodput_tolerance"))
    except Exception:  # noqa: BLE001 - flags may not be registered yet
        return 0.02


class _Frame:
    """One open attribution interval on a thread's frame stack."""

    __slots__ = ("category", "t0", "claimed")

    def __init__(self, category: str, t0: float):
        self.category = category
        self.t0 = t0
        self.claimed = 0.0


class GoodputLedger:
    """Process-wide wall-clock accountant. Thread-safe; the frame
    stack is thread-local so concurrent recorders (serving threads,
    the checkpoint writer) attribute independently."""

    def __init__(self, registry: Optional[MetricRegistry] = None,
                 now: Callable[[], float] = time.monotonic):
        self._now = now
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._t0: Optional[float] = None
        self._acc = {c: 0.0 for c in CATEGORIES}
        self._idle_exported = 0.0
        self._replay_steps = 0
        reg = registry or default_registry()
        self._c_seconds = reg.counter(
            "paddle_goodput_seconds_total",
            "wall-clock seconds attributed per goodput category "
            "(step = productive; the rest are typed badput; idle is "
            "synced so scraped categories sum to elapsed)",
            ("category",))
        self._g_fraction = reg.gauge(
            "paddle_goodput_fraction",
            "productive (step) fraction of elapsed wall-clock since "
            "the ledger started")
        # label children cached: the step frame close is on the hot path
        self._children = {c: self._c_seconds.labels(category=c)
                          for c in CATEGORIES + (IDLE,)}
        reg.register_collector(self._collect, name="goodput_ledger")

    # ------------------------------------------------------- lifecycle
    def start(self, t: Optional[float] = None) -> "GoodputLedger":
        """Mark the run start. Idempotent; the first recording
        auto-starts the clock if this was never called."""
        with self._lock:
            if self._t0 is None:
                self._t0 = self._now() if t is None else float(t)
        return self

    def reset(self):
        with self._lock:
            self._t0 = None
            self._acc = {c: 0.0 for c in CATEGORIES}
            self._idle_exported = 0.0
            self._replay_steps = 0

    @property
    def started(self) -> bool:
        with self._lock:
            return self._t0 is not None

    # ------------------------------------------------------- recording
    def record(self, category: str, seconds: float):
        """Attribute ``seconds`` to ``category``. Inside an open frame
        on this thread the seconds are also *claimed* from that frame,
        so the frame's own category gets only the unclaimed remainder
        — the no-double-count rule."""
        if category not in self._acc:
            raise ValueError(
                f"unknown goodput category {category!r} "
                f"(have {CATEGORIES})")
        seconds = max(0.0, float(seconds))
        self.start()
        with self._lock:
            self._acc[category] += seconds
        self._children[category].inc(seconds)
        stack = getattr(self._tls, "stack", None)
        if stack:
            stack[-1].claimed += seconds

    def begin(self, category: str) -> None:
        """Open an attribution frame on this thread (pair with
        ``end()``; ``timed()`` is the context-manager form)."""
        self.start()
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        stack.append(_Frame(category, self._now()))

    def end(self) -> float:
        """Close the innermost frame: its category receives the
        frame's elapsed minus whatever nested recordings claimed; the
        full elapsed propagates to the parent frame's claim. Returns
        the frame's wall elapsed seconds."""
        stack = getattr(self._tls, "stack", None)
        if not stack:
            return 0.0
        frame = stack.pop()
        elapsed = max(0.0, self._now() - frame.t0)
        own = max(0.0, elapsed - frame.claimed)
        category = frame.category
        if category == "step":
            category = self._consume_replay() or category
        with self._lock:
            self._acc[category] += own
        self._children[category].inc(own)
        if stack:
            stack[-1].claimed += elapsed
        return elapsed

    @contextmanager
    def timed(self, category: str):
        self.begin(category)
        try:
            yield self
        finally:
            self.end()

    # ------------------------------------------------------- recovery
    def arm_replay(self, n_steps: int):
        """Restore path: the next ``n_steps`` step frames are re-runs
        of work lost to the preemption — they land in ``recovery``,
        not ``step`` (MegaScale's replay badput)."""
        with self._lock:
            self._replay_steps += max(0, int(n_steps))

    def _consume_replay(self) -> Optional[str]:
        with self._lock:
            if self._replay_steps > 0:
                self._replay_steps -= 1
                return "recovery"
        return None

    # ------------------------------------------------------- reporting
    def report(self, tolerance: Optional[float] = None) -> dict:
        """The ``/goodputz`` accounting document. Categories (plus
        derived idle) sum to elapsed wall-clock; ``accounting.closes``
        asserts it within ``tolerance`` (attribution can only overrun
        elapsed via overlapping recorders — concurrent threads each
        claiming wall time — which the report surfaces rather than
        hides)."""
        tol = _tolerance() if tolerance is None else float(tolerance)
        with self._lock:
            t0 = self._t0
            acc = dict(self._acc)
        elapsed = max(0.0, self._now() - t0) if t0 is not None else 0.0
        attributed = sum(acc.values())
        idle = max(0.0, elapsed - attributed)
        overlap = max(0.0, attributed - elapsed)
        categories = {c: round(v, 6) for c, v in acc.items()}
        categories[IDLE] = round(idle, 6)
        total = attributed + idle
        err = abs(total - elapsed) / elapsed if elapsed > 0 else 0.0
        goodput = acc["step"] / elapsed if elapsed > 0 else 0.0
        self._sync_idle(idle)
        self._g_fraction.set(goodput)
        return {
            "started": t0 is not None,
            "elapsed_s": round(elapsed, 6),
            "categories_s": categories,
            "goodput_fraction": round(goodput, 6),
            "badput_fraction": round(
                (attributed - acc["step"]) / elapsed
                if elapsed > 0 else 0.0, 6),
            "replay_steps_pending": self._replay_steps,
            "accounting": {
                "sum_s": round(total, 6),
                "error_fraction": round(err, 6),
                "overlap_s": round(overlap, 6),
                "tolerance": tol,
                "closes": err <= tol,
            },
        }

    def _sync_idle(self, idle: float):
        """Keep the exported idle counter monotone and equal to the
        derived idle, so a scrape's categories also sum to elapsed."""
        with self._lock:
            delta = idle - self._idle_exported
            if delta <= 0:
                return
            self._idle_exported = idle
        self._children[IDLE].inc(delta)

    def _collect(self, _reg):
        """Scrape-time collector: refresh the fraction gauge and the
        idle counter just before exposition."""
        if self.started:
            self.report()


# ------------------------------------------------------------- default
_default_lock = threading.Lock()
_default: Optional[GoodputLedger] = None


def default_ledger() -> GoodputLedger:
    """The process-wide ledger every built-in recorder reports into
    (TrainStep, the fit telemetry callback, CheckpointManager, the
    compile-cache miss path, the jax compile listeners)."""
    global _default
    with _default_lock:
        if _default is None:
            _default = GoodputLedger()
        return _default


def set_default_ledger(ledger: Optional[GoodputLedger]
                       ) -> Optional[GoodputLedger]:
    """Swap the process-wide ledger (tests; ``None`` resets to a fresh
    one on next use). Returns the previous ledger."""
    global _default
    with _default_lock:
        prev, _default = _default, ledger
    return prev


def record(category: str, seconds: float):
    """Module-level convenience onto the default ledger."""
    default_ledger().record(category, seconds)


@contextmanager
def timed(category: str):
    with default_ledger().timed(category):
        yield


def goodput_report(tolerance: Optional[float] = None) -> dict:
    return default_ledger().report(tolerance=tolerance)


def goodputz_payload() -> dict:
    """The ``/goodputz`` document: the accounting report plus the
    continuous step profiler's live summary."""
    from . import stepprof
    return {
        "goodput": goodput_report(),
        "steps": stepprof.default_profiler().summary(),
    }
