"""Numerics & silent-data-corruption observability.

Every other layer in this package watches *performance*; this one
watches *correctness* — the reference framework's nan/inf debugger
(``FLAGS_check_nan_inf``, /root/reference/paddle/fluid/framework/details/
nan_inf_utils_detail.cc) rebuilt for a fleet where the dominant wrong-
answer sources are fused Pallas kernels, int8 KV quantization, and
per-chip silent data corruption:

- **NaN/Inf tripwires** — fixed-shape on-device reductions over
  TrainStep grads and CachedDecoder dispatch logits (finite fraction,
  max-abs, argmax-entropy collapse, grad norm + EWMA drift, loss
  scale). Host publication is deferred ONE step: each note enqueues
  its device scalars and publishes the previous entry's, so the hot
  path never gains a device sync. ``FLAGS_check_nan_inf`` arms every
  step; ``FLAGS_numerics_sample_rate`` gives a sampled regime.
- **Sampled shadow-verification** — a low-duty-cycle re-execution of
  decode/chunked/verify dispatches through the pure-JAX oracle
  (``use_pallas=False``), publishing max-abs logit divergence as
  ``paddle_numerics_shadow_divergence{kind,dtype}``. Published as a
  GAUGE family plus host-side ``PercentileWindow`` percentiles in the
  /numericsz payload: metric_discipline's MD003 unit contract reserves
  histogram names for ``_ms``/``_bytes``/``_seconds`` quantities, and
  a unitless logit delta is none of those.
- **Device canary sweeps** — a deterministic uint32 LCG/xorshift
  checksum workload with a bit-exact numpy golden twin, run per worker
  on ``FLAGS_numerics_canary_period_s`` and on readiness transitions.
  A mismatch is per-chip SDC: the replica is quarantined (readiness
  flip + breaker open) by ``fleet.worker.arm_canary`` rather than
  silently serving garbage.

Anomalies (non-finite outputs, shadow blow-ups, canary failures) are
emitted as tail-promoted error spans into the trace flight recorder
and handed to ``xstats.on_anomaly`` — the existing arm-gated,
rate-limited path that spawns exactly one background ``/profilez``
capture per episode, tagged with the promoted trace id.

Everything here is garnish on hot paths: every note swallows its own
exceptions, and with all three knobs at their 0.0 defaults every hook
is a cheap no-op.
"""
from __future__ import annotations

import collections
import math
import threading
import time
from typing import Callable, Dict, Optional, Tuple

import numpy as np

__all__ = [
    "SHADOW_SITES", "CanaryRunner",
    "enabled", "tripwire_rate", "shadow_rate", "train_tripwire_armed",
    "sample_decision", "set_rng_for_tests", "reset_for_tests", "drain",
    "note_serving_logits", "note_train_step", "note_shadow_divergence",
    "note_int8_scales",
    "canary_reference", "run_device_canary",
    "numericsz_payload",
]

# dispatch sites eligible for oracle shadow-verification (prefill is
# excluded: its cost dwarfs a decode step and the chunked path covers
# the same kernel)
SHADOW_SITES = ("generate_decode", "generate_chunked", "generate_verify")

_EWMA_ALPHA = 0.1        # grad-norm drift smoothing
_PENDING_MAX = 64        # deferred-publication queue bound
_SHADOW_WINDOW = 512     # divergence percentile window per (kind, dtype)

_CANARY_N = 4096
_CANARY_ROUNDS = 4
_CANARY_MASK = (1 << 32) - 1


# ----------------------------------------------------------- knobs
def _flag(name: str, default):
    try:
        from ..framework.flags import flag_value
        return flag_value(name)
    except KeyError:
        return default


def tripwire_rate() -> float:
    """Effective tripwire duty cycle: ``FLAGS_check_nan_inf`` arms
    every step (the reference debugger's contract), otherwise
    ``FLAGS_numerics_sample_rate`` gives the cheap sampled regime."""
    if bool(_flag("FLAGS_check_nan_inf", False)):
        return 1.0
    try:
        rate = float(_flag("FLAGS_numerics_sample_rate", 0.0))
    except (TypeError, ValueError):
        return 0.0
    return max(0.0, min(1.0, rate))


def shadow_rate() -> float:
    try:
        rate = float(_flag("FLAGS_numerics_shadow_rate", 0.0))
    except (TypeError, ValueError):
        return 0.0
    return max(0.0, min(1.0, rate))


def enabled() -> bool:
    return tripwire_rate() > 0.0 or shadow_rate() > 0.0


def train_tripwire_armed() -> bool:
    """Whether TrainStep should fuse the grad-health reductions into
    its compiled step. Pinned at TrainStep construction (arming
    mid-lifetime would change the compiled program — same contract as
    CachedDecoder's use_pallas pin)."""
    return tripwire_rate() > 0.0


# ------------------------------------------------------------- rng
_RNG_LOCK = threading.Lock()
_rng = None


def set_rng_for_tests(rng) -> None:
    """Swap the sampling RNG (tests inject a seeded ``random.Random``
    so duty-cycle decisions are reproducible); None restores the
    default."""
    global _rng
    with _RNG_LOCK:
        _rng = rng


def sample_decision(rate: float) -> bool:
    """One Bernoulli draw against ``rate`` from the module RNG."""
    if rate <= 0.0:
        return False
    if rate >= 1.0:
        return True
    global _rng
    with _RNG_LOCK:
        if _rng is None:
            import random
            _rng = random.Random(0x9E3779B9)
        return _rng.random() < rate


# --------------------------------------------------------- metrics
_METRICS_LOCK = threading.Lock()
_metrics = None


class _Metrics:
    """Lazy singleton over the default registry (families are
    get-or-create, so re-instantiation after ``reset_for_tests`` is
    idempotent)."""

    def __init__(self):
        from .registry import default_registry
        reg = default_registry()
        self.checks = reg.counter(
            "paddle_numerics_checks_total",
            "tripwire health checks published, by dispatch kind",
            ("kind",))
        self.anomalies = reg.counter(
            "paddle_numerics_anomalies_total",
            "numerics anomalies (non-finite outputs, shadow blow-ups, "
            "canary failures) by kind and reason", ("kind", "reason"))
        self.shadow_checks = reg.counter(
            "paddle_numerics_shadow_checks_total",
            "sampled oracle shadow re-executions", ("kind", "dtype"))
        self.canary_runs = reg.counter(
            "paddle_numerics_canary_runs_total",
            "device canary sweeps run")
        self.canary_failures = reg.counter(
            "paddle_numerics_canary_failures_total",
            "device canary sweeps whose checksum mismatched (SDC)")
        self.finite_fraction = reg.gauge(
            "paddle_numerics_finite_fraction",
            "fraction of finite values in the last checked output",
            ("kind",))
        self.max_abs = reg.gauge(
            "paddle_numerics_logit_max_abs",
            "max |logit| of the last checked output (finite values "
            "only)", ("kind",))
        self.argmax_entropy = reg.gauge(
            "paddle_numerics_argmax_entropy",
            "entropy of the batch argmax-id distribution — a collapse "
            "to 0 on a busy batch means every lane argmaxes the same "
            "token", ("kind",))
        self.grad_norm = reg.gauge(
            "paddle_numerics_grad_norm",
            "global grad norm of the last checked train step")
        self.grad_norm_drift = reg.gauge(
            "paddle_numerics_grad_norm_drift",
            "relative deviation of the last grad norm from its EWMA")
        self.loss_scale = reg.gauge(
            "paddle_numerics_loss_scale",
            "live dynamic loss scale of the fused AMP step")
        self.shadow_divergence = reg.gauge(
            "paddle_numerics_shadow_divergence",
            "max-abs logit divergence of the last shadow-verified "
            "dispatch vs the pure-JAX oracle (unitless logit delta — "
            "gauge + payload percentiles, not a histogram, per the "
            "MD003 unit contract)", ("kind", "dtype"))
        self.int8_scale_drift = reg.gauge(
            "paddle_numerics_int8_scale_drift",
            "relative drift of the int8 KV absmax-scale magnitude vs "
            "its first-seen baseline", ("kind",))
        self.canary_ok = reg.gauge(
            "paddle_numerics_canary_ok",
            "1 while the latest canary sweep matched its golden "
            "checksum, 0 after a mismatch")


def _get_metrics() -> _Metrics:
    global _metrics
    with _METRICS_LOCK:
        if _metrics is None:
            _metrics = _Metrics()
        return _metrics


# ------------------------------------------------- jitted reducers
_FNS_LOCK = threading.Lock()
_jit_fns: Dict[str, object] = {}
_canary_ref_memo = None


def _logit_stats_fn():
    """Jitted [finite_fraction, max_abs, argmax_entropy] reduction
    over a logits array ([B, vocab] or [B, S, vocab]); fixed output
    shape (3,) so every call reuses one executable per input shape."""
    with _FNS_LOCK:
        fn = _jit_fns.get("logit_stats")
        if fn is None:
            import jax
            import jax.numpy as jnp

            def stats(logits):
                x = logits.astype(jnp.float32)
                finite = jnp.isfinite(x)
                frac = jnp.mean(finite.astype(jnp.float32))
                safe = jnp.where(finite, x, 0.0)
                max_abs = jnp.max(jnp.abs(safe))
                flat = safe.reshape(-1, safe.shape[-1])
                am = jnp.argmax(flat, axis=-1)
                counts = jnp.zeros(
                    (safe.shape[-1],), jnp.float32).at[am].add(1.0)
                p = counts / jnp.maximum(jnp.sum(counts), 1.0)
                ent = -jnp.sum(jnp.where(p > 0.0, p * jnp.log(p), 0.0))
                return jnp.stack([frac, max_abs, ent])

            fn = jax.jit(stats)
            _jit_fns["logit_stats"] = fn
        return fn


def _scale_summary_fn(n: int):
    """Jitted mean of per-leaf mean-|scale| over ``n`` scale planes."""
    key = f"int8_scales:{n}"
    with _FNS_LOCK:
        fn = _jit_fns.get(key)
        if fn is None:
            import jax
            import jax.numpy as jnp

            def summ(*xs):
                acc = None
                for x in xs:
                    m = jnp.mean(jnp.abs(x.astype(jnp.float32)))
                    acc = m if acc is None else acc + m
                return acc / float(len(xs))

            fn = jax.jit(summ)
            _jit_fns[key] = fn
        return fn


def _canary_fn():
    with _FNS_LOCK:
        fn = _jit_fns.get("canary")
        if fn is None:
            import jax
            import jax.numpy as jnp

            def sweep():
                x = jnp.arange(_CANARY_N, dtype=jnp.uint32)
                for _ in range(_CANARY_ROUNDS):
                    x = x * jnp.uint32(1664525) + jnp.uint32(1013904223)
                    x = x ^ (x >> 16)
                return jnp.sum(x)   # wrapping uint32 sum

            fn = jax.jit(sweep)
            _jit_fns["canary"] = fn
        return fn


def canary_reference() -> int:
    """Host golden twin of the device canary: the same uint32
    LCG+xorshift rounds in numpy (integer arrays wrap modularly), with
    the wrapping device sum emulated as a uint64 sum mod 2^32."""
    global _canary_ref_memo
    with _FNS_LOCK:
        if _canary_ref_memo is None:
            x = np.arange(_CANARY_N, dtype=np.uint32)
            for _ in range(_CANARY_ROUNDS):
                x = x * np.uint32(1664525) + np.uint32(1013904223)
                x = x ^ (x >> np.uint32(16))
            _canary_ref_memo = int(x.astype(np.uint64).sum()) & _CANARY_MASK
        return _canary_ref_memo


# ----------------------------------------------------- state store
class _State:
    """All host-side numerics bookkeeping behind one lock. Device
    scalars live in ``_pending`` until the NEXT note (or a drain)
    publishes them — by then their computation has long completed, so
    the read never stalls the step that produced them."""

    def __init__(self):
        from .registry import PercentileWindow
        self._window_cls = PercentileWindow
        self._lock = threading.Lock()
        self._pending = collections.deque(maxlen=_PENDING_MAX)
        self._serving: Dict[str, dict] = {}
        self._train = {"steps": 0, "grad_norm": None,
                       "grad_norm_ewma": None, "grad_norm_drift": None,
                       "grad_finite_fraction": None, "loss_finite": None,
                       "loss_scale": None}
        self._shadow: Dict[Tuple[str, str], dict] = {}
        self._int8: Dict[str, dict] = {}
        self._canary = {"runs": 0, "failures": 0, "ok": None,
                        "corrupt": False, "last": None}
        self._anomalies = {"total": 0, "by_reason": {}, "last": None}

    # -- deferred-publication queue
    def push(self, entry: dict):
        with self._lock:
            self._pending.append(entry)
            out = []
            while len(self._pending) > 1:
                out.append(self._pending.popleft())
            return out

    def pop_all(self):
        with self._lock:
            out = list(self._pending)
            self._pending.clear()
            return out

    # -- per-domain updates (values are already host floats here)
    def serving_update(self, kind, frac, max_abs, ent):
        with self._lock:
            rec = self._serving.setdefault(kind,
                                           {"checks": 0, "anomalies": 0})
            rec["checks"] += 1
            rec["finite_fraction"] = frac
            rec["max_abs"] = max_abs
            rec["argmax_entropy"] = ent
            rec["unix_ms"] = int(time.time() * 1e3)

    def serving_anomaly(self, kind):
        with self._lock:
            rec = self._serving.setdefault(kind,
                                           {"checks": 0, "anomalies": 0})
            rec["anomalies"] += 1

    def train_update(self, norm, frac, loss_finite, scale):
        with self._lock:
            t = self._train
            t["steps"] += 1
            t["grad_norm"] = norm
            t["grad_finite_fraction"] = frac
            t["loss_finite"] = bool(loss_finite >= 1.0)
            if scale is not None:
                t["loss_scale"] = scale
            drift = t["grad_norm_drift"]
            if math.isfinite(norm):
                ewma = t["grad_norm_ewma"]
                if ewma is None:
                    drift = 0.0
                    ewma = norm
                else:
                    drift = abs(norm - ewma) / max(abs(ewma), 1e-12)
                    ewma = (1.0 - _EWMA_ALPHA) * ewma \
                        + _EWMA_ALPHA * norm
                t["grad_norm_ewma"] = ewma
            t["grad_norm_drift"] = drift
            return drift if drift is not None else 0.0

    def shadow_update(self, kind, dtype, val):
        with self._lock:
            rec = self._shadow.get((kind, dtype))
            if rec is None:
                rec = {"count": 0, "last": 0.0, "max": 0.0,
                       "window": self._window_cls(maxlen=_SHADOW_WINDOW)}
                self._shadow[(kind, dtype)] = rec
            rec["count"] += 1
            rec["last"] = val
            if math.isfinite(val):
                rec["max"] = max(rec["max"], val)
                rec["window"].observe(val)

    def int8_update(self, kind, val):
        with self._lock:
            rec = self._int8.get(kind)
            if rec is None:
                rec = {"baseline": val, "last": val, "drift": 0.0,
                       "notes": 0}
                self._int8[kind] = rec
            rec["notes"] += 1
            rec["last"] = val
            base = rec["baseline"]
            rec["drift"] = abs(val - base) / max(abs(base), 1e-12)
            return rec["drift"]

    def canary_begin(self, ok: bool) -> bool:
        """Counter + sticky-corrupt update; True when this failure
        opens a NEW corruption episode (anomaly + quarantine fire once
        per episode, not per sweep)."""
        with self._lock:
            c = self._canary
            c["runs"] += 1
            newly = (not ok) and not c["corrupt"]
            if not ok:
                c["failures"] += 1
            c["ok"] = ok
            c["corrupt"] = not ok
            return newly

    def canary_finish(self, res: dict):
        with self._lock:
            self._canary["last"] = dict(res)

    def record_anomaly(self, kind, reason, trace_id, detail):
        with self._lock:
            a = self._anomalies
            a["total"] += 1
            a["by_reason"][reason] = a["by_reason"].get(reason, 0) + 1
            a["last"] = {"kind": kind, "reason": reason,
                         "trace_id": trace_id,
                         "unix_ms": int(time.time() * 1e3),
                         "detail": detail}

    def payload(self) -> dict:
        with self._lock:
            shadow = {}
            for (kind, dtype), rec in self._shadow.items():
                snap = rec["window"].snapshot((50, 95, 99))
                shadow[f"{kind}/{dtype}"] = {
                    "count": rec["count"], "last": rec["last"],
                    "max": rec["max"], "p50": snap["p50"],
                    "p95": snap["p95"], "p99": snap["p99"]}
            return {
                "serving": {k: dict(v) for k, v in self._serving.items()},
                "train": dict(self._train),
                "shadow": shadow,
                "int8": {k: dict(v) for k, v in self._int8.items()},
                "canary": dict(self._canary),
                "anomalies": {"total": self._anomalies["total"],
                              "by_reason": dict(
                                  self._anomalies["by_reason"]),
                              "last": self._anomalies["last"]},
                "pending": len(self._pending),
            }


_STATE_LOCK = threading.Lock()
_state_obj: Optional[_State] = None


def _state() -> _State:
    global _state_obj
    with _STATE_LOCK:
        if _state_obj is None:
            _state_obj = _State()
        return _state_obj


def reset_for_tests() -> None:
    """Fresh state store + default RNG (metric families persist —
    registration is get-or-create)."""
    global _state_obj
    with _STATE_LOCK:
        _state_obj = _State()
    set_rng_for_tests(None)


# ------------------------------------------------------- anomalies
def _emit_anomaly(kind: str, reason: str,
                  detail: Optional[dict] = None) -> Optional[str]:
    """One numerics anomaly: counter, tail-promoted error span in the
    trace flight recorder, and the xstats anomaly hook (arm-gated +
    rate-limited there, so a NaN storm spawns exactly one /profilez
    capture). Returns the promoted trace id (None if tracing is
    unavailable)."""
    detail = {k: v for k, v in (detail or {}).items()
              if isinstance(v, (str, int, float, bool)) or v is None}
    try:
        _get_metrics().anomalies.labels(kind=kind, reason=reason).inc()
    except Exception:  # noqa: BLE001 - observability is garnish
        pass
    trace_id = None
    try:
        from . import tracing
        ctx = tracing.new_context(sampled=True)
        attrs = {"kind": kind, "reason": reason}
        attrs.update(detail)
        tracing.record_span(
            ctx, f"numerics::{reason}", stage="numerics",
            start_unix_ns=time.time_ns(), duration_ms=0.0,
            attrs=attrs, status="error", root=True)
        trace_id = ctx.trace_id
    except Exception:  # noqa: BLE001
        pass
    try:
        from . import xstats
        env = {"source": "numerics", "kind": kind, "reason": reason}
        env.update(detail)
        xstats.on_anomaly(env, trace_id)
    except Exception:  # noqa: BLE001
        pass
    _state().record_anomaly(kind, reason, trace_id, detail)
    return trace_id


# ------------------------------------------------- publication path
def _publish(entry: dict) -> None:
    """Host-side publication of one queued entry (its device scalars
    are from a PREVIOUS step and long since materialized)."""
    try:
        t = entry["type"]
        if t == "serving":
            _publish_serving(entry)
        elif t == "train":
            _publish_train(entry)
        elif t == "shadow":
            _publish_shadow(entry)
        elif t == "int8":
            _publish_int8(entry)
    except Exception:  # noqa: BLE001 - a broken scalar must never
        pass           # take down the path that enqueued it


def _publish_serving(entry: dict) -> None:
    kind = entry["kind"]
    vals = np.asarray(entry["stats"], np.float64).reshape(-1)
    frac, max_abs, ent = float(vals[0]), float(vals[1]), float(vals[2])
    m = _get_metrics()
    m.finite_fraction.labels(kind=kind).set(frac)
    m.max_abs.labels(kind=kind).set(max_abs)
    m.argmax_entropy.labels(kind=kind).set(ent)
    _state().serving_update(kind, frac, max_abs, ent)
    if not math.isfinite(frac) or frac < 1.0:
        _state().serving_anomaly(kind)
        _emit_anomaly(kind, "nonfinite",
                      {"finite_fraction": frac, "max_abs": max_abs})


def _publish_train(entry: dict) -> None:
    vals = np.asarray(entry["stats"], np.float64).reshape(-1)
    norm, frac, loss_finite = (float(vals[0]), float(vals[1]),
                               float(vals[2]))
    scale = entry.get("loss_scale")
    if scale is not None:
        scale = float(np.asarray(scale))
    m = _get_metrics()
    if math.isfinite(norm):
        m.grad_norm.set(norm)
    drift = _state().train_update(norm, frac, loss_finite, scale)
    m.grad_norm_drift.set(drift)
    if scale is not None:
        m.loss_scale.set(scale)
    m.finite_fraction.labels(kind="train").set(frac)
    if not math.isfinite(norm) or frac < 1.0 or loss_finite < 1.0:
        _emit_anomaly("train", "nonfinite",
                      {"grad_norm_finite": math.isfinite(norm),
                       "grad_finite_fraction": frac,
                       "loss_finite": bool(loss_finite >= 1.0)})


def _publish_shadow(entry: dict) -> None:
    kind, dtype = entry["kind"], entry["dtype"]
    val = float(np.asarray(entry["stats"]))
    _get_metrics().shadow_divergence.labels(
        kind=kind, dtype=dtype).set(val)
    _state().shadow_update(kind, dtype, val)
    if not math.isfinite(val):
        _emit_anomaly(kind, "shadow_nonfinite", {"dtype": dtype})


def _publish_int8(entry: dict) -> None:
    kind = entry["kind"]
    val = float(np.asarray(entry["stats"]))
    drift = _state().int8_update(kind, val)
    _get_metrics().int8_scale_drift.labels(kind=kind).set(drift)


def _enqueue(entry: dict) -> None:
    for e in _state().push(entry):
        _publish(e)


def drain() -> int:
    """Publish every queued entry now (forces the deferred host reads
    — tests and the /numericsz scrape call this; hot paths never do).
    Returns the number of entries published."""
    entries = _state().pop_all()
    for e in entries:
        _publish(e)
    return len(entries)


# ------------------------------------------------------- note APIs
def note_serving_logits(kind: str, logits) -> None:
    """Queue fixed-shape on-device health stats for one dispatch's
    logits ([B, vocab] or [B, S, vocab]); the host read is deferred
    one note (see ``_State``)."""
    try:
        stats = _logit_stats_fn()(logits)
    except Exception:  # noqa: BLE001 - garnish
        return
    try:
        _get_metrics().checks.labels(kind=kind).inc()
    except Exception:  # noqa: BLE001
        pass
    _enqueue({"type": "serving", "kind": kind, "stats": stats})


def note_train_step(stats, *, loss_scale=None) -> None:
    """Queue one train step's in-graph health vector
    ``[grad_norm, grad_finite_fraction, loss_is_finite]`` (device
    scalars out of the fused step's reserved ``numerics`` output)."""
    try:
        _get_metrics().checks.labels(kind="train").inc()
    except Exception:  # noqa: BLE001
        pass
    _enqueue({"type": "train", "stats": stats, "loss_scale": loss_scale})


def note_shadow_divergence(kind: str, dtype: str, value) -> None:
    """Queue one shadow-verified dispatch's max-abs logit divergence
    vs the pure-JAX oracle; ``dtype`` labels the live KV regime
    (``f32``/``int8``)."""
    try:
        _get_metrics().shadow_checks.labels(kind=kind,
                                            dtype=dtype).inc()
    except Exception:  # noqa: BLE001
        pass
    _enqueue({"type": "shadow", "kind": kind, "dtype": dtype,
              "stats": value})


def note_int8_scales(kind: str, k, v) -> None:
    """Queue the mean |absmax scale| over the float scale planes of an
    int8-quantized KV pool pytree — its drift against the first-seen
    baseline is the live int8-vs-f32 health signal."""
    try:
        import jax
        leaves = [a for a in jax.tree_util.tree_leaves((k, v))
                  if np.issubdtype(np.dtype(a.dtype), np.floating)]
        if not leaves:
            return
        s = _scale_summary_fn(len(leaves))(*leaves)
    except Exception:  # noqa: BLE001 - garnish
        return
    _enqueue({"type": "int8", "kind": kind, "stats": s})


# --------------------------------------------------------- canary
def _record_canary(res: dict) -> None:
    newly = _state().canary_begin(ok=bool(res.get("ok")))
    m = _get_metrics()
    try:
        m.canary_runs.inc()
        m.canary_ok.set(1.0 if res.get("ok") else 0.0)
        if not res.get("ok"):
            m.canary_failures.inc()
    except Exception:  # noqa: BLE001
        pass
    if newly:
        res["trace_id"] = _emit_anomaly(
            "canary", "canary_failure",
            {"name": res.get("name"), "got": res.get("got"),
             "want": res.get("want"), "probe_ok":
                 (res.get("probe") or {}).get("ok")
                 if isinstance(res.get("probe"), dict) else None})
    _state().canary_finish(res)


def run_device_canary(record: bool = True) -> dict:
    """One deterministic checksum sweep on the accelerator, compared
    bit-exactly against the numpy golden twin. A mismatch IS silent
    data corruption on this chip (the workload is integer-only — no
    rounding freedom)."""
    t0 = time.perf_counter()
    got, err = None, None
    try:
        got = int(np.asarray(_canary_fn()())) & _CANARY_MASK
    except Exception as e:  # noqa: BLE001 - a crashed sweep is a
        err = repr(e)       # failure, not an exception to propagate
    want = canary_reference()
    res = {"ok": err is None and got == want, "got": got, "want": want,
           "ms": (time.perf_counter() - t0) * 1e3,
           "unix_ms": int(time.time() * 1e3)}
    if err is not None:
        res["error"] = err
    if record:
        _record_canary(res)
    return res


class CanaryRunner:
    """Per-worker canary sweeps on a period and on not-ready→ready
    transitions.

    ``probe`` (a backend-supplied corruption self-check returning
    ``{"ok": bool, ...}``) replaces the generic device checksum when
    given — a stub backend has no accelerator to checksum but knows
    how to round-trip its own arithmetic; a real backend gets the
    device sweep. ``on_corrupt`` fires once per corruption episode
    (quarantine wiring — readiness flip + breaker open — lives in
    ``fleet.worker.arm_canary``)."""

    def __init__(self, *, name: str = "", period_s: float = 0.0,
                 probe: Optional[Callable[[], dict]] = None,
                 ready_fn: Optional[Callable[[], bool]] = None,
                 on_corrupt: Optional[Callable[[], None]] = None,
                 device: Optional[bool] = None):
        self.name = name
        self.period_s = float(period_s)
        self._probe = probe
        self._ready_fn = ready_fn
        self._on_corrupt = on_corrupt
        self._device = (probe is None) if device is None else bool(device)
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._corrupt = False
        self._fired = False
        self._last: Optional[dict] = None

    @property
    def corrupt(self) -> bool:
        with self._lock:
            return self._corrupt

    @property
    def last(self) -> Optional[dict]:
        with self._lock:
            return self._last

    def run_once(self) -> dict:
        res = (run_device_canary(record=False) if self._device
               else {"ok": True})
        if self._probe is not None:
            try:
                p = self._probe()
            except Exception as e:  # noqa: BLE001 - a crashed probe
                p = {"ok": False, "error": repr(e)}   # is a failure
            res = dict(res)
            res["probe"] = p
            res["ok"] = bool(res.get("ok", True)) and bool(p.get("ok"))
        res.setdefault("unix_ms", int(time.time() * 1e3))
        res["name"] = self.name
        _record_canary(res)
        fire = False
        with self._lock:
            self._last = res
            if not res["ok"]:
                self._corrupt = True
                if not self._fired:
                    self._fired = True
                    fire = True
            else:
                # corruption cleared (e.g. chaos restore) — the NEXT
                # episode must fire on_corrupt again
                self._corrupt = False
                self._fired = False
        if fire and self._on_corrupt is not None:
            try:
                self._on_corrupt()
            except Exception:  # noqa: BLE001 - quarantine wiring must
                pass           # not kill the sweep loop
        return res

    def start(self) -> Optional["CanaryRunner"]:
        if self.period_s <= 0.0:
            return None
        t = threading.Thread(
            target=self._loop, daemon=True,
            name=f"numerics-canary-{self.name or 'worker'}")
        with self._lock:
            self._thread = t
        t.start()
        return self

    def _loop(self):
        next_due = time.monotonic()     # first sweep right away
        last_ready = None
        while not self._stop.is_set():
            ready = None
            if self._ready_fn is not None:
                try:
                    ready = bool(self._ready_fn())
                except Exception:  # noqa: BLE001
                    ready = None
            transition = ready is True and last_ready is False
            if ready is not None:
                last_ready = ready
            if transition or time.monotonic() >= next_due:
                try:
                    self.run_once()
                except Exception:  # noqa: BLE001 - keep sweeping
                    pass
                next_due = time.monotonic() + self.period_s
            self._stop.wait(min(max(self.period_s, 0.01), 0.05))

    def stop(self):
        self._stop.set()
        with self._lock:
            t = self._thread
        if t is not None:
            t.join(timeout=2.0)


# ------------------------------------------------------- /numericsz
def numericsz_payload() -> dict:
    """The /numericsz document: knobs, per-kind serving health, train
    health, shadow-divergence percentiles, int8 drift, canary state,
    and the anomaly ledger (with the last promoted trace id). Scraping
    drains the deferred-publication queue first."""
    drain()
    doc = _state().payload()
    doc["enabled"] = enabled()
    doc["rates"] = {
        "tripwire": tripwire_rate(),
        "shadow": shadow_rate(),
        "check_nan_inf": bool(_flag("FLAGS_check_nan_inf", False)),
        "canary_period_s": float(
            _flag("FLAGS_numerics_canary_period_s", 0.0) or 0.0),
    }
    return doc
