"""paddle_tpu.observability — the unified telemetry layer.

The reference framework ships first-class observability
(platform/monitor.cc's STAT_ADD registry, the HostTracer/CudaTracer
profiler pair); this package is its production-grade TPU-native
counterpart and the ONE place every subsystem reports into:

- ``registry``: typed metric families — ``Counter``, ``Gauge``,
  ``Histogram`` — with Prometheus-style label sets, plus
  ``PercentileWindow``, the bounded-window nearest-rank percentile
  estimator shared with ``serving.metrics``;
- ``exposition``: Prometheus text format 0.0.4 + a JSON mirror;
- ``httpd``: a stdlib ``http.server`` endpoint (``/metrics``,
  ``/healthz`` liveness, ``/readyz`` readiness, ``/statusz``) that
  ``InferenceServer`` attaches via ``FLAGS_serving_telemetry_port``
  and scripts start with ``start_telemetry_server()``;
- ``runtime``: JAX compile-event listeners, device-memory gauges, and
  profiler RecordEvent span mirroring;
- ``tracing``: distributed request tracing — W3C-shaped trace
  contexts propagated router -> replica worker -> serving engine,
  typed per-stage spans into a bounded in-process flight recorder
  (``/tracez``), head sampling (``FLAGS_trace_sample_rate``) with
  error/shed/deadline tail promotion, latency-histogram exemplars,
  and a chrome-trace exporter that merges with the profiler's;
- ``training``: a ``Model.fit`` callback + ``optimizer.step`` hook for
  step time / examples-per-sec / loss (lazy — imported on first
  attribute access so this package stays importable before hapi and
  optimizer exist in the import order);
- ``goodput``: the wall-clock goodput ledger — every second of a
  training run classified as productive step time or typed badput
  (compile / checkpoint / data stall / recovery / idle), with the
  sum-to-elapsed accounting identity served at ``/goodputz``;
- ``stepprof``: the always-on continuous step profiler — a bounded
  ring of per-step timing envelopes with an EWMA+MAD straggler
  detector that promotes slow steps into the trace flight recorder;
- ``slo``: declarative latency SLOs evaluated over deterministic
  rolling windows on the existing latency histograms, multi-window
  multi-burn-rate alerting (``/sloz``, alert sinks,
  ``paddle_slo_*`` gauges);
- ``xstats``: the executable cost & roofline registry — every compile
  site registers its executables with XLA ``cost_analysis()`` /
  ``memory_analysis()`` and provenance, joined with stepprof
  envelopes into live ``paddle_mfu{kind=}`` / bandwidth-utilization
  gauges and a roofline classification (``/execz``), plus the
  on-demand and anomaly-triggered device-profile capture ring
  (``/profilez``);
- ``numerics``: the correctness-observability plane — NaN/Inf
  tripwires over TrainStep grads and CachedDecoder logits
  (``FLAGS_check_nan_inf`` implemented for real), sampled
  shadow-verification of fused kernels against the pure-JAX oracle,
  deterministic per-chip SDC canary sweeps feeding replica
  quarantine, and the ``/numericsz`` surface.

``framework.monitor``'s stat_add/stat_get are a Counter view onto the
default registry; ``serving.ServingMetrics`` is backed by these types
while keeping its ``snapshot()`` schema byte-compatible.
"""
from __future__ import annotations

from . import (exposition, goodput, httpd, numerics,  # noqa: F401
               registry, runtime, slo, stepprof, tracing, xstats)
from .exposition import (  # noqa: F401
    PROMETHEUS_CONTENT_TYPE, json_snapshot, json_text, prometheus_text,
)
from .goodput import (  # noqa: F401
    GoodputLedger, default_ledger, goodput_report, goodputz_payload,
    set_default_ledger,
)
from .httpd import (  # noqa: F401
    TelemetryServer, add_health_check, add_readiness_check,
    get_telemetry_server, healthz, readyz, remove_health_check,
    remove_readiness_check, start_telemetry_server,
    stop_telemetry_server,
)
from .numerics import (  # noqa: F401
    CanaryRunner, note_serving_logits, note_shadow_divergence,
    numericsz_payload, run_device_canary,
)
from .registry import (  # noqa: F401
    DEFAULT_MS_BUCKETS, Counter, Gauge, Histogram, MetricRegistry,
    PercentileWindow, default_registry, sanitize_metric_name,
)
from .runtime import (  # noqa: F401
    install_build_info, install_device_memory_collector,
    install_jax_monitoring, mirror_profiler_spans,
)
from .slo import (  # noqa: F401
    BurnRule, LatencySLO, SLOMonitor, add_alert_sink, default_monitor,
    latency_slo, remove_alert_sink, set_default_monitor, sloz_payload,
)
from .stepprof import (  # noqa: F401
    StepProfiler, default_profiler, record_step, set_default_profiler,
)
from .tracing import (  # noqa: F401
    Span, SpanBuffer, TraceContext, current_context, default_buffer,
    export_chrome_trace, new_context, parse_traceparent,
    record_exemplar, record_span, request_context, start_span,
    tracez_payload, use_context,
)
from .xstats import (  # noqa: F401
    ExecEntry, ExecRegistry, ProfileRing, capture_profile,
    default_exec_registry, default_profile_ring, device_peaks,
    execz_payload, profilez_payload, register_executable,
)

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricRegistry",
    "PercentileWindow", "default_registry",
    "sanitize_metric_name", "DEFAULT_MS_BUCKETS",
    "prometheus_text", "json_snapshot", "json_text",
    "PROMETHEUS_CONTENT_TYPE",
    "TelemetryServer", "start_telemetry_server", "get_telemetry_server",
    "stop_telemetry_server", "add_health_check", "remove_health_check",
    "healthz", "add_readiness_check", "remove_readiness_check",
    "readyz",
    "install_jax_monitoring", "install_device_memory_collector",
    "mirror_profiler_spans", "install_build_info",
    "GoodputLedger", "default_ledger", "set_default_ledger",
    "goodput_report", "goodputz_payload",
    "StepProfiler", "default_profiler", "set_default_profiler",
    "record_step",
    "BurnRule", "LatencySLO", "SLOMonitor", "default_monitor",
    "set_default_monitor", "latency_slo", "add_alert_sink",
    "remove_alert_sink", "sloz_payload",
    "TraceContext", "Span", "SpanBuffer", "new_context",
    "request_context", "current_context", "use_context",
    "parse_traceparent", "start_span", "record_span",
    "default_buffer", "tracez_payload", "export_chrome_trace",
    "record_exemplar",
    "ExecEntry", "ExecRegistry", "ProfileRing",
    "default_exec_registry", "default_profile_ring",
    "register_executable", "device_peaks", "execz_payload",
    "profilez_payload", "capture_profile",
    "CanaryRunner", "note_serving_logits", "note_shadow_divergence",
    "numericsz_payload", "run_device_canary",
    "TrainingTelemetryCallback", "instrument_optimizers",
    "uninstrument_optimizers",
    "registry", "exposition", "httpd", "numerics", "runtime",
    "training", "tracing", "goodput", "stepprof", "slo", "xstats",
]

_LAZY = {
    "TrainingTelemetryCallback": "training",
    "instrument_optimizers": "training",
    "uninstrument_optimizers": "training",
    "training": None,
}


def __getattr__(name):
    # training pulls in the optimizer package; defer it so importing
    # paddle_tpu.observability (framework.monitor does, very early)
    # never walks back up into partially-initialized siblings
    if name in _LAZY:
        import importlib
        mod = importlib.import_module(__name__ + ".training")
        if _LAZY[name] is None:
            return mod
        return getattr(mod, name)
    raise AttributeError(name)
