"""paddle_tpu.observability — the unified telemetry layer.

The reference framework ships first-class observability
(platform/monitor.cc's STAT_ADD registry, the HostTracer/CudaTracer
profiler pair); this package is its production-grade TPU-native
counterpart and the ONE place every subsystem reports into:

- ``registry``: typed metric families — ``Counter``, ``Gauge``,
  ``Histogram`` — with Prometheus-style label sets, plus
  ``PercentileWindow``, the bounded-window nearest-rank percentile
  estimator shared with ``serving.metrics``;
- ``exposition``: Prometheus text format 0.0.4 + a JSON mirror;
- ``httpd``: a stdlib ``http.server`` endpoint (``/metrics``,
  ``/healthz`` liveness, ``/readyz`` readiness, ``/statusz``) that
  ``InferenceServer`` attaches via ``FLAGS_serving_telemetry_port``
  and scripts start with ``start_telemetry_server()``;
- ``runtime``: JAX compile-event listeners, device-memory gauges, and
  profiler RecordEvent span mirroring;
- ``tracing``: distributed request tracing — W3C-shaped trace
  contexts propagated router -> replica worker -> serving engine,
  typed per-stage spans into a bounded in-process flight recorder
  (``/tracez``), head sampling (``FLAGS_trace_sample_rate``) with
  error/shed/deadline tail promotion, latency-histogram exemplars,
  and a chrome-trace exporter that merges with the profiler's;
- ``training``: a ``Model.fit`` callback + ``optimizer.step`` hook for
  step time / examples-per-sec / loss (lazy — imported on first
  attribute access so this package stays importable before hapi and
  optimizer exist in the import order).

``framework.monitor``'s stat_add/stat_get are a Counter view onto the
default registry; ``serving.ServingMetrics`` is backed by these types
while keeping its ``snapshot()`` schema byte-compatible.
"""
from __future__ import annotations

from . import exposition, httpd, registry, runtime, tracing  # noqa: F401
from .exposition import (  # noqa: F401
    PROMETHEUS_CONTENT_TYPE, json_snapshot, json_text, prometheus_text,
)
from .httpd import (  # noqa: F401
    TelemetryServer, add_health_check, add_readiness_check,
    get_telemetry_server, healthz, readyz, remove_health_check,
    remove_readiness_check, start_telemetry_server,
    stop_telemetry_server,
)
from .registry import (  # noqa: F401
    DEFAULT_MS_BUCKETS, Counter, Gauge, Histogram, MetricRegistry,
    PercentileWindow, default_registry, sanitize_metric_name,
)
from .runtime import (  # noqa: F401
    install_device_memory_collector, install_jax_monitoring,
    mirror_profiler_spans,
)
from .tracing import (  # noqa: F401
    Span, SpanBuffer, TraceContext, current_context, default_buffer,
    export_chrome_trace, new_context, parse_traceparent,
    record_exemplar, record_span, request_context, start_span,
    tracez_payload, use_context,
)

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricRegistry",
    "PercentileWindow", "default_registry",
    "sanitize_metric_name", "DEFAULT_MS_BUCKETS",
    "prometheus_text", "json_snapshot", "json_text",
    "PROMETHEUS_CONTENT_TYPE",
    "TelemetryServer", "start_telemetry_server", "get_telemetry_server",
    "stop_telemetry_server", "add_health_check", "remove_health_check",
    "healthz", "add_readiness_check", "remove_readiness_check",
    "readyz",
    "install_jax_monitoring", "install_device_memory_collector",
    "mirror_profiler_spans",
    "TraceContext", "Span", "SpanBuffer", "new_context",
    "request_context", "current_context", "use_context",
    "parse_traceparent", "start_span", "record_span",
    "default_buffer", "tracez_payload", "export_chrome_trace",
    "record_exemplar",
    "TrainingTelemetryCallback", "instrument_optimizers",
    "uninstrument_optimizers",
    "registry", "exposition", "httpd", "runtime", "training",
    "tracing",
]

_LAZY = {
    "TrainingTelemetryCallback": "training",
    "instrument_optimizers": "training",
    "uninstrument_optimizers": "training",
    "training": None,
}


def __getattr__(name):
    # training pulls in the optimizer package; defer it so importing
    # paddle_tpu.observability (framework.monitor does, very early)
    # never walks back up into partially-initialized siblings
    if name in _LAZY:
        import importlib
        mod = importlib.import_module(__name__ + ".training")
        if _LAZY[name] is None:
            return mod
        return getattr(mod, name)
    raise AttributeError(name)
