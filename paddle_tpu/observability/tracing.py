"""Distributed request tracing — spans from router ingress to device step.

The profiler (``paddle_tpu.profiler``) answers "where does time go in
THIS process"; since the serving stack became a fleet (router process →
replica worker process → ``InferenceServer``/``GenerationServer`` →
jitted device dispatch) no single-process artifact can answer "where
did this slow REQUEST spend its 100 ms". This module is the
Dapper-style layer that can:

- **TraceContext** — a per-request identity (128-bit trace id + 64-bit
  span id + sampled flag) carried across processes in the W3C
  ``traceparent`` header shape (``00-<32hex>-<16hex>-<02x>``). The
  fleet codec and worker HTTP endpoints propagate it; anything can
  mint one at ingress with ``request_context()``.
- **Span** — one typed, timed unit of work (``stage`` names the
  pipeline stage: queue / assembly / dispatch / device_wait / fetch /
  prefill / decode_step / ...), with wall-clock start (comparable
  across processes on one host), measured duration, per-span attrs,
  and an ok/error status.
- **SpanBuffer** — the flight recorder: a lock-guarded bounded
  in-process ring of completed spans (``FLAGS_trace_buffer_spans``),
  per-trace span caps (``FLAGS_trace_max_spans_per_trace``) so one
  long decode stream cannot evict everything else. ``/tracez`` on the
  observability httpd serves it as JSON; the fleet router's
  ``/tracez`` fans out to every replica and stitches by trace id.
- **Head sampling + tail promotion** — ``FLAGS_trace_sample_rate``
  decides at ingress (deterministically, from the trace id, so every
  process agrees); spans of UNsampled requests are parked on the
  context and flushed only if the request later errors, sheds, or
  blows a deadline (``promote``), so failures are always traceable
  while steady-state overhead stays a coin flip plus a list append.
- **Exemplars** — ``record_exemplar`` keeps the latest trace id seen
  per latency-histogram bucket, so a bad p99 bucket on
  ``paddle_serving_latency_ms`` / ``paddle_fleet_request_ms`` links
  to a concrete retrievable trace.
- **Chrome export** — ``export_chrome_trace`` writes merged spans in
  the same ``{"traceEvents": [...]}`` schema the profiler's
  ``export_chrome_tracing`` uses (optionally splicing the profiler's
  own python spans in), so one chrome://tracing load shows the fleet
  request timeline next to host spans.

Everything here is stdlib-only and import-light, like the rest of the
observability package.
"""
from __future__ import annotations

import json
import os
import re
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Dict, Iterable, List, Optional, Sequence

__all__ = [
    "TraceContext", "Span", "SpanBuffer",
    "new_context", "request_context", "current_context", "use_context",
    "parse_traceparent", "sample_decision",
    "start_span", "record_span", "promote",
    "default_buffer", "set_default_buffer",
    "group_traces", "tracez_payload", "merge_span_dicts",
    "chrome_trace_events", "export_chrome_trace",
    "record_exemplar", "exemplars", "clear_exemplars",
    "set_process_name", "process_name",
]


def _flag(name, default):
    from ..framework.flags import flag_value
    try:
        return flag_value(name)
    except KeyError:
        return default


_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")


def _gen_trace_id() -> str:
    return os.urandom(16).hex()


def _gen_span_id() -> str:
    return os.urandom(8).hex()


def sample_decision(trace_id: str, rate: float) -> bool:
    """Deterministic head-sampling decision: a hash-free projection of
    the trace id onto [0, 1) compared against ``rate``. Every process
    that sees the same trace id makes the same call, so a trace is
    never half-sampled across the fleet."""
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    return int(trace_id[:16], 16) / float(1 << 64) < rate


class _TraceState:
    """Per-trace, per-process mutable state shared by every context of
    one trace: the parked spans of an unsampled request (flushed on
    promotion) and the promotion flag itself."""

    __slots__ = ("lock", "pending", "promoted", "dropped")

    def __init__(self):
        self.lock = threading.Lock()
        self.pending: List["Span"] = []
        self.promoted = False
        self.dropped = 0


class TraceContext:
    """Identity of one request's trace at one point in the call tree:
    ``span_id`` is the CURRENT span (new child spans parent to it),
    ``parent_id`` is its own parent (used when the span for this
    context is recorded)."""

    __slots__ = ("trace_id", "span_id", "parent_id", "sampled",
                 "state")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: str = "", sampled: bool = False,
                 state: Optional[_TraceState] = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.sampled = bool(sampled)
        self.state = state if state is not None else _TraceState()

    def child(self) -> "TraceContext":
        """A fresh span identity under this one (same trace, same
        local state)."""
        return TraceContext(self.trace_id, _gen_span_id(),
                            parent_id=self.span_id,
                            sampled=self.sampled, state=self.state)

    @property
    def recording(self) -> bool:
        return self.sampled or self.state.promoted

    def to_traceparent(self) -> str:
        flags = 1 if self.recording else 0
        return f"00-{self.trace_id}-{self.span_id}-{flags:02x}"

    def __repr__(self):
        return (f"TraceContext({self.to_traceparent()!r}, "
                f"promoted={self.state.promoted})")


def parse_traceparent(header: Optional[str]
                      ) -> Optional[TraceContext]:
    """W3C-shaped ``traceparent`` -> context, or None for anything
    malformed (a bad header degrades to 'untraced', never an error)."""
    if not header or not isinstance(header, str):
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if not m:
        return None
    version, trace_id, span_id, flags = m.groups()
    if version == "ff" or trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return TraceContext(trace_id, span_id,
                        sampled=bool(int(flags, 16) & 1))


def new_context(sampled: Optional[bool] = None) -> TraceContext:
    """Mint a fresh trace at an ingress point. ``sampled=None`` makes
    the head-sampling decision from ``FLAGS_trace_sample_rate``."""
    trace_id = _gen_trace_id()
    if sampled is None:
        sampled = sample_decision(
            trace_id, float(_flag("FLAGS_trace_sample_rate", 0.0)))
    return TraceContext(trace_id, _gen_span_id(), sampled=sampled)


# ------------------------------------------------------------- ambient
_tls = threading.local()


def current_context() -> Optional[TraceContext]:
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


@contextmanager
def use_context(ctx: Optional[TraceContext]):
    """Make ``ctx`` the ambient context for this thread (``submit`` /
    ``submit_generate`` pick it up)."""
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append(ctx)
    try:
        yield ctx
    finally:
        stack.pop()


def request_context() -> Optional[TraceContext]:
    """The context a request should be traced under: the ambient one
    when set, else a freshly sampled one when tracing is on
    (``FLAGS_trace_sample_rate > 0``), else None — the no-tracing fast
    path is one TLS read and one flag read."""
    ctx = current_context()
    if ctx is not None:
        return ctx
    if float(_flag("FLAGS_trace_sample_rate", 0.0)) > 0.0:
        return new_context()
    return None


# ------------------------------------------------------------- process
_proc_lock = threading.Lock()
_process_name: Optional[str] = None


def set_process_name(name: str):
    """Stamp every span this process records (router / replica-N /
    the bare pid by default) — the cross-process axis of the stitched
    view."""
    global _process_name
    with _proc_lock:
        _process_name = str(name)


def process_name() -> str:
    global _process_name
    with _proc_lock:
        if _process_name is None:
            _process_name = f"pid-{os.getpid()}"
        return _process_name


# ------------------------------------------------------------- spans
class Span:
    """One completed, typed unit of work inside a trace."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "stage",
                 "process", "pid", "tid", "start_unix_ns",
                 "duration_ms", "status", "attrs")

    def __init__(self, trace_id, span_id, parent_id, name, stage,
                 start_unix_ns, duration_ms, status="ok", attrs=None,
                 process=None, pid=None, tid=None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.stage = stage
        self.process = process if process is not None \
            else process_name()
        self.pid = int(pid) if pid is not None else os.getpid()
        self.tid = int(tid) if tid is not None \
            else threading.get_ident()
        self.start_unix_ns = int(start_unix_ns)
        self.duration_ms = float(duration_ms)
        self.status = status
        self.attrs = dict(attrs) if attrs else {}

    def to_dict(self) -> dict:
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "parent_id": self.parent_id, "name": self.name,
                "stage": self.stage, "process": self.process,
                "pid": self.pid, "tid": self.tid,
                "start_unix_ns": self.start_unix_ns,
                "duration_ms": round(self.duration_ms, 4),
                "status": self.status, "attrs": self.attrs}

    @classmethod
    def from_dict(cls, d: dict) -> "Span":
        return cls(d["trace_id"], d["span_id"],
                   d.get("parent_id", ""), d.get("name", ""),
                   d.get("stage", ""), d["start_unix_ns"],
                   d["duration_ms"], status=d.get("status", "ok"),
                   attrs=d.get("attrs"), process=d.get("process"),
                   pid=d.get("pid", 0), tid=d.get("tid", 0))


class SpanBuffer:
    """The flight recorder: a bounded, lock-guarded in-process ring of
    completed spans. Oldest spans are evicted past ``max_spans``;
    one trace is capped at ``max_per_trace`` spans (a long decode
    stream records its first N steps and counts the rest as dropped)
    so a single request cannot monopolize the recorder."""

    def __init__(self, max_spans: Optional[int] = None,
                 max_per_trace: Optional[int] = None):
        self._max = int(max_spans if max_spans is not None
                        else _flag("FLAGS_trace_buffer_spans", 4096))
        self._per_trace = int(
            max_per_trace if max_per_trace is not None
            else _flag("FLAGS_trace_max_spans_per_trace", 256))
        self._lock = threading.Lock()
        self._spans: deque = deque()
        self._per_trace_counts: Dict[str, int] = {}
        self._dropped = 0
        self._total = 0

    def __len__(self):
        with self._lock:
            return len(self._spans)

    @property
    def capacity(self) -> int:
        return self._max

    def add(self, span: Span):
        with self._lock:
            n = self._per_trace_counts.get(span.trace_id, 0)
            if n >= self._per_trace:
                self._dropped += 1
                return
            self._per_trace_counts[span.trace_id] = n + 1
            self._spans.append(span)
            self._total += 1
            while len(self._spans) > self._max:
                old = self._spans.popleft()
                c = self._per_trace_counts.get(old.trace_id, 1) - 1
                if c > 0:
                    self._per_trace_counts[old.trace_id] = c
                else:
                    self._per_trace_counts.pop(old.trace_id, None)

    def add_many(self, spans: Iterable[Span]):
        for s in spans:
            self.add(s)

    def snapshot(self, trace_id: Optional[str] = None,
                 min_duration_ms: Optional[float] = None
                 ) -> List[dict]:
        with self._lock:
            spans = list(self._spans)
        out = []
        for s in spans:
            if trace_id is not None and s.trace_id != trace_id:
                continue
            if min_duration_ms is not None and \
                    s.duration_ms < min_duration_ms:
                continue
            out.append(s.to_dict())
        return out

    def stats(self) -> dict:
        with self._lock:
            return {"spans": len(self._spans), "capacity": self._max,
                    "max_per_trace": self._per_trace,
                    "dropped": self._dropped,
                    "total_recorded": self._total,
                    "traces": len(self._per_trace_counts)}

    def clear(self):
        with self._lock:
            self._spans.clear()
            self._per_trace_counts.clear()
            self._dropped = 0
            self._total = 0


_default_lock = threading.Lock()
_default_buffer: Optional[SpanBuffer] = None


def default_buffer() -> SpanBuffer:
    """The process-wide flight recorder ``/tracez`` serves."""
    global _default_buffer
    with _default_lock:
        if _default_buffer is None:
            _default_buffer = SpanBuffer()
        return _default_buffer


def set_default_buffer(buf: Optional[SpanBuffer]
                       ) -> Optional[SpanBuffer]:
    """Swap the process-wide buffer (tests; ``None`` resets to a fresh
    one on next use). Returns the previous buffer."""
    global _default_buffer
    with _default_lock:
        prev, _default_buffer = _default_buffer, buf
    return prev


# ------------------------------------------------------------- record
def promote(ctx: TraceContext, reason: str = "",
            buffer: Optional[SpanBuffer] = None):
    """Tail promotion: flush this trace's parked spans into the
    recorder and record everything from here on, sampled or not —
    called on error / shed / deadline paths so failures are always
    traceable."""
    buf = buffer if buffer is not None else default_buffer()
    with ctx.state.lock:
        if ctx.state.promoted:
            return
        ctx.state.promoted = True
        pending, ctx.state.pending = ctx.state.pending, []
    for s in pending:
        if reason:
            s.attrs.setdefault("promoted", reason)
        buf.add(s)


def record_span(ctx: Optional[TraceContext], name: str, *,
                stage: str = "", start_unix_ns: int,
                duration_ms: float, attrs: Optional[dict] = None,
                status: str = "ok", root: bool = False,
                buffer: Optional[SpanBuffer] = None
                ) -> Optional[Span]:
    """Record one measured span under ``ctx`` (no-op when untraced).
    ``root=True`` records the span AS the context's own span id (the
    span this context was created for); otherwise a fresh child id is
    minted. ``status="error"`` promotes the trace."""
    if ctx is None:
        return None
    span = Span(ctx.trace_id,
                ctx.span_id if root else _gen_span_id(),
                ctx.parent_id if root else ctx.span_id,
                name, stage, start_unix_ns, duration_ms,
                status=status, attrs=attrs)
    buf = buffer if buffer is not None else default_buffer()
    if status == "error":
        promote(ctx, reason=str(attrs.get("error", "error"))
                if attrs else "error", buffer=buf)
    if ctx.recording:
        buf.add(span)
        return span
    with ctx.state.lock:
        cap = int(_flag("FLAGS_trace_max_spans_per_trace", 256))
        if len(ctx.state.pending) < cap:
            ctx.state.pending.append(span)
        else:
            ctx.state.dropped += 1
    return span


class _LiveSpan:
    """Handle yielded by ``start_span``: carries the child context for
    further nesting/propagation and collects attrs until exit."""

    __slots__ = ("ctx", "name", "stage", "attrs", "_t0_ns",
                 "_wall0_ns", "_buffer", "status")

    def __init__(self, ctx, name, stage, attrs, buffer):
        self.ctx = ctx
        self.name = name
        self.stage = stage
        self.attrs = dict(attrs) if attrs else {}
        self._buffer = buffer
        self._t0_ns = time.perf_counter_ns()
        self._wall0_ns = time.time_ns()
        self.status = "ok"

    def set_attr(self, key, value):
        self.attrs[key] = value

    def finish(self):
        dur_ms = (time.perf_counter_ns() - self._t0_ns) / 1e6
        record_span(self.ctx, self.name, stage=self.stage,
                    start_unix_ns=self._wall0_ns, duration_ms=dur_ms,
                    attrs=self.attrs, status=self.status, root=True,
                    buffer=self._buffer)


@contextmanager
def start_span(name: str, *, stage: str = "",
               ctx: Optional[TraceContext] = None,
               attrs: Optional[dict] = None,
               buffer: Optional[SpanBuffer] = None):
    """Open a live child span under ``ctx`` (default: the ambient
    context) and make its child context ambient for the block, so
    nested ``start_span`` / ``submit`` calls parent correctly. An
    escaping exception marks the span errored (which promotes the
    trace) and re-raises. Untraced: yields an inert handle."""
    parent = ctx if ctx is not None else current_context()
    if parent is None:
        yield _NOOP_SPAN
        return
    live = _LiveSpan(parent.child(), name, stage, attrs, buffer)
    with use_context(live.ctx):
        try:
            yield live
        except BaseException as e:
            live.status = "error"
            live.attrs.setdefault(
                "error", f"{type(e).__name__}: {e}")
            live.finish()
            raise
        live.finish()


class _NoopSpan:
    __slots__ = ()
    ctx = None
    status = "ok"

    def set_attr(self, key, value):
        pass

    def finish(self):
        pass


_NOOP_SPAN = _NoopSpan()


# ------------------------------------------------------------- views
def merge_span_dicts(*span_lists: Sequence[dict]) -> List[dict]:
    """Concatenate span-dict lists from several processes, de-duplicated
    by (trace_id, span_id) — the router's stitch primitive."""
    seen = set()
    out: List[dict] = []
    for spans in span_lists:
        for s in spans:
            key = (s.get("trace_id"), s.get("span_id"))
            if key in seen:
                continue
            seen.add(key)
            out.append(s)
    return out


def group_traces(span_dicts: Sequence[dict],
                 trace_id: Optional[str] = None,
                 min_duration_ms: Optional[float] = None,
                 limit: Optional[int] = None) -> List[dict]:
    """Group span dicts into per-trace records (newest first). A
    trace's duration is its span envelope (earliest start to latest
    end) — the stitched cross-process view. ``min_duration_ms``
    filters on that envelope; ``trace_id`` on identity."""
    by_trace: Dict[str, List[dict]] = {}
    for s in span_dicts:
        by_trace.setdefault(s["trace_id"], []).append(s)
    traces = []
    for tid, spans in by_trace.items():
        if trace_id is not None and tid != trace_id:
            continue
        spans = sorted(spans, key=lambda s: (s["start_unix_ns"],
                                             s.get("span_id", "")))
        t0 = min(s["start_unix_ns"] for s in spans)
        t1 = max(s["start_unix_ns"] + s["duration_ms"] * 1e6
                 for s in spans)
        dur = (t1 - t0) / 1e6
        if min_duration_ms is not None and dur < min_duration_ms:
            continue
        traces.append({
            "trace_id": tid,
            "start_unix_ms": round(t0 / 1e6, 3),
            "duration_ms": round(dur, 3),
            "n_spans": len(spans),
            "processes": sorted({s.get("process", "") for s in spans}),
            "errored": any(s.get("status") == "error" for s in spans),
            "spans": spans,
        })
    traces.sort(key=lambda t: -t["start_unix_ms"])
    if limit is not None:
        traces = traces[:int(limit)]
    return traces


def tracez_payload(buffer: Optional[SpanBuffer] = None,
                   trace_id: Optional[str] = None,
                   min_duration_ms: Optional[float] = None,
                   limit: Optional[int] = 100,
                   extra_spans: Optional[Sequence[dict]] = None
                   ) -> dict:
    """The ``/tracez`` JSON document: recent traces (grouped, filtered)
    plus recorder stats and the exemplar table. ``extra_spans`` merges
    remote span dicts in (the router's fan-out view)."""
    buf = buffer if buffer is not None else default_buffer()
    spans = buf.snapshot(trace_id=trace_id)
    if extra_spans:
        spans = merge_span_dicts(spans, extra_spans)
    return {
        "process": process_name(),
        "traces": group_traces(spans, trace_id=trace_id,
                               min_duration_ms=min_duration_ms,
                               limit=limit),
        "buffer": buf.stats(),
        "exemplars": exemplars(),
    }


# ------------------------------------------------------------- chrome
def chrome_trace_events(span_dicts: Sequence[dict]) -> List[dict]:
    """Span dicts -> chrome-trace events in the profiler's export
    schema ("X" complete events + process_name metadata), so the fleet
    timeline and ``profiler.export_chrome_tracing`` output co-exist in
    one viewer."""
    events: List[dict] = []
    procs: Dict[int, str] = {}
    for s in span_dicts:
        pid = int(s.get("pid", 0))
        procs.setdefault(pid, s.get("process", f"pid-{pid}"))
        args = {"trace_id": s["trace_id"], "span_id": s["span_id"],
                "parent_id": s.get("parent_id", ""),
                "status": s.get("status", "ok")}
        args.update(s.get("attrs") or {})
        events.append({
            "name": s.get("name", ""),
            "cat": s.get("stage") or "span",
            "ph": "X",
            "ts": s["start_unix_ns"] / 1e3,      # chrome wants us
            "dur": s["duration_ms"] * 1e3,
            "pid": pid,
            "tid": int(s.get("tid", 0)),
            "args": args,
        })
    for pid, name in sorted(procs.items()):
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "args": {"name": name}})
    return events


def export_chrome_trace(path: str,
                        span_dicts: Optional[Sequence[dict]] = None,
                        include_profiler: bool = False,
                        buffer: Optional[SpanBuffer] = None) -> int:
    """Write spans (default: the whole flight recorder) as a chrome
    trace. ``include_profiler=True`` splices the profiler's python-side
    RecordEvent spans into the same file. Returns the event count."""
    if span_dicts is None:
        buf = buffer if buffer is not None else default_buffer()
        span_dicts = buf.snapshot()
    events = chrome_trace_events(span_dicts)
    if include_profiler:
        from .. import profiler
        events.extend(dict(e) for e in profiler._tracer.events)
    with open(path, "w") as f:
        json.dump({"traceEvents": events}, f)
    return len(events)


# ------------------------------------------------------------- exemplars
# Bucket bounds mirror the registry's default ms histogram buckets so
# an exemplar maps 1:1 onto the Prometheus ``le`` the operator is
# staring at.
from .registry import DEFAULT_MS_BUCKETS  # noqa: E402 (cycle-free)


class _ExemplarStore:
    """Latest trace id observed per (metric, le-bucket) — bounded by
    construction: #metrics x #buckets entries."""

    def __init__(self, bounds: Sequence[float] = DEFAULT_MS_BUCKETS):
        self._bounds = tuple(sorted(float(b) for b in bounds))
        self._lock = threading.Lock()
        self._latest: Dict[str, Dict[str, dict]] = {}

    def _le(self, value: float) -> str:
        for b in self._bounds:
            if value <= b:
                return str(b)
        return "+Inf"

    def record(self, metric: str, value_ms: float, trace_id: str):
        entry = {"trace_id": trace_id,
                 "value_ms": round(float(value_ms), 4),
                 "unix_ms": round(time.time() * 1e3, 1)}
        le = self._le(float(value_ms))
        with self._lock:
            self._latest.setdefault(metric, {})[le] = entry

    def snapshot(self, metric: Optional[str] = None) -> dict:
        with self._lock:
            if metric is not None:
                return dict(self._latest.get(metric, {}))
            return {m: dict(v) for m, v in self._latest.items()}

    def clear(self):
        with self._lock:
            self._latest.clear()


_exemplars = _ExemplarStore()


def record_exemplar(metric: str, value_ms: float, trace_id: str):
    """Attach ``trace_id`` as the latest exemplar of ``metric``'s
    latency bucket for ``value_ms`` — the p99-bucket-to-trace link."""
    _exemplars.record(metric, value_ms, trace_id)


def exemplars(metric: Optional[str] = None) -> dict:
    """``{metric: {le: {trace_id, value_ms, unix_ms}}}`` (or one
    metric's table)."""
    return _exemplars.snapshot(metric)


def clear_exemplars():
    _exemplars.clear()
