"""Training-step instrumentation: a hapi ``Model.fit`` callback and an
``optimizer.step`` hook, both reporting into the metric registry.

``TrainingTelemetryCallback`` is duck-typed against the hapi callback
surface (it implements every ``on_*`` hook) rather than inheriting
``hapi.callbacks.Callback``, so this module imports cleanly before the
hapi package exists — observability sits below hapi in the import
order. ``Model.fit`` injects it automatically when
``FLAGS_training_telemetry`` is on; scripts can also add it explicitly
to ``callbacks=[...]``.

``instrument_optimizers()`` registers a step observer with
``paddle_tpu.optimizer`` so every ``Optimizer.apply_gradients`` (the
update half of ``step``) records its duration, parameter count, and
current LR — covering raw training loops that never go through hapi.
"""
from __future__ import annotations

import time
from typing import Callable, Optional

from .registry import MetricRegistry, default_registry

__all__ = ["TrainingTelemetryCallback", "instrument_optimizers",
           "uninstrument_optimizers"]


class TrainingTelemetryCallback:
    """Records per-step training metrics from the fit loop:

    - ``paddle_training_steps_total`` / ``paddle_training_epochs_total``
    - ``paddle_training_step_ms`` histogram (bounded-window percentiles)
    - ``paddle_training_loss`` gauge (last step's loss)
    - ``paddle_training_examples_per_sec`` gauge when ``batch_size`` is
      known (pass it to the constructor; the fit loop's loader owns it
      and does not forward it through callback params).

    It is also the fit loop's feed into the goodput ledger and the
    continuous step profiler: each train batch opens a ``step`` frame
    (nested compile/checkpoint recordings subtract themselves, so the
    accounting identity holds), the gap between one batch's end and
    the next one's begin is attributed to ``data_stall`` (the input
    pipeline had the wheel), and every step drops an envelope into the
    step profiler's ring (straggler detection included).

    ``now`` is injected for deterministic tests.
    """

    def __init__(self, registry: Optional[MetricRegistry] = None,
                 batch_size: Optional[int] = None,
                 now: Callable[[], float] = time.monotonic,
                 ledger=None, step_profiler=None):
        from .goodput import default_ledger
        from .stepprof import default_profiler
        reg = registry or default_registry()
        self._now = now
        self.batch_size = batch_size
        self._ledger = ledger if ledger is not None else \
            default_ledger()
        self._prof = step_profiler if step_profiler is not None else \
            default_profiler()
        self._t_batch_end = None
        self._frame_open = False
        self._steps = reg.counter(
            "paddle_training_steps_total", "optimizer steps seen by the "
            "hapi fit loop")
        self._epochs = reg.counter(
            "paddle_training_epochs_total", "completed fit epochs")
        self._step_ms = reg.histogram(
            "paddle_training_step_ms", "wall time of one fit train step "
            "(forward+backward+update)")
        self._loss = reg.gauge(
            "paddle_training_loss", "last training-step loss")
        self._eps = reg.gauge(
            "paddle_training_examples_per_sec",
            "examples/sec from the last step (needs batch_size)")
        self.model = None
        self.params = {}
        self._t0 = None

    # -- hapi callback surface (duck-typed)
    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = dict(params or {})

    def on_train_begin(self, logs=None):
        self._ledger.start()
        self._t_batch_end = None

    def on_train_end(self, logs=None):
        # post-fit time is idle/eval, not input stall
        self._t_batch_end = None

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        self._epochs.inc()

    def on_train_batch_begin(self, step, logs=None):
        self._t0 = self._now()
        if self._t_batch_end is not None:
            # the time between the previous step's end and this one's
            # begin belonged to the input pipeline
            gap = self._now() - self._t_batch_end
            self._t_batch_end = None
            if gap > 0:
                self._ledger.record("data_stall", gap)
        self._ledger.begin("step")
        self._frame_open = True

    def on_train_batch_end(self, step, logs=None):
        if self._frame_open:
            self._frame_open = False
            self._ledger.end()
        self._t_batch_end = self._now()
        self._steps.inc()
        if self._t0 is not None:
            dt = self._now() - self._t0
            self._t0 = None
            self._step_ms.observe(dt * 1e3)
            self._prof.record_step(dt * 1e3, kind="train",
                                   step=int(step) if step is not None
                                   else None)
            if self.batch_size and dt > 0:
                self._eps.set(self.batch_size / dt)
        loss = (logs or {}).get("loss")
        if loss is not None:
            try:
                self._loss.set(float(loss))
            except (TypeError, ValueError):
                pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


_optimizer_observer = None


def instrument_optimizers(registry: Optional[MetricRegistry] = None
                          ) -> bool:
    """Hook every Optimizer.apply_gradients in the process. Idempotent;
    returns True once the observer is registered."""
    global _optimizer_observer
    if _optimizer_observer is not None:
        return True
    reg = registry or default_registry()
    steps = reg.counter(
        "paddle_optimizer_steps_total",
        "optimizer update calls (apply_gradients)", ("optimizer",))
    step_ms = reg.histogram(
        "paddle_optimizer_step_ms",
        "wall time of one optimizer update", ("optimizer",))
    lr_gauge = reg.gauge(
        "paddle_optimizer_lr", "current learning rate", ("optimizer",))
    params_gauge = reg.gauge(
        "paddle_optimizer_params",
        "parameter tensors updated by the last step", ("optimizer",))

    def _observer(opt, duration_s, n_params):
        name = type(opt).__name__
        steps.labels(optimizer=name).inc()
        step_ms.labels(optimizer=name).observe(duration_s * 1e3)
        params_gauge.labels(optimizer=name).set(n_params)
        try:
            lr_gauge.labels(optimizer=name).set(float(opt.get_lr()))
        except Exception:  # noqa: BLE001 - LR is best-effort garnish
            pass

    from ..optimizer import optimizer as opt_mod
    opt_mod.register_step_observer(_observer)
    _optimizer_observer = _observer
    return True


def uninstrument_optimizers():
    global _optimizer_observer
    if _optimizer_observer is None:
        return
    from ..optimizer import optimizer as opt_mod
    opt_mod.unregister_step_observer(_optimizer_observer)
    _optimizer_observer = None
