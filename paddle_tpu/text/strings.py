"""String tensor type + strings ops + the faster_tokenizer kernel.

Reference:
- StringTensor: /root/reference/paddle/phi/core/string_tensor.h — a
  tensor of variable-length utf8 strings (pstring elements).
- strings kernels: /root/reference/paddle/phi/kernels/strings/
  (strings_empty_kernel.cc, strings_lower_upper_kernel.h with
  ``use_utf8_encoding``: ASCII mode maps only A-Z/a-z, utf8 mode applies
  the full unicode case mapping via unicode.h's tables).
- faster_tokenizer: /root/reference/paddle/fluid/operators/string/
  faster_tokenizer_op.{h,cc} — BERT BasicTokenizer (whitespace cleanup,
  CJK spacing, accent stripping under do_lower_case, punctuation split)
  + WordpieceTokenizer ("##" continuations, [UNK] fallback) + pair
  encoding with [CLS]/[SEP] framing, segment ids, max_seq_len
  truncation and optional padding.

TPU-native design: strings never touch the device — they are host-side
preprocessing exactly as in the reference (its kernels are CPU-only
too); the tokenizer's OUTPUT (input_ids/segment_ids int64 arrays) is
what crosses onto the TPU. Python's str type IS the unicode layer, so
the ~2k-line unicode.cc table machinery collapses into str.lower()/
unicodedata — same mapping, maintained by CPython.
"""
from __future__ import annotations

import unicodedata
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

__all__ = ["StringTensor", "strings_empty", "strings_lower",
           "strings_upper", "BasicTokenizer", "WordpieceTokenizer",
           "BertTokenizerKernel", "faster_tokenizer"]


class StringTensor:
    """A shaped container of utf8 strings (phi::StringTensor analog).

    Backed by a numpy object array; supports the same surface the
    reference exposes through pybind (shape/numel/indexing) without
    pretending strings live on device."""

    def __init__(self, data, name: str = ""):
        arr = np.asarray(data, dtype=object)
        bad = [x for x in arr.reshape(-1) if not isinstance(x, str)]
        if bad:
            raise TypeError(
                f"StringTensor holds utf8 strings; got {type(bad[0])}")
        self._data = arr
        self.name = name

    @property
    def shape(self) -> List[int]:
        return list(self._data.shape)

    def numel(self) -> int:
        return int(self._data.size)

    def numpy(self) -> np.ndarray:
        return self._data

    def __getitem__(self, idx):
        out = self._data[idx]
        return out if isinstance(out, str) else StringTensor(out)

    def __repr__(self):
        return f"StringTensor(shape={self.shape}, {self._data!r})"

    def tolist(self):
        return self._data.tolist()


def _as_string_array(x) -> np.ndarray:
    if isinstance(x, StringTensor):
        return x._data
    return np.asarray(x, dtype=object)


def strings_empty(shape: Sequence[int]) -> StringTensor:
    """strings_empty_kernel.cc: a StringTensor of empty strings."""
    arr = np.full(tuple(shape), "", dtype=object)
    return StringTensor(arr)


def _case_map(s: str, lower: bool, use_utf8_encoding: bool) -> str:
    if use_utf8_encoding:
        # full unicode case mapping (reference unicode.h tables ==
        # CPython's unicode database)
        return s.lower() if lower else s.upper()
    # ASCII mode (reference case_utils.h AsciiToLower/Upper): only A-Z
    # and a-z move; every other byte passes through untouched
    delta = 32 if lower else -32
    lo, hi = ("A", "Z") if lower else ("a", "z")
    return "".join(chr(ord(c) + delta) if lo <= c <= hi else c
                   for c in s)


def strings_lower(x, use_utf8_encoding: bool = False) -> StringTensor:
    arr = _as_string_array(x)
    out = np.frompyfunc(
        lambda s: _case_map(s, True, use_utf8_encoding), 1, 1)(arr)
    return StringTensor(out.astype(object))


def strings_upper(x, use_utf8_encoding: bool = False) -> StringTensor:
    arr = _as_string_array(x)
    out = np.frompyfunc(
        lambda s: _case_map(s, False, use_utf8_encoding), 1, 1)(arr)
    return StringTensor(out.astype(object))


# ------------------------------------------------------------ tokenizer

def _is_whitespace(ch: str) -> bool:
    if ch in (" ", "\t", "\n", "\r"):
        return True
    return unicodedata.category(ch) == "Zs"


def _is_control(ch: str) -> bool:
    if ch in ("\t", "\n", "\r"):
        return False
    return unicodedata.category(ch).startswith("C")


def _is_punctuation(ch: str) -> bool:
    cp = ord(ch)
    # ASCII punctuation ranges + unicode P* (faster_tokenizer_op.h
    # IsPunctuation == BERT's convention)
    if (33 <= cp <= 47) or (58 <= cp <= 64) or (91 <= cp <= 96) or \
            (123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


def _is_chinese_char(cp: int) -> bool:
    return ((0x4E00 <= cp <= 0x9FFF) or (0x3400 <= cp <= 0x4DBF) or
            (0x20000 <= cp <= 0x2A6DF) or (0x2A700 <= cp <= 0x2B73F) or
            (0x2B740 <= cp <= 0x2B81F) or (0x2B820 <= cp <= 0x2CEAF) or
            (0xF900 <= cp <= 0xFAFF) or (0x2F800 <= cp <= 0x2FA1F))


class BasicTokenizer:
    """faster_tokenizer_op.h BasicTokenizer: unicode cleanup, CJK
    spacing, optional lowercase + accent stripping, punctuation split."""

    def __init__(self, do_lower_case: bool = True):
        self.do_lower_case = do_lower_case

    def tokenize(self, text: str) -> List[str]:
        out = []
        for ch in text:
            cp = ord(ch)
            if cp == 0 or cp == 0xFFFD or _is_control(ch):
                continue
            if _is_chinese_char(cp):
                out.append(f" {ch} ")
            elif _is_whitespace(ch):
                out.append(" ")
            else:
                out.append(ch)
        tokens = []
        for tok in "".join(out).split():
            if self.do_lower_case:
                tok = tok.lower()
                tok = "".join(c for c in unicodedata.normalize("NFD", tok)
                              if unicodedata.category(c) != "Mn")
            cur = []
            for ch in tok:
                if _is_punctuation(ch):
                    if cur:
                        tokens.append("".join(cur))
                        cur = []
                    tokens.append(ch)
                else:
                    cur.append(ch)
            if cur:
                tokens.append("".join(cur))
        return tokens


class WordpieceTokenizer:
    """Greedy longest-match-first wordpiece with "##" continuations
    (faster_tokenizer_op.h WordPieceTokenizer)."""

    def __init__(self, vocab: Dict[str, int], unk_token: str = "[UNK]",
                 max_input_chars_per_word: int = 100):
        self.vocab = vocab
        self.unk_token = unk_token
        self.max_chars = max_input_chars_per_word

    def tokenize(self, token: str) -> List[str]:
        if len(token) > self.max_chars:
            return [self.unk_token]
        out, start = [], 0
        while start < len(token):
            end = len(token)
            cur = None
            while start < end:
                piece = token[start:end]
                if start > 0:
                    piece = "##" + piece
                if piece in self.vocab:
                    cur = piece
                    break
                end -= 1
            if cur is None:
                return [self.unk_token]
            out.append(cur)
            start = end
        return out


class BertTokenizerKernel:
    """The faster_tokenizer op body: Basic + Wordpiece + pair framing.

    Matches the reference kernel contract (faster_tokenizer_op.h
    BertTokenizer::Encode/BatchEncode): [CLS] A [SEP] (B [SEP]),
    segment ids 0/0/1, longest-first truncation to max_seq_len, optional
    right-padding with [PAD]."""

    def __init__(self, vocab: Dict[str, int], do_lower_case: bool = False,
                 unk_token: str = "[UNK]", pad_token: str = "[PAD]",
                 cls_token: str = "[CLS]", mask_token: str = "[MASK]",
                 sep_token: str = "[SEP]"):
        self.vocab = dict(vocab)
        self.basic = BasicTokenizer(do_lower_case)
        self.wordpiece = WordpieceTokenizer(self.vocab, unk_token)
        for tok in (unk_token, pad_token, cls_token, sep_token):
            if tok not in self.vocab:
                raise ValueError(f"vocab is missing special token {tok!r}")
        self.cls_id = self.vocab[cls_token]
        self.sep_id = self.vocab[sep_token]
        self.pad_id = self.vocab[pad_token]

    def _ids(self, text: str) -> List[int]:
        ids = []
        for tok in self.basic.tokenize(text):
            for piece in self.wordpiece.tokenize(tok):
                ids.append(self.vocab[piece])
        return ids

    def encode(self, text: str, text_pair: Optional[str] = None,
               max_seq_len: int = 0, pad_to_max_seq_len: bool = False,
               ) -> Tuple[List[int], List[int]]:
        a = self._ids(text)
        b = self._ids(text_pair) if text_pair is not None else None
        n_special = 3 if b is not None else 2
        if max_seq_len > 0:
            # floor at 0: max_seq_len < n_special would send the budget
            # negative and the pop-loop could never satisfy it
            budget = max(max_seq_len - n_special, 0)
            # longest-first truncation; ties pop from the PAIR side
            # (faster_tokenizer_op.cc:307 TruncateSequence)
            while b is not None and len(a) + len(b) > budget:
                if len(a) > len(b):
                    a = a[:-1]
                else:
                    b = b[:-1]
            if b is None and len(a) > budget:
                a = a[:budget]
        ids = [self.cls_id] + a + [self.sep_id]
        seg = [0] * len(ids)
        if b is not None:
            ids += b + [self.sep_id]
            seg += [1] * (len(b) + 1)
        if max_seq_len > 0 and pad_to_max_seq_len and \
                len(ids) < max_seq_len:
            pad = max_seq_len - len(ids)
            ids += [self.pad_id] * pad
            seg += [0] * pad
        return ids, seg

    def batch_encode(self, texts: Sequence[str],
                     text_pairs: Optional[Sequence[str]] = None,
                     max_seq_len: int = 0,
                     pad_to_max_seq_len: bool = False,
                     ) -> Tuple[np.ndarray, np.ndarray]:
        pairs = text_pairs if text_pairs is not None else [None] * len(texts)
        encoded = [self.encode(t, p, max_seq_len, pad_to_max_seq_len)
                   for t, p in zip(texts, pairs)]
        if not encoded:     # empty shard: (0, w) int64 outputs
            w = max_seq_len if (max_seq_len > 0 and pad_to_max_seq_len) \
                else 0
            return (np.zeros((0, w), np.int64), np.zeros((0, w), np.int64))
        width = max(len(ids) for ids, _ in encoded)
        input_ids = np.full((len(encoded), width), self.pad_id, np.int64)
        seg_ids = np.zeros((len(encoded), width), np.int64)
        for i, (ids, seg) in enumerate(encoded):
            input_ids[i, :len(ids)] = ids
            seg_ids[i, :len(seg)] = seg
        return input_ids, seg_ids


def faster_tokenizer(vocab: Dict[str, int],
                     text: Union[StringTensor, Sequence[str]],
                     text_pair=None, do_lower_case: bool = False,
                     is_split_into_words: bool = False,
                     max_seq_len: int = 0,
                     pad_to_max_seq_len: bool = False):
    """The faster_tokenizer op surface (faster_tokenizer_op.cc): returns
    (InputIds, SegmentIds) as int64 arrays."""
    if is_split_into_words:
        raise NotImplementedError(
            "faster_tokenizer is_split_into_words (pre-tokenized input) "
            "is not supported yet")
    texts = list(_as_string_array(text).reshape(-1))
    pairs = None
    if text_pair is not None:
        pairs = list(_as_string_array(text_pair).reshape(-1))
        if len(pairs) != len(texts):
            raise ValueError(
                f"Text has {len(texts)} entries but TextPair has "
                f"{len(pairs)} (faster_tokenizer_op.cc pair contract)")
    kern = BertTokenizerKernel(vocab, do_lower_case=do_lower_case)
    return kern.batch_encode(texts, pairs, max_seq_len, pad_to_max_seq_len)
