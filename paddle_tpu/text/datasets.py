"""paddle.text.datasets — text corpus parsers with hermetic fallbacks.

Reference: python/paddle/text/datasets/{uci_housing,imdb,imikolov,
movielens,conll05,wmt14,wmt16}.py. Those auto-download; here each class
parses a local archive passed via ``data_file`` and, where a corpus is
small and synthesizable, generates deterministic stand-in data when no
file is given (so DataLoader pipelines run without egress). Item tuple
shapes match the reference loaders.
"""
from __future__ import annotations

import gzip
import os
import re
import tarfile

import numpy as np

from ..io.dataset import Dataset
from ..utils.download import require_local_file as _require

__all__ = ["UCIHousing", "Imdb", "Imikolov", "Movielens", "Conll05", "Conll05st",
           "WMT14", "WMT16", "MovieInfo", "UserInfo"]


class Conll05st(Dataset):
    """CoNLL-2005 semantic-role-labeling dataset (reference
    text/datasets/conll05.py). Samples are 8 aligned int64 sequences
    (word, 5 context predicates, mark) + the label sequence. Hermetic:
    without data files, deterministic synthetic sentences over the same
    field layout are generated (the reference's download path does not
    apply offline)."""

    WORD_DICT_LEN = 44068
    LABEL_DICT_LEN = 3257
    PRED_DICT_LEN = 3162

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None, mode="train",
                 download=True, n_samples=200):
        self._inner = None
        if data_file is not None:
            # real data: the Conll05 tar/dict parser below already
            # handles the reference layout — delegate
            self._inner = Conll05(data_file, word_dict_file,
                                  verb_dict_file, target_dict_file,
                                  mode=mode, download=download)
            return
        rng = np.random.RandomState(0 if mode == "train" else 1)
        self._samples = []
        for _ in range(n_samples):
            ln = int(rng.randint(5, 30))
            word = rng.randint(0, self.WORD_DICT_LEN, ln)
            ctxs = [rng.randint(0, self.WORD_DICT_LEN, ln)
                    for _ in range(5)]
            pred = np.full(ln, rng.randint(0, self.PRED_DICT_LEN))
            mark = (rng.rand(ln) < 0.2).astype(np.int64)
            label = rng.randint(0, self.LABEL_DICT_LEN, ln)
            self._samples.append(tuple(
                np.asarray(a, np.int64)
                for a in (word, *ctxs, pred, mark, label)))

    def get_dict(self):
        word_dict = {f"w{i}": i for i in range(100)}
        verb_dict = {f"v{i}": i for i in range(50)}
        label_dict = {f"l{i}": i for i in range(50)}
        return word_dict, verb_dict, label_dict

    def get_embedding(self):
        raise NotImplementedError(
            "Conll05st.get_embedding needs the emb file download")

    def __getitem__(self, idx):
        if self._inner is not None:
            return self._inner[idx]
        return self._samples[idx]

    def __len__(self):
        if self._inner is not None:
            return len(self._inner)
        return len(self._samples)


class UCIHousing(Dataset):
    """Boston housing regression (reference: uci_housing.py).

    data_file: whitespace-separated housing.data (506 rows x 14 cols).
    Without a file, deterministic synthetic rows with the same
    normalization contract are generated.
    """

    FEATURE_NUM = 14
    TRAIN_RATIO = 0.8

    def __init__(self, data_file=None, mode="train", download=True):
        self.dtype = "float32"
        if data_file is not None:
            data_file = _require(data_file, "uci housing data")
            raw = np.fromfile(data_file, sep=" ", dtype=np.float32)
        else:
            rng = np.random.RandomState(0)
            raw = rng.rand(506 * self.FEATURE_NUM).astype(np.float32)
        data = raw.reshape(-1, self.FEATURE_NUM)
        # feature normalization exactly as the reference: (x - avg) / range
        maxs = data.max(axis=0)
        mins = data.min(axis=0)
        avgs = data.mean(axis=0)
        for i in range(self.FEATURE_NUM - 1):
            rng_ = maxs[i] - mins[i]
            data[:, i] = (data[:, i] - avgs[i]) / (rng_ if rng_ else 1.0)
        split = int(data.shape[0] * self.TRAIN_RATIO)
        self.data = data[:split] if mode == "train" else data[split:]

    def __getitem__(self, idx):
        row = self.data[idx]
        return (np.asarray(row[:-1], self.dtype),
                np.asarray(row[-1:], self.dtype))

    def __len__(self):
        return len(self.data)


class Imdb(Dataset):
    """IMDB sentiment (reference: imdb.py). Parses the aclImdb tar:
    train/pos, train/neg document files -> word-id docs + 0/1 labels."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=True):
        if data_file is None:
            # deterministic synthetic corpus with a learnable signal
            rng = np.random.RandomState(1)
            vocab = 200
            self.word_idx = {f"w{i}": i for i in range(vocab)}
            self.docs, self.labels = [], []
            for k in range(256):
                label = k % 2
                base = rng.randint(0, vocab // 2, size=rng.randint(5, 30))
                bias = np.full(4, vocab // 2 + label, dtype=np.int64)
                self.docs.append(np.concatenate([base, bias]))
                self.labels.append(label)
            return
        data_file = _require(data_file, "aclImdb_v1.tar.gz")
        pat = re.compile(rf"aclImdb/{mode}/(pos|neg)/.*\.txt$")
        # vocab covers BOTH splits (reference imdb.py builds word_idx over
        # train|test) so train/test ids are compatible
        vocab_pat = re.compile(r"aclImdb/(train|test)/(pos|neg)/.*\.txt$")
        self.word_idx = self._build_vocab(data_file, vocab_pat, cutoff)
        self.docs, self.labels = [], []
        unk = self.word_idx["<unk>"]
        with tarfile.open(data_file, "r:*") as tf:
            for member in tf.getmembers():
                m = pat.match(member.name)
                if not m:
                    continue
                words = self._tokenize(tf.extractfile(member).read())
                self.docs.append(np.asarray(
                    [self.word_idx.get(w, unk) for w in words]))
                self.labels.append(0 if m.group(1) == "pos" else 1)

    @staticmethod
    def _tokenize(raw):
        # byte-exact mirror of the reference tokenizer
        # (/root/reference/python/paddle/text/datasets/imdb.py:112):
        # rstrip newlines, DELETE all punctuation ("don't"→"dont",
        # "<br />"→"br "), lowercase, split — so vocab contents and word
        # ids line up with reference-built checkpoints
        import string
        return raw.rstrip(b"\n\r") \
            .translate(None, string.punctuation.encode("latin-1")) \
            .decode("latin-1").lower().split()

    def _build_vocab(self, data_file, pat, cutoff):
        from collections import Counter
        freq = Counter()
        with tarfile.open(data_file, "r:*") as tf:
            for member in tf.getmembers():
                if pat.match(member.name):
                    freq.update(self._tokenize(tf.extractfile(member).read()))
        words = [w for w, c in freq.most_common() if c > cutoff]
        word_idx = {w: i for i, w in enumerate(words)}
        word_idx["<unk>"] = len(words)
        return word_idx

    def __getitem__(self, idx):
        return np.asarray(self.docs[idx]), np.asarray([self.labels[idx]])

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """PTB n-gram / seq dataset (reference: imikolov.py).

    data_type='NGRAM' yields window tuples; 'SEQ' yields (src, trg)
    shifted sequences.
    """

    def __init__(self, data_file=None, data_type="NGRAM", window_size=-1,
                 mode="train", min_word_freq=50, download=True):
        self.data_type = data_type.upper()
        self.window_size = window_size
        if self.data_type == "NGRAM" and window_size < 1:
            raise ValueError("NGRAM mode requires window_size >= 1")
        lines = self._load_lines(data_file, mode)
        self.word_idx = self._build_vocab(
            self._load_lines(data_file, "train"), min_word_freq)
        self.data = []
        unk = self.word_idx["<unk>"]
        for line in lines:
            if self.data_type == "NGRAM":
                toks = ["<s>"] + line + ["<e>"]
                ids = [self.word_idx.get(w, unk) for w in toks]
                if len(ids) >= window_size:
                    for i in range(window_size, len(ids) + 1):
                        self.data.append(tuple(ids[i - window_size:i]))
            else:
                toks = ["<s>"] + line + ["<e>"]
                ids = [self.word_idx.get(w, unk) for w in toks]
                self.data.append((ids[:-1], ids[1:]))

    def _load_lines(self, data_file, mode):
        if data_file is None:
            rng = np.random.RandomState(2)
            words = [f"t{i}" for i in range(64)]
            return [[words[rng.randint(0, 64)] for _ in range(
                rng.randint(3, 12))] for _ in range(200)]
        data_file = _require(data_file, "simple-examples.tgz (PTB)")
        name = f"./simple-examples/data/ptb.{'train' if mode == 'train' else 'valid'}.txt"
        with tarfile.open(data_file, "r:*") as tf:
            for member in tf.getmembers():
                if member.name.lstrip("./").endswith(name.lstrip("./")):
                    raw = tf.extractfile(member).read().decode()
                    return [ln.strip().split() for ln in raw.splitlines()
                            if ln.strip()]
        raise ValueError(f"{name} not found in archive")

    @staticmethod
    def _build_vocab(lines, min_word_freq):
        from collections import Counter
        freq = Counter()
        for ln in lines:
            freq.update(ln)
        freq.pop("<unk>", None)
        # reference rule: strictly > min_word_freq, ordered by
        # (-frequency, word)
        kept = sorted(((w, c) for w, c in freq.items() if c > min_word_freq),
                      key=lambda wc: (-wc[1], wc[0]))
        words = [w for w, _ in kept]
        word_idx = {w: i for i, w in enumerate(words)}
        word_idx["<unk>"] = len(words)
        word_idx.setdefault("<s>", len(word_idx))
        word_idx.setdefault("<e>", len(word_idx))
        return word_idx

    def __getitem__(self, idx):
        return tuple(np.asarray(d) for d in self.data[idx])

    def __len__(self):
        return len(self.data)


class MovieInfo:
    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self, categories_dict, movie_title_dict):
        return [
            [self.index],
            [categories_dict[c] for c in self.categories],
            [movie_title_dict[w.lower()] for w in self.title.split()],
        ]


class UserInfo:
    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == "M"
        self.age = [1, 18, 25, 35, 45, 50, 56].index(int(age))
        self.job_id = int(job_id)

    def value(self):
        return [[self.index], [0 if self.is_male else 1], [self.age],
                [self.job_id]]


class Movielens(Dataset):
    """MovieLens-1M ratings (reference: movielens.py). Parses ml-1m.zip;
    item tuple = user fields + movie fields + [score]."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=True):
        import zipfile
        if data_file is None:
            self._synth(mode, test_ratio, rand_seed)
            return
        data_file = _require(data_file, "ml-1m.zip")
        self.movie_info, self.user_info = {}, {}
        self.categories_dict, self.movie_title_dict = {}, {}
        with zipfile.ZipFile(data_file) as zf:
            movies = zf.read("ml-1m/movies.dat").decode("latin1")
            users = zf.read("ml-1m/users.dat").decode("latin1")
            ratings = zf.read("ml-1m/ratings.dat").decode("latin1")
        for ln in movies.splitlines():
            if not ln.strip():
                continue
            idx, title, cats = ln.strip().split("::")
            cats = cats.split("|")
            title = re.sub(r"\(\d{4}\)$", "", title).strip()
            for c in cats:
                self.categories_dict.setdefault(c, len(self.categories_dict))
            for w in title.split():
                self.movie_title_dict.setdefault(
                    w.lower(), len(self.movie_title_dict))
            self.movie_info[int(idx)] = MovieInfo(idx, cats, title)
        for ln in users.splitlines():
            if not ln.strip():
                continue
            idx, gender, age, job, _ = ln.strip().split("::")
            self.user_info[int(idx)] = UserInfo(idx, gender, age, job)
        rng = np.random.RandomState(rand_seed)
        self.data = []
        for ln in ratings.splitlines():
            if not ln.strip():
                continue
            uid, mid, rating, _ = ln.strip().split("::")
            uid, mid = int(uid), int(mid)
            is_test = rng.rand() < test_ratio
            if (mode == "test") != is_test:
                continue
            if uid not in self.user_info or mid not in self.movie_info:
                continue
            self.data.append(
                self.user_info[uid].value()
                + self.movie_info[mid].value(self.categories_dict,
                                             self.movie_title_dict)
                + [[float(rating)]])

    def _synth(self, mode, test_ratio, rand_seed):
        rng = np.random.RandomState(rand_seed)
        self.data = []
        n = 512
        for i in range(n):
            is_test = rng.rand() < test_ratio
            if (mode == "test") != is_test:
                continue
            self.data.append([
                [rng.randint(1, 100)], [rng.randint(0, 2)],
                [rng.randint(0, 7)], [rng.randint(0, 20)],
                [rng.randint(1, 200)], list(rng.randint(0, 18, 2)),
                list(rng.randint(0, 500, 3)), [float(rng.randint(1, 6))]])

    def __getitem__(self, idx):
        return tuple(np.asarray(d) for d in self.data[idx])

    def __len__(self):
        return len(self.data)


class Conll05(Dataset):
    """CoNLL-2005 SRL (reference: conll05.py). Requires local data_file
    (test.wsj tar), word/verb/target dict files."""

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None, emb_file=None,
                 mode="train", download=True):
        data_file = _require(data_file, "conll05st-tests tar")
        word_dict_file = _require(word_dict_file, "wordDict.txt")
        verb_dict_file = _require(verb_dict_file, "verbDict.txt")
        target_dict_file = _require(target_dict_file, "targetDict.txt")
        self.word_dict = self._load_dict(word_dict_file)
        self.predicate_dict = self._load_dict(verb_dict_file)
        self.label_dict = self._load_label_dict(target_dict_file)
        self.data = self._parse(data_file)

    @staticmethod
    def _load_dict(path):
        d = {}
        with open(path) as f:
            for i, ln in enumerate(f):
                d[ln.strip()] = i
        return d

    @staticmethod
    def _load_label_dict(path):
        d = {}
        tag_dict = set()
        with open(path) as f:
            for ln in f:
                ln = ln.strip()
                if ln.startswith("B-"):
                    tag_dict.add(ln[2:])
        index = 0
        for tag in sorted(tag_dict):
            d["B-" + tag] = index
            index += 1
            d["I-" + tag] = index
            index += 1
        d["O"] = index
        return d

    def _parse(self, data_file):
        """Extract (words, predicate, labels) triples from the archive's
        words/props files."""
        sentences, props = [], []
        with tarfile.open(data_file, "r:*") as tf:
            wfile = pfile = None
            for m in tf.getmembers():
                if m.name.endswith("words.gz"):
                    wfile = gzip.decompress(tf.extractfile(m).read()).decode()
                elif m.name.endswith("props.gz"):
                    pfile = gzip.decompress(tf.extractfile(m).read()).decode()
            if wfile is None or pfile is None:
                raise ValueError("words.gz/props.gz not found in archive")
        cur_w, cur_p = [], []
        for wl, pl in zip(wfile.splitlines(), pfile.splitlines()):
            if not wl.strip():
                if cur_w:
                    sentences.append(cur_w)
                    props.append(cur_p)
                cur_w, cur_p = [], []
                continue
            cur_w.append(wl.strip())
            cur_p.append(pl.strip().split())
        if cur_w:
            sentences.append(cur_w)
            props.append(cur_p)
        data = []
        unk = self.word_dict.get("<unk>", 0)
        for words, prop in zip(sentences, props):
            if not prop or len(prop[0]) < 2:
                continue
            n_preds = len(prop[0]) - 1
            for p in range(n_preds):
                verb = next((prop[i][0] for i in range(len(prop))
                             if prop[i][p + 1].startswith("(V")), None)
                if verb is None or verb == "-":
                    continue
                labels = self._spans_to_iob([r[p + 1] for r in prop])
                wids = np.asarray([self.word_dict.get(w.lower(), unk)
                                   for w in words])
                vid = self.predicate_dict.get(verb, 0)
                lids = np.asarray([self.label_dict.get(l, self.label_dict["O"])
                                   for l in labels])
                data.append((wids, np.asarray([vid]), lids))
        return data

    @staticmethod
    def _spans_to_iob(col):
        out, state = [], None
        for tok in col:
            label = "O"
            m = re.match(r"\(([^*()]+)", tok)
            if m:
                state = m.group(1)
                label = "B-" + state
            elif state is not None:
                label = "I-" + state
            out.append(label)
            if ")" in tok:
                state = None
        return out

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class _WMTBase(Dataset):
    """Shared src/trg id-sequence contract: item = (src_ids, trg_ids,
    trg_ids_next) (reference: wmt14.py/wmt16.py)."""

    BOS, EOS, UNK = 0, 1, 2

    def _synth(self, seed, dict_size):
        rng = np.random.RandomState(seed)
        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        for _ in range(128):
            n = rng.randint(3, 15)
            src = rng.randint(3, dict_size, n).tolist()
            trg = rng.randint(3, dict_size, n).tolist()
            self.src_ids.append([self.BOS] + src + [self.EOS])
            self.trg_ids.append([self.BOS] + trg)
            self.trg_ids_next.append(trg + [self.EOS])
        self.src_dict = {i: f"s{i}" for i in range(dict_size)}
        self.trg_dict = {i: f"t{i}" for i in range(dict_size)}

    def __getitem__(self, idx):
        return (np.asarray(self.src_ids[idx]), np.asarray(self.trg_ids[idx]),
                np.asarray(self.trg_ids_next[idx]))

    def __len__(self):
        return len(self.src_ids)


class WMT14(_WMTBase):
    """WMT14 en-fr (reference: wmt14.py). data_file: wmt14 tar with
    train/test token files ('src \\t trg' per line)."""

    def __init__(self, data_file=None, mode="train", dict_size=30000,
                 download=True):
        if data_file is None:
            self._synth(3, min(dict_size, 64))
            return
        data_file = _require(data_file, "wmt14 archive")
        self._parse_tar(data_file, "train" if mode == "train" else "test",
                        dict_size, dict_size)

    def _parse_tar(self, data_file, split, src_dict_size, trg_dict_size,
                   swap_columns=False):
        """Parse 'src \\t trg' token files under ``split``/ in the tar.
        swap_columns=True reads the pair as (col1, col0) — WMT16's
        lang='de' direction."""
        from collections import Counter
        sub = split.rstrip("/") + "/"
        pairs = []
        with tarfile.open(data_file, "r:*") as tf:
            for m in tf.getmembers():
                if sub not in m.name or not m.isfile():
                    continue
                for ln in tf.extractfile(m).read().decode(
                        "latin1").splitlines():
                    parts = ln.split("\t")
                    if len(parts) >= 2:
                        s, t = parts[0].split(), parts[1].split()
                        pairs.append((t, s) if swap_columns else (s, t))
        sfreq, tfreq = Counter(), Counter()
        for s, t in pairs:
            sfreq.update(s)
            tfreq.update(t)

        def build(freq, size):
            words = [w for w, _ in freq.most_common(size - 3)]
            d = {"<s>": self.BOS, "<e>": self.EOS, "<unk>": self.UNK}
            for i, w in enumerate(words):
                d[w] = i + 3
            return d

        self.src_dict = build(sfreq, src_dict_size)
        self.trg_dict = build(tfreq, trg_dict_size)
        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        for s, t in pairs:
            sid = [self.src_dict.get(w, self.UNK) for w in s]
            tid = [self.trg_dict.get(w, self.UNK) for w in t]
            self.src_ids.append([self.BOS] + sid + [self.EOS])
            self.trg_ids.append([self.BOS] + tid)
            self.trg_ids_next.append(tid + [self.EOS])


class WMT16(_WMTBase):
    """WMT16 en-de (reference: wmt16.py); same item contract, tar layout
    wmt16/{train,val,test}. lang='en' reads en->de, lang='de' the
    reverse."""

    def __init__(self, data_file=None, mode="train", src_dict_size=30000,
                 trg_dict_size=30000, lang="en", download=True):
        if src_dict_size <= 0 or trg_dict_size <= 0:
            raise ValueError("src_dict_size/trg_dict_size must be positive")
        if mode not in ("train", "test", "val"):
            raise ValueError(f"unknown WMT16 mode {mode!r}")
        if data_file is None:
            self._synth(4, min(src_dict_size, 64))
            return
        data_file = _require(data_file, "wmt16 archive")
        WMT14._parse_tar(self, data_file, mode, src_dict_size,
                         trg_dict_size, swap_columns=(lang == "de"))
