"""paddle.text — text-domain utilities.

Reference: /root/reference/python/paddle/text/ (datasets: Imdb/Conll05/
UCIHousing/WMT14/...; plus the viterbi_decode op family living in
paddle.text.viterbi_decode / ViterbiDecoder, backed by the
viterbi_decode yaml op). TPU-native: the Viterbi recursion is a
lax.scan (compiles to one fused program); datasets ship as small
in-memory generators (the reference's downloads don't apply offline).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply_op
from ..nn.layer.layers import Layer

__all__ = ["viterbi_decode", "ViterbiDecoder", "Vocab", "datasets",
           "StringTensor", "strings_empty", "strings_lower",
           "strings_upper", "faster_tokenizer", "BertTokenizerKernel",
           "Conll05st", "Imdb", "Imikolov", "Movielens", "UCIHousing",
           "WMT14", "WMT16"]

from . import datasets  # noqa: E402,F401
from .datasets import (  # noqa: E402,F401
    Conll05st, Imdb, Imikolov, Movielens, UCIHousing, WMT14, WMT16,
)
from .strings import (  # noqa: E402,F401
    BertTokenizerKernel, StringTensor, faster_tokenizer, strings_empty,
    strings_lower, strings_upper,
)


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True, name=None):
    """CRF Viterbi decode (reference: paddle.text.viterbi_decode over the
    viterbi_decode op, phi ops.yaml). potentials [B, T, N] emission
    scores, transition_params [N, N]; returns (scores [B], paths [B, T]).
    ``lengths`` [B] masks padded steps (defaults to full length).
    """
    def _decode(pot, trans, lens):
        b, t, n = pot.shape

        def step(alpha, emit_t):
            # [B, N_prev, N_cur]
            scores = alpha[:, :, None] + trans[None] + emit_t[:, None, :]
            best_prev = jnp.argmax(scores, axis=1).astype(jnp.int32)
            alpha_new = jnp.max(scores, axis=1)
            return alpha_new, best_prev

        alpha0 = pot[:, 0]
        _, backptrs = jax.lax.scan(step, alpha0,
                                   jnp.swapaxes(pot[:, 1:], 0, 1))
        # mask beyond lengths: freeze alpha at the last valid step
        steps = jnp.arange(1, t)[:, None, None]             # [T-1,1,1]
        valid = steps < lens[None, :, None]                 # [T-1,B,1]
        # recompute alphas per step to select the final one
        def step2(carry, inp):
            alpha = carry
            emit_t, v = inp
            scores = alpha[:, :, None] + trans[None] + emit_t[:, None, :]
            alpha_new = jnp.max(scores, axis=1)
            alpha = jnp.where(v, alpha_new, alpha)
            return alpha, alpha
        alpha_final, _ = jax.lax.scan(
            step2, alpha0, (jnp.swapaxes(pot[:, 1:], 0, 1), valid))
        best_last = jnp.argmax(alpha_final, axis=1).astype(jnp.int32)
        best_score = jnp.max(alpha_final, axis=1)

        # backtrack (reverse scan over backpointers)
        def back(carry, inp):
            tag = carry
            bp, v = inp                                     # bp [B,N]
            prev = jnp.take_along_axis(bp, tag[:, None], 1)[:, 0]
            prev = jnp.where(v[:, 0], prev, tag)
            return prev, tag

        tag0, tags_rev = jax.lax.scan(back, best_last,
                                      (backptrs[::-1], valid[::-1]))
        # carries emitted on ENTRY: tags_rev = [tag_{T-1}, ..., tag_1];
        # the final carry is tag_0
        path = jnp.concatenate([tag0[None], tags_rev[::-1]], axis=0)
        return best_score, jnp.swapaxes(path, 0, 1).astype(jnp.int64)

    if lengths is None:
        b, t = (potentials.shape[0], potentials.shape[1])
        import paddle_tpu as P
        lengths = P.to_tensor(np.full((b,), t, np.int64))
    return apply_op("viterbi_decode", _decode, potentials,
                    transition_params, lengths)


class ViterbiDecoder(Layer):
    """reference paddle.text.ViterbiDecoder."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


class Vocab:
    """Token <-> index vocabulary (reference paddlenlp-style Vocab used by
    the text datasets; minimal core: build from counter/tokens, lookup,
    unk handling)."""

    def __init__(self, counter=None, max_size=None, min_freq=1,
                 token_to_idx=None, unk_token="[UNK]", pad_token="[PAD]",
                 bos_token=None, eos_token=None):
        self.unk_token = unk_token
        self.pad_token = pad_token
        if token_to_idx is not None:
            self._t2i = dict(token_to_idx)
        else:
            specials = [t for t in (pad_token, unk_token, bos_token,
                                    eos_token) if t is not None]
            self._t2i = {t: i for i, t in enumerate(specials)}
            if counter:
                items = sorted(counter.items(),
                               key=lambda kv: (-kv[1], kv[0]))
                for tok, freq in items:
                    if freq < min_freq or tok in self._t2i:
                        continue
                    if max_size and len(self._t2i) >= max_size:
                        break
                    self._t2i[tok] = len(self._t2i)
        self._i2t = {i: t for t, i in self._t2i.items()}

    def __len__(self):
        return len(self._t2i)

    def __contains__(self, token):
        return token in self._t2i

    def to_indices(self, tokens):
        unk = self._t2i.get(self.unk_token)
        if isinstance(tokens, (list, tuple)):
            return [self._t2i.get(t, unk) for t in tokens]
        return self._t2i.get(tokens, unk)

    def to_tokens(self, indices):
        if isinstance(indices, (list, tuple)):
            return [self._i2t.get(int(i), self.unk_token) for i in indices]
        return self._i2t.get(int(indices), self.unk_token)

    @property
    def token_to_idx(self):
        return dict(self._t2i)

    @property
    def idx_to_token(self):
        return dict(self._i2t)
