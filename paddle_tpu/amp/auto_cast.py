"""Automatic mixed precision.

Reference: /root/reference/python/paddle/amp/auto_cast.py:668 (O1 allowlist
autocast / O2 pure-half with master weights). TPU-native stance: bfloat16 is
the native half dtype (no loss scaling needed); fp16 is accepted for parity.
O1 is implemented at the dispatch layer: ops on the allowlist cast their
floating inputs to the amp dtype before execution.
"""
from __future__ import annotations

import contextlib
import threading

import jax.numpy as jnp

from ..framework import dtype as dtype_mod

_state = threading.local()

# Allowlist mirrors the reference's fp16 white list (matmul/conv class ops,
# /root/reference/python/paddle/amp/auto_cast.py:141-152)
WHITE_LIST = {
    "matmul", "linear", "conv1d", "conv2d", "conv3d", "conv1d_transpose",
    "conv2d_transpose", "conv3d_transpose", "einsum", "mm", "bmm", "mv",
    "scaled_dot_product_attention", "addmm",
}
# Blacklist ops stay in fp32 (numerically sensitive)
BLACK_LIST = {
    "exp", "square", "log", "mean", "sum", "cos_sim", "softmax",
    "softmax_with_cross_entropy", "sigmoid_cross_entropy_with_logits",
    "cross_entropy", "c_softmax_with_cross_entropy", "layer_norm", "erf",
    "logsumexp", "log_softmax", "batch_norm", "group_norm", "instance_norm",
}


def amp_state():
    return getattr(_state, "amp", None)


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16", use_promote=True):
    prev = amp_state()
    if enable:
        white = set(WHITE_LIST)
        black = set(BLACK_LIST)
        if custom_white_list:
            white |= set(custom_white_list)
            black -= set(custom_white_list)
        if custom_black_list:
            black |= set(custom_black_list)
            white -= set(custom_black_list)
        _state.amp = {
            "level": level,
            "dtype": dtype_mod.convert_dtype(dtype),
            "white": white,
            "black": black,
        }
    else:
        _state.amp = None
    try:
        yield
    finally:
        _state.amp = prev


amp_guard = auto_cast


def maybe_autocast_args(op_name, arrays):
    """Called by dispatch: cast float inputs per AMP state. O1 = allowlist;
    O2 = everything except blacklist."""
    st = amp_state()
    if st is None or op_name is None:
        return arrays
    name = op_name.split("/")[-1]
    target = st["dtype"].np_dtype
    if name in st["black"]:
        cast_to = jnp.float32
    elif name in st["white"] or st["level"] == "O2":
        cast_to = target
    else:
        return arrays
    out = []
    for a in arrays:
        if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating) and \
                a.dtype != jnp.float64:
            out.append(a.astype(cast_to) if a.dtype != cast_to else a)
        else:
            out.append(a)
    return out


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """O2 decoration: cast model params to the amp dtype (master weights live
    in the optimizer's f32 moments — Adam here always keeps f32 state)."""
    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    for m in model_list:
        m.astype(dtype)
    if optimizers is None:
        return models if single else model_list
    return (models if single else model_list), optimizers
