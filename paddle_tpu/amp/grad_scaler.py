"""GradScaler — dynamic loss scaling
(reference: /root/reference/python/paddle/amp/grad_scaler.py:602, AmpScaler:38).

bf16 training doesn't need loss scaling; this exists for fp16 parity and
follows the reference's found_inf / incr-decr protocol.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor


class GradScaler:
    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return self._scale

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        params = optimizer._parameters or []
        inv = 1.0 / self._scale
        found = False
        for p in params:
            if p.grad is None:
                continue
            g = p.grad._data
            finite = bool(jnp.all(jnp.isfinite(g)))
            if not finite:
                found = True
            p.grad._data = (g * inv).astype(g.dtype)
        self._found_inf = found

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        if not getattr(self, "_unscaled", False):
            self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self._unscaled = False

    def update(self):
        if not self._enable or not self._dynamic:
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)
        self.update()

    def state_dict(self):
        # counters may be device scalars when a TrainStep runs the scaler
        # in-graph; materialize to python numbers here
        return {
            "scale": float(np.asarray(self._scale)),
            "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
            "incr_count": int(np.asarray(self._good_steps)),
            "decr_count": int(np.asarray(self._bad_steps)),
        }

    def load_state_dict(self, state):
        self._scale = state.get("scale", self._scale)
        self._good_steps = state.get("incr_count", 0)
        self._bad_steps = state.get("decr_count", 0)
        # invalidate any TrainStep's cached device-side scaler state
        self._epoch = getattr(self, "_epoch", 0) + 1


AmpScaler = GradScaler
