from .auto_cast import amp_guard, auto_cast, decorate  # noqa: F401
from .grad_scaler import AmpScaler, GradScaler  # noqa: F401
