"""Optimizer base + standard optimizers
(reference: /root/reference/python/paddle/optimizer/optimizer.py:91).

Updates are pure jax functions jitted once per (optimizer, param-shape/dtype)
and applied to the raw arrays — functional inside, stateful paddle API outside
(accumulators, grad clip, regularization, LR schedulers). Under
paddle_tpu.jit the same ``_update_rule`` runs traced, so one code path serves
eager and compiled training.
"""
from __future__ import annotations

import functools
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from ..core.autograd import no_grad
from ..core.tensor import Tensor
from .lr import LRScheduler

# Step observers: ``fn(optimizer, duration_s, n_params)`` called after
# every apply_gradients. The observability layer registers here
# (training.instrument_optimizers) so raw training loops — not just
# hapi fit — feed step metrics; zero overhead while the list is empty.
_step_observers: List = []


def register_step_observer(fn):
    if fn not in _step_observers:
        _step_observers.append(fn)
    return fn


def unregister_step_observer(fn):
    if fn in _step_observers:
        _step_observers.remove(fn)


class Optimizer:
    _accum_names: List[str] = []

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        self._lr = learning_rate
        self._parameters = list(parameters) if parameters is not None else None
        self._grad_clip = grad_clip
        if isinstance(weight_decay, (int, float)) or weight_decay is None:
            self._l2_coeff = float(weight_decay or 0.0)
            self._wd_obj = None
        else:
            self._wd_obj = weight_decay  # L1Decay / L2Decay object
            self._l2_coeff = getattr(weight_decay, "coeff", 0.0)
        # name -> param_id -> jax array
        self._accumulators: Dict[str, Dict[int, jax.Array]] = {}
        self._step_count = 0

    # ------------- lr -------------
    def get_lr(self) -> float:
        if isinstance(self._lr, LRScheduler):
            return float(self._lr())
        return float(self._lr)

    def set_lr(self, value):
        if isinstance(self._lr, LRScheduler):
            raise RuntimeError(
                "set_lr cannot be used while the lr is an LRScheduler")
        self._lr = float(value)

    def set_lr_scheduler(self, scheduler):
        self._lr = scheduler

    @property
    def _learning_rate(self):
        return self._lr

    # ------------- accumulators -------------
    def _get_accum(self, name, p, init=None):
        store = self._accumulators.setdefault(name, {})
        pid = id(p)
        if pid not in store:
            store[pid] = jnp.zeros_like(p._data) if init is None else init
        return store[pid]

    def _set_accum(self, name, p, value):
        self._accumulators[name][id(p)] = value

    def _accum_spec(self, name, p):
        """(shape, dtype) of accumulator ``name`` for ``p`` WITHOUT
        materializing it — used by TrainStep.aot_lower for abstract
        (LazyGuard) planning of huge configs."""
        import numpy as _np
        dt = getattr(p._data, "dtype", _np.float32)
        return tuple(p.shape), dt

    # ------------- the update -------------
    def _update_rule(self, p_data, grad, lr, t, wd, state: dict) -> tuple:
        """Return (new_p, new_state). Pure function of arrays; ``wd`` is the
        traced decoupled weight-decay coefficient (0 when gated off)."""
        raise NotImplementedError

    @no_grad()
    def step(self):
        params = self._parameters
        if params is None:
            raise ValueError(
                "Optimizer created without parameters; pass parameters=")
        params_grads = [(p, p.grad) for p in params
                        if not p.stop_gradient and p.grad is not None]
        self.apply_gradients(params_grads)

    @no_grad()
    def apply_gradients(self, params_grads):
        """Apply explicit (param, grad) pairs — the update half of ``step``.
        Used by ``step`` and by static-mode ``Executor.run`` replaying a
        ``minimize``d Program (reference: apply_gradients,
        /root/reference/python/paddle/optimizer/optimizer.py:969)."""
        t0 = time.perf_counter() if _step_observers else None
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        lr_val = self.get_lr()
        self._step_count += 1
        for p, g in params_grads:
            if g is None:
                continue
            g_arr = g._data if isinstance(g, Tensor) else g
            if g_arr.dtype != p._data.dtype:
                g_arr = g_arr.astype(p._data.dtype)
            # regularization: per-param regularizer wins over the optimizer's
            # weight_decay (paddle precedence); decay objects (L1Decay/
            # L2Decay) apply their own rule
            p_reg = getattr(p, "regularizer", None)
            if p_reg is not None:
                g_arr = p_reg.apply(g_arr, p._data)
            elif self._wd_obj is not None:
                g_arr = self._wd_obj.apply(g_arr, p._data)
            elif self._l2_coeff and not self._decoupled_wd():
                g_arr = g_arr + self._l2_coeff * p._data
            p_lr = lr_val * getattr(p, "optimize_attr",
                                    {"learning_rate": 1.0})["learning_rate"]
            state = {name: self._get_accum(name, p)
                     for name in self._accum_names}
            new_p, new_state = self._apply_jit(
                p._data, g_arr, jnp.asarray(p_lr, jnp.float32),
                jnp.asarray(self._step_count, jnp.int32),
                jnp.asarray(self._wd_for(p), jnp.float32), state)
            p._data = new_p
            for name in self._accum_names:
                self._set_accum(name, p, new_state[name])
        if t0 is not None:
            dt = time.perf_counter() - t0
            for fn in list(_step_observers):
                try:
                    fn(self, dt, len(params_grads))
                except Exception:  # noqa: BLE001 - telemetry must never
                    pass           # fail the update it observes

    def _decoupled_wd(self):
        return False

    def _wd_for(self, p) -> float:
        """Decoupled weight decay coefficient for this param (AdamW-style)."""
        return 0.0

    @functools.partial(jax.jit, static_argnums=0)
    def _apply_jit(self, p, g, lr, t, wd, state):
        return self._update_rule(p, g, lr, t, wd, state)

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        from ..static import program as static_program
        if static_program.in_static_mode():
            # Static mode: register the train step on the Program;
            # Executor.run computes jax.grad of the replay and applies this
            # optimizer's update rule to the parameters (the analog of the
            # optimize ops minimize() appends to the ProgramDesc,
            # /root/reference/python/paddle/optimizer/optimizer.py:1115).
            program = static_program.default_main_program()
            params = list(parameters or self._parameters
                          or program.all_parameters())
            if self._parameters is None:
                self._parameters = params
            for p in params:
                program.params.setdefault(id(p), p)
                program.var_by_id.setdefault(id(p), p)
            program.train_spec = (id(loss), self, [id(p) for p in params])
            # fetchable grad vars, like the reference's returned
            # params_grads (append_backward registers them in grad_map
            # on the current default program)
            from ..static import append_backward
            pairs = append_backward(loss, parameter_list=params,
                                    no_grad_set=no_grad_set)
            return None, pairs
        loss.backward()
        self.step()
        return None, [(p, p.grad) for p in (parameters or self._parameters)]

    @no_grad()
    def clear_grad(self, set_to_zero=True):
        if self._parameters:
            for p in self._parameters:
                p.clear_grad()

    clear_gradients = clear_grad

    # ------------- state dict -------------
    def state_dict(self):
        sd = {}
        for name, store in self._accumulators.items():
            for i, p in enumerate(self._parameters or []):
                if id(p) in store:
                    sd[f"{p.name}_{name}"] = Tensor(store[id(p)])
        if isinstance(self._lr, LRScheduler):
            sd["LR_Scheduler"] = self._lr.state_dict()
        sd["@step"] = self._step_count
        return sd

    def set_state_dict(self, state_dict):
        for p in self._parameters or []:
            for name in self._accum_names:
                key = f"{p.name}_{name}"
                if key in state_dict:
                    v = state_dict[key]
                    arr = v._data if isinstance(v, Tensor) else jnp.asarray(v)
                    self._accumulators.setdefault(name, {})[id(p)] = arr
        if "LR_Scheduler" in state_dict and isinstance(self._lr, LRScheduler):
            self._lr.set_state_dict(state_dict["LR_Scheduler"])
        self._step_count = int(state_dict.get("@step", self._step_count))

    set_dict = set_state_dict


class SGD(Optimizer):
    _accum_names: List[str] = []

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)

    def _update_rule(self, p, g, lr, t, wd, state):
        return p - lr.astype(p.dtype) * g, state


class Momentum(Optimizer):
    _accum_names = ["velocity"]

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, rescale_grad=1.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _update_rule(self, p, g, lr, t, wd, state):
        v = state["velocity"]
        lr = lr.astype(p.dtype)
        v_new = self._momentum * v + g
        if self._nesterov:
            p_new = p - lr * (g + self._momentum * v_new)
        else:
            p_new = p - lr * v_new
        return p_new, {"velocity": v_new}


class LarsMomentum(Optimizer):
    """Layer-wise Adaptive Rate Scaling momentum (reference:
    /root/reference/python/paddle/fluid/optimizer.py:1786
    LarsMomentumOptimizer):

        local_lr = lr * lars_coeff * ||p|| / (||g|| + wd * ||p|| + eps)
        v        = mu * v + local_lr * (g + wd * p)
        p        = p - v

    The trust ratio falls back to the plain lr when either norm is zero
    (the kernel's guard for freshly-initialized or frozen layers)."""

    _accum_names = ["velocity"]

    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameters=None, grad_clip=None,
                 exclude_from_weight_decay=None, epsilon=0.0,
                 multi_precision=False, rescale_grad=1.0, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip)
        self._momentum = momentum
        self._coeff = lars_coeff
        self._lars_wd = lars_weight_decay
        self._exclude = list(exclude_from_weight_decay or [])
        self._eps = epsilon
        self._rescale = rescale_grad

    def _wd_for(self, p) -> float:
        pname = getattr(p, "name", "") or ""
        if any(tag in pname for tag in self._exclude):
            return 0.0
        return self._lars_wd

    def _update_rule(self, p, g, lr, t, wd, state):
        g = g * self._rescale
        lr = lr.astype(jnp.float32)
        wd = wd.astype(jnp.float32)
        p_norm = jnp.sqrt(jnp.sum(jnp.square(p.astype(jnp.float32))))
        g_norm = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
        local_lr = jnp.where(
            (p_norm > 0) & (g_norm > 0),
            lr * self._coeff * p_norm / (g_norm + wd * p_norm + self._eps),
            lr)
        v = self._momentum * state["velocity"] + \
            local_lr.astype(p.dtype) * (g + wd.astype(p.dtype) * p)
        return p - v, {"velocity": v}


class Adam(Optimizer):
    _accum_names = ["moment1", "moment2"]

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 use_multi_tensor=False, moment_dtype="float32", name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1 = beta1
        self._beta2 = beta2
        self._eps = epsilon
        # moment_dtype="bfloat16" halves optimizer-state HBM (8 bytes ->
        # 4 bytes per param): the update math still runs in f32 (states are
        # upcast inside the rule), enabling billion-parameter single-chip
        # training that f32 moments cannot fit
        self._moment_dtype = jnp.dtype(moment_dtype)

    def _update_rule(self, p, g, lr, t, wd, state):
        md = self._moment_dtype
        m = state["moment1"].astype(jnp.float32)
        v = state["moment2"].astype(jnp.float32)
        g32 = g.astype(jnp.float32)
        m = self._beta1 * m + (1 - self._beta1) * g32
        v = self._beta2 * v + (1 - self._beta2) * jnp.square(g32)
        tf = t.astype(jnp.float32)
        m_hat = m / (1 - self._beta1 ** tf)
        v_hat = v / (1 - self._beta2 ** tf)
        upd = lr * m_hat / (jnp.sqrt(v_hat) + self._eps)
        return (p.astype(jnp.float32) - upd).astype(p.dtype), \
            {"moment1": m.astype(md), "moment2": v.astype(md)}

    def _get_accum(self, name, p, init=None):
        store = self._accumulators.setdefault(name, {})
        pid = id(p)
        if pid not in store:
            store[pid] = jnp.zeros(p._data.shape, self._moment_dtype)
        return store[pid]

    def _accum_spec(self, name, p):
        return tuple(p.shape), self._moment_dtype


class AdamW(Adam):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False,
                 moment_dtype="float32", name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, moment_dtype=moment_dtype)
        self._wd = float(weight_decay) if not hasattr(weight_decay, "coeff") \
            else weight_decay.coeff
        self._apply_decay_fn = apply_decay_param_fun

    def _decoupled_wd(self):
        return True

    def _wd_for(self, p) -> float:
        if self._apply_decay_fn is not None and \
                not self._apply_decay_fn(p.name):
            return 0.0
        return self._wd

    def _update_rule(self, p, g, lr, t, wd, state):
        md = self._moment_dtype
        m = state["moment1"].astype(jnp.float32)
        v = state["moment2"].astype(jnp.float32)
        g32 = g.astype(jnp.float32)
        m = self._beta1 * m + (1 - self._beta1) * g32
        v = self._beta2 * v + (1 - self._beta2) * jnp.square(g32)
        tf = t.astype(jnp.float32)
        m_hat = m / (1 - self._beta1 ** tf)
        v_hat = v / (1 - self._beta2 ** tf)
        p32 = p.astype(jnp.float32)
        p32 = p32 * (1.0 - lr * wd)
        upd = lr * m_hat / (jnp.sqrt(v_hat) + self._eps)
        return (p32 - upd).astype(p.dtype), \
            {"moment1": m.astype(md), "moment2": v.astype(md)}


class Adagrad(Optimizer):
    _accum_names = ["moment"]

    def __init__(self, learning_rate, epsilon=1e-06, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._eps = epsilon
        self._init_val = initial_accumulator_value

    def _update_rule(self, p, g, lr, t, wd, state):
        mom = state["moment"] + jnp.square(g)
        p_new = p - lr.astype(p.dtype) * g / (jnp.sqrt(mom) + self._eps)
        return p_new, {"moment": mom}


class Adadelta(Optimizer):
    _accum_names = ["avg_squared_grad", "avg_squared_update"]

    def __init__(self, learning_rate=0.001, epsilon=1e-06, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._eps = epsilon
        self._rho = rho

    def _update_rule(self, p, g, lr, t, wd, state):
        sq_g = self._rho * state["avg_squared_grad"] + (1 - self._rho) * g * g
        upd = g * jnp.sqrt(state["avg_squared_update"] + self._eps) / \
            jnp.sqrt(sq_g + self._eps)
        sq_u = self._rho * state["avg_squared_update"] + (1 - self._rho) * upd * upd
        return p - lr.astype(p.dtype) * upd, \
            {"avg_squared_grad": sq_g, "avg_squared_update": sq_u}


class Adamax(Optimizer):
    _accum_names = ["moment", "inf_norm"]

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def _update_rule(self, p, g, lr, t, wd, state):
        m = self._beta1 * state["moment"] + (1 - self._beta1) * g
        u = jnp.maximum(self._beta2 * state["inf_norm"], jnp.abs(g))
        tf = t.astype(jnp.float32)
        lr_t = (lr / (1 - self._beta1 ** tf)).astype(p.dtype)
        p_new = p - lr_t * m / (u + self._eps)
        return p_new, {"moment": m, "inf_norm": u}


class RMSProp(Optimizer):
    _accum_names = ["mean_square", "mean_grad", "momentum_acc"]

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-06, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._rho, self._eps = rho, epsilon
        self._momentum = momentum
        self._centered = centered

    def _update_rule(self, p, g, lr, t, wd, state):
        ms = self._rho * state["mean_square"] + (1 - self._rho) * g * g
        if self._centered:
            mg = self._rho * state["mean_grad"] + (1 - self._rho) * g
            denom = jnp.sqrt(ms - mg * mg + self._eps)
        else:
            mg = state["mean_grad"]
            denom = jnp.sqrt(ms + self._eps)
        mom = self._momentum * state["momentum_acc"] + \
            lr.astype(p.dtype) * g / denom
        return p - mom, {"mean_square": ms, "mean_grad": mg,
                         "momentum_acc": mom}


class Lamb(Optimizer):
    _accum_names = ["moment1", "moment2"]

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-06, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _wd_for(self, p) -> float:
        if self._exclude_fn is not None and self._exclude_fn(p):
            return 0.0
        return self._lamb_wd

    def _update_rule(self, p, g, lr, t, wd, state):
        m = self._beta1 * state["moment1"] + (1 - self._beta1) * g
        v = self._beta2 * state["moment2"] + (1 - self._beta2) * g * g
        tf = t.astype(jnp.float32)
        m_hat = m / (1 - self._beta1 ** tf)
        v_hat = v / (1 - self._beta2 ** tf)
        r = m_hat / (jnp.sqrt(v_hat) + self._eps) + wd.astype(p.dtype) * p
        w_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
        r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return p - lr.astype(p.dtype) * trust * r, \
            {"moment1": m, "moment2": v}
