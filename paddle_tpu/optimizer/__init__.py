from . import lr  # noqa: F401
from .optimizer import (  # noqa: F401
    SGD, Adadelta, Adagrad, Adam, Adamax, AdamW, Lamb, LarsMomentum,
    Momentum, Optimizer, RMSProp,
)

# reference compat name (fluid/optimizer.py:1786)
LarsMomentumOptimizer = LarsMomentum
