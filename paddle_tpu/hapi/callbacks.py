"""hapi callbacks (reference: /root/reference/python/paddle/hapi/
callbacks.py — Callback base :97, ProgBarLogger :284, ModelCheckpoint
:575, LRScheduler :661, EarlyStopping :737, config_callbacks :40)."""
from __future__ import annotations

import numbers
import os
import time

import numpy as np

__all__ = ["Callback", "CallbackList", "ProgBarLogger", "ModelCheckpoint",
           "ElasticCheckpoint", "LRScheduler", "EarlyStopping",
           "config_callbacks"]


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = dict(params or {})

    # -- hooks (reference callback surface)
    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks=None):
        self.callbacks = list(callbacks or [])

    def append(self, cb):
        self.callbacks.append(cb)

    def set_model(self, model):
        for cb in self.callbacks:
            cb.set_model(model)

    def set_params(self, params):
        for cb in self.callbacks:
            cb.set_params(params)

    def __getattr__(self, name):
        if not name.startswith("on_"):
            raise AttributeError(name)

        def call(*args, **kwargs):
            for cb in self.callbacks:
                getattr(cb, name)(*args, **kwargs)

        return call


class ProgBarLogger(Callback):
    """Per-step/epoch console logging (reference :284)."""

    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch
        self._t0 = time.time()

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            items = ", ".join(
                f"{k}: {v:.4f}" if isinstance(v, numbers.Number) else
                f"{k}: {v}" for k, v in (logs or {}).items())
            epochs = self.params.get("epochs")
            print(f"Epoch {self._epoch + 1}/{epochs} step {step}: {items}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._t0
            print(f"Epoch {epoch + 1} done in {dt:.1f}s")


class ModelCheckpoint(Callback):
    """Periodic save (reference :575): <save_dir>/<epoch>.pdparams +
    final.pdparams at train end."""

    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            os.makedirs(self.save_dir, exist_ok=True)
            self.model.save(os.path.join(self.save_dir, str(epoch)))

    def on_train_end(self, logs=None):
        if self.save_dir:
            os.makedirs(self.save_dir, exist_ok=True)
            self.model.save(os.path.join(self.save_dir, "final"))


class ElasticCheckpoint(Callback):
    """Preemption-tolerant checkpointing for ``Model.fit`` — the hapi
    face of ``paddle_tpu.elastic.CheckpointManager``. Unlike
    :class:`ModelCheckpoint` (per-epoch ``model.save``), this captures
    FULL training state (optimizer slots, LR step, RNG) every
    ``save_interval_steps`` global steps into an atomic, kill-9-safe
    checkpoint directory, restores it when training starts, and wires
    SIGTERM/SIGINT to a final bounded-deadline save.

    ``fit`` replays data from the epoch start, so after a restore the
    already-covered steps of the interrupted epoch are re-run — state
    is never wrong, some work may repeat (job-level elasticity,
    SURVEY §5.3). The restore result is exposed as ``.restored``."""

    def __init__(self, directory, save_interval_steps=None,
                 save_interval_s=None, keep=None, restore=True,
                 preemption_handlers=True):
        super().__init__()
        self.directory = directory
        self._kw = {"save_interval_steps": save_interval_steps,
                    "save_interval_s": save_interval_s, "keep": keep}
        self._restore = restore
        self._preempt = preemption_handlers
        self.manager = None
        self.restored = None
        self._global_step = 0
        self._epoch = 0

    def on_train_begin(self, logs=None):
        from ..elastic import CheckpointManager
        if self.manager is None:
            self.manager = CheckpointManager(
                self.directory, model=self.model.network,
                optimizer=getattr(self.model, "_optimizer", None),
                **{k: v for k, v in self._kw.items() if v is not None})
        if self._restore:
            self.restored = self.manager.restore_latest()
            if self.restored is not None:
                self._global_step = self.restored.step
                self._epoch = self.restored.epoch or 0
        if self._preempt:
            self.manager.install_preemption_handlers()

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch

    def on_train_batch_end(self, step, logs=None):
        self._global_step += 1
        self.manager.step(self._global_step, epoch=self._epoch,
                          offset=step)

    def on_train_end(self, logs=None):
        if self.manager is not None:
            self.manager.save(self._global_step, epoch=self._epoch,
                              block=True, reason="final")
            self.manager.close()


class LRScheduler(Callback):
    """Steps the optimizer's LRScheduler (reference :661)."""

    def __init__(self, by_step=False, by_epoch=True):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_lr", None) if opt is not None else None
        return lr if hasattr(lr, "step") else None

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            s = self._sched()
            if s is not None:
                s.step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            s = self._sched()
            if s is not None:
                s.step()


class EarlyStopping(Callback):
    """Stop when a monitored metric stops improving (reference :737)."""

    def __init__(self, monitor="loss", mode="auto", patience=0,
                 verbose=1, min_delta=0, baseline=None,
                 save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.verbose = verbose
        self.save_best_model = save_best_model
        if mode == "auto":
            mode = "min" if "loss" in monitor else "max"
        self.mode = mode
        self._op = (lambda a, b: a < b - self.min_delta) if mode == "min" \
            else (lambda a, b: a > b + self.min_delta)
        self.best = None
        self.wait = 0
        self.stopped_epoch = -1

    def _value(self, logs):
        v = (logs or {}).get(self.monitor)
        if isinstance(v, (list, tuple, np.ndarray)):
            v = float(np.asarray(v).reshape(-1)[0])
        return v

    def _snapshot(self):
        if self.save_best_model and self.model is not None:
            net = getattr(self.model, "network", None)
            if net is not None:
                self._best_state = {
                    k: np.asarray(t.numpy()).copy()
                    for k, t in net.state_dict().items()}

    def on_eval_end(self, logs=None):
        v = self._value(logs)
        if v is None:
            return
        if self.best is None:
            # first eval establishes the baseline; it is not a "wait".
            # With an explicit baseline, the current weights only become
            # the restore candidate once a later eval BEATS the baseline.
            self.best = v if self.baseline is None else self.baseline
            if self.baseline is None:
                self._snapshot()
                return
        if self._op(v, self.best):
            self.best = v
            self.wait = 0
            self._snapshot()
        else:
            self.wait += 1
            if self.wait > self.patience:
                self.model.stop_training = True
                restored = ""
                if self.save_best_model and \
                        getattr(self, "_best_state", None) is not None:
                    self.model.network.set_state_dict(self._best_state)
                    restored = (f" (best {self.monitor}={self.best:.4f} "
                                f"restored)")
                if self.verbose:
                    print(f"EarlyStopping: no {self.monitor} improvement "
                          f"for {self.wait} evals; stopping{restored}")


def config_callbacks(callbacks=None, model=None, epochs=None, steps=None,
                     log_freq=1, verbose=2, save_freq=1, save_dir=None,
                     metrics=None, mode="train"):
    """reference :40 — normalize the callback list, injecting defaults."""
    cbs = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbs) and verbose:
        cbs.append(ProgBarLogger(log_freq, verbose=verbose))
    if not any(isinstance(c, ModelCheckpoint) for c in cbs) and save_dir:
        cbs.append(ModelCheckpoint(save_freq, save_dir))
    if not any(isinstance(c, LRScheduler) for c in cbs) and model is not None:
        cbs.append(LRScheduler())
    if mode == "train":
        try:
            from ..framework.flags import flag_value
            if flag_value("FLAGS_training_telemetry"):
                from ..observability.training import \
                    TrainingTelemetryCallback
                if not any(isinstance(c, TrainingTelemetryCallback)
                           for c in cbs):
                    cbs.append(TrainingTelemetryCallback())
        except Exception:  # noqa: BLE001 - telemetry is additive; fit
            pass           # must run even if the registry is broken
    clist = CallbackList(cbs)
    clist.set_model(model)
    clist.set_params({"epochs": epochs, "steps": steps, "verbose": verbose,
                      "metrics": metrics or []})
    return clist
