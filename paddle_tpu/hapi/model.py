"""High-level Model API (reference: /root/reference/python/paddle/hapi/model.py:1045,
fit at :1740) — Keras-like train/eval/predict over a Layer."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from ..io import DataLoader


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._loss = None
        self._optimizer = None
        self._metrics = []
        self.stop_training = False

    def prepare(self, optimizer=None, loss=None, metrics=None, **kwargs):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is not None:
            self._metrics = metrics if isinstance(metrics, (list, tuple)) \
                else [metrics]

    def _loader(self, data, batch_size, shuffle):
        if data is None or isinstance(data, DataLoader):
            return data
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          drop_last=shuffle)

    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if labels is None or isinstance(labels, (list, tuple)) \
            else [labels]
        outputs = self.network(*inputs)
        losses = self._loss(outputs, *labels) if labels else self._loss(outputs)
        losses.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        metrics = []
        for m in self._metrics:
            m.update(m.compute(outputs, *labels))
        return [float(losses.numpy())], [m.accumulate() for m in self._metrics]

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if labels is None or isinstance(labels, (list, tuple)) \
            else [labels]
        outputs = self.network(*inputs)
        losses = self._loss(outputs, *labels) if self._loss else None
        for m in self._metrics:
            m.update(m.compute(outputs, *labels))
        return ([float(losses.numpy())] if losses is not None else [],
                [m.accumulate() for m in self._metrics])

    def predict_batch(self, inputs):
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        from ..core.autograd import no_grad
        with no_grad():
            return self.network(*inputs)

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            **kwargs):
        from .callbacks import config_callbacks
        loader = self._loader(train_data, batch_size, shuffle)
        cbks = config_callbacks(callbacks, model=self, epochs=epochs,
                                log_freq=log_freq, verbose=verbose,
                                save_freq=save_freq, save_dir=save_dir,
                                metrics=[m.name() for m in self._metrics])
        history = {"loss": []}
        self.stop_training = False
        cbks.on_train_begin()
        for epoch in range(epochs):
            for m in self._metrics:
                m.reset()
            cbks.on_epoch_begin(epoch)
            for step, batch in enumerate(loader):
                cbks.on_train_batch_begin(step)
                x, y = batch[0], batch[1] if len(batch) > 1 else None
                loss, metrics = self.train_batch(x, y)
                history["loss"].append(loss[0])
                logs = {"loss": loss[0]}
                for m, v in zip(self._metrics, metrics):
                    logs[m.name()] = v
                cbks.on_train_batch_end(step, logs)
            cbks.on_epoch_end(epoch, {"loss": history["loss"][-1]
                                      if history["loss"] else None})
            if eval_data is not None and (epoch + 1) % eval_freq == 0:
                self.evaluate(eval_data, batch_size=batch_size,
                              verbose=verbose, callbacks=cbks)
            if self.stop_training:
                break
        cbks.on_train_end()
        return history

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, **kwargs):
        from .callbacks import CallbackList
        if isinstance(callbacks, CallbackList):
            cbks = callbacks
        else:
            cbks = CallbackList(callbacks or [])
            cbks.set_model(self)
        loader = self._loader(eval_data, batch_size, False)
        for m in self._metrics:
            m.reset()
        cbks.on_eval_begin()
        losses = []
        for step, batch in enumerate(loader):
            cbks.on_eval_batch_begin(step)
            x, y = batch[0], batch[1] if len(batch) > 1 else None
            loss, _ = self.eval_batch(x, y)
            losses.extend(loss)
            cbks.on_eval_batch_end(step, {"loss": loss[0] if loss else None})
        result = {"loss": [float(np.mean(losses))] if losses else []}
        for m in self._metrics:
            result[m.name()] = m.accumulate()
        cbks.on_eval_end({"loss": result["loss"][0] if result["loss"]
                          else None, **{m.name(): result[m.name()]
                                        for m in self._metrics}})
        if verbose:
            print("Eval:", result)
        return result

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, callbacks=None, verbose=1, **kwargs):
        loader = self._loader(test_data, batch_size, False)
        outputs = []
        for batch in loader:
            x = batch[0] if isinstance(batch, (list, tuple)) else batch
            outputs.append(self.predict_batch(x))
        return outputs

    def save(self, path, training=True):
        import paddle_tpu as P
        P.save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            P.save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        import paddle_tpu as P
        self.network.set_state_dict(P.load(path + ".pdparams"))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        from .summary import summary as _summary
        return _summary(self.network, input_size, dtypes=dtype)
