"""High-level Model API (reference: /root/reference/python/paddle/hapi/model.py:1045,
fit at :1740) — Keras-like train/eval/predict over a Layer."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from ..io import DataLoader


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._loss = None
        self._optimizer = None
        self._metrics = []
        self._inputs = inputs if inputs is None or isinstance(
            inputs, (list, tuple)) else [inputs]
        self._labels = labels
        self._amp_level = None
        self._scaler = None
        self.stop_training = False

    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None, **kwargs):
        """reference Model.prepare (hapi/model.py:1565): wires optimizer,
        loss, metrics and AMP. ``amp_configs`` accepts "O1"/"O2" or a dict
        with "level" (+ optional GradScaler init args)."""
        self._optimizer = optimizer
        self._loss = loss
        if metrics is not None:
            self._metrics = metrics if isinstance(metrics, (list, tuple)) \
                else [metrics]
        if amp_configs is not None:
            if isinstance(amp_configs, str):
                amp_configs = {"level": amp_configs}
            self._amp_level = amp_configs.get("level", "O1")
            if self._amp_level not in ("O0", None):
                from ..amp import GradScaler
                scaler_kw = {k: v for k, v in amp_configs.items()
                             if k in ("init_loss_scaling", "incr_ratio",
                                      "decr_ratio", "incr_every_n_steps",
                                      "decr_every_n_nan_or_inf")}
                self._scaler = GradScaler(enable=True, **scaler_kw)

    def _loader(self, data, batch_size, shuffle, num_workers=0,
                distributed=True):
        if data is None or isinstance(data, DataLoader):
            return data
        from ..distributed import get_world_size
        if distributed and get_world_size() > 1:
            # distributed fit: each rank consumes its own shard of the
            # dataset (reference fit() builds a DistributedBatchSampler,
            # hapi/model.py:1774)
            from ..io import DistributedBatchSampler
            sampler = DistributedBatchSampler(
                data, batch_size=batch_size, shuffle=shuffle,
                drop_last=shuffle)
            return DataLoader(data, batch_sampler=sampler,
                              num_workers=num_workers)
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          drop_last=shuffle, num_workers=num_workers)

    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if labels is None or isinstance(labels, (list, tuple)) \
            else [labels]
        if self._amp_level and self._amp_level != "O0":
            from ..amp import auto_cast
            with auto_cast(level=self._amp_level):
                outputs = self.network(*inputs)
                losses = self._loss(outputs, *labels) if labels \
                    else self._loss(outputs)
            if self._scaler is not None:
                scaled = self._scaler.scale(losses)
                scaled.backward()
                if update:
                    self._scaler.step(self._optimizer)
                    self._scaler.update()
                    self._optimizer.clear_grad()
            else:
                losses.backward()
                if update:
                    self._optimizer.step()
                    self._optimizer.clear_grad()
        else:
            outputs = self.network(*inputs)
            losses = self._loss(outputs, *labels) if labels \
                else self._loss(outputs)
            losses.backward()
            if update:
                self._optimizer.step()
                self._optimizer.clear_grad()
        metrics = []
        for m in self._metrics:
            m.update(m.compute(outputs, *labels))
        return [float(losses.numpy())], [m.accumulate() for m in self._metrics]

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if labels is None or isinstance(labels, (list, tuple)) \
            else [labels]
        outputs = self.network(*inputs)
        losses = self._loss(outputs, *labels) if self._loss else None
        for m in self._metrics:
            m.update(m.compute(outputs, *labels))
        return ([float(losses.numpy())] if losses is not None else [],
                [m.accumulate() for m in self._metrics])

    def predict_batch(self, inputs):
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        from ..core.autograd import no_grad
        with no_grad():
            return self.network(*inputs)

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            **kwargs):
        from .callbacks import config_callbacks
        loader = self._loader(train_data, batch_size, shuffle, num_workers)
        cbks = config_callbacks(callbacks, model=self, epochs=epochs,
                                log_freq=log_freq, verbose=verbose,
                                save_freq=save_freq, save_dir=save_dir,
                                metrics=[m.name() for m in self._metrics])
        history = {"loss": []}
        self.stop_training = False
        cbks.on_train_begin()
        for epoch in range(epochs):
            for m in self._metrics:
                m.reset()
            cbks.on_epoch_begin(epoch)
            for step, batch in enumerate(loader):
                cbks.on_train_batch_begin(step)
                x, y = batch[0], batch[1] if len(batch) > 1 else None
                loss, metrics = self.train_batch(x, y)
                history["loss"].append(loss[0])
                logs = {"loss": loss[0]}
                for m, v in zip(self._metrics, metrics):
                    logs[m.name()] = v
                cbks.on_train_batch_end(step, logs)
            epoch_logs = {"loss": history["loss"][-1]
                          if history["loss"] else None}
            for m, v in zip(self._metrics,
                            [m.accumulate() for m in self._metrics]):
                epoch_logs[m.name()] = v
            if eval_data is not None and (epoch + 1) % eval_freq == 0:
                eval_result = self.evaluate(eval_data,
                                            batch_size=batch_size,
                                            verbose=verbose, callbacks=cbks)
                # thread eval metrics into the epoch logs (reference fit
                # reports eval_<metric> per epoch) so EarlyStopping /
                # ReduceLROnPlateau callbacks can monitor them
                for k, v in eval_result.items():
                    epoch_logs[f"eval_{k}"] = v[0] if isinstance(
                        v, (list, tuple)) and v else v
                history.setdefault("eval_loss", []).extend(
                    eval_result.get("loss", []))
            cbks.on_epoch_end(epoch, epoch_logs)
            if self.stop_training:
                break
        cbks.on_train_end()
        return history

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, **kwargs):
        from .callbacks import CallbackList
        if isinstance(callbacks, CallbackList):
            cbks = callbacks
        else:
            cbks = CallbackList(callbacks or [])
            cbks.set_model(self)
        # evaluation runs the FULL dataset on every rank (not a shard):
        # rank-local metrics feed callbacks (EarlyStopping) whose decisions
        # must agree across ranks, or collective training hangs
        loader = self._loader(eval_data, batch_size, False,
                              distributed=False)
        for m in self._metrics:
            m.reset()
        cbks.on_eval_begin()
        losses = []
        for step, batch in enumerate(loader):
            cbks.on_eval_batch_begin(step)
            x, y = batch[0], batch[1] if len(batch) > 1 else None
            loss, _ = self.eval_batch(x, y)
            losses.extend(loss)
            cbks.on_eval_batch_end(step, {"loss": loss[0] if loss else None})
        result = {"loss": [float(np.mean(losses))] if losses else []}
        for m in self._metrics:
            result[m.name()] = m.accumulate()
        cbks.on_eval_end({"loss": result["loss"][0] if result["loss"]
                          else None, **{m.name(): result[m.name()]
                                        for m in self._metrics}})
        if verbose:
            print("Eval:", result)
        return result

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, callbacks=None, verbose=1, **kwargs):
        loader = self._loader(test_data, batch_size, False)
        outputs = []
        for batch in loader:
            x = batch[0] if isinstance(batch, (list, tuple)) else batch
            outputs.append(self.predict_batch(x))
        return outputs

    def save(self, path, training=True):
        import paddle_tpu as P
        if not training:
            # inference export (reference Model.save(training=False) →
            # save_inference_model): requires the input spec given at
            # construction, exports through paddle.jit.save
            if not self._inputs:
                raise ValueError(
                    "Model.save(training=False) needs inputs= InputSpec "
                    "at Model() construction to trace the export")
            from ..jit.api import save as jit_save
            jit_save(self.network, path, input_spec=list(self._inputs))
            return
        P.save(self.network.state_dict(), path + ".pdparams")
        if self._optimizer is not None:
            P.save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        import paddle_tpu as P
        self.network.set_state_dict(P.load(path + ".pdparams"))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        from .summary import summary as _summary
        return _summary(self.network, input_size, dtypes=dtype)
