"""paddle.summary (reference: /root/reference/python/paddle/hapi/model_summary.py)."""
from __future__ import annotations

import numpy as np


def summary(net, input_size=None, dtypes=None, input=None):  # noqa: A002
    total_params = 0
    trainable_params = 0
    rows = []
    for name, p in net.named_parameters():
        n = p.size
        total_params += n
        if not p.stop_gradient:
            trainable_params += n
        rows.append((name, tuple(p.shape), n))
    width = max((len(r[0]) for r in rows), default=20) + 2
    lines = [f"{'Param':<{width}}{'Shape':<20}{'Count':>12}"]
    for name, shape, n in rows:
        lines.append(f"{name:<{width}}{str(shape):<20}{n:>12,}")
    lines.append("-" * (width + 32))
    lines.append(f"Total params: {total_params:,}")
    lines.append(f"Trainable params: {trainable_params:,}")
    print("\n".join(lines))
    return {"total_params": total_params, "trainable_params": trainable_params}
