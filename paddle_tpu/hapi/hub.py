"""paddle.hub — hubconf.py-driven model discovery.

Reference: python/paddle/hapi/hub.py (list/help/load at :175/:223/:268)
supporting github/gitee/local sources. No network egress here, so only
``source='local'`` is functional; remote sources raise with a clear
message. The hubconf contract matches the reference: a repo directory
containing ``hubconf.py`` whose public callables are the entrypoints and
whose ``dependencies`` list is checked before loading.
"""
from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]

_HUBCONF = "hubconf.py"


def _check_source(source):
    if source not in ("github", "gitee", "local"):
        raise ValueError(
            f"unknown source {source!r}: expected github/gitee/local")
    if source != "local":
        raise RuntimeError(
            "paddle.hub: remote sources (github/gitee) need network "
            "access, which this environment does not have. Clone the "
            "repo locally and use source='local'.")


def _import_hubconf(repo_dir):
    path = os.path.join(repo_dir, _HUBCONF)
    if not os.path.exists(path):
        raise FileNotFoundError(f"no {_HUBCONF} found in {repo_dir}")
    spec = importlib.util.spec_from_file_location("paddle_tpu_hubconf", path)
    module = importlib.util.module_from_spec(spec)
    sys.path.insert(0, repo_dir)
    try:
        spec.loader.exec_module(module)
    finally:
        sys.path.remove(repo_dir)
    deps = getattr(module, "dependencies", [])
    missing = []
    for d in deps:
        if importlib.util.find_spec(d) is None:
            missing.append(d)
    if missing:
        raise RuntimeError(
            f"hubconf dependencies not installed: {missing}")
    return module


def _entrypoints(module):
    return {
        name: fn for name, fn in vars(module).items()
        if callable(fn) and not name.startswith("_")
    }


def list(repo_dir, source="local", force_reload=False):
    """Names of all entrypoints exposed by the repo's hubconf.py."""
    _check_source(source)
    return sorted(_entrypoints(_import_hubconf(repo_dir)))


def help(repo_dir, model, source="local", force_reload=False):
    """Docstring of one hubconf entrypoint."""
    _check_source(source)
    eps = _entrypoints(_import_hubconf(repo_dir))
    if model not in eps:
        raise RuntimeError(
            f"entrypoint {model!r} not found; available: {sorted(eps)}")
    return eps[model].__doc__


def load(repo_dir, model, source="local", force_reload=False, **kwargs):
    """Instantiate one hubconf entrypoint with **kwargs."""
    _check_source(source)
    eps = _entrypoints(_import_hubconf(repo_dir))
    if model not in eps:
        raise RuntimeError(
            f"entrypoint {model!r} not found; available: {sorted(eps)}")
    return eps[model](**kwargs)
