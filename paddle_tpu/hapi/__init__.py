from .model import Model  # noqa: F401
from .summary import summary  # noqa: F401
from . import callbacks  # noqa: F401
