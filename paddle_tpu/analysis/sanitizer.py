"""Runtime lockdep: observe the lock order the program *actually*
uses, and fail fast on inversions.

The static half (``analysis.lock_order``) proves what the source
*could* do; this module watches what a running process *does*. It is
a Linux-lockdep-style sanitizer for ``threading`` primitives:

* ``install()`` patches ``threading.Lock``/``RLock``/``Condition``
  with instrumented factories. Only locks constructed from code
  inside the repository root are instrumented (a cheap frame walk at
  construction time); third-party and stdlib internals get the native
  primitive back — zero overhead, zero compatibility risk outside
  our own code.
* Each instrumented lock belongs to a **lock class** keyed by its
  construction site (``file:line``), the lockdep trick that keeps the
  order graph bounded no matter how many instances a test suite
  creates: every ``FleetRouter.__init__`` run yields the same class.
* Every acquire pushes onto a per-thread stack; the first time class
  B is acquired while class A is held, the edge A->B joins the
  observed-order graph. If B->A was already observed, that is an
  **inversion** — a deadlock waiting for the right interleaving —
  and it is reported the first time it is *seen*, not the day it
  finally hangs: recorded always, raised as ``LockdepViolation`` in
  the acquiring thread when ``FLAGS_lockdep_raise`` is set.
* Holds longer than ``FLAGS_lockdep_hold_warn_ms`` are recorded as
  hold-time warnings (the runtime twin of static LD002: a long hold
  under traffic is a convoy).

``report()`` returns everything observed; the tier-1 conftest
installs the sanitizer when ``FLAGS_lockdep`` is set and fails any
test on whose watch a new violation appeared, so the whole suite
runs sanitized. ``findings()`` bridges the report into pdlint
``Finding`` objects (rules LD001/LD002 with a ``runtime:`` detail
prefix) so runtime evidence rides the same SARIF pipeline as static
results.

Everything here is stdlib-only and must stay importable with no
side effects; nothing is patched until ``install()``.
"""
# pdlint: disable=resource_pairing  -- this module IS the lock
# implementation: acquire/release intentionally pair across methods
# (__enter__/__exit__, _release_save/_acquire_restore)
from __future__ import annotations

import os
import threading
import time
import sys
from typing import Dict, List, Optional, Set, Tuple

from ..framework.flags import flag_ref

# live registry objects, bound once — the acquire/release hot path
# reads .value off them instead of a registry lookup per call
_HOLD_WARN_MS = flag_ref("FLAGS_lockdep_hold_warn_ms")
_RAISE_ON_INVERSION = flag_ref("FLAGS_lockdep_raise")

__all__ = [
    "LockdepViolation", "install", "uninstall", "installed",
    "report", "reset", "findings", "repo_root",
    "set_root_for_tests",
]

# the real primitives, captured before any patching
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_CONDITION = threading.Condition

_THIS_DIR = os.path.dirname(os.path.abspath(__file__))
_root_override: Optional[str] = None


class LockdepViolation(RuntimeError):
    """Raised in the acquiring thread on the first observed
    lock-order inversion for a lock-class pair."""


def repo_root() -> str:
    """The directory whose code gets instrumented locks: the
    repository root (two levels above ``paddle_tpu.analysis``),
    unless overridden via ``set_root_for_tests``."""
    if _root_override is not None:
        return _root_override
    return os.path.dirname(os.path.dirname(_THIS_DIR))


def set_root_for_tests(path: Optional[str]) -> None:
    """Point the instrumentation boundary somewhere else (self-tests
    construct locks from tmp files / interactive frames that are not
    under the repo checkout). ``None`` restores the default."""
    global _root_override
    _root_override = path


# ===================================================================
# global sanitizer state
# ===================================================================
class _State:
    def __init__(self):
        self.mu = _REAL_LOCK()            # guards everything below
        # observed order: class A -> {class B: (thread, stacknote)}
        self.order: Dict[str, Dict[str, str]] = {}
        self.inversions: List[dict] = []
        self.long_holds: List[dict] = []
        self.seen_pairs: Set[Tuple[str, str]] = set()
        self.classes: Dict[str, int] = {}   # class -> instances made
        self.acquires = 0

    def snapshot(self) -> dict:
        with self.mu:
            return {
                "classes": dict(self.classes),
                "edges": {a: sorted(bs) for a, bs in
                          sorted(self.order.items())},
                "inversions": list(self.inversions),
                "long_holds": list(self.long_holds),
                "acquires": self.acquires,
            }


_state = _State()
_tls = threading.local()
# Bumped on reset(): per-thread seen-edge sets are keyed on it so a
# reset invalidates every thread's fast-path cache, not just the
# resetting thread's.
_GEN = 0


def _held_stack() -> list:
    st = getattr(_tls, "held", None)
    if st is None:
        st = []
        _tls.held = st
    return st


def _thread_seen() -> set:
    """This thread's already-recorded (held, acquired) class pairs —
    the acquire fast path consults it instead of the global state."""
    if getattr(_tls, "gen", -1) != _GEN:
        _tls.gen = _GEN
        _tls.seen_edges = set()
    return _tls.seen_edges


def _site_class(skip_self: bool = True) -> Optional[str]:
    """Construction-site lock class ``rel:line`` for the innermost
    caller frame inside the repo root, or None (-> don't
    instrument). Skips sanitizer and threading frames."""
    root = repo_root() + os.sep
    f = sys._getframe(2)
    for _ in range(12):                    # bounded walk
        if f is None:
            return None
        fn = f.f_code.co_filename
        if fn.startswith(_THIS_DIR) or fn.endswith("threading.py"):
            f = f.f_back
            continue
        if fn.startswith(root):
            rel = os.path.relpath(fn, repo_root())
            return f"{rel}:{f.f_lineno}"
        return None
    return None


def _record_acquired(cls: str, t0: float):
    """Called with the lock just acquired: order-graph bookkeeping.

    Fast path: every (held, acquiring) class pair this thread has
    already processed costs one thread-local set lookup and NO global
    lock — steady-state traffic over a stable locking pattern runs
    with zero cross-thread serialization.  Only the first time a
    thread meets a pair does it enter the slow path, which updates the
    shared order graph under ``_state.mu`` and runs the inversion
    check.  An inversion is still always caught: whichever thread is
    first to record the second orientation has, by definition, never
    seen that pair before, so it cannot skip the check.

    Raises LockdepViolation on a fresh inversion when configured."""
    held = _held_stack()
    _state.acquires += 1      # informational; unlocked by design
    if held:
        seen = _thread_seen()
        fresh = [p for p, _ in held
                 if p != cls and (p, cls) not in seen]
        if fresh:
            raise_msg = _record_pairs(cls, fresh, seen)
            held.append((cls, t0))
            if raise_msg is not None:
                raise LockdepViolation(raise_msg)
            return
    held.append((cls, t0))


def _record_pairs(cls: str, fresh: list, seen: set) -> Optional[str]:
    """Slow path: merge this thread's new order edges into the global
    graph and check each against the reverse orientation."""
    raise_msg = None
    with _state.mu:
        for prev_cls in fresh:
            pair = (prev_cls, cls)
            _state.order.setdefault(prev_cls, {}).setdefault(
                cls, threading.current_thread().name)
            rev = _state.order.get(cls, {})
            if prev_cls in rev and pair not in _state.seen_pairs \
                    and (cls, prev_cls) not in _state.seen_pairs:
                _state.seen_pairs.add(pair)
                info = {
                    "kind": "inversion",
                    "first": cls, "second": prev_cls,
                    "thread": threading.current_thread().name,
                    "note": (f"{prev_cls} -> {cls} observed here; "
                             f"{cls} -> {prev_cls} observed "
                             f"earlier by {rev[prev_cls]}"),
                }
                _state.inversions.append(info)
                if _RAISE_ON_INVERSION.value:
                    raise_msg = (
                        f"lock-order inversion: acquiring {cls} "
                        f"while holding {prev_cls}, but the "
                        f"opposite order was already observed "
                        f"({info['note']}) — potential deadlock")
    for prev_cls in fresh:
        seen.add((prev_cls, cls))
    return raise_msg


def _record_released(cls: str):
    held = _held_stack()
    for i in range(len(held) - 1, -1, -1):
        if held[i][0] == cls:
            t0 = held[i][1]
            del held[i]
            warn_ms = _HOLD_WARN_MS.value or 0.0
            if warn_ms > 0:
                held_ms = (time.perf_counter() - t0) * 1e3
                if held_ms > warn_ms:
                    with _state.mu:
                        _state.long_holds.append({
                            "kind": "long_hold", "cls": cls,
                            "held_ms": round(held_ms, 3),
                            "thread":
                                threading.current_thread().name,
                        })
            return


# ===================================================================
# instrumented primitives
# ===================================================================
class _InstrumentedBase:
    """Shared acquire/release bookkeeping over an inner native lock.

    Implements the private Condition protocol (``_is_owned``,
    ``_release_save``, ``_acquire_restore``) so a real
    ``threading.Condition`` can drive an instrumented lock."""

    _reentrant = False

    def __init__(self, inner, cls: str):
        self._inner = inner
        self._cls = cls
        self._depth = 0                   # meaningful for RLock only

    # -- core ----------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            if self._reentrant and self._depth > 0:
                self._depth += 1          # nested: no new hold
            else:
                self._depth = 1
                # clock AFTER acquisition: hold time measures how long
                # the lock was held, not how long we waited for it
                t0 = time.perf_counter()
                try:
                    _record_acquired(self._cls, t0)
                except LockdepViolation:
                    # abort the violating acquire entirely: the
                    # caller does NOT hold the lock after the raise
                    held = _held_stack()
                    for i in range(len(held) - 1, -1, -1):
                        if held[i][0] == self._cls:
                            del held[i]
                            break
                    self._depth = 0
                    self._inner.release()
                    raise
        return got

    def release(self):
        if self._reentrant and self._depth > 1:
            self._depth -= 1
            self._inner.release()
            return
        self._depth = 0
        # release FIRST, bookkeep after: the sanitizer must not
        # lengthen the critical section waiters are blocked on (and an
        # unowned release raises before any bookkeeping runs)
        self._inner.release()
        _record_released(self._cls)

    def locked(self):
        return self._inner.locked() if hasattr(self._inner, "locked") \
            else self._depth > 0

    # stdlib Lock/RLock alias __enter__ to acquire (the context value
    # is the acquire result, not the lock) — mirror it, one call less
    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return (f"<lockdep {type(self).__name__} class={self._cls} "
                f"inner={self._inner!r}>")

    # -- Condition protocol --------------------------------------
    def _is_owned(self):
        inner = self._inner
        if hasattr(inner, "_is_owned"):
            return inner._is_owned()
        # plain Lock: owned iff locked and not acquirable
        if inner.acquire(False):
            inner.release()
            return False
        return True

    def _release_save(self):
        depth = self._depth
        self._depth = 0
        if hasattr(self._inner, "_release_save"):
            state = self._inner._release_save()
        else:
            self._inner.release()
            state = None
        _record_released(self._cls)
        return (depth, state)

    def _acquire_restore(self, saved):
        depth, state = saved
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(state)
        else:
            self._inner.acquire()
        self._depth = depth
        _record_acquired(self._cls, time.perf_counter())


class _InstrumentedLock(_InstrumentedBase):
    _reentrant = False


class _InstrumentedRLock(_InstrumentedBase):
    _reentrant = True


def _track_class(cls: Optional[str]) -> Optional[str]:
    if cls is None:
        return None
    with _state.mu:
        _state.classes[cls] = _state.classes.get(cls, 0) + 1
    return cls


def _lock_factory():
    cls = _track_class(_site_class())
    if cls is None:
        return _REAL_LOCK()
    return _InstrumentedLock(_REAL_LOCK(), cls)


def _rlock_factory():
    cls = _track_class(_site_class())
    if cls is None:
        return _REAL_RLOCK()
    return _InstrumentedRLock(_REAL_RLOCK(), cls)


def _condition_factory(lock=None):
    if lock is None:
        cls = _track_class(_site_class())
        if cls is None:
            return _REAL_CONDITION()
        lock = _InstrumentedRLock(_REAL_RLOCK(), cls)
    # a REAL Condition driving the instrumented lock through the
    # Condition protocol; its internal waiter locks come from
    # _thread.allocate_lock and are never instrumented
    return _REAL_CONDITION(lock)


# ===================================================================
# install / report
# ===================================================================
_installed = False


def install() -> None:
    """Patch ``threading.Lock``/``RLock``/``Condition`` with the
    instrumented factories. Idempotent."""
    global _installed
    if _installed:
        return
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    threading.Condition = _condition_factory
    _installed = True


def uninstall() -> None:
    """Restore the native primitives. Already-created instrumented
    locks keep working (they wrap real locks)."""
    global _installed
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    threading.Condition = _REAL_CONDITION
    _installed = False


def installed() -> bool:
    return _installed


def report() -> dict:
    """Everything observed so far: lock classes, the order graph,
    inversions, long holds, total acquire count."""
    return _state.snapshot()


def reset() -> None:
    """Clear observed state (tests). Does not uninstall."""
    global _state, _GEN
    _state = _State()
    _GEN += 1          # invalidate every thread's fast-path cache


def findings() -> List["Finding"]:
    """Bridge the runtime report into pdlint findings: inversions as
    LD001, long holds as LD002, both with a ``runtime:`` detail
    prefix so they are distinguishable from static results in SARIF
    and never collide with the static baseline."""
    from .core import Finding
    snap = report()
    out: List[Finding] = []
    for inv in snap["inversions"]:
        path, _, line = inv["first"].partition(":")
        out.append(Finding(
            "lockdep", "LD001", path, int(line or 0), 0,
            f"runtime lock-order inversion: {inv['note']} "
            f"(thread {inv['thread']})",
            symbol=inv["first"],
            detail=f"runtime:{inv['first']}<->{inv['second']}"))
    for h in snap["long_holds"]:
        path, _, line = h["cls"].partition(":")
        out.append(Finding(
            "lockdep", "LD002", path, int(line or 0), 0,
            f"lock held {h['held_ms']} ms (> "
            f"FLAGS_lockdep_hold_warn_ms) by thread "
            f"{h['thread']} — long holds under traffic are "
            f"convoys",
            symbol=h["cls"], detail=f"runtime:hold:{h['cls']}",
            severity="warning"))
    return out
