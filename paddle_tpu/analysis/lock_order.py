"""Lock-order analyzer: the system of locks, not just each lock.

``lock_discipline`` (LK001-3) checks that individual attributes are
guarded; nothing checked that the *collection* of locks the serving
stack now carries (router + breakers, supervisor, engine Condition,
autoscaler, watchdogs, telemetry handlers) is deadlock-free, or that
no thread blocks on I/O while holding one. This analyzer is the
static half of the PR 19 lockdep pair (``analysis.sanitizer`` is the
runtime half): it computes lock-acquisition paths through the PR 14
repo-wide call graph, builds a global lock-order graph keyed by
``(module, attr)`` lock identity, and reports:

  LD001  lock-order inversion: a cycle in the observed acquisition-
         order graph (some path acquires A then B, another B then A)
         — a potential deadlock the moment both paths run
         concurrently.
  LD002  blocking call while a lock is held — socket/HTTP I/O
         (``urlopen``, opener ``.open``, ``create_connection``),
         ``subprocess`` spawn/wait, timeout-less ``queue.get()`` /
         ``Future.result()`` / ``.wait()`` / ``.join()``, and device
         sync (``.block_until_ready()``, ``jax.device_get``).
         Interprocedural: a helper reached from a ``with self._lock:``
         body is analyzed as lock-held; ``threading.Thread(target=)``
         and ``functools.partial`` hand-offs do NOT propagate the held
         set (the target runs on its own thread / later).
  LD003  ``Condition.wait`` outside a predicate loop — a spurious or
         stolen wakeup silently breaks the invariant the wait was
         guarding (``wait_for`` supplies its own loop and is clean).

Lock identity is syntactic and deliberately per-owner: a ``with
self._lock:`` in class ``C`` of module ``m`` is the lock ``(m,
"C._lock")``; module-level locks are ``(m, NAME)`` and follow
imports. Two classes sharing one runtime lock object get distinct
identities — that can MISS an inversion (the runtime sanitizer's
job) but never invents one. Acquisitions counted are ``with`` blocks;
bare ``.acquire()`` pairing is resource_pairing's RP002.

One resolution extension over the engine: an unresolved
``self.attr(...)`` call resolves to the unique same-module
``__call__`` method when exactly one exists — the factory-callable
idiom (``self.factory(rid)`` -> ``ProcessReplicaFactory.__call__``),
which is precisely where the fleet hides a subprocess spawn.

Scope: the threaded packages (serving/observability/elastic/
distributed), same as lock_discipline. ``build_lock_graph`` exposes
the order graph for ``tools/pdlint.py --dump-lock-graph``.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Analyzer, Finding, SourceFile, in_scope
from .engine import CallGraph, dotted_name

__all__ = ["LockOrderAnalyzer", "LockOrderGraph", "build_lock_graph"]

_DEFAULT_DIRS = ("paddle_tpu/serving/", "paddle_tpu/observability/",
                 "paddle_tpu/elastic/", "paddle_tpu/distributed/")
_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}
_HTTP_FNS = {"urlopen", "create_connection"}
_SUBPROCESS_FNS = {"run", "call", "check_call", "check_output"}
# entry roots: what starts a thread of control
_LOOP_NAMES = ("run", "serve_forever")
_HANDLER_NAMES = {"do_GET", "do_POST", "do_PUT", "do_DELETE",
                  "do_HEAD"}

LockId = Tuple[str, str]                  # (module rel path, attr)


def _display(lock: LockId) -> str:
    return f"{lock[0]}:{lock[1]}"


def _ctor_name(node: ast.AST) -> Optional[str]:
    """'Lock'/'RLock'/... when ``node`` is a lock construction."""
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    name = f.attr if isinstance(f, ast.Attribute) else \
        (f.id if isinstance(f, ast.Name) else "")
    return name if name in _LOCK_CTORS else None


def _lockish_attr(attr: str) -> bool:
    low = attr.lower()
    return ("lock" in low or "mutex" in low or "cond" in low
            or low.endswith("_cv") or low == "cv")


class _EdgeSite:
    """Where an order edge was first observed."""

    __slots__ = ("path", "line", "col", "func", "via")

    def __init__(self, path, line, col, func, via=None):
        self.path = path
        self.line = line
        self.col = col
        self.func = func
        self.via = via       # lock carried in from a caller, or None


class LockOrderGraph:
    """The global acquisition-order graph: ``edges[a][b]`` means some
    path acquires ``b`` while holding ``a``."""

    def __init__(self):
        self.locks: Dict[LockId, str] = {}        # id -> ctor kind
        self.edges: Dict[LockId, Dict[LockId, _EdgeSite]] = {}
        self.roots: Dict[Tuple[str, str], str] = {}  # func key -> via

    def add_lock(self, lock: LockId, kind: str):
        self.locks.setdefault(lock, kind)

    def add_edge(self, a: LockId, b: LockId, site: _EdgeSite):
        if a == b:
            return
        self.edges.setdefault(a, {}).setdefault(b, site)

    # ------------------------------------------------------ cycles
    def cycles(self) -> List[List[LockId]]:
        """Strongly connected components with more than one lock —
        each is a potential-deadlock inversion set. Deterministic
        order (sorted members, sorted components)."""
        index: Dict[LockId, int] = {}
        low: Dict[LockId, int] = {}
        on_stack: Set[LockId] = set()
        stack: List[LockId] = []
        out: List[List[LockId]] = []
        counter = [0]
        nodes = sorted(set(self.locks) | set(self.edges)
                       | {b for m in self.edges.values() for b in m})

        def strongconnect(v: LockId):
            # iterative Tarjan: (node, iterator) frames
            work = [(v, iter(sorted(self.edges.get(v, ()))))]
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on_stack.add(v)
            while work:
                node, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on_stack.add(w)
                        work.append(
                            (w, iter(sorted(self.edges.get(w, ())))))
                        advanced = True
                        break
                    if w in on_stack:
                        low[node] = min(low[node], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    comp = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        comp.append(w)
                        if w == node:
                            break
                    if len(comp) > 1:
                        out.append(sorted(comp))

        for v in nodes:
            if v not in index:
                strongconnect(v)
        return sorted(out)

    # ------------------------------------------------------ dot
    def to_dot(self) -> str:
        """Graphviz DOT of the order graph; inversion-cycle members
        are drawn red."""
        cyclic = {l for comp in self.cycles() for l in comp}
        lines = ["digraph lock_order {",
                 "  rankdir=LR;",
                 "  node [shape=box, fontname=monospace];"]
        names = {}
        for i, lock in enumerate(sorted(set(self.locks)
                                        | set(self.edges))):
            names[lock] = f"n{i}"
            color = ', color=red' if lock in cyclic else ''
            lines.append(
                f'  n{i} [label="{_display(lock)}\\n'
                f'({self.locks.get(lock, "implicit")})"{color}];')
        for a in sorted(self.edges):
            for b in sorted(self.edges[a]):
                if b not in names:
                    names[b] = f"n{len(names)}"
                    lines.append(f'  {names[b]} '
                                 f'[label="{_display(b)}"];')
                site = self.edges[a][b]
                attrs = f'label="{site.func}", fontsize=9'
                if a in cyclic and b in cyclic:
                    attrs += ", color=red"
                lines.append(f"  {names[a]} -> {names[b]} [{attrs}];")
        lines.append("}")
        return "\n".join(lines) + "\n"


# ===================================================================
# per-function facts
# ===================================================================
class _Acquire:
    __slots__ = ("lock", "held", "line", "col")

    def __init__(self, lock, held, line, col):
        self.lock = lock
        self.held = held                   # tuple of LockId held before

    # line/col in __init__ to keep slots simple
        self.line = line
        self.col = col


class _Blocking:
    __slots__ = ("token", "held", "line", "col")

    def __init__(self, token, held, line, col):
        self.token = token
        self.held = held
        self.line = line
        self.col = col


class _CallSite:
    __slots__ = ("targets", "held")

    def __init__(self, targets, held):
        self.targets = targets             # tuple of func keys
        self.held = held                   # tuple of LockId


class _FuncFacts:
    __slots__ = ("key", "qualname", "rel", "acquires", "blocking",
                 "calls", "ld003")

    def __init__(self, key, qualname, rel):
        self.key = key
        self.qualname = qualname
        self.rel = rel
        self.acquires: List[_Acquire] = []
        self.blocking: List[_Blocking] = []
        self.calls: List[_CallSite] = []
        self.ld003: List[Tuple[str, int, int]] = []


class _ModuleLocks:
    """Lock identities one module defines or imports."""

    __slots__ = ("globals_", "class_attrs", "cond_attrs")

    def __init__(self):
        self.globals_: Dict[str, Tuple[LockId, str]] = {}
        # (class, attr) -> (LockId, kind)
        self.class_attrs: Dict[Tuple[str, str], Tuple[LockId, str]] = {}
        self.cond_attrs: Set[Tuple[str, str]] = set()


def _discover_locks(cg: CallGraph, rels: Set[str]
                    ) -> Dict[str, _ModuleLocks]:
    out: Dict[str, _ModuleLocks] = {}
    for rel in rels:
        mi = cg.modules.get(rel)
        if mi is None:
            continue
        ml = _ModuleLocks()
        out[rel] = ml
        tree = mi.sf.tree
        # module-level: X = threading.Lock()
        for node in tree.body:
            if isinstance(node, ast.Assign):
                kind = _ctor_name(node.value)
                if kind:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            ml.globals_[t.id] = ((rel, t.id), kind)
        # class attrs: self.X = <ctor> anywhere in the class; plus
        # implicit lock-named attrs used as with-contexts
        for node in ast.walk(tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for n in ast.walk(node):
                if isinstance(n, ast.Assign):
                    kind = _ctor_name(n.value)
                    if not kind:
                        continue
                    for t in n.targets:
                        if isinstance(t, ast.Attribute) and \
                                isinstance(t.value, ast.Name) and \
                                t.value.id == "self":
                            lock = (rel, f"{node.name}.{t.attr}")
                            ml.class_attrs[(node.name, t.attr)] = \
                                (lock, kind)
                            if kind == "Condition":
                                ml.cond_attrs.add((node.name, t.attr))
                elif isinstance(n, ast.With):
                    for item in n.items:
                        c = item.context_expr
                        if isinstance(c, ast.Attribute) and \
                                isinstance(c.value, ast.Name) and \
                                c.value.id == "self" and \
                                _lockish_attr(c.attr):
                            ml.class_attrs.setdefault(
                                (node.name, c.attr),
                                ((rel, f"{node.name}.{c.attr}"),
                                 "implicit"))
    return out


def _blocking_token(call: ast.Call) -> Optional[str]:
    """The LD002 blocking classification of one call, or None."""
    f = call.func
    d = dotted_name(f)
    last = f.attr if isinstance(f, ast.Attribute) else \
        (f.id if isinstance(f, ast.Name) else "")
    kwargs = {kw.arg for kw in call.keywords}
    bounded = "timeout" in kwargs or None in kwargs    # **kw: trust
    nargs = len(call.args)
    if last in _HTTP_FNS:
        return last            # network RTT under a lock: timeout or
    if last == "open":         # not, the convoy is the bug
        recv = f.value if isinstance(f, ast.Attribute) else None
        name = recv.id if isinstance(recv, ast.Name) else (
            recv.attr if isinstance(recv, ast.Attribute) else "")
        if name and "opener" in name.lower():
            return "opener.open"
    if last == "Popen":
        return "subprocess.Popen"
    if last in _SUBPROCESS_FNS and d and \
            d.split(".")[0] == "subprocess":
        return f"subprocess.{last}"
    if last == "communicate" and not bounded:
        return "communicate"
    if last == "get" and nargs == 0 and not kwargs:
        return "queue.get"
    if last == "result" and nargs == 0 and not kwargs:
        return "Future.result"
    if last in ("wait", "join") and nargs == 0 and not bounded:
        return last
    if last == "block_until_ready":
        return "block_until_ready"
    if last == "device_get":
        return "device_get"
    return None


class _FactsBuilder:
    """Walks one function body tracking the lexically-held lock set
    and loop nesting; records acquisitions, call sites, blocking
    calls, and naked Condition.waits."""

    def __init__(self, cg: CallGraph, mi, fn, locks_by_rel, aliases):
        self.cg = cg
        self.mi = mi
        self.fn = fn
        self.locks = locks_by_rel
        self.aliases = aliases
        self.facts = _FuncFacts(fn.key, fn.qualname, fn.sf.rel)

    # ------------------------------------------------- lock identity
    def _lock_of(self, expr: ast.AST) -> Optional[Tuple[LockId, str]]:
        ml = self.locks.get(self.fn.sf.rel)
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self":
            if self.fn.class_name is None or ml is None:
                return None
            got = ml.class_attrs.get((self.fn.class_name, expr.attr))
            if got:
                return got
            if _lockish_attr(expr.attr):
                return ((self.fn.sf.rel,
                         f"{self.fn.class_name}.{expr.attr}"),
                        "implicit")
            return None
        if isinstance(expr, ast.Name):
            if ml and expr.id in ml.globals_:
                return ml.globals_[expr.id]
            # imported module-level lock: from .x import LOCK
            resolved = self.mi.imports.resolve(expr.id)
            head, _, lname = resolved.rpartition(".")
            tm = self.cg.by_modname.get(head) if head else None
            if tm is not None:
                tml = self.locks.get(tm.sf.rel)
                if tml and lname in tml.globals_:
                    return tml.globals_[lname]
        return None

    def _is_condition(self, expr: ast.AST) -> bool:
        got = self._lock_of(expr)
        if got and got[1] == "Condition":
            return True
        if isinstance(expr, ast.Attribute):
            a = expr.attr.lower()
            return ("cond" in a or a.endswith("_cv") or a == "cv"
                    or a in ("not_empty", "not_full",
                             "all_tasks_done"))
        return False

    # ------------------------------------------------- traversal
    def build(self) -> _FuncFacts:
        body = self.fn.node.body
        if not isinstance(body, list):     # lambda
            body = [ast.Expr(value=body)]
        self._stmts(body, (), False)
        return self.facts

    def _stmts(self, stmts, held, in_loop):
        for s in stmts:
            self._stmt(s, held, in_loop)

    def _stmt(self, s, held, in_loop):
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return                         # separate call-graph node
        if isinstance(s, (ast.With, ast.AsyncWith)):
            inner = held
            for item in s.items:
                self._exprs([item.context_expr], inner, in_loop)
                got = self._lock_of(item.context_expr)
                if got:
                    lock, kind = got
                    self.facts.acquires.append(_Acquire(
                        lock, inner, item.context_expr.lineno,
                        item.context_expr.col_offset))
                    if lock not in inner:
                        inner = inner + (lock,)
            self._stmts(s.body, inner, in_loop)
            return
        if isinstance(s, (ast.While,)):
            self._exprs([s.test], held, in_loop)
            self._stmts(s.body, held, True)
            self._stmts(s.orelse, held, in_loop)
            return
        if isinstance(s, (ast.For, ast.AsyncFor)):
            self._exprs([s.iter], held, in_loop)
            self._stmts(s.body, held, True)
            self._stmts(s.orelse, held, in_loop)
            return
        if isinstance(s, ast.If):
            self._exprs([s.test], held, in_loop)
            self._stmts(s.body, held, in_loop)
            self._stmts(s.orelse, held, in_loop)
            return
        if isinstance(s, ast.Try):
            self._stmts(s.body, held, in_loop)
            for h in s.handlers:
                self._stmts(h.body, held, in_loop)
            self._stmts(s.orelse, held, in_loop)
            self._stmts(s.finalbody, held, in_loop)
            return
        self._exprs([s], held, in_loop)

    def _exprs(self, roots, held, in_loop):
        """Scan expressions (not descending into nested defs/lambdas)
        for calls."""
        stack = list(roots)
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            if isinstance(n, ast.Call):
                self._call(n, held, in_loop)
            stack.extend(ast.iter_child_nodes(n))

    # ------------------------------------------------- calls
    def _call(self, call: ast.Call, held, in_loop):
        f = call.func
        # LD003: naked Condition.wait outside a predicate loop
        if isinstance(f, ast.Attribute) and f.attr == "wait" and \
                self._is_condition(f.value) and not in_loop:
            recv = dotted_name(f.value) or "<cond>"
            self.facts.ld003.append(
                (f"{recv}.wait", call.lineno, call.col_offset))
        # LD002 blocking classification — a Condition's own wait
        # RELEASES its lock, so it is LD003's business, not LD002's
        token = _blocking_token(call)
        if token and not (token == "wait" and isinstance(f,
                          ast.Attribute) and
                          self._is_condition(f.value)):
            self.facts.blocking.append(_Blocking(
                token, held, call.lineno, call.col_offset))
        # propagation edges: direct calls only — Thread(target=) and
        # partial() run on another thread / later, without our locks
        targets = tuple(self.cg._resolve_target(
            self.mi, self.fn, f, self.aliases))
        if not targets and isinstance(f, ast.Attribute) and \
                isinstance(f.value, ast.Name) and \
                f.value.id == "self":
            # factory-callable idiom: unique same-module __call__
            calls_ = self.mi.by_last.get("__call__", ())
            if len(calls_) == 1:
                targets = (self.mi.funcs[calls_[0]].key,)
        if targets:
            self.facts.calls.append(_CallSite(targets, held))


def _build_aliases(cg: CallGraph, mi, fn) -> Dict[str, Tuple[str, str]]:
    """Local callable aliases, mirroring engine._callees."""
    from .engine import iter_own_body
    aliases: Dict[str, Tuple[str, str]] = {}
    for n in iter_own_body(fn.node):
        if isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                isinstance(n.targets[0], ast.Name):
            tgt = n.targets[0].id
            if isinstance(n.value, ast.Lambda):
                lam = f"{fn.qualname}.{tgt}"
                if lam in mi.funcs:
                    aliases[tgt] = mi.funcs[lam].key
            elif isinstance(n.value, (ast.Name, ast.Attribute)):
                keys = cg._resolve_target(mi, fn, n.value, aliases)
                if len(keys) == 1:
                    aliases[tgt] = keys[0]
    return aliases


def _thread_roots(cg: CallGraph, rels: Set[str]
                  ) -> Dict[Tuple[str, str], str]:
    """Thread-entry roots: Thread targets, HTTP handlers, worker
    loops, signal handlers."""
    roots: Dict[Tuple[str, str], str] = {}
    for rel in sorted(rels):
        mi = cg.modules.get(rel)
        if mi is None:
            continue
        for qual, fn in mi.funcs.items():
            last = qual.split(".")[-1]
            if last in _HANDLER_NAMES:
                roots.setdefault(fn.key, "http-handler")
            elif last.endswith("_loop") or last in _LOOP_NAMES:
                roots.setdefault(fn.key, "worker-loop")
        for n in ast.walk(mi.sf.tree):
            if not isinstance(n, ast.Call):
                continue
            d = dotted_name(n.func)
            last = d.split(".")[-1] if d else ""
            if last == "Thread":
                for kw in n.keywords:
                    if kw.arg == "target":
                        t = dotted_name(kw.value)
                        if t:
                            name = t.split(".")[-1]
                            for q in mi.by_last.get(name, ()):
                                roots.setdefault(mi.funcs[q].key,
                                                 "thread-target")
            elif last == "signal" and d and \
                    d.split(".")[0] == "signal" and len(n.args) >= 2:
                t = dotted_name(n.args[1])
                if t:
                    name = t.split(".")[-1]
                    for q in mi.by_last.get(name, ()):
                        roots.setdefault(mi.funcs[q].key,
                                         "signal-handler")
    return roots


# ===================================================================
# the analysis
# ===================================================================
def _analyze(files: Sequence[SourceFile], dirs: Sequence[str]
             ) -> Tuple[LockOrderGraph, List[Finding], str]:
    name = LockOrderAnalyzer.name
    scoped = [sf for sf in files if sf.tree is not None
              and in_scope(sf.rel, dirs)]
    graph = LockOrderGraph()
    if not scoped:
        return graph, [], name
    cg = CallGraph.shared(files)
    rels = {sf.rel for sf in scoped}
    locks_by_rel = _discover_locks(cg, rels)
    for ml in locks_by_rel.values():
        for lock, kind in ml.globals_.values():
            graph.add_lock(lock, kind)
        for lock, kind in ml.class_attrs.values():
            graph.add_lock(lock, kind)
    graph.roots = _thread_roots(cg, rels)

    facts: Dict[Tuple[str, str], _FuncFacts] = {}
    for rel in sorted(rels):
        mi = cg.modules[rel]
        for fn in mi.funcs.values():
            aliases = _build_aliases(cg, mi, fn)
            facts[fn.key] = _FactsBuilder(
                cg, mi, fn, locks_by_rel, aliases).build()

    # ---- interprocedural: locks held at function entry (union over
    # call sites, to fixpoint)
    entry_held: Dict[Tuple[str, str], Set[LockId]] = \
        {k: set() for k in facts}
    entry_src: Dict[Tuple[str, str], Dict[LockId, str]] = \
        {k: {} for k in facts}
    changed = True
    while changed:
        changed = False
        for key, fc in facts.items():
            base = entry_held[key]
            for cs in fc.calls:
                flow = set(cs.held) | base
                if not flow:
                    continue
                for tgt in cs.targets:
                    if tgt not in entry_held or tgt == key:
                        continue
                    new = flow - entry_held[tgt]
                    if new:
                        entry_held[tgt] |= new
                        for lock in new:
                            entry_src[tgt].setdefault(lock,
                                                      fc.qualname)
                        changed = True

    # ---- order edges
    for key, fc in facts.items():
        inherited = entry_held[key]
        for acq in fc.acquires:
            lex = list(acq.held)
            for h in lex:
                graph.add_edge(h, acq.lock, _EdgeSite(
                    fc.rel, acq.line, acq.col, fc.qualname))
            for h in sorted(inherited):
                if h not in lex:
                    graph.add_edge(h, acq.lock, _EdgeSite(
                        fc.rel, acq.line, acq.col, fc.qualname,
                        via=entry_src[key].get(h)))

    findings: List[Finding] = []

    # ---- LD001: inversion cycles
    for comp in graph.cycles():
        cycle_key = " <-> ".join(_display(c) for c in comp)
        # exemplar edge inside the component, deterministic
        site = None
        funcs: List[str] = []
        for a in comp:
            for b, s in sorted(graph.edges.get(a, {}).items()):
                if b in comp:
                    funcs.append(s.func)
                    if site is None or (s.path, s.line) < \
                            (site.path, site.line):
                        site = s
        findings.append(Finding(
            name, "LD001", site.path, site.line, site.col,
            f"lock-order inversion between {cycle_key}: different "
            f"paths acquire these locks in opposite orders "
            f"(via {sorted(set(funcs))}) — a deadlock the moment "
            f"the paths run concurrently; pick one global order",
            symbol=cycle_key, detail="cycle"))

    # ---- LD002: blocking while holding a lock
    for key, fc in sorted(facts.items()):
        inherited = entry_held[key]
        for b in fc.blocking:
            held_eff = list(b.held) + sorted(inherited -
                                             set(b.held))
            if not held_eff:
                continue
            lock = held_eff[0]
            how = "held here" if b.held else (
                f"held by caller "
                f"{entry_src[key].get(lock, '?')}")
            findings.append(Finding(
                name, "LD002", fc.rel, b.line, b.col,
                f"blocking call {b.token} while "
                f"{_display(lock)} is {how} — every thread "
                f"needing the lock now waits on this I/O; move "
                f"the blocking work outside the critical section "
                f"(snapshot under the lock, block outside)",
                symbol=fc.qualname,
                detail=f"{b.token}@{lock[1]}"))

    # ---- LD003: Condition.wait outside a predicate loop
    for key, fc in sorted(facts.items()):
        for recv, line, col in fc.ld003:
            findings.append(Finding(
                name, "LD003", fc.rel, line, col,
                f"{recv} outside a predicate loop — spurious/stolen "
                f"wakeups silently break the waited-for condition; "
                f"use `while not pred: cond.wait()` or "
                f"cond.wait_for(pred)",
                symbol=fc.qualname, detail=recv))

    return graph, findings, name


class LockOrderAnalyzer(Analyzer):
    name = "lock_order"

    def __init__(self, dirs: Sequence[str] = _DEFAULT_DIRS):
        self.dirs = tuple(dirs)
        # scope is configurable, so the run-cache key must carry it
        self.cache_token = "lock_order:" + ",".join(self.dirs)

    def run(self, files: Sequence[SourceFile]) -> List[Finding]:
        _, findings, _ = _analyze(files, self.dirs)
        return findings


def build_lock_graph(files: Sequence[SourceFile],
                     dirs: Sequence[str] = _DEFAULT_DIRS
                     ) -> LockOrderGraph:
    """The global lock-order graph (for --dump-lock-graph and
    tooling); same scoping as the analyzer."""
    graph, _, _ = _analyze(files, dirs)
    return graph
