"""Recompile-risk analyzer: compile-site and signature discipline.

PR 13 multiplied compile sites (StaticFunction, TrainStep, Predictor,
four CachedDecoder sites) and made ``compile_cache.get_or_compile``
THE chokepoint: it is where persistent-cache tiers, xstats provenance
and the goodput compile ledger all attach. A new AOT site wired around
it compiles invisibly — no hit/miss counters, no cost analysis, no
badput attribution. And any data-dependent Python value reaching a
traced signature (a raw ``len(batch)``, an unbucketed ``arr.shape[i]``,
a set iteration ordering pytree leaves) recompiles per distinct value
— the unbounded-recompilation failure mode shape bucketing exists to
prevent.

Rules:

  RR001  an AOT compile site (``<x>.lower(...).compile()``) in the
         serving/inference/jit layers whose enclosing function never
         routes through ``get_or_compile`` — xstats/provenance go dark
  RR002  a raw data-dependent size (``len(<param>)``,
         ``<param>.shape[i]``, or a local bound to one) passed to a
         jit-wrapped callable without passing through a bucketing
         helper (``bucket_seq`` / ``bucket_batch`` / ``next_pow2`` /
         ``pages_for``) — one executable per distinct value
  RR003  iteration over a ``set`` inside a trace-reachable function —
         hash-randomized order bakes a different pytree leaf order
         into the trace per process, defeating fingerprint/cache keys
         (iterate ``sorted(s)`` instead)

RR001/RR002 are scoped to the production dispatch layers
(``serving/``, ``inference/``, ``jit/``); RR003 runs wherever the
tracer-safety entry detection finds trace-reachable code.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set

from .core import Analyzer, Finding, SourceFile, in_scope
from .engine import (CallGraph, Taint, dotted_name, iter_own_body,
                     jit_entries)

__all__ = ["RecompileRiskAnalyzer"]

_COMPILE_DIRS = ("paddle_tpu/serving/", "paddle_tpu/inference/",
                 "paddle_tpu/jit/")
_BUCKET_HELPERS = {"bucket_seq", "bucket_batch", "next_pow2",
                   "pages_for", "bucket", "min", "max"}


def _is_aot_site(call: ast.Call) -> bool:
    """``<x>.lower(...).compile()``"""
    f = call.func
    return (isinstance(f, ast.Attribute) and f.attr == "compile"
            and isinstance(f.value, ast.Call)
            and isinstance(f.value.func, ast.Attribute)
            and f.value.func.attr == "lower")


def _jit_wrapped_names(fn) -> Set[str]:
    """Locals bound to a jit/pjit call result in this function."""
    out: Set[str] = set()
    for n in iter_own_body(fn):
        if isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                isinstance(n.targets[0], ast.Name) and \
                isinstance(n.value, ast.Call):
            d = dotted_name(n.value.func)
            if d and d.split(".")[-1] in ("jit", "pjit"):
                out.add(n.targets[0].id)
    return out


def _raw_size_expr(expr: ast.AST, taint: Taint) -> Optional[str]:
    """``len(p)`` / ``p.shape[i]`` over a tainted (parameter-derived)
    value -> a stable description, else None."""
    if isinstance(expr, ast.Call) and \
            isinstance(expr.func, ast.Name) and \
            expr.func.id == "len" and len(expr.args) == 1 and \
            taint.touches(expr.args[0]):
        d = dotted_name(expr.args[0]) or "<expr>"
        return f"len({d})"
    if isinstance(expr, ast.Subscript):
        d = dotted_name(expr.value)
        if d and d.endswith(".shape") and taint.touches(expr.value):
            return f"{d}[i]"
    return None


class RecompileRiskAnalyzer(Analyzer):
    name = "recompile_risk"

    def __init__(self, compile_dirs: Sequence[str] = _COMPILE_DIRS):
        self.compile_dirs = tuple(compile_dirs)

    def run(self, files: Sequence[SourceFile]) -> List[Finding]:
        out: List[Finding] = []
        scoped = [sf for sf in files
                  if in_scope(sf.rel, self.compile_dirs)]
        for sf in scoped:
            out.extend(self._check_compile_sites(sf))
            out.extend(self._check_signature_taint(sf))
        out.extend(self._check_set_iteration(files))
        return out

    # ------------------------------------------------- RR001
    def _check_compile_sites(self, sf: SourceFile) -> List[Finding]:
        findings: List[Finding] = []

        def visit(node, func_stack: List):
            for child in ast.iter_child_nodes(node):
                stack = func_stack
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    stack = func_stack + [child]
                if isinstance(child, ast.Call) and \
                        _is_aot_site(child):
                    # routed if ANY enclosing def calls get_or_compile
                    # (build thunks are nested inside the site that
                    # hands them to the cache)
                    if not any(self._routed(e) for e in stack):
                        qual = stack[-1].name if stack else "<module>"
                        findings.append(Finding(
                            self.name, "RR001", sf.rel,
                            child.lineno, child.col_offset,
                            f"AOT compile site in {qual!r} is not "
                            f"routed through compile_cache."
                            f"get_or_compile — no persistent tier, no "
                            f"xstats provenance, no compile-badput "
                            f"attribution", symbol=qual,
                            detail="lower().compile()"))
                visit(child, stack)

        visit(sf.tree, [])
        return findings

    @staticmethod
    def _routed(encl) -> bool:
        """The enclosing def (build thunks included — they live inside
        it) calls get_or_compile somewhere."""
        if encl is None:
            return False
        for n in ast.walk(encl):
            if isinstance(n, ast.Call):
                d = dotted_name(n.func)
                if d and d.split(".")[-1] == "get_or_compile":
                    return True
        return False

    # ------------------------------------------------- RR002
    def _check_signature_taint(self, sf: SourceFile) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                findings.extend(self._taint_function(sf, node))
        return findings

    def _taint_function(self, sf: SourceFile, fn) -> List[Finding]:
        jitted = _jit_wrapped_names(fn)
        if not jitted:
            return []
        taint = Taint(fn)
        raw_sizes: Dict[str, str] = {}   # local -> description
        findings: List[Finding] = []
        for n in iter_own_body(fn):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                    isinstance(n.targets[0], ast.Name):
                desc = _raw_size_expr(n.value, taint)
                if desc is not None:
                    raw_sizes[n.targets[0].id] = desc
                elif isinstance(n.value, ast.Call):
                    d = dotted_name(n.value.func) or ""
                    if d.split(".")[-1] in _BUCKET_HELPERS:
                        raw_sizes.pop(n.targets[0].id, None)
            taint.note_stmt(n)
            if not isinstance(n, ast.Call):
                continue
            f = n.func
            if not (isinstance(f, ast.Name) and f.id in jitted):
                continue
            for i, arg in enumerate(n.args):
                desc = _raw_size_expr(arg, taint)
                if desc is None and isinstance(arg, ast.Name) and \
                        arg.id in raw_sizes:
                    desc = raw_sizes[arg.id]
                if desc is not None:
                    findings.append(Finding(
                        self.name, "RR002", sf.rel, arg.lineno,
                        arg.col_offset,
                        f"unbucketed data-dependent size {desc} flows "
                        f"into jitted call {f.id}() at position {i} — "
                        f"one fresh compile per distinct value; route "
                        f"it through the bucketing helpers "
                        f"(in {fn.name!r})",
                        symbol=fn.name,
                        detail=f"{f.id}:arg{i}:{desc}"))
        return findings

    # ------------------------------------------------- RR003
    def _check_set_iteration(self,
                             files: Sequence[SourceFile]
                             ) -> List[Finding]:
        cg = CallGraph.shared(files)
        reach = cg.reachable(jit_entries(cg))
        findings: List[Finding] = []
        for key in sorted(reach):
            fn = cg.funcs[key]
            via = reach[key]
            findings.extend(self._set_iters(fn, via))
        return findings

    def _set_iters(self, fn, via: str) -> List[Finding]:
        node = fn.node
        if isinstance(node, ast.Lambda):
            return []
        set_vars: Set[str] = set()
        findings: List[Finding] = []

        def is_set_expr(e: ast.AST) -> bool:
            if isinstance(e, ast.Set) or isinstance(e, ast.SetComp):
                return True
            if isinstance(e, ast.Call):
                d = dotted_name(e.func) or ""
                return d in ("set", "frozenset")
            if isinstance(e, ast.Name):
                return e.id in set_vars
            if isinstance(e, ast.BinOp) and \
                    isinstance(e.op, (ast.BitOr, ast.BitAnd,
                                      ast.Sub)):
                return is_set_expr(e.left) or is_set_expr(e.right)
            return False

        def check_iter(it: ast.AST, where: ast.AST):
            if is_set_expr(it):
                d = dotted_name(it) if isinstance(
                    it, (ast.Name, ast.Attribute)) else None
                findings.append(Finding(
                    self.name, "RR003", fn.sf.rel, where.lineno,
                    where.col_offset,
                    f"iteration over a set in {fn.qualname!r} (traced "
                    f"via {via}) — hash-randomized order changes the "
                    f"traced pytree per process; iterate sorted(...) "
                    f"instead", symbol=fn.qualname,
                    detail=f"set-iter:{d or 'set'}"))

        for n in iter_own_body(node):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                    isinstance(n.targets[0], ast.Name) and \
                    is_set_expr(n.value):
                set_vars.add(n.targets[0].id)
            if isinstance(n, (ast.For, ast.AsyncFor)):
                check_iter(n.iter, n)
            elif isinstance(n, (ast.ListComp, ast.SetComp,
                                ast.DictComp, ast.GeneratorExp)):
                for gen in n.generators:
                    check_iter(gen.iter, n)
        return findings
