"""Metric-discipline analyzer: registry families must stay coherent.

The metric registry (``paddle_tpu.observability.registry``) is
runtime-checked only: a family name that breaks Prometheus conventions
scrapes fine until a real Prometheus server rejects it, and a name
registered as a Counter in one module and a Gauge in another raises —
but only on the code path that registers second, possibly deep into a
serving process's lifetime. This analyzer restores the compile-time
contract over the same trees the flag analyzer covers:

  MD001  registry family registration (``reg.counter/gauge/histogram(
         "<name>", ...)``) whose name does not match
         ``paddle_[a-z0-9_]+``, or whose name is registered elsewhere
         with a DIFFERENT family type — one family per name, one type
         per family
  MD002  a histogram/window ``observe``/``observe_many`` call with a
         negative numeric duration literal — durations are measured,
         never negative; a negative literal is a sign error waiting to
         skew a latency percentile
  MD003  Prometheus naming-convention suffixes: a counter registered
         without a ``_total`` suffix, or a histogram whose name lacks
         a unit suffix (``_ms`` / ``_bytes`` / ``_seconds``) — the
         scraped name is the unit contract; an unsuffixed counter
         reads like a gauge on a dashboard and an unitless histogram
         invites ms-vs-seconds confusion downstream

Only calls whose first argument is a string literal count as
registrations, so ``np.histogram(arr, bins=...)`` and dynamic names
are never false positives. Files that intentionally register
synthetic names (registry unit tests) opt out with
``# pdlint: disable=metric_discipline``.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Sequence, Tuple

from .core import Analyzer, Finding, SourceFile

__all__ = ["MetricDisciplineAnalyzer"]

_NAME_PATTERN = re.compile(r"paddle_[a-z0-9_]+")
_REGISTER_METHODS = ("counter", "gauge", "histogram")
_OBSERVE_METHODS = ("observe", "observe_many")
_HISTOGRAM_UNIT_SUFFIXES = ("_ms", "_bytes", "_seconds")


def _neg_literals(node: ast.AST) -> List[Tuple[float, int, int]]:
    """Negative numeric literals in an expression (covers the bare
    ``-5`` argument and ``[-1.0, 2.0]`` inside observe_many lists)."""
    out = []
    for n in ast.walk(node):
        if isinstance(n, ast.UnaryOp) and \
                isinstance(n.op, ast.USub) and \
                isinstance(n.operand, ast.Constant) and \
                isinstance(n.operand.value, (int, float)) and \
                not isinstance(n.operand.value, bool):
            out.append((-float(n.operand.value), n.lineno,
                        n.col_offset))
    return out


class _Reg:
    __slots__ = ("name", "kind", "path", "line", "col")

    def __init__(self, name, kind, path, line, col):
        self.name = name
        self.kind = kind
        self.path = path
        self.line = line
        self.col = col


class MetricDisciplineAnalyzer(Analyzer):
    name = "metric_discipline"

    def run(self, files: Sequence[SourceFile]) -> List[Finding]:
        regs: List[_Reg] = []
        findings: List[Finding] = []
        for sf in files:
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                if not isinstance(f, ast.Attribute):
                    continue
                if f.attr in _REGISTER_METHODS:
                    if node.args and \
                            isinstance(node.args[0], ast.Constant) \
                            and isinstance(node.args[0].value, str):
                        regs.append(_Reg(node.args[0].value, f.attr,
                                         sf.rel, node.lineno,
                                         node.col_offset))
                elif f.attr in _OBSERVE_METHODS:
                    for arg in node.args:
                        for val, line, col in _neg_literals(arg):
                            findings.append(Finding(
                                self.name, "MD002", sf.rel, line, col,
                                f"{f.attr}() called with negative "
                                f"duration literal {val} — durations "
                                f"are measured, never negative",
                                symbol=f.attr, detail=str(val)))

        first_kind: Dict[str, _Reg] = {}
        for r in regs:
            if not _NAME_PATTERN.fullmatch(r.name):
                findings.append(Finding(
                    self.name, "MD001", r.path, r.line, r.col,
                    f"registry metric name {r.name!r} must match "
                    f"paddle_[a-z0-9_]+ (lowercase, paddle_ prefix)",
                    symbol=r.name, detail=r.name))
            if r.kind == "counter" and not r.name.endswith("_total"):
                findings.append(Finding(
                    self.name, "MD003", r.path, r.line, r.col,
                    f"counter {r.name!r} lacks the _total suffix — "
                    f"Prometheus counters are cumulative and the "
                    f"suffix is the convention dashboards key on",
                    symbol=r.name, detail="counter_suffix"))
            elif r.kind == "histogram" and not \
                    r.name.endswith(_HISTOGRAM_UNIT_SUFFIXES):
                findings.append(Finding(
                    self.name, "MD003", r.path, r.line, r.col,
                    f"histogram {r.name!r} lacks a unit suffix "
                    f"({'/'.join(_HISTOGRAM_UNIT_SUFFIXES)}) — the "
                    f"scraped name is the unit contract",
                    symbol=r.name, detail="histogram_unit"))
            prev = first_kind.get(r.name)
            if prev is None:
                first_kind[r.name] = r
            elif prev.kind != r.kind:
                findings.append(Finding(
                    self.name, "MD001", r.path, r.line, r.col,
                    f"metric {r.name!r} registered as {r.kind} here "
                    f"but as {prev.kind} at {prev.path}:{prev.line} — "
                    f"one family per name, one type per family",
                    symbol=r.name,
                    detail=f"{prev.kind}!={r.kind}"))
        return findings
