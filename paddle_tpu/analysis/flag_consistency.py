"""Flag-consistency analyzer: every FLAGS_* reference must resolve.

The reference framework registers ~90 gflags in one compile-checked
translation unit (paddle/phi/core/flags.cc): a dangling FLAGS_* name
or a type-mismatched default is a build break. Our registry
(``framework/flags.py``) is runtime-checked only — ``flag_value`` on
an undefined name raises in production, and a stale string in an env
dict silently does nothing. This analyzer restores the compile-time
contract over ``paddle_tpu/``, ``tools/`` and ``tests/``:

  FC001  FLAGS_* string referenced but never defined via define_flag
  FC002  flag defined but never read anywhere (warning — compat
         shims live in the baseline)
  FC003  set_flags({...}) literal whose type can't coerce to the
         flag's default type
  FC004  duplicate define_flag with a different default type

Docstring mentions count for FC001 resolution (stale docs are stale
code) but not as "reads" for FC002 — documenting a flag is not using
it.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Sequence

from .core import Analyzer, Finding, SourceFile

__all__ = ["FlagConsistencyAnalyzer"]

_TOKEN = re.compile(r"FLAGS_[A-Za-z0-9_]+")


def _norm(name: str) -> str:
    return name if name.startswith("FLAGS_") else "FLAGS_" + name


class _Def:
    __slots__ = ("name", "path", "line", "col", "type_")

    def __init__(self, name, path, line, col, type_):
        self.name = name
        self.path = path
        self.line = line
        self.col = col
        self.type_ = type_          # python type of the default, or None


class _Ref:
    __slots__ = ("name", "path", "line", "col", "is_doc")

    def __init__(self, name, path, line, col, is_doc):
        self.name = name
        self.path = path
        self.line = line
        self.col = col
        self.is_doc = is_doc


def _call_name(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


# types the registry's _Flag.set coerces without loss
_COMPATIBLE = {
    bool: (bool,),
    int: (int,),            # bool is a subclass of int; allowed via it
    float: (int, float),
    str: (str,),
}


class FlagConsistencyAnalyzer(Analyzer):
    name = "flag_consistency"

    def run(self, files: Sequence[SourceFile]) -> List[Finding]:
        defs: Dict[str, _Def] = {}
        dupes: List[Finding] = []
        refs: List[_Ref] = []
        type_errs: List[Finding] = []

        for sf in files:
            doc_spans = _docstring_nodes(sf.tree)
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Call) and \
                        _call_name(node) == "define_flag":
                    self._collect_def(sf, node, defs, dupes)
                if isinstance(node, ast.Constant) and \
                        isinstance(node.value, str):
                    for tok in _TOKEN.findall(node.value):
                        if tok.endswith("_"):   # "FLAGS_serving_" prose
                            continue
                        refs.append(_Ref(tok, sf.rel, node.lineno,
                                         node.col_offset,
                                         id(node) in doc_spans))

        # FC003 runs as a second pass so every definition is known,
        # whatever order the files were walked in
        for sf in files:
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Call) and \
                        _call_name(node) == "set_flags":
                    type_errs.extend(self._check_set_flags(
                        sf, node, defs))

        findings: List[Finding] = list(dupes) + type_errs
        def_sites = {(d.path, d.line) for d in defs.values()}
        read = set()
        for r in refs:
            name = _norm(r.name)
            at_def = (r.path, r.line) in def_sites
            if name not in defs and not at_def:
                findings.append(Finding(
                    self.name, "FC001", r.path, r.line, r.col,
                    f"{name} referenced but never defined via "
                    f"define_flag (framework/flags.py)",
                    symbol=name, detail=name))
            if not r.is_doc and not at_def:
                read.add(name)
        for name, d in sorted(defs.items()):
            if name not in read:
                findings.append(Finding(
                    self.name, "FC002", d.path, d.line, d.col,
                    f"{name} is defined but never read (dead flag, or "
                    f"a compat shim worth baselining)",
                    symbol=name, detail=name, severity="warning"))
        return findings

    def _collect_def(self, sf, node, defs, dupes):
        if not node.args or not isinstance(node.args[0], ast.Constant) \
                or not isinstance(node.args[0].value, str):
            return
        name = _norm(node.args[0].value)
        type_ = None
        if len(node.args) > 1 and isinstance(node.args[1], ast.Constant):
            type_ = type(node.args[1].value)
        d = _Def(name, sf.rel, node.lineno, node.col_offset, type_)
        prev = defs.get(name)
        if prev is None:
            defs[name] = d
        elif type_ is not None and prev.type_ is not None and \
                type_ is not prev.type_:
            dupes.append(Finding(
                self.name, "FC004", sf.rel, node.lineno,
                node.col_offset,
                f"{name} redefined with default type "
                f"{type_.__name__}, first defined as "
                f"{prev.type_.__name__} at {prev.path}:{prev.line}",
                symbol=name, detail=f"{prev.type_.__name__}->"
                                    f"{type_.__name__}"))

    def _check_set_flags(self, sf, node, defs) -> List[Finding]:
        out = []
        if not node.args or not isinstance(node.args[0], ast.Dict):
            return out
        for k, v in zip(node.args[0].keys, node.args[0].values):
            if not (isinstance(k, ast.Constant)
                    and isinstance(k.value, str)
                    and isinstance(v, ast.Constant)):
                continue
            name = _norm(k.value)
            d = defs.get(name)
            if d is None or d.type_ is None:
                continue        # FC001 covers undefined names
            ok_types = _COMPATIBLE.get(d.type_, (d.type_,))
            vt = type(v.value)
            # a bool literal satisfies int-typed flags (python bool IS
            # an int) and, per _Flag.set, bool flags parse strings
            if vt is bool and d.type_ in (bool, int):
                continue
            if d.type_ is bool and vt is str:
                continue
            if vt not in ok_types:
                out.append(Finding(
                    self.name, "FC003", sf.rel, k.lineno, k.col_offset,
                    f"set_flags gives {name} a {vt.__name__} literal "
                    f"but its default is {d.type_.__name__}",
                    symbol=name, detail=f"{vt.__name__}!="
                                        f"{d.type_.__name__}"))
        return out


def _docstring_nodes(tree) -> set:
    """id()s of Constant nodes sitting in docstring position."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            body = node.body
            if body and isinstance(body[0], ast.Expr) and \
                    isinstance(body[0].value, ast.Constant) and \
                    isinstance(body[0].value.value, str):
                out.add(id(body[0].value))
    return out
