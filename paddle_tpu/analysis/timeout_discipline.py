"""Timeout-discipline analyzer: no unbounded blocking I/O in serving.

The serving fleet's resilience story (deadline propagation, breakers,
the wedge watchdog) is only as good as its weakest blocking call: one
``urlopen`` without a timeout inside the router turns a wedged replica
into a wedged ROUTER — the exact failure class PR 15 exists to bound.
The repo convention is that every intra-fleet HTTP call goes through a
helper that supplies a timeout (``FleetRouter._http``); this analyzer
makes the convention a compile-time contract over
``paddle_tpu/serving/``:

  TD001  a blocking socket/HTTP call — ``urlopen(...)``,
         ``socket.create_connection(...)``, an
         ``HTTPConnection``/``HTTPSConnection`` construction, or
         ``<opener>.open(...)`` on a urllib opener — without an
         explicit timeout (the ``timeout=`` keyword, or the
         positional timeout slot those signatures define). The
         stdlib default for all of them is "block forever"; a fleet
         data or control plane may never wait forever on a peer that
         PERF.md history shows can silently wedge.

Only ``paddle_tpu/serving/`` is in scope: benches and tests block on
purpose, and non-serving library code has no peer that can wedge it.
Deliberate-negative files opt out with
``# pdlint: disable=timeout_discipline``.
"""
from __future__ import annotations

import ast
from typing import List, Optional, Sequence

from .core import Analyzer, Finding, SourceFile, in_scope

__all__ = ["TimeoutDisciplineAnalyzer"]

_SCOPE_DIRS = ("paddle_tpu/serving",)

# call name -> index of the positional timeout slot (None = keyword
# only). urlopen(url, data=None, timeout=...) -> slot 2;
# create_connection(address, timeout=...) -> slot 1;
# HTTP(S)Connection(host, port=None, timeout=...) -> slot 2.
_BLOCKING_CALLS = {
    "urlopen": 2,
    "create_connection": 1,
    "HTTPConnection": 2,
    "HTTPSConnection": 2,
}


def _call_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _is_opener_open(func: ast.AST) -> bool:
    """``<receiver>.open(...)`` where the receiver reads as a urllib
    opener (``_OPENER.open``, ``self.opener.open``, ...). Plain
    ``open()`` (the builtin) and file-ish receivers never match."""
    if not (isinstance(func, ast.Attribute) and func.attr == "open"):
        return False
    recv = func.value
    name = None
    if isinstance(recv, ast.Name):
        name = recv.id
    elif isinstance(recv, ast.Attribute):
        name = recv.attr
    return name is not None and "opener" in name.lower()


def _has_timeout(call: ast.Call, pos_slot: Optional[int]) -> bool:
    for kw in call.keywords:
        if kw.arg == "timeout":
            return True
        if kw.arg is None:      # **kwargs: assume the caller knows
            return True
    return pos_slot is not None and len(call.args) > pos_slot


class _Visitor(ast.NodeVisitor):
    def __init__(self, analyzer: "TimeoutDisciplineAnalyzer",
                 sf: SourceFile, findings: List[Finding]):
        self.analyzer = analyzer
        self.sf = sf
        self.findings = findings
        self.stack: List[str] = []

    def visit_FunctionDef(self, node):  # noqa: N802 - ast ABI
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):  # noqa: N802 - ast ABI
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    def visit_Call(self, node):  # noqa: N802 - ast ABI
        name = _call_name(node.func)
        hit = None
        if name in _BLOCKING_CALLS:
            if not _has_timeout(node, _BLOCKING_CALLS[name]):
                hit = name
        elif _is_opener_open(node.func):
            if not _has_timeout(node, None):
                hit = "opener.open"
        if hit is not None:
            qual = ".".join(self.stack) or "<module>"
            self.findings.append(Finding(
                self.analyzer.name, "TD001", self.sf.rel,
                node.lineno, node.col_offset,
                f"blocking call {hit}() without an explicit timeout "
                f"in serving code — the stdlib default blocks "
                f"forever, so a wedged peer wedges this process too; "
                f"pass timeout= (route fleet HTTP through the "
                f"router/worker helpers that supply one)",
                symbol=qual, detail=hit))
        self.generic_visit(node)


class TimeoutDisciplineAnalyzer(Analyzer):
    name = "timeout_discipline"

    def run(self, files: Sequence[SourceFile]) -> List[Finding]:
        findings: List[Finding] = []
        for sf in files:
            if sf.tree is None or \
                    not in_scope(sf.rel, _SCOPE_DIRS):
                continue
            _Visitor(self, sf, findings).visit(sf.tree)
        return findings
