"""Resource-pairing analyzer: acquire without release on SOME path.

The refcounted ``PagedKVCache`` (PR 12) made page accounting a
correctness invariant: a ``retain``/``alloc`` whose ``release``/
``free`` is skipped on an exception path leaks pool pages until the
engine wedges at admission — ``assert_no_leaks`` catches it at
runtime, this analyzer catches it in CI. Same discipline for bare
``lock.acquire()`` (use ``with`` or pair on every path) and manual
``__enter__`` driving.

Rules (all evaluated over the engine CFG, exception edges included):

  RP001  ``<x>.alloc(...)`` result / ``<x>.retain(name)`` argument
         reaches a function exit — normal or exceptional — on some
         path with no ``release``/``free`` and no ownership transfer
  RP002  ``<x>.acquire()`` outside a ``with`` item, with a path to an
         exit that never calls ``<x>.release()``
  RP003  ``<x>.__enter__()`` with a path to an exit that never calls
         ``<x>.__exit__(...)``

Ownership transfer (kills tracking): the resource name is returned /
yielded, stored into an attribute / subscript / container, or passed
as an argument to any call that is not a releaser — the callee or the
holding object owns the release from there (the engine stores admitted
pages in ``_ActiveSeq``/``self._slots`` and frees them in
``_release``; that pattern is clean by construction here). A branch
proving the name ``None`` (``if pages is None:``) also kills: the
all-or-nothing allocator returned nothing.

Scope: ``paddle_tpu/`` production code. Tests deliberately leak
(tripwire assertions) and tools hold resources for their whole run.
"""
from __future__ import annotations

import ast
from typing import List, Optional, Sequence, Set, Tuple

from .core import Analyzer, Finding, SourceFile, in_scope
from .engine import CFG, build_cfg, dotted_name, head_exprs

__all__ = ["ResourcePairingAnalyzer"]

_DEFAULT_DIRS = ("paddle_tpu/",)

_ACQUIRERS = {
    # attr -> (rule, kind, releaser attrs)
    "alloc": ("RP001", "pages", ("release", "free")),
    "retain": ("RP001", "pages", ("release", "free")),
    "acquire": ("RP002", "lock", ("release",)),
    "__enter__": ("RP003", "context", ("__exit__",)),
}


class _Resource:
    __slots__ = ("rule", "kind", "var", "recv", "releasers", "node",
                 "line", "col", "detail")

    def __init__(self, rule, kind, var, recv, releasers, node,
                 line, col):
        self.rule = rule
        self.kind = kind
        self.var = var          # tracked local name (pages kinds)
        self.recv = recv        # receiver dotted string (lock/context)
        self.releasers = releasers
        self.node = node        # CFGNode of the acquire
        self.line = line
        self.col = col
        self.detail = f"{recv}.{kind}" if var is None else \
            f"{var}:{kind}"


def _call_of(stmt: ast.AST):
    """Iterate every Call this CFG node's statement itself evaluates
    (compound heads evaluate only their head expressions)."""
    for part in head_exprs(stmt):
        for n in ast.walk(part):
            if isinstance(n, ast.Call):
                yield n


def _arg_names(call: ast.Call) -> Set[str]:
    out: Set[str] = set()
    for a in list(call.args) + [kw.value for kw in call.keywords]:
        for n in ast.walk(a):
            if isinstance(n, ast.Name):
                out.add(n.id)
    return out


class ResourcePairingAnalyzer(Analyzer):
    name = "resource_pairing"

    def __init__(self, dirs: Sequence[str] = _DEFAULT_DIRS):
        self.dirs = tuple(dirs)

    def run(self, files: Sequence[SourceFile]) -> List[Finding]:
        out: List[Finding] = []
        for sf in files:
            if not in_scope(sf.rel, self.dirs):
                continue
            cls_of = {}
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.ClassDef):
                    for m in node.body:
                        if isinstance(m, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                            cls_of[id(m)] = node
            for node in ast.walk(sf.tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    out.extend(self._check_function(
                        sf, node, cls_of.get(id(node))))
        return out

    # -------------------------------------------------- per function
    def _check_function(self, sf: SourceFile, fn,
                        cls: Optional[ast.ClassDef]) -> List[Finding]:
        cfg = build_cfg(fn)
        resources = self._find_acquires(fn, cfg, cls)
        findings: List[Finding] = []
        qual = fn.name
        for res in resources:
            leak = self._walk(res, cfg)
            if leak is None:
                continue
            exit_kind = ("an exception path" if leak == "exc"
                         else "a normal path")
            if res.var is not None:
                msg = (f"{res.kind} resource {res.var!r} acquired here "
                       f"can reach a function exit on {exit_kind} "
                       f"without {' / '.join(res.releasers)} — leaked "
                       f"{res.kind}")
            else:
                msg = (f"{res.recv}.{'/'.join(res.releasers)} is never "
                       f"called on {exit_kind} after this acquire")
            findings.append(Finding(
                self.name, res.rule, sf.rel, res.line, res.col,
                f"{msg} (in {qual!r})", symbol=qual,
                detail=res.detail))
        return findings

    # -------------------------------------------------- acquire sites
    def _find_acquires(self, fn, cfg: CFG,
                       cls: Optional[ast.ClassDef]) -> List[_Resource]:
        # receivers used as `with` items are exempt (the context
        # manager releases); so are with-item __enter__ sugar forms
        with_recvs: Set[str] = set()
        for n in ast.walk(fn):
            if isinstance(n, (ast.With, ast.AsyncWith)):
                for item in n.items:
                    d = dotted_name(item.context_expr)
                    if d:
                        with_recvs.add(d)
                    elif isinstance(item.context_expr, ast.Call):
                        d = dotted_name(item.context_expr.func)
                        if d:
                            with_recvs.add(d)

        out: List[_Resource] = []
        for node in cfg.nodes:
            stmt = node.stmt
            if stmt is None:
                continue
            for call in _call_of(stmt):
                f = call.func
                if not isinstance(f, ast.Attribute):
                    continue
                spec = _ACQUIRERS.get(f.attr)
                if spec is None:
                    continue
                rule, kind, releasers = spec
                recv = dotted_name(f.value) or "<expr>"
                if f.attr == "alloc":
                    var = self._assigned_name(stmt, call)
                    if var is None:
                        continue        # result unused/complex: skip
                    out.append(_Resource(rule, kind, var, recv,
                                         releasers, node,
                                         call.lineno,
                                         call.col_offset))
                elif f.attr == "retain":
                    # only bare-Name retains are tracked; list
                    # literals belong to structures that own them
                    if len(call.args) == 1 and \
                            isinstance(call.args[0], ast.Name):
                        out.append(_Resource(
                            rule, kind, call.args[0].id, recv,
                            releasers, node, call.lineno,
                            call.col_offset))
                elif f.attr == "acquire":
                    # lock protocol only: argless (or kw-only timeout)
                    # acquire — pool/semaphore acquires that take
                    # operands follow cross-method ownership protocols
                    if call.args or recv in with_recvs or \
                            recv == "<expr>":
                        continue
                    if self._class_pairs(cls, fn, recv, releasers):
                        continue
                    out.append(_Resource(rule, kind, None, recv,
                                         releasers, node, call.lineno,
                                         call.col_offset))
                elif f.attr == "__enter__":
                    if recv in with_recvs or recv == "<expr>":
                        continue
                    # delegation: the __enter__ RESULT is handed to
                    # the caller / stored — whoever holds it owns the
                    # __exit__ (the `return ctx.__enter__()` protocol)
                    if self._result_escapes(stmt, call):
                        continue
                    if self._class_pairs(cls, fn, recv, releasers):
                        continue
                    out.append(_Resource(rule, kind, None, recv,
                                         releasers, node, call.lineno,
                                         call.col_offset))
        return out

    @staticmethod
    def _result_escapes(stmt: ast.AST, call: ast.Call) -> bool:
        """The call's value is returned / yielded / stored into an
        attribute — ownership of the paired release moves with it."""
        if isinstance(stmt, ast.Return) and stmt.value is call:
            return True
        if isinstance(stmt, ast.Expr) and isinstance(
                stmt.value, (ast.Yield, ast.YieldFrom)):
            return True
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                if isinstance(t, (ast.Attribute, ast.Subscript)):
                    return True
        return False

    @staticmethod
    def _class_pairs(cls: Optional[ast.ClassDef], fn, recv: str,
                     releasers) -> bool:
        """Cross-method protocol: an acquire on a ``self.<attr>``
        receiver whose releaser is called on the SAME receiver
        anywhere else in the class (begin/end, __enter__/__exit__
        delegation) is paired at object scope, not path scope."""
        if cls is None or not recv.startswith("self."):
            return False
        for m in cls.body:
            if not isinstance(m, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)) or m is fn:
                continue
            for n in ast.walk(m):
                if isinstance(n, ast.Call) and \
                        isinstance(n.func, ast.Attribute) and \
                        n.func.attr in releasers and \
                        dotted_name(n.func.value) == recv:
                    return True
        return False

    @staticmethod
    def _assigned_name(stmt: ast.AST, call: ast.Call) -> Optional[str]:
        """``X = <recv>.alloc(...)`` -> 'X' (simple Name target whose
        value IS the alloc call)."""
        if isinstance(stmt, ast.Assign) and stmt.value is call and \
                len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name):
            return stmt.targets[0].id
        return None

    # -------------------------------------------------- CFG dataflow
    def _walk(self, res: _Resource, cfg: CFG) -> Optional[str]:
        """DFS from the acquire's NORMAL successors; returns 'exc' /
        'normal' for the first exit reached while still held, or None
        when every path releases / transfers ownership."""
        start = res.node.succ           # acquire raising = not acquired
        seen: Set[int] = set()
        stack = list(start)
        while stack:
            node = stack.pop()
            if id(node) in seen:
                continue
            seen.add(id(node))
            if node.kind == "exit":
                return "normal"
            if node.kind == "exc_exit":
                return "exc"
            if res.var is not None and res.var in node.none_names:
                continue                # statically None: not acquired
            action = self._transfer(res, node.stmt)
            if action == "kill":
                continue
            stack.extend(node.all_succ())
        return None

    def _transfer(self, res: _Resource, stmt: ast.AST) -> Optional[str]:
        """Effect of one statement on the tracked resource."""
        if res.var is None:
            # lock/context: matched by receiver string
            for call in _call_of(stmt):
                f = call.func
                if isinstance(f, ast.Attribute) and \
                        f.attr in res.releasers and \
                        dotted_name(f.value) == res.recv:
                    return "kill"
            return None
        name = res.var
        # release/free first — their args don't count as escapes
        for call in _call_of(stmt):
            f = call.func
            if isinstance(f, ast.Attribute) and \
                    f.attr in res.releasers and name in _arg_names(call):
                return "kill"
        # ownership transfer: returned / yielded / stored into a
        # structure / passed to any other call
        if isinstance(stmt, ast.Return) and stmt.value is not None and \
                self._mentions(stmt.value, name):
            return "kill"
        for part in head_exprs(stmt):
            for n in ast.walk(part):
                if isinstance(n, (ast.Yield, ast.YieldFrom)) and \
                        n.value is not None and \
                        self._mentions(n.value, name):
                    return "kill"
        if isinstance(stmt, (ast.Assign, ast.AugAssign)):
            targets = stmt.targets if isinstance(stmt, ast.Assign) \
                else [stmt.target]
            value = stmt.value
            for t in targets:
                if isinstance(t, (ast.Attribute, ast.Subscript)) and \
                        self._mentions(value, name):
                    return "kill"       # stored: structure owns it now
                if isinstance(t, ast.Name) and t.id == name and \
                        not self._mentions(value, name):
                    return "kill"       # rebound to something new
                if isinstance(t, ast.Tuple):
                    for e in t.elts:
                        if isinstance(e, ast.Name) and e.id == name \
                                and not self._mentions(value, name):
                            return "kill"
        for call in _call_of(stmt):
            f = call.func
            is_releaser = isinstance(f, ast.Attribute) and \
                f.attr in res.releasers
            if not is_releaser and name in _arg_names(call):
                return "kill"           # callee owns it now
        return None

    @staticmethod
    def _mentions(expr: ast.AST, name: str) -> bool:
        return any(isinstance(n, ast.Name) and n.id == name
                   for n in ast.walk(expr))
