"""Lock-discipline analyzer: unguarded shared state in threaded code.

``paddle_tpu/serving/``, ``paddle_tpu/observability/``,
``paddle_tpu/elastic/`` and ``paddle_tpu/distributed/`` are the places
this codebase runs real threads or holds cross-thread shared state
(batching worker, completion thread, telemetry HTTP handlers,
collectors, the async checkpoint writer + its done callbacks and
signal handlers, the sharding API's generation counter and metric
registration). The discipline their classes follow — established in
PRs 1-3 — is: shared mutable
attributes are written inside ``with self._lock:``. This analyzer
flags the drift cases that compile fine and fail only under traffic:

  LK001  attribute written BOTH inside and outside a with-lock block
         (outside __init__) — the unguarded write races the guarded
         ones
  LK002  attribute written without a lock in a method that runs on its
         own thread (``threading.Thread(target=self.m)``) while other
         methods also touch it (warning)
  LK003  module-level global assigned both inside and outside a
         ``with <lock>:`` block

A class with no lock-like attribute at all is skipped: single-threaded
helpers (dataclasses, request objects) are not the target, and
"add a lock" is a design decision, not a lint fix.

Lock-like: ``self.X = threading.Lock()/RLock()/Condition(...)``, plus
any attribute whose name contains "lock" used as a ``with`` context.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Analyzer, Finding, SourceFile

__all__ = ["LockDisciplineAnalyzer"]

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}
_DEFAULT_DIRS = ("paddle_tpu/serving/", "paddle_tpu/observability/",
                 "paddle_tpu/elastic/", "paddle_tpu/distributed/")


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and \
            node.value.id == "self":
        return node.attr
    return None


def _is_lock_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    name = f.attr if isinstance(f, ast.Attribute) else \
        (f.id if isinstance(f, ast.Name) else "")
    return name in _LOCK_CTORS


class _Write:
    __slots__ = ("attr", "method", "guarded", "line", "col")

    def __init__(self, attr, method, guarded, line, col):
        self.attr = attr
        self.method = method
        self.guarded = guarded
        self.line = line
        self.col = col


class LockDisciplineAnalyzer(Analyzer):
    def __init__(self, dirs: Sequence[str] = _DEFAULT_DIRS):
        self.dirs = tuple(dirs)

    name = "lock_discipline"

    def run(self, files: Sequence[SourceFile]) -> List[Finding]:
        out: List[Finding] = []
        for sf in files:
            if self.dirs and not any(sf.rel.startswith(d)
                                     for d in self.dirs):
                continue
            for node in sf.tree.body:
                if isinstance(node, ast.ClassDef):
                    out.extend(self._check_class(sf, node))
            out.extend(self._check_module_globals(sf))
        return out

    # ------------------------------------------------------ classes
    def _check_class(self, sf: SourceFile, cls: ast.ClassDef
                     ) -> List[Finding]:
        methods = [n for n in cls.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]
        lock_attrs: Set[str] = set()
        for m in methods:
            for n in ast.walk(m):
                if isinstance(n, ast.Assign) and _is_lock_ctor(n.value):
                    for t in n.targets:
                        a = _self_attr(t)
                        if a:
                            lock_attrs.add(a)
                if isinstance(n, ast.With):
                    for item in n.items:
                        a = _self_attr(item.context_expr)
                        if a and "lock" in a.lower():
                            lock_attrs.add(a)
        if not lock_attrs:
            return []

        writes: List[_Write] = []
        reads: Dict[str, Set[str]] = {}       # attr -> methods reading
        thread_targets: Set[str] = set()
        callsites: Dict[str, List[Tuple[str, bool]]] = {}
        for m in methods:
            self._scan_method(m, lock_attrs, writes, reads,
                              thread_targets, callsites)

        # a private helper whose EVERY call site holds the lock (either
        # lexically or because the caller is itself such a helper) runs
        # lock-held — the "# lock held" convention, made checkable.
        # Optimistic fixpoint; public methods are never inferred held
        # (external callers are invisible).
        held = {m.name: True for m in methods
                if m.name.startswith("_") and callsites.get(m.name)}
        changed = True
        while changed:
            changed = False
            for name in list(held):
                if held[name] and any(
                        not g and not held.get(caller, False)
                        for caller, g in callsites[name]):
                    held[name] = False
                    changed = True
        for w in writes:
            if not w.guarded and held.get(w.method, False):
                w.guarded = True

        findings: List[Finding] = []
        by_attr: Dict[str, List[_Write]] = {}
        for w in writes:
            by_attr.setdefault(w.attr, []).append(w)

        for attr, ws in sorted(by_attr.items()):
            post_init = [w for w in ws if w.method != "__init__"]
            guarded = [w for w in post_init if w.guarded]
            unguarded = [w for w in post_init if not w.guarded]
            qual = f"{cls.name}.{attr}"
            if guarded and unguarded:
                for w in unguarded:
                    findings.append(Finding(
                        self.name, "LK001", sf.rel, w.line, w.col,
                        f"self.{attr} is written under the lock "
                        f"elsewhere in {cls.name} but unguarded here "
                        f"in {w.method!r}",
                        symbol=qual, detail=w.method))
            elif unguarded and thread_targets:
                touchers = {w.method for w in ws} | \
                    reads.get(attr, set())
                for w in unguarded:
                    if w.method in thread_targets and \
                            touchers - {w.method}:
                        findings.append(Finding(
                            self.name, "LK002", sf.rel, w.line, w.col,
                            f"self.{attr} written without the lock in "
                            f"thread-target {w.method!r} and touched "
                            f"by {sorted(touchers - {w.method})} — "
                            f"unguarded shared state",
                            symbol=qual, detail=w.method,
                            severity="warning"))
        return findings

    def _scan_method(self, m, lock_attrs, writes, reads,
                     thread_targets, callsites):
        def walk(node, guarded):
            for child in ast.iter_child_nodes(node):
                g = guarded
                if isinstance(child, ast.With):
                    for item in child.items:
                        a = _self_attr(item.context_expr)
                        if a in lock_attrs:
                            g = True
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    continue            # nested scope ≠ this method
                if isinstance(child, ast.Call):
                    callee = _self_attr(child.func)
                    if callee:
                        callsites.setdefault(callee, []).append(
                            (m.name, g))
                self._note(child, m.name, g, lock_attrs, writes,
                           reads, thread_targets)
                walk(child, g)
        walk(m, False)

    @staticmethod
    def _note(node, method, guarded, lock_attrs, writes, reads,
              thread_targets):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Tuple):
                    elts = t.elts
                else:
                    elts = [t]
                for e in elts:
                    a = _self_attr(e)
                    if a and a not in lock_attrs:
                        writes.append(_Write(a, method, guarded,
                                             e.lineno, e.col_offset))
        elif isinstance(node, ast.Attribute) and \
                isinstance(node.ctx, ast.Load):
            a = _self_attr(node)
            if a:
                reads.setdefault(a, set()).add(method)
        if isinstance(node, ast.Call):
            f = node.func
            ctor = f.attr if isinstance(f, ast.Attribute) else \
                (f.id if isinstance(f, ast.Name) else "")
            if ctor == "Thread":
                for kw in node.keywords:
                    if kw.arg == "target":
                        t = _self_attr(kw.value)
                        if t:
                            thread_targets.add(t)

    # ------------------------------------------------------ globals
    def _check_module_globals(self, sf: SourceFile) -> List[Finding]:
        """LK003: module globals written both inside and outside
        ``with <lock>:`` across the module's functions."""
        guarded_writes: Dict[str, Tuple[int, int]] = {}
        unguarded: Dict[str, List[Tuple[int, int, str]]] = {}
        lock_names: Set[str] = set()
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Assign) and \
                    _is_lock_ctor(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        lock_names.add(t.id)
        if not lock_names:
            return []

        def scan_func(fn):
            declared = {n for s in ast.walk(fn)
                        if isinstance(s, ast.Global) for n in s.names}
            if not declared:
                return

            def walk(node, guarded):
                for child in ast.iter_child_nodes(node):
                    g = guarded
                    if isinstance(child, ast.With):
                        for item in child.items:
                            ctx = item.context_expr
                            if isinstance(ctx, ast.Name) and \
                                    ctx.id in lock_names:
                                g = True
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                        continue
                    if isinstance(child, ast.Assign):
                        for t in child.targets:
                            names = t.elts if isinstance(t, ast.Tuple) \
                                else [t]
                            for e in names:
                                if isinstance(e, ast.Name) and \
                                        e.id in declared:
                                    if g:
                                        guarded_writes.setdefault(
                                            e.id, (e.lineno,
                                                   e.col_offset))
                                    else:
                                        unguarded.setdefault(
                                            e.id, []).append(
                                            (e.lineno, e.col_offset,
                                             fn.name))
                    walk(child, g)
            walk(fn, False)

        for node in sf.tree.body:
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                scan_func(node)

        out = []
        for name in sorted(set(guarded_writes) & set(unguarded)):
            for line, col, fn_name in unguarded[name]:
                out.append(Finding(
                    self.name, "LK003", sf.rel, line, col,
                    f"module global {name!r} is lock-guarded elsewhere "
                    f"but written bare in {fn_name!r}",
                    symbol=name, detail=fn_name))
        return out
