"""Shared static-analysis core: file walker, findings, baseline.

Reference analog: the C++ tree catches whole classes of misuse at
compile time (typed gflags in paddle/phi/core/flags.cc, lock
annotations, tracer asserts). A Python/JAX rebuild has no compiler to
lean on, so this package supplies the equivalent as AST-based
analyzers that run in CI (tests/test_static_analysis.py) and from the
command line (tools/pdlint.py).

Everything here is stdlib-only (ast/os/json) — an analyzer run never
imports the modules it inspects, so pdlint can vet code that would
crash at import time.

Findings carry a line number for humans but fingerprint WITHOUT it
(rule + path + symbol + detail), so a committed baseline survives
unrelated edits shifting lines.
"""
from __future__ import annotations

import ast
import json
import os
import re
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set

__all__ = [
    "Finding", "SourceFile", "Analyzer", "iter_python_files",
    "parse_files", "run_analyzers", "load_baseline", "write_baseline",
    "filter_new", "baseline_entry", "stale_entries", "to_sarif",
    "changed_files", "in_scope", "clear_run_cache",
]


def in_scope(rel: str, dirs: Sequence[str]) -> bool:
    """Whether a repo-relative path sits under one of the scope
    directory prefixes. Matches at any path depth (``d in the middle
    of rel`` as a full segment), so analyzer self-tests that rebuild a
    ``paddle_tpu/serving/...`` tree under a tmp dir scope the same way
    the real tree does. Empty ``dirs`` = everything in scope."""
    if not dirs:
        return True
    return any(rel.startswith(d) or f"/{d}" in rel for d in dirs)

_SKIP_DIRS = {".git", "__pycache__", ".claude", "build", "dist",
              ".pytest_cache", "fixtures", "node_modules"}

# per-file suppression for deliberate-negative code (analyzer
# self-tests, fixtures that must reference phantom flags) — a comment
# reading "pdlint: skip-file", or "pdlint: disable=<name,...>" with
# analyzer names (the literal syntax is spelled out in README.md; not
# repeated here or this module would opt itself out)
_PRAGMA = re.compile(
    r"#[ \t]*pdlint:[ \t]*(skip-file|disable=([A-Za-z0-9_, \t]+))")


@dataclass(frozen=True)
class Finding:
    """One diagnostic. ``severity`` is "error" (blocks) or "warning"
    (reported, still baselined/gated so new ones can't creep in).
    ``symbol`` is the enclosing context (qualname, class attr, flag
    name); ``detail`` the offending token — together with rule+path
    they form the line-number-independent fingerprint."""

    analyzer: str
    rule: str
    path: str           # repo-relative, posix separators
    line: int
    col: int
    message: str
    symbol: str = ""
    detail: str = ""
    severity: str = "error"

    @property
    def fingerprint(self) -> str:
        return f"{self.rule}:{self.path}:{self.symbol}:{self.detail}"

    def format(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"[{self.analyzer}/{self.rule}] {self.severity}: "
                f"{self.message}")

    def to_dict(self) -> dict:
        return {"analyzer": self.analyzer, "rule": self.rule,
                "path": self.path, "line": self.line, "col": self.col,
                "severity": self.severity, "symbol": self.symbol,
                "detail": self.detail, "message": self.message,
                "fingerprint": self.fingerprint}


@dataclass
class SourceFile:
    """A parsed file handed to every analyzer: one walk + one
    ``ast.parse`` shared by all three."""

    path: str           # absolute
    rel: str            # repo-relative, posix
    source: str
    tree: Optional[ast.AST] = None
    error: Optional[Finding] = field(default=None)
    disabled: Set[str] = field(default_factory=set)

    @staticmethod
    def parse_pragmas(source: str) -> Set[str]:
        """Analyzer names this file opts out of; {"*"} = all."""
        out: Set[str] = set()
        m = _PRAGMA.search(source)
        if m:
            if m.group(1) == "skip-file":
                out.add("*")
            else:
                out.update(n.strip() for n in m.group(2).split(",")
                           if n.strip())
        return out


class Analyzer:
    """Base: subclasses set ``name`` and implement ``run``."""

    name = "base"

    def run(self, files: Sequence[SourceFile]) -> List[Finding]:
        raise NotImplementedError


def iter_python_files(paths: Iterable[str],
                      root: Optional[str] = None) -> List[str]:
    """Expand files/directories into a sorted, deduplicated list of .py
    paths, skipping VCS/cache/fixture directories."""
    out = []
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p) and p.endswith(".py"):
            out.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in _SKIP_DIRS)
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return sorted(set(out))


def parse_files(file_paths: Sequence[str],
                root: Optional[str] = None) -> List[SourceFile]:
    """Read + parse every path; a syntax error becomes a CORE001
    finding on the file instead of aborting the run."""
    root = os.path.abspath(root or os.getcwd())
    files = []
    for path in file_paths:
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
        except OSError as e:
            files.append(SourceFile(path, rel, "", error=Finding(
                "core", "CORE002", rel, 0, 0,
                f"unreadable file: {e}", detail="unreadable")))
            continue
        sf = SourceFile(path, rel, source,
                        disabled=SourceFile.parse_pragmas(source))
        try:
            sf.tree = ast.parse(source, filename=rel)
        except SyntaxError as e:
            sf.error = Finding(
                "core", "CORE001", rel, e.lineno or 0, e.offset or 0,
                f"syntax error: {e.msg}", detail="syntax-error")
        files.append(sf)
    return files


# Repeated identical runs are common — the tier-1 repo gate, the
# ratchet check, the SARIF emitter and gen_api_golden all analyze the
# same unchanged tree in one process.  Findings are a pure function of
# (file contents, analyzer set), so run_analyzers memoizes on
# (root, per-file mtime_ns+size, per-analyzer cache token) and replays
# the finding list instead of re-walking ~250 ASTs.  Any edit to any
# analyzed file changes its stat signature and misses the cache.
_RUN_CACHE: "OrderedDict[tuple, List[Finding]]" = OrderedDict()
_RUN_CACHE_MAX = 8


def _run_cache_key(file_list: Sequence[str],
                   analyzers: Sequence[Analyzer],
                   root: Optional[str]):
    sig = []
    for p in file_list:
        try:
            st = os.stat(p)
        except OSError:
            return None                  # vanished mid-run: don't cache
        sig.append((p, st.st_mtime_ns, st.st_size))
    tokens = tuple(getattr(an, "cache_token", an.name)
                   for an in analyzers)
    return (root, tuple(sig), tokens)


def clear_run_cache():
    """Drop memoized run_analyzers results (and the engine's shared
    call-graph entries). The runtime-budget self-test calls this so it
    times a genuinely cold run."""
    _RUN_CACHE.clear()
    from . import engine
    engine.clear_shared_graphs()


def run_analyzers(paths: Sequence[str], analyzers: Sequence[Analyzer],
                  root: Optional[str] = None) -> List[Finding]:
    """Walk ``paths``, parse once, run every analyzer; findings come
    back sorted by (path, line, rule) for stable output.  Identical
    repeat runs (same files by stat signature, same analyzer set) are
    served from an in-process cache."""
    file_list = iter_python_files(paths, root)
    key = _run_cache_key(file_list, analyzers, root)
    if key is not None and key in _RUN_CACHE:
        _RUN_CACHE.move_to_end(key)
        return list(_RUN_CACHE[key])
    files = parse_files(file_list, root)
    findings = [f.error for f in files
                if f.error is not None and "*" not in f.disabled]
    parsed = [f for f in files if f.tree is not None]
    for an in analyzers:
        findings.extend(an.run(
            [f for f in parsed
             if "*" not in f.disabled and an.name not in f.disabled]))
    result = sorted(findings, key=lambda f: (f.path, f.line, f.rule,
                                             f.detail))
    if key is not None:
        _RUN_CACHE[key] = list(result)
        while len(_RUN_CACHE) > _RUN_CACHE_MAX:
            _RUN_CACHE.popitem(last=False)
    return result


# ------------------------------------------------------------ baseline
def baseline_entry(f: Finding) -> dict:
    """The readable on-disk form; matching is by fingerprint only, the
    rest is context for whoever prunes the file."""
    return {"fingerprint": f.fingerprint, "rule": f.rule,
            "path": f.path, "symbol": f.symbol,
            "severity": f.severity, "message": f.message}


def load_baseline(path: str) -> Dict[str, dict]:
    """fingerprint -> entry; an absent file is an empty baseline."""
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return {e["fingerprint"]: e for e in data.get("findings", [])}


def write_baseline(path: str, findings: Sequence[Finding]):
    entries = sorted((baseline_entry(f) for f in findings),
                     key=lambda e: e["fingerprint"])
    # one entry per fingerprint: repeats of the same pattern in one
    # symbol are suppressed together, as intended
    seen, unique = set(), []
    for e in entries:
        if e["fingerprint"] not in seen:
            seen.add(e["fingerprint"])
            unique.append(e)
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "tool": "pdlint",
                   "findings": unique}, f, indent=1, sort_keys=True)
        f.write("\n")


def filter_new(findings: Sequence[Finding],
               baseline: Dict[str, dict]) -> List[Finding]:
    """Findings not excused by the baseline — what the CI gate fails
    on."""
    return [f for f in findings if f.fingerprint not in baseline]


def stale_entries(findings: Sequence[Finding],
                  baseline: Dict[str, dict]) -> List[str]:
    """The RATCHET: baselined fingerprints the repo no longer produces.
    A fixed finding must be pruned from the baseline, so the file only
    ever shrinks — it can excuse history, never accumulate room for
    new debt. Meaningful only for a run over the same trees the
    baseline was written from (a subtree run makes everything look
    stale)."""
    live = {f.fingerprint for f in findings}
    return sorted(set(baseline) - live)


# --------------------------------------------------------------- sarif
_SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/"
                 "sarif-spec/master/Schemata/sarif-schema-2.1.0.json")


# one-line rule docs surfaced as SARIF shortDescription (code-scanning
# UIs show these next to each result); unlisted rules still emit a
# bare rule object
RULE_DOCS = {
    "LD001": "lock-order inversion cycle in the global acquisition-"
             "order graph — potential deadlock",
    "LD002": "blocking call (socket/HTTP, subprocess, timeout-less "
             "get/result/wait/join, device sync) while a lock is "
             "held",
    "LD003": "Condition.wait outside a predicate loop — spurious "
             "wakeups break the waited-for invariant",
    "LK001": "unguarded write to a lock-protected attribute",
    "TD001": "blocking socket/HTTP call without an explicit timeout "
             "in serving code",
    "RP002": "lock.acquire() without a release on some path",
}


def to_sarif(findings: Sequence[Finding],
             analyzer_names: Sequence[str],
             baseline: Optional[Dict[str, dict]] = None) -> dict:
    """Findings as a SARIF 2.1.0 document (one run, driver 'pdlint').
    Baselined findings get ``baselineState: "unchanged"`` so SARIF
    viewers and code-scanning UIs fold them away; new ones are
    ``"new"``. Fingerprints ride ``partialFingerprints`` under the
    same key the CI gate matches on."""
    baseline = baseline or {}
    rules_seen: Dict[str, dict] = {}
    results = []
    for f in findings:
        rule = {
            "id": f.rule,
            "name": f.rule,
            "properties": {"analyzer": f.analyzer},
        }
        if f.rule in RULE_DOCS:
            rule["shortDescription"] = {"text": RULE_DOCS[f.rule]}
        rules_seen.setdefault(f.rule, rule)
        results.append({
            "ruleId": f.rule,
            "level": "error" if f.severity == "error" else "warning",
            "message": {"text": f.message},
            "baselineState": ("unchanged" if f.fingerprint in baseline
                              else "new"),
            "partialFingerprints": {"pdlint/v1": f.fingerprint},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path,
                                         "uriBaseId": "SRCROOT"},
                    "region": {"startLine": max(1, f.line),
                               "startColumn": max(1, f.col + 1)},
                },
                "logicalLocations": ([{"name": f.symbol}]
                                     if f.symbol else []),
            }],
        })
    return {
        "$schema": _SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "pdlint",
                "informationUri":
                    "https://github.com/paddle-tpu/paddle-tpu",
                "rules": [rules_seen[r] for r in sorted(rules_seen)],
                "properties": {"analyzers": list(analyzer_names)},
            }},
            "columnKind": "unicodeCodePoints",
            "results": results,
        }],
    }


# ----------------------------------------------------------- changed
def changed_files(ref: str, root: str) -> Optional[Set[str]]:
    """Repo-relative posix paths changed vs ``ref`` (committed diff +
    staged + unstaged + untracked), or None when git can't answer
    (not a checkout, unknown ref) — callers should fall back to a full
    run rather than silently analyzing nothing."""
    import subprocess
    out: Set[str] = set()
    for args in (["git", "diff", "--name-only", ref, "--"],
                 ["git", "ls-files", "--others",
                  "--exclude-standard"]):
        try:
            r = subprocess.run(args, cwd=root, capture_output=True,
                               text=True, timeout=30)
        except (OSError, subprocess.SubprocessError):
            return None
        if r.returncode != 0:
            return None
        out.update(line.strip().replace(os.sep, "/")
                   for line in r.stdout.splitlines() if line.strip())
    return out
