"""Tracer-safety analyzer: host syncs and impurity under tracing.

JAX tracing (``jax.jit``, ``paddle.jit.to_static``, ``TrainStep``)
runs the Python body ONCE with abstract values; anything that forces a
concrete value (``.numpy()``, ``float(x)``, ``if tensor:``) either
crashes at trace time or — worse — silently bakes a trace-time
constant into the compiled program. Wall clocks, Python/NumPy RNG and
``os.environ`` reads don't crash at all: they execute once and freeze,
so every later call replays the first call's value. The reference
framework catches the C++ analogs at compile time; here the tracer
only finds out at runtime, on device. This analyzer finds them in CI.

Entry points: functions decorated with (or passed to) ``jit`` /
``to_static`` / ``pjit``, plus functions named ``train_step``.
Reachability is per module over a name-resolution call graph (bare
calls to module functions, ``self.method`` calls), so helpers a jitted
function calls are checked too.

Rules:
  TS001  host-sync call (.numpy()/.item()/.tolist())
  TS002  Python coercion / branch on a traced value
         (float()/int()/bool() of a parameter-derived name, ``if x:``)
  TS003  Python/NumPy RNG call (random.*, np.random.*)
  TS004  wall-clock read (time.time/perf_counter/monotonic/...)
  TS005  os.environ / os.getenv read
"""
from __future__ import annotations

import ast
from collections import deque
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Analyzer, Finding, SourceFile

__all__ = ["TracerSafetyAnalyzer"]

_JIT_NAMES = {"jit", "to_static", "pjit"}
_SYNC_ATTRS = {"numpy", "item", "tolist"}
_CLOCK_ATTRS = {"time", "perf_counter", "monotonic", "process_time",
                "time_ns", "perf_counter_ns", "monotonic_ns",
                "clock_gettime"}


def _dotted(node: ast.AST) -> Optional[str]:
    """x.y.z attribute chain as 'x.y.z', or None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class _Imports(ast.NodeVisitor):
    """alias -> fully dotted module/name, for resolving np.random etc."""

    def __init__(self):
        self.aliases: Dict[str, str] = {}

    def visit_Import(self, node):
        for a in node.names:
            self.aliases[a.asname or a.name.split(".")[0]] = \
                a.name if a.asname else a.name.split(".")[0]

    def visit_ImportFrom(self, node):
        if node.level:         # relative import — in-package, never
            return             # stdlib random/time/os
        mod = node.module or ""
        for a in node.names:
            self.aliases[a.asname or a.name] = f"{mod}.{a.name}"

    def resolve(self, dotted: str) -> str:
        head, _, rest = dotted.partition(".")
        head = self.aliases.get(head, head)
        return f"{head}.{rest}" if rest else head


class _FuncInfo:
    __slots__ = ("node", "qualname", "is_method", "entry_via")

    def __init__(self, node, qualname, is_method):
        self.node = node
        self.qualname = qualname
        self.is_method = is_method
        self.entry_via: Optional[str] = None   # why it became an entry


class _Collector(ast.NodeVisitor):
    """All function defs with qualnames + jit-call-site entries."""

    def __init__(self):
        self.stack: List[str] = []
        self.class_depth = 0
        self.funcs: Dict[str, _FuncInfo] = {}
        self.jit_call_args: List[Tuple[str, str]] = []  # (name, via)

    def _visit_func(self, node):
        qual = ".".join(self.stack + [node.name])
        self.funcs[qual] = _FuncInfo(node, qual, self.class_depth > 0)
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_ClassDef(self, node):
        self.stack.append(node.name)
        self.class_depth += 1
        self.generic_visit(node)
        self.class_depth -= 1
        self.stack.pop()

    def visit_Call(self, node):
        # jax.jit(fn) / to_static(fn): first positional arg that is a
        # bare name becomes an entry point
        via = _jit_identifier(node.func)
        if via and node.args and isinstance(node.args[0], ast.Name):
            self.jit_call_args.append((node.args[0].id, via))
        self.generic_visit(node)


def _jit_identifier(node: ast.AST) -> Optional[str]:
    """'jit'/'to_static'/... when this expression names a jit wrapper
    (Name, dotted attribute, or functools.partial(jax.jit, ...))."""
    if isinstance(node, ast.Call):       # partial(jax.jit, ...)
        for sub in [node.func] + list(node.args):
            got = _jit_identifier(sub)
            if got:
                return got
        return None
    d = _dotted(node)
    if d is None:
        return None
    last = d.split(".")[-1]
    return last if last in _JIT_NAMES else None


def _decorated_entry(node) -> Optional[str]:
    for dec in node.decorator_list:
        got = _jit_identifier(dec)
        if got:
            return got
    return None


class TracerSafetyAnalyzer(Analyzer):
    name = "tracer_safety"

    def run(self, files: Sequence[SourceFile]) -> List[Finding]:
        out: List[Finding] = []
        for sf in files:
            out.extend(self._run_file(sf))
        return out

    # ------------------------------------------------------ per file
    def _run_file(self, sf: SourceFile) -> List[Finding]:
        imports = _Imports()
        imports.visit(sf.tree)
        coll = _Collector()
        coll.visit(sf.tree)
        if not coll.funcs:
            return []

        by_last: Dict[str, List[str]] = {}
        for qual in coll.funcs:
            by_last.setdefault(qual.split(".")[-1], []).append(qual)

        entries: List[str] = []
        for qual, info in coll.funcs.items():
            via = _decorated_entry(info.node)
            if via is None and info.node.name == "train_step":
                via = "train_step"
            if via is not None:
                info.entry_via = via
                entries.append(qual)
        for name, via in coll.jit_call_args:
            for qual in by_last.get(name, ()):
                if coll.funcs[qual].entry_via is None:
                    coll.funcs[qual].entry_via = via
                    entries.append(qual)
        if not entries:
            return []

        # reachability over bare-name and self.method calls
        reach: Dict[str, str] = {}      # qualname -> root entry
        work = [(q, coll.funcs[q].entry_via or "jit") for q in entries]
        while work:
            qual, root = work.pop()
            if qual in reach:
                continue
            reach[qual] = root
            for callee in self._callees(coll.funcs[qual].node):
                for cq in by_last.get(callee, ()):
                    if cq not in reach:
                        work.append((cq, root))

        findings: List[Finding] = []
        for qual, root in sorted(reach.items()):
            findings.extend(self._check_body(
                sf, coll.funcs[qual], root, imports))
        return findings

    @staticmethod
    def _callees(func_node) -> Set[str]:
        """Bare and self.* call targets in this function's own body
        (nested defs are separate functions)."""
        out: Set[str] = set()
        for node in _own_body_walk(func_node):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Name):
                out.add(f.id)
            elif isinstance(f, ast.Attribute) and \
                    isinstance(f.value, ast.Name) and \
                    f.value.id in ("self", "cls"):
                out.add(f.attr)
        return out

    # ------------------------------------------------------ checks
    def _check_body(self, sf: SourceFile, info: _FuncInfo, root: str,
                    imports: _Imports) -> List[Finding]:
        node = info.node
        tainted = {a.arg for a in
                   list(node.args.posonlyargs) + list(node.args.args)
                   + list(node.args.kwonlyargs)
                   + ([node.args.vararg] if node.args.vararg else [])
                   } - {"self", "cls"}
        findings: List[Finding] = []

        def emit(n, rule, detail, msg, severity="error"):
            findings.append(Finding(
                self.name, rule, sf.rel, n.lineno, n.col_offset,
                f"{msg} in {info.qualname!r} (traced via {root})",
                symbol=info.qualname, detail=detail, severity=severity))

        for n in _own_body_walk(node):
            # taint propagation: x = <expr touching a tainted name>
            if isinstance(n, ast.Assign) and _touches(n.value, tainted):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        tainted.add(t.id)
            if isinstance(n, ast.Call):
                self._check_call(n, emit, tainted, imports)
            if isinstance(n, (ast.If, ast.While)) and \
                    isinstance(n.test, ast.Name) and \
                    n.test.id in tainted:
                emit(n.test, "TS002", f"if {n.test.id}:",
                     f"branch on traced value {n.test.id!r} — trace-"
                     f"time concretization")
            if isinstance(n, ast.Subscript) and \
                    isinstance(n.ctx, ast.Load):
                d = _dotted(n.value)
                if d and imports.resolve(d) == "os.environ":
                    emit(n, "TS005", "os.environ[]",
                         "os.environ read freezes at trace time")
        return findings

    def _check_call(self, n: ast.Call, emit, tainted, imports):
        f = n.func
        if isinstance(f, ast.Attribute):
            if f.attr in _SYNC_ATTRS and not n.args:
                base = _dotted(f.value)
                root_seg = base.split(".")[0] if base else None
                # module-attr calls (np.x.item) aren't value syncs;
                # anything else (locals, self.*, call results) is
                if root_seg is None or root_seg not in imports.aliases:
                    emit(n, "TS001", f".{f.attr}()",
                         f".{f.attr}() forces a host sync/device "
                         f"round-trip")
                    return
            d = _dotted(f)
            if d is not None:
                r = imports.resolve(d)
                if r.startswith("random.") or \
                        r.startswith("numpy.random."):
                    emit(n, "TS003", r,
                         f"{r}() is host RNG — value freezes into the "
                         f"trace; use paddle RNG / jax.random")
                    return
                head, _, attr = r.rpartition(".")
                if head == "time" and attr in _CLOCK_ATTRS:
                    emit(n, "TS004", r,
                         f"{r}() reads the wall clock at trace time "
                         f"only")
                    return
                if r == "os.getenv" or r == "os.environ.get":
                    emit(n, "TS005", r,
                         f"{r}() environment read freezes at trace "
                         f"time")
                    return
        elif isinstance(f, ast.Name) and f.id in ("float", "int",
                                                  "bool") \
                and len(n.args) == 1:
            a = n.args[0]
            name = a.id if isinstance(a, ast.Name) else \
                (_dotted(a) if isinstance(a, ast.Attribute) else None)
            root_name = (name or "").split(".")[0]
            if root_name in tainted:
                emit(n, "TS002", f"{f.id}({name})",
                     f"{f.id}() concretizes traced value {name!r}")


def _own_body_walk(func_node):
    """Pre-order, SOURCE-ORDER walk of this function's own body (taint
    propagation needs assignments seen before later uses) — nested
    function defs are separate call-graph nodes, not descended into."""
    queue = deque(func_node.body)
    while queue:
        n = queue.popleft()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        queue.extendleft(reversed(list(ast.iter_child_nodes(n))))


def _touches(expr: ast.AST, names: Set[str]) -> bool:
    return any(isinstance(n, ast.Name) and n.id in names
               for n in ast.walk(expr))
