"""Tracer-safety analyzer: host syncs and impurity under tracing.

JAX tracing (``jax.jit``, ``paddle.jit.to_static``, ``TrainStep``)
runs the Python body ONCE with abstract values; anything that forces a
concrete value (``.numpy()``, ``float(x)``, ``if tensor:``) either
crashes at trace time or — worse — silently bakes a trace-time
constant into the compiled program. Wall clocks, Python/NumPy RNG and
``os.environ`` reads don't crash at all: they execute once and freeze,
so every later call replays the first call's value. The reference
framework catches the C++ analogs at compile time; here the tracer
only finds out at runtime, on device. This analyzer finds them in CI.

Entry points: functions decorated with (or passed to) ``jit`` /
``to_static`` / ``pjit``, plus functions named ``train_step``.
Reachability runs over the engine's REPO-WIDE call graph
(``analysis.engine.CallGraph``): bare calls, ``self.method``,
module-qualified calls across files, ``functools.partial(target,
...)`` pre-binding, and lambdas/function aliases assigned to locals —
the PR 4 per-module walker missed the last two (helpers dispatched
through ``partial(self.m, ...)`` or a local lambda were unchecked).

Rules:
  TS001  host-sync call (.numpy()/.item()/.tolist())
  TS002  Python coercion / branch on a traced value
         (float()/int()/bool() of a parameter-derived name, ``if x:``)
  TS003  Python/NumPy RNG call (random.*, np.random.*)
  TS004  wall-clock read (time.time/perf_counter/monotonic/...)
  TS005  os.environ / os.getenv read
"""
from __future__ import annotations

import ast
from typing import List, Optional, Sequence

from .core import Analyzer, Finding, SourceFile
from .engine import (CallGraph, FuncNode, Taint, dotted_name,
                     iter_own_body, jit_entries)

__all__ = ["TracerSafetyAnalyzer"]

_SYNC_ATTRS = {"numpy", "item", "tolist"}
_CLOCK_ATTRS = {"time", "perf_counter", "monotonic", "process_time",
                "time_ns", "perf_counter_ns", "monotonic_ns",
                "clock_gettime"}


class TracerSafetyAnalyzer(Analyzer):
    name = "tracer_safety"

    def run(self, files: Sequence[SourceFile]) -> List[Finding]:
        graph = CallGraph.shared(files)
        reach = graph.reachable(jit_entries(graph))
        findings: List[Finding] = []
        for key in sorted(reach):
            fn = graph.funcs[key]
            findings.extend(self._check_body(
                fn, reach[key], graph.modules[key[0]].imports))
        return findings

    # ------------------------------------------------------ checks
    def _check_body(self, fn: FuncNode, root: str,
                    imports) -> List[Finding]:
        node = fn.node
        taint = Taint(node)
        # TS002's taint premise — "parameters are tracers" — only
        # holds where jit binds the signature: the DIRECT entry. A
        # transitively-reached helper's params are routinely host
        # config (bool flags, op names) the caller passes statically;
        # the impurity rules (TS001/3/4/5) stay context-free and apply
        # everywhere reachable.
        direct = fn.entry_via is not None
        findings: List[Finding] = []

        def emit(n, rule, detail, msg, severity="error"):
            findings.append(Finding(
                self.name, rule, fn.sf.rel, n.lineno, n.col_offset,
                f"{msg} in {fn.qualname!r} (traced via {root})",
                symbol=fn.qualname, detail=detail, severity=severity))

        for n in iter_own_body(node):
            taint.note_stmt(n)
            if isinstance(n, ast.Call):
                self._check_call(n, emit, taint if direct else None,
                                 imports)
            if direct and isinstance(n, (ast.If, ast.While)) and \
                    isinstance(n.test, ast.Name) and \
                    n.test.id in taint.names:
                emit(n.test, "TS002", f"if {n.test.id}:",
                     f"branch on traced value {n.test.id!r} — trace-"
                     f"time concretization")
            if isinstance(n, ast.Subscript) and \
                    isinstance(n.ctx, ast.Load):
                d = dotted_name(n.value)
                if d and imports.resolve(d) == "os.environ":
                    emit(n, "TS005", "os.environ[]",
                         "os.environ read freezes at trace time")
        return findings

    def _check_call(self, n: ast.Call, emit,
                    taint: Optional[Taint], imports):
        f = n.func
        if isinstance(f, ast.Attribute):
            if f.attr in _SYNC_ATTRS and not n.args:
                base = dotted_name(f.value)
                root_seg = base.split(".")[0] if base else None
                # module-attr calls (np.x.item) aren't value syncs;
                # anything else (locals, self.*, call results) is
                if root_seg is None or \
                        root_seg not in imports.aliases:
                    emit(n, "TS001", f".{f.attr}()",
                         f".{f.attr}() forces a host sync/device "
                         f"round-trip")
                    return
            d = dotted_name(f)
            if d is not None:
                r = imports.resolve(d)
                if r.startswith("random.") or \
                        r.startswith("numpy.random."):
                    emit(n, "TS003", r,
                         f"{r}() is host RNG — value freezes into the "
                         f"trace; use paddle RNG / jax.random")
                    return
                head, _, attr = r.rpartition(".")
                if head == "time" and attr in _CLOCK_ATTRS:
                    emit(n, "TS004", r,
                         f"{r}() reads the wall clock at trace time "
                         f"only")
                    return
                if r == "os.getenv" or r == "os.environ.get":
                    emit(n, "TS005", r,
                         f"{r}() environment read freezes at trace "
                         f"time")
                    return
        elif taint is not None and isinstance(f, ast.Name) and \
                f.id in ("float", "int", "bool") and len(n.args) == 1:
            a = n.args[0]
            name = a.id if isinstance(a, ast.Name) else \
                (dotted_name(a) if isinstance(a, ast.Attribute)
                 else None)
            root_name = (name or "").split(".")[0]
            if root_name in taint.names:
                emit(n, "TS002", f"{f.id}({name})",
                     f"{f.id}() concretizes traced value {name!r}")
