"""paddle_tpu.analysis — interprocedural static analysis with a CI gate.

The compile-time checks the reference framework gets from C++ (typed
gflags registration, tracer asserts, lock annotations, the inplace/
donation pass), rebuilt as linters over this repo's Python. Since
pdlint v2 the analyzers share one interprocedural engine
(``analysis.engine``): a repo-wide call graph (bare / ``self.`` /
module-qualified calls, ``functools.partial``, local lambdas/aliases,
thread targets), a per-function CFG with exception edges, and a common
taint lattice.

- ``TracerSafetyAnalyzer`` — host syncs / impurity reachable from
  ``@jit`` / ``to_static`` / ``train_step`` entry points, repo-wide
  (TS001-TS005);
- ``FlagConsistencyAnalyzer`` — every ``FLAGS_*`` string resolves to a
  ``define_flag`` definition with a compatible type; dead flags are
  reported (FC001-FC004);
- ``LockDisciplineAnalyzer`` — unguarded shared-state writes in the
  threaded serving/observability/elastic/distributed packages
  (LK001-LK003);
- ``MetricDisciplineAnalyzer`` — registry metric families: naming,
  one type per name, unit suffixes, non-negative duration literals
  (MD001-MD003);
- ``DonationSafetyAnalyzer`` — reads of buffers already donated to a
  ``donate_argnums`` dispatch, and donated ``self``/module attributes
  that outlive the call (DS001-DS002);
- ``RecompileRiskAnalyzer`` — AOT compile sites outside the
  ``compile_cache.get_or_compile`` chokepoint, unbucketed
  data-dependent sizes in jitted signatures, set iteration ordering a
  traced pytree (RR001-RR003);
- ``ResourcePairingAnalyzer`` — ``PagedKVCache`` page retain/alloc
  without release/free on some path (exception edges included), bare
  ``lock.acquire()`` and manual ``__enter__`` without their pairs
  (RP001-RP003);
- ``TimeoutDisciplineAnalyzer`` — blocking socket/HTTP calls
  (``urlopen``, ``socket.create_connection``, ``HTTPConnection``,
  opener ``.open``) without an explicit timeout inside
  ``paddle_tpu/serving/`` — an unbounded wait on a wedgeable peer
  defeats the fleet's deadline/watchdog resilience (TD001);
- ``LockOrderAnalyzer`` — the *system* of locks: a global
  acquisition-order graph over the repo-wide call graph, reporting
  order-inversion cycles (LD001), blocking I/O / subprocess / device
  sync while a lock is held (LD002), and ``Condition.wait`` outside a
  predicate loop (LD003).

The runtime twin of ``LockOrderAnalyzer`` is ``analysis.sanitizer``
(lockdep): opt-in instrumented ``Lock``/``RLock``/``Condition`` that
observe the order the running program actually uses, raise on the
first observed inversion, and bridge into the same Finding/SARIF
pipeline via ``sanitizer.findings()`` (enabled under tier-1 with
``FLAGS_lockdep``).

Entry points: ``tools/pdlint.py`` (CLI: text/JSON/SARIF, git-aware
``--changed-only``, baseline ratchet, exit codes) and
``tests/test_static_analysis.py`` (the gate — fails on any finding not
excused by ``tests/fixtures/pdlint_baseline.json`` AND on stale
baseline entries, so the baseline only ever shrinks). Pure stdlib: an
analysis run parses, never imports, the code under inspection.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

from .core import (Analyzer, Finding, SourceFile, baseline_entry,
                   changed_files, clear_run_cache, filter_new,
                   in_scope, iter_python_files, load_baseline,
                   parse_files, run_analyzers, stale_entries,
                   to_sarif, write_baseline)
from .donation_safety import DonationSafetyAnalyzer
from .flag_consistency import FlagConsistencyAnalyzer
from .lock_discipline import LockDisciplineAnalyzer
from .lock_order import (LockOrderAnalyzer, LockOrderGraph,
                         build_lock_graph)
from .metric_discipline import MetricDisciplineAnalyzer
from .recompile_risk import RecompileRiskAnalyzer
from .resource_pairing import ResourcePairingAnalyzer
from .timeout_discipline import TimeoutDisciplineAnalyzer
from .tracer_safety import TracerSafetyAnalyzer

__all__ = [
    "Analyzer", "Finding", "SourceFile",
    "TracerSafetyAnalyzer", "FlagConsistencyAnalyzer",
    "LockDisciplineAnalyzer", "MetricDisciplineAnalyzer",
    "DonationSafetyAnalyzer", "RecompileRiskAnalyzer",
    "ResourcePairingAnalyzer", "TimeoutDisciplineAnalyzer",
    "LockOrderAnalyzer", "LockOrderGraph", "build_lock_graph",
    "all_analyzers", "analyzer_names", "default_paths", "repo_root",
    "default_baseline_path", "run_project",
    "iter_python_files", "parse_files", "run_analyzers",
    "load_baseline", "write_baseline", "filter_new", "baseline_entry",
    "stale_entries", "to_sarif", "changed_files", "in_scope",
    "clear_run_cache",
]


def all_analyzers() -> List[Analyzer]:
    return [TracerSafetyAnalyzer(), FlagConsistencyAnalyzer(),
            LockDisciplineAnalyzer(), MetricDisciplineAnalyzer(),
            DonationSafetyAnalyzer(), RecompileRiskAnalyzer(),
            ResourcePairingAnalyzer(), TimeoutDisciplineAnalyzer(),
            LockOrderAnalyzer()]


def analyzer_names() -> List[str]:
    return [a.name for a in all_analyzers()]


def repo_root() -> str:
    """The checkout root (parent of the installed package dir)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def default_paths(root: Optional[str] = None) -> List[str]:
    """The trees the flag-consistency contract spans; only the ones
    that exist (an installed wheel has no tools/ or tests/)."""
    root = root or repo_root()
    return [p for p in (os.path.join(root, d)
                        for d in ("paddle_tpu", "tools", "tests"))
            if os.path.isdir(p)]


def default_baseline_path(root: Optional[str] = None) -> str:
    return os.path.join(root or repo_root(), "tests", "fixtures",
                        "pdlint_baseline.json")


def run_project(paths: Optional[Sequence[str]] = None,
                analyzers: Optional[Sequence[Analyzer]] = None,
                root: Optional[str] = None,
                baseline_path: Optional[str] = None) -> Dict:
    """One-call project run: walk, analyze, apply baseline. Returns
    ``{"findings": [...], "new": [...], "baseline_size": int,
    "stale": [...]}`` — ``new`` is what a CI gate should fail on;
    ``stale`` are ratchet violations (baselined fingerprints the repo
    no longer produces — prune them, the baseline only shrinks)."""
    root = root or repo_root()
    findings = run_analyzers(paths or default_paths(root),
                             analyzers or all_analyzers(), root=root)
    bl_path = baseline_path if baseline_path is not None \
        else default_baseline_path(root)
    baseline = load_baseline(bl_path) if bl_path else {}
    return {"findings": findings,
            "new": filter_new(findings, baseline),
            "stale": stale_entries(findings, baseline),
            "baseline_size": len(baseline)}
