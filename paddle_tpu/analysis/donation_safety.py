"""Donation-safety analyzer: use-after-donate across jit dispatches.

``donate_argnums`` hands an input buffer to XLA for reuse as an
output: after the dispatch the Python reference still exists but the
device buffer is DELETED — any later read raises (best case) or, on
backends that recycle lazily, silently reads freshly-written output
bytes. The reference framework's inplace/donation pass catches the C++
analog at compile time; here the failure is a runtime crash on device,
under traffic, on the first batch that actually donates. The serving
dispatch (``FLAGS_serving_donate_inputs``), TrainStep's
``donate_argnums=(0, 2)`` step and every CachedDecoder pool carry are
exactly this shape.

Rules:

  DS001  a local name passed at a donated argument position of a
         donating callable is READ again on some path after the call
         without first being rebound
  DS002  the expression at a donated position is ``self.<attr>`` (or a
         module-level name) and some path reaches the function exit
         without storing a fresh value back — the attribute outlives
         the call holding a deleted buffer for every later method

Donating callables are discovered statically, no imports: names/attrs
bound to ``jax.jit(fn, donate_argnums=...)`` / ``pjit(...)``, both as
locals (``fn = jax.jit(step, donate_argnums=(0,))``) and as class
state (``self._compiled = jax.jit(..., donate_argnums=donate)`` in one
method, dispatched from another — resolved through the class-level
binding map). ``donate_argnums`` values resolve through int/tuple
literals, a local name bound to one, and the
``(0, 2) if flag else ()`` conditional idiom (union of branches:
may-donate is the right semantics for a safety rule).

The normal idiom — ``state = fn(state, batch)`` rebinding the donated
name to the fresh output — is recognized and clean.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Analyzer, Finding, SourceFile, in_scope
from .engine import build_cfg, dotted_name, head_exprs, iter_own_body

__all__ = ["DonationSafetyAnalyzer"]

_DEFAULT_DIRS = ("paddle_tpu/", "tools/")


def _jit_call_donations(call: ast.Call) -> Optional[ast.AST]:
    """The donate_argnums value expr of a jit/pjit call, or None."""
    f = call.func
    d = dotted_name(f)
    if d is None or d.split(".")[-1] not in ("jit", "pjit"):
        return None
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            return kw.value
    return None


def _int_tuple(expr: ast.AST) -> Optional[Set[int]]:
    """Tuple/list of int literals, or a single int literal."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, int) \
            and not isinstance(expr.value, bool):
        return {expr.value}
    if isinstance(expr, (ast.Tuple, ast.List)):
        out: Set[int] = set()
        for e in expr.elts:
            if not (isinstance(e, ast.Constant)
                    and isinstance(e.value, int)):
                return None
            out.add(e.value)
        return out
    return None


def _resolve_donations(expr: ast.AST,
                       local_consts: Dict[str, Set[int]]
                       ) -> Optional[Set[int]]:
    got = _int_tuple(expr)
    if got is not None:
        return got
    if isinstance(expr, ast.IfExp):
        a = _resolve_donations(expr.body, local_consts)
        b = _resolve_donations(expr.orelse, local_consts)
        if a is None and b is None:
            return None
        return (a or set()) | (b or set())
    if isinstance(expr, ast.Name):
        return local_consts.get(expr.id)
    return None


class DonationSafetyAnalyzer(Analyzer):
    name = "donation_safety"

    def __init__(self, dirs: Sequence[str] = _DEFAULT_DIRS):
        self.dirs = tuple(dirs)

    def run(self, files: Sequence[SourceFile]) -> List[Finding]:
        out: List[Finding] = []
        for sf in files:
            if not in_scope(sf.rel, self.dirs):
                continue
            out.extend(self._run_file(sf))
        return out

    # ------------------------------------------------------ per file
    def _run_file(self, sf: SourceFile) -> List[Finding]:
        findings: List[Finding] = []
        for node in sf.tree.body:
            if isinstance(node, ast.ClassDef):
                findings.extend(self._check_class(sf, node))
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                findings.extend(self._check_function(sf, node, {}))
        return findings

    def _check_class(self, sf: SourceFile,
                     cls: ast.ClassDef) -> List[Finding]:
        methods = [n for n in cls.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]
        # class-level donating attrs: self.X = jit(..., donate_argnums=)
        attr_don: Dict[str, Set[int]] = {}
        for m in methods:
            consts = self._local_int_tuples(m)
            for n in iter_own_body(m):
                if not isinstance(n, ast.Assign) or \
                        not isinstance(n.value, ast.Call):
                    continue
                dexpr = _jit_call_donations(n.value)
                if dexpr is None:
                    continue
                pos = _resolve_donations(dexpr, consts)
                if not pos:
                    continue
                for t in n.targets:
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        attr_don[t.attr] = \
                            attr_don.get(t.attr, set()) | pos
        out: List[Finding] = []
        for m in methods:
            out.extend(self._check_function(sf, m, attr_don,
                                            qual=f"{cls.name}.{m.name}"))
        return out

    @staticmethod
    def _local_int_tuples(fn) -> Dict[str, Set[int]]:
        """Names bound (once) to an int-tuple literal or a
        two-tuple-literal conditional — donate_argnums feeders."""
        out: Dict[str, Set[int]] = {}
        for n in iter_own_body(fn):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                    isinstance(n.targets[0], ast.Name):
                got = _resolve_donations(n.value, out)
                if got is not None:
                    out[n.targets[0].id] = got
        return out

    # ------------------------------------------------------ function
    def _check_function(self, sf: SourceFile, fn,
                        attr_don: Dict[str, Set[int]],
                        qual: Optional[str] = None) -> List[Finding]:
        qual = qual or fn.name
        consts = self._local_int_tuples(fn)
        # local donating callables: F = jax.jit(..., donate_argnums=...)
        local_don: Dict[str, Set[int]] = {}
        for n in iter_own_body(fn):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                    isinstance(n.targets[0], ast.Name) and \
                    isinstance(n.value, ast.Call):
                dexpr = _jit_call_donations(n.value)
                if dexpr is not None:
                    pos = _resolve_donations(dexpr, consts)
                    if pos:
                        local_don[n.targets[0].id] = pos

        cfg = build_cfg(fn)
        findings: List[Finding] = []
        for node in cfg.nodes:
            if node.stmt is None:
                continue
            for call in (c for part in head_exprs(node.stmt)
                         for c in ast.walk(part)
                         if isinstance(c, ast.Call)):
                pos = self._donated_positions(call, local_don,
                                              attr_don, consts)
                if not pos:
                    continue
                findings.extend(self._check_call(
                    sf, qual, cfg, node, call, pos))
        return findings

    @staticmethod
    def _donated_positions(call: ast.Call,
                           local_don: Dict[str, Set[int]],
                           attr_don: Dict[str, Set[int]],
                           consts: Dict[str, Set[int]]
                           ) -> Optional[Set[int]]:
        f = call.func
        if isinstance(f, ast.Name) and f.id in local_don:
            return local_don[f.id]
        if isinstance(f, ast.Attribute) and \
                isinstance(f.value, ast.Name) and f.value.id == "self" \
                and f.attr in attr_don:
            return attr_don[f.attr]
        if isinstance(f, ast.Call):
            # jax.jit(fn, donate_argnums=...)(args...): direct dispatch
            dexpr = _jit_call_donations(f)
            if dexpr is not None:
                return _resolve_donations(dexpr, consts)
        return None

    def _check_call(self, sf: SourceFile, qual: str, cfg, node,
                    call: ast.Call, positions: Set[int]
                    ) -> List[Finding]:
        callee = dotted_name(call.func) or "<jit>"
        findings: List[Finding] = []
        for p in sorted(positions):
            if p >= len(call.args):
                continue
            arg = call.args[p]
            if isinstance(arg, ast.Name):
                hit = self._read_after(cfg, node, call, arg.id)
                if hit is not None:
                    findings.append(Finding(
                        self.name, "DS001", sf.rel,
                        hit.lineno, hit.col_offset,
                        f"{arg.id!r} is read after being donated at "
                        f"position {p} of {callee}() — the buffer is "
                        f"deleted by the dispatch (in {qual!r})",
                        symbol=qual,
                        detail=f"{callee}:arg{p}:{arg.id}"))
            elif isinstance(arg, ast.Attribute) and \
                    isinstance(arg.value, ast.Name) and \
                    arg.value.id == "self":
                if self._attr_outlives(cfg, node, call, arg.attr):
                    findings.append(Finding(
                        self.name, "DS002", sf.rel,
                        arg.lineno, arg.col_offset,
                        f"'self.{arg.attr}' is donated at position "
                        f"{p} of {callee}() but not rebound on every "
                        f"path — the attribute outlives the call "
                        f"holding a deleted buffer (in {qual!r})",
                        symbol=qual,
                        detail=f"{callee}:arg{p}:self.{arg.attr}"))
        return findings

    # --------------------------------------------------- CFG queries
    @staticmethod
    def _stmt_rebinds(stmt: ast.AST, name: str) -> bool:
        if not isinstance(stmt, ast.Assign):
            return False
        for t in stmt.targets:
            elts = t.elts if isinstance(t, ast.Tuple) else [t]
            for e in elts:
                if isinstance(e, ast.Name) and e.id == name:
                    return True
        return False

    @staticmethod
    def _stmt_reads(stmt: ast.AST, name: str) -> Optional[ast.AST]:
        for part in head_exprs(stmt):
            for n in ast.walk(part):
                if isinstance(n, ast.Name) and n.id == name and \
                        isinstance(n.ctx, ast.Load):
                    return n
        return None

    def _read_after(self, cfg, node, call: ast.Call,
                    name: str) -> Optional[ast.AST]:
        """First read of ``name`` on some path after ``node`` without
        an intervening rebind (DS001); the dispatch statement itself
        rebinding (``x = fn(x)``) is the clean idiom."""
        if self._stmt_rebinds(node.stmt, name):
            return None
        seen: Set[int] = set()
        stack = list(node.succ | node.exc_succ)
        while stack:
            cur = stack.pop()
            if id(cur) in seen or cur.kind != "stmt":
                continue
            seen.add(id(cur))
            hit = self._stmt_reads(cur.stmt, name)
            if hit is not None:
                return hit
            if self._stmt_rebinds(cur.stmt, name):
                continue
            stack.extend(cur.all_succ())
        return None

    def _attr_outlives(self, cfg, node, call: ast.Call,
                       attr: str) -> bool:
        """Some path from the dispatch to an exit with no
        ``self.<attr> = ...`` store (DS002)."""
        def stores(stmt) -> bool:
            if not isinstance(stmt, ast.Assign):
                return False
            for t in stmt.targets:
                elts = t.elts if isinstance(t, ast.Tuple) else [t]
                for e in elts:
                    if isinstance(e, ast.Attribute) and \
                            e.attr == attr and \
                            isinstance(e.value, ast.Name) and \
                            e.value.id == "self":
                        return True
            return False

        if stores(node.stmt):
            return False
        seen: Set[int] = set()
        stack = list(node.succ)     # dispatch raising = not donated
        while stack:
            cur = stack.pop()
            if id(cur) in seen:
                continue
            seen.add(id(cur))
            if cur.kind != "stmt":
                return True         # reached an exit un-rebound
            if stores(cur.stmt):
                continue
            stack.extend(cur.all_succ())
        return False
