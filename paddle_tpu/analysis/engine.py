"""Interprocedural dataflow engine shared by the pdlint analyzers.

PR 4/9/11 grew per-module, single-pass analyzers; the bug classes
added since (use-after-donate across a `donate_argnums` dispatch,
KV-page leaks on exception paths, compile sites outside the
`get_or_compile` chokepoint) need three things those walkers lack,
supplied here once for every analyzer:

- **CallGraph** — a repo-wide graph over every parsed file: bare calls
  to module functions, ``self.method`` / ``cls.method`` calls,
  module-qualified calls through the import table (``mod.fn`` where
  ``mod`` resolves to a repo module, absolute or relative import),
  ``functools.partial(target, ...)`` pre-binding, lambdas and function
  aliases assigned to locals, and ``threading.Thread(target=...)``
  hand-offs. Nodes are ``(repo-relative-path, qualname)`` keys so
  fingerprints stay line-independent.
- **CFG** — a lightweight per-function control-flow graph at statement
  granularity with EXCEPTION edges: every statement that can raise has
  an edge to the nearest enclosing handler/finally (else the
  exceptional exit), ``finally`` bodies sit on both the normal and the
  exceptional continuation, ``return`` routes through enclosing
  ``finally`` blocks. Two distinguished exits (normal, exceptional)
  let resource analyses ask "held at *any* exit on *some* path?".
  Exception edges are may-edges: any statement containing a call /
  subscript / attribute access is assumed able to raise.
- **Taint** — the tiny forward lattice ``tracer_safety`` has always
  used (parameter-derived names, assignment propagation), factored out
  so the donation/recompile analyzers share one definition of
  "data-dependent value".

Everything stays stdlib-``ast``: code is parsed, never imported.
"""
from __future__ import annotations

import ast
from collections import deque
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .core import SourceFile

__all__ = [
    "FuncNode", "ModuleInfo", "CallGraph", "CFG", "CFGNode",
    "build_cfg", "dotted_name", "iter_own_body", "Taint",
    "module_name_of", "head_exprs", "jit_identifier",
    "decorated_entry", "jit_entries",
]


# ===================================================================
# shared AST helpers
# ===================================================================
def dotted_name(node: ast.AST) -> Optional[str]:
    """x.y.z attribute chain as 'x.y.z', or None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def iter_own_body(func_node):
    """Pre-order, SOURCE-ORDER walk of one function's own body (taint
    propagation needs assignments before later uses). Nested defs and
    lambdas are separate call-graph nodes, not descended into. Accepts
    defs (``.body`` is a list) and lambdas (``.body`` is an expr).

    Every analyzer re-walks the same bodies, so the flattened node
    list is cached on the def node itself — AST nodes carry a
    ``__dict__``, and the tree outlives any analyzer pass."""
    cached = getattr(func_node, "_pdlint_own_body", None)
    if cached is not None:
        return cached
    body = func_node.body
    out = []
    queue = deque(body if isinstance(body, list) else [body])
    while queue:
        n = queue.popleft()
        out.append(n)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        queue.extendleft(reversed(list(ast.iter_child_nodes(n))))
    try:
        func_node._pdlint_own_body = out
    except (AttributeError, TypeError):    # e.g. a slotted fake node
        pass
    return out


def head_exprs(stmt: ast.AST) -> List[ast.AST]:
    """The expressions a CFG node for ``stmt`` actually evaluates:
    compound statements (if/while/for/with/try) evaluate only their
    HEAD — their bodies are separate CFG nodes. Dataflow consumers
    must scan these instead of ``ast.walk(stmt)`` or every nested
    statement would be double-counted at each enclosing head."""
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [i.context_expr for i in stmt.items]
    if isinstance(stmt, ast.Try):
        return []
    return [stmt]


def module_name_of(rel: str) -> str:
    """Repo-relative posix path -> importable dotted module name
    ('paddle_tpu/serving/__init__.py' -> 'paddle_tpu.serving')."""
    name = rel[:-3] if rel.endswith(".py") else rel
    name = name.replace("/", ".")
    if name.endswith(".__init__"):
        name = name[: -len(".__init__")]
    return name


class Taint:
    """Parameter-derived names with forward assignment propagation —
    the shared definition of "data-dependent Python value"."""

    def __init__(self, func_node, extra: Iterable[str] = ()):
        a = func_node.args
        self.names: Set[str] = {p.arg for p in
                                list(a.posonlyargs) + list(a.args)
                                + list(a.kwonlyargs)
                                + ([a.vararg] if a.vararg else [])
                                + ([a.kwarg] if a.kwarg else [])
                                } - {"self", "cls"}
        self.names.update(extra)

    def touches(self, expr: ast.AST) -> bool:
        return any(isinstance(n, ast.Name) and n.id in self.names
                   for n in ast.walk(expr))

    def note_stmt(self, stmt: ast.AST):
        """Propagate through ``x = <expr touching tainted>``."""
        if isinstance(stmt, ast.Assign) and self.touches(stmt.value):
            for t in stmt.targets:
                targets = t.elts if isinstance(t, ast.Tuple) else [t]
                for e in targets:
                    if isinstance(e, ast.Name):
                        self.names.add(e.id)


# ===================================================================
# call graph
# ===================================================================
class FuncNode:
    """One function/method/named-lambda in the repo-wide graph."""

    __slots__ = ("key", "node", "sf", "qualname", "class_name",
                 "is_method", "entry_via")

    def __init__(self, sf: SourceFile, node, qualname: str,
                 class_name: Optional[str]):
        self.key: Tuple[str, str] = (sf.rel, qualname)
        self.node = node
        self.sf = sf
        self.qualname = qualname
        self.class_name = class_name
        self.is_method = class_name is not None
        self.entry_via: Optional[str] = None


class _Imports(ast.NodeVisitor):
    """alias -> absolute dotted module/name, relative imports resolved
    against the importing module's package."""

    def __init__(self, modname: str, is_package: bool):
        self.aliases: Dict[str, str] = {}
        self._mod = modname
        self._is_pkg = is_package

    def _rel_base(self, level: int) -> str:
        parts = self._mod.split(".")
        # level 1 = the containing package: for a plain module that
        # means dropping the module segment itself
        drop = level - 1 if self._is_pkg else level
        return ".".join(parts[: len(parts) - drop]) if drop else \
            self._mod

    def visit_Import(self, node):
        for a in node.names:
            if a.asname:
                self.aliases[a.asname] = a.name
            else:
                head = a.name.split(".")[0]
                self.aliases[head] = head

    def visit_ImportFrom(self, node):
        if node.level:
            base = self._rel_base(node.level)
            mod = f"{base}.{node.module}" if node.module else base
        else:
            mod = node.module or ""
        for a in node.names:
            self.aliases[a.asname or a.name] = \
                f"{mod}.{a.name}" if mod else a.name

    def resolve(self, dotted: str) -> str:
        head, _, rest = dotted.partition(".")
        head = self.aliases.get(head, head)
        return f"{head}.{rest}" if rest else head


class _FuncCollector(ast.NodeVisitor):
    """All defs (plus lambdas assigned to names) with qualnames."""

    def __init__(self, sf: SourceFile):
        self.sf = sf
        self.stack: List[str] = []
        self.class_stack: List[str] = []
        self.funcs: Dict[str, FuncNode] = {}

    def _add(self, node, name: str):
        qual = ".".join(self.stack + [name])
        cls = self.class_stack[-1] if self.class_stack and \
            self.stack and self.stack[-1] == self.class_stack[-1] \
            else None
        self.funcs.setdefault(qual, FuncNode(self.sf, node, qual, cls))

    def _visit_func(self, node):
        self._add(node, node.name)
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_ClassDef(self, node):
        self.stack.append(node.name)
        self.class_stack.append(node.name)
        self.generic_visit(node)
        self.class_stack.pop()
        self.stack.pop()

    def visit_Assign(self, node):
        # h = lambda ...: a named lambda is a real call-graph node
        if isinstance(node.value, ast.Lambda) and \
                len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            self._add(node.value, node.targets[0].id)
        self.generic_visit(node)


class ModuleInfo:
    """Per-file slice of the graph: functions, imports, name index."""

    __slots__ = ("sf", "modname", "imports", "funcs", "by_last",
                 "by_class_method")

    def __init__(self, sf: SourceFile):
        self.sf = sf
        self.modname = module_name_of(sf.rel)
        is_pkg = sf.rel.endswith("__init__.py")
        self.imports = _Imports(self.modname, is_pkg)
        self.imports.visit(sf.tree)
        coll = _FuncCollector(sf)
        coll.visit(sf.tree)
        self.funcs: Dict[str, FuncNode] = coll.funcs
        self.by_last: Dict[str, List[str]] = {}
        self.by_class_method: Dict[Tuple[str, str], str] = {}
        for qual, fn in self.funcs.items():
            self.by_last.setdefault(qual.split(".")[-1], []).append(qual)
            if fn.class_name is not None:
                self.by_class_method[(fn.class_name,
                                      qual.split(".")[-1])] = qual


# CallGraph.shared(): one run_analyzers pass hands the SAME parsed
# SourceFile objects to every analyzer, and three analyzers
# (recompile_risk, tracer_safety, lock_order) each need the repo call
# graph — building it once per parse instead of once per analyzer cuts
# a full pdlint run by roughly a third.  Keyed on the identity of the
# SourceFile objects; each entry keeps strong references so the ids
# stay valid for the life of the entry.
_SHARED_GRAPHS: list = []
_SHARED_GRAPHS_MAX = 2


def clear_shared_graphs():
    _SHARED_GRAPHS.clear()


class CallGraph:
    """Repo-wide call graph over a set of parsed SourceFiles."""

    @classmethod
    def shared(cls, files: Sequence[SourceFile]) -> "CallGraph":
        """Memoized constructor for analyzers running over the same
        parse (see module note above)."""
        flist = [sf for sf in files if sf.tree is not None]
        key = tuple(id(sf) for sf in flist)
        for k, _refs, g in _SHARED_GRAPHS:
            if k == key:
                return g
        g = cls(flist)
        _SHARED_GRAPHS.append((key, flist, g))
        del _SHARED_GRAPHS[:len(_SHARED_GRAPHS) - _SHARED_GRAPHS_MAX]
        return g

    def __init__(self, files: Sequence[SourceFile]):
        self.modules: Dict[str, ModuleInfo] = {}   # rel -> info
        self.by_modname: Dict[str, ModuleInfo] = {}
        self.funcs: Dict[Tuple[str, str], FuncNode] = {}
        for sf in files:
            if sf.tree is None:
                continue
            mi = ModuleInfo(sf)
            self.modules[sf.rel] = mi
            self.by_modname[mi.modname] = mi
            for f in mi.funcs.values():
                self.funcs[f.key] = f
        self.edges: Dict[Tuple[str, str], Set[Tuple[str, str]]] = {}
        for mi in self.modules.values():
            for fn in mi.funcs.values():
                self.edges[fn.key] = self._callees(mi, fn)

    # ------------------------------------------------- call resolution
    def _resolve_dotted(self, mi: ModuleInfo,
                        dotted: str) -> List[Tuple[str, str]]:
        """'mod.fn' / 'pkg.mod.fn' through the import table to another
        repo module's function, or a same-module name."""
        resolved = mi.imports.resolve(dotted)
        head, _, last = resolved.rpartition(".")
        if not head:
            return [mi.funcs[q].key for q in mi.by_last.get(last, ())]
        out: List[Tuple[str, str]] = []
        target = self.by_modname.get(head)
        if target is not None and last in target.funcs:
            out.append(target.funcs[last].key)
        # Class.method via an imported class: pkg.mod.Class.method
        head2, _, cls = head.rpartition(".")
        if head2:
            tm = self.by_modname.get(head2)
            if tm is not None and (cls, last) in tm.by_class_method:
                out.append(tm.funcs[tm.by_class_method[(cls,
                                                        last)]].key)
        return out

    def _resolve_target(self, mi: ModuleInfo, fn: FuncNode,
                        expr: ast.AST, aliases: Dict[str, str]
                        ) -> List[Tuple[str, str]]:
        """A callable expression -> candidate FuncNode keys."""
        if isinstance(expr, ast.Name):
            if expr.id in aliases:          # local alias / named lambda
                key = aliases[expr.id]
                if key in self.funcs:
                    return [key]
            scoped = f"{fn.qualname}.{expr.id}"
            if scoped in mi.funcs:          # nested def / local lambda
                return [mi.funcs[scoped].key]
            out = self._resolve_dotted(mi, expr.id)
            if out:
                return out
            return [mi.funcs[q].key
                    for q in mi.by_last.get(expr.id, ())]
        if isinstance(expr, ast.Attribute):
            if isinstance(expr.value, ast.Name) and \
                    expr.value.id in ("self", "cls"):
                if fn.class_name is not None:
                    q = mi.by_class_method.get((fn.class_name,
                                                expr.attr))
                    if q is not None:
                        return [mi.funcs[q].key]
                return [mi.funcs[q].key
                        for q in mi.by_last.get(expr.attr, ())
                        if mi.funcs[q].is_method]
            d = dotted_name(expr)
            if d is not None:
                return self._resolve_dotted(mi, d)
        return []

    def _callees(self, mi: ModuleInfo,
                 fn: FuncNode) -> Set[Tuple[str, str]]:
        out: Set[Tuple[str, str]] = set()
        aliases: Dict[str, Tuple[str, str]] = {}
        for n in iter_own_body(fn.node):
            # h = helper / h = self.m / h = lambda...: local callable
            # aliases; calls through them resolve to the target
            if isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                    isinstance(n.targets[0], ast.Name):
                tgt = n.targets[0].id
                if isinstance(n.value, ast.Lambda):
                    lam = f"{fn.qualname}.{tgt}"
                    if lam in mi.funcs:
                        aliases[tgt] = mi.funcs[lam].key
                elif isinstance(n.value, (ast.Name, ast.Attribute)):
                    keys = self._resolve_target(mi, fn, n.value,
                                                aliases)
                    if len(keys) == 1:
                        aliases[tgt] = keys[0]
            if not isinstance(n, ast.Call):
                continue
            out.update(self._resolve_target(mi, fn, n.func, aliases))
            d = dotted_name(n.func)
            last = d.split(".")[-1] if d else ""
            if last == "partial" and n.args:
                # functools.partial(target, ...): pre-bound call edge
                out.update(self._resolve_target(mi, fn, n.args[0],
                                                aliases))
            if last == "Thread":
                for kw in n.keywords:
                    if kw.arg == "target":
                        out.update(self._resolve_target(
                            mi, fn, kw.value, aliases))
        out.discard(fn.key)
        return out

    # ------------------------------------------------- reachability
    def reachable(self, roots: Iterable[Tuple[Tuple[str, str], str]]
                  ) -> Dict[Tuple[str, str], str]:
        """BFS over edges from ``(key, via)`` roots; returns
        ``key -> via`` attribution of the first root that reached it."""
        reach: Dict[Tuple[str, str], str] = {}
        work = deque(roots)
        while work:
            key, via = work.popleft()
            if key in reach or key not in self.funcs:
                continue
            reach[key] = via
            for callee in self.edges.get(key, ()):
                if callee not in reach:
                    work.append((callee, via))
        return reach


# ===================================================================
# control-flow graph with exception edges
# ===================================================================
class CFGNode:
    """One statement (or a synthetic exit). ``succ`` are normal-flow
    successors; ``exc_succ`` is where control goes if THIS statement
    raises mid-execution (its own side effects incomplete) — resource
    analyses start tracking only after an acquire completes, so they
    follow ``succ | exc_succ`` everywhere except at the acquire node
    itself, where only ``succ`` applies."""

    __slots__ = ("stmt", "kind", "succ", "exc_succ", "none_names")

    def __init__(self, stmt, kind: str = "stmt"):
        self.stmt = stmt
        self.kind = kind                   # stmt | exit | exc_exit
        self.succ: Set["CFGNode"] = set()
        self.exc_succ: Set["CFGNode"] = set()
        # names statically known to be None when control enters this
        # node (then-branch of `if x is None:` / `if not x:`): a
        # resource variable that is None was never acquired
        self.none_names: Set[str] = set()

    def all_succ(self) -> Set["CFGNode"]:
        return self.succ | self.exc_succ

    def __repr__(self):  # pragma: no cover - debugging aid
        if self.kind != "stmt":
            return f"<{self.kind}>"
        return f"<{type(self.stmt).__name__}@{self.stmt.lineno}>"


class CFG:
    __slots__ = ("entry", "exit", "exc_exit", "nodes")

    def __init__(self):
        self.exit = CFGNode(None, "exit")
        self.exc_exit = CFGNode(None, "exc_exit")
        self.nodes: List[CFGNode] = []
        self.entry: Optional[CFGNode] = None


_RAISERS = (ast.Call, ast.Subscript)


def _may_raise(stmt: ast.stmt) -> bool:
    """Statements that get an exception edge: calls and subscripts
    (the realistic raisers — IndexError/KeyError and anything a callee
    throws), plus explicit raise/assert. Bare attribute access and
    arithmetic are treated as non-raising: modeling them as raisers
    floods leak analysis with AttributeError-on-self paths no real
    program takes. May-edges either way."""
    if isinstance(stmt, (ast.Raise, ast.Assert)):
        return True
    for n in ast.walk(stmt):
        if isinstance(n, _RAISERS):
            return True
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            return False
    return False


def _none_test(test: ast.AST) -> Optional[Tuple[str, bool]]:
    """``x is None``/``not x`` -> (x, True): the THEN branch sees x
    None; ``x is not None`` -> (x, False): the ELSE branch does. An
    ``and`` conjunction guarantees every conjunct in its THEN branch,
    so a positive none-test inside one carries through."""
    if isinstance(test, ast.Compare) and len(test.ops) == 1 and \
            isinstance(test.comparators[0], ast.Constant) and \
            test.comparators[0].value is None and \
            isinstance(test.left, ast.Name):
        return test.left.id, isinstance(test.ops[0], ast.Is)
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not) \
            and isinstance(test.operand, ast.Name):
        return test.operand.id, True
    if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
        for v in test.values:
            nt = _none_test(v)
            if nt is not None and nt[1]:
                return nt
    return None


class _Frag:
    """A built sub-graph: its entry node and the open fall-through
    ends the caller must connect to whatever follows."""

    __slots__ = ("entry", "outs")

    def __init__(self, entry: Optional[CFGNode], outs: List[CFGNode]):
        self.entry = entry
        self.outs = outs


class _Frame:
    """Enclosing-construct context while building."""

    __slots__ = ("exc_cont", "break_out", "continue_to", "parent",
                 "fin_frag", "saw_return", "saw_raise")

    def __init__(self, exc_cont, break_out=None, continue_to=None,
                 parent=None, fin_frag=None):
        self.exc_cont: CFGNode = exc_cont  # where raises go
        self.break_out = break_out         # pending break nodes, or None
        self.continue_to = continue_to
        self.parent = parent
        self.fin_frag: Optional[_Frag] = fin_frag
        self.saw_return = False            # a return routed through here
        self.saw_raise = False             # an exception routed through

    def nearest_loop(self) -> Optional["_Frame"]:
        f = self
        while f is not None:
            if f.break_out is not None:
                return f
            f = f.parent
        return None


class _Builder:
    """Statement-level CFG. ``finally`` bodies are built once and sit
    on every continuation that actually routes through them (normal
    fall-through, exception propagation when the protected body can
    raise, return) — a may-path over-approximation that keeps leak
    analysis usable without path duplication."""

    def __init__(self):
        self.cfg = CFG()

    def node(self, stmt) -> CFGNode:
        n = CFGNode(stmt)
        self.cfg.nodes.append(n)
        return n

    def build(self, func_node) -> CFG:
        body = func_node.body
        if not isinstance(body, list):     # lambda
            body = [ast.Expr(value=func_node.body)]
        root = _Frame(self.cfg.exc_exit)
        frag = self._seq(body, root)
        self.cfg.entry = frag.entry if frag.entry is not None \
            else self.cfg.exit
        for o in frag.outs:
            o.succ.add(self.cfg.exit)
        return self.cfg

    # ------------------------------------------------------ sequences
    def _seq(self, stmts: List[ast.stmt], frame: _Frame) -> _Frag:
        entry: Optional[CFGNode] = None
        outs: List[CFGNode] = []
        started = False
        for stmt in stmts:
            f = self._stmt(stmt, frame)
            if f.entry is None:
                continue
            if not started:
                entry, started = f.entry, True
            else:
                for o in outs:
                    o.succ.add(f.entry)
            outs = f.outs
            if not outs:                   # terminal: rest is dead code
                break
        return _Frag(entry, outs)

    # ------------------------------------------------------ statements
    def _stmt(self, stmt: ast.stmt, frame: _Frame) -> _Frag:
        if isinstance(stmt, ast.If):
            return self._if(stmt, frame)
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            return self._loop(stmt, frame)
        if isinstance(stmt, ast.Try):
            return self._try(stmt, frame)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            head = self.node(stmt)
            self._exc_edge(head, stmt, frame)
            body = self._seq(stmt.body, frame)
            if body.entry is not None:
                head.succ.add(body.entry)
                return _Frag(head, body.outs)
            return _Frag(head, [head])
        n = self.node(stmt)
        self._exc_edge(n, stmt, frame)
        if isinstance(stmt, ast.Return):
            n.succ.add(self._return_target(frame))
            return _Frag(n, [])
        if isinstance(stmt, ast.Raise):
            frame.saw_raise = True
            n.succ.add(frame.exc_cont)
            return _Frag(n, [])
        if isinstance(stmt, ast.Break):
            loop = frame.nearest_loop()
            if loop is not None:
                loop.break_out.append(n)
            return _Frag(n, [])
        if isinstance(stmt, ast.Continue):
            loop = frame.nearest_loop()
            if loop is not None and loop.continue_to is not None:
                n.succ.add(loop.continue_to)
            return _Frag(n, [])
        return _Frag(n, [n])

    def _if(self, stmt: ast.If, frame: _Frame) -> _Frag:
        head = self.node(stmt)
        self._exc_edge(head, stmt.test, frame, walk=True)
        nt = _none_test(stmt.test)
        outs: List[CFGNode] = []
        then = self._seq(stmt.body, frame)
        if then.entry is not None:
            head.succ.add(then.entry)
            if nt and nt[1]:
                then.entry.none_names.add(nt[0])
            outs.extend(then.outs)
        if stmt.orelse:
            els = self._seq(stmt.orelse, frame)
            if els.entry is not None:
                head.succ.add(els.entry)
                if nt and not nt[1]:
                    els.entry.none_names.add(nt[0])
                outs.extend(els.outs)
            else:
                outs.append(head)
        else:
            outs.append(head)              # condition-false fall-through
        return _Frag(head, outs)

    def _loop(self, stmt, frame: _Frame) -> _Frag:
        head = self.node(stmt)
        self._exc_edge(head, stmt, frame)
        inner = _Frame(frame.exc_cont, break_out=[], continue_to=head,
                       parent=frame, fin_frag=None)
        body = self._seq(stmt.body, inner)
        if body.entry is not None:
            head.succ.add(body.entry)
            if isinstance(stmt, ast.While):
                nt = _none_test(stmt.test)
                if nt and nt[1]:
                    body.entry.none_names.add(nt[0])
            for o in body.outs:
                o.succ.add(head)           # back edge
        outs = [head] + inner.break_out
        if stmt.orelse:
            els = self._seq(stmt.orelse, frame)
            if els.entry is not None:
                head.succ.add(els.entry)
                outs = inner.break_out + els.outs
        frame.saw_return |= inner.saw_return
        frame.saw_raise |= inner.saw_raise
        return _Frag(head, outs)

    def _try(self, stmt: ast.Try, frame: _Frame) -> _Frag:
        fin = self._seq(stmt.finalbody, frame) if stmt.finalbody \
            else None

        # handlers: exceptions raised INSIDE a handler route through
        # the finally (if any) or the enclosing continuation
        handler_exc = fin.entry if fin is not None and \
            fin.entry is not None else frame.exc_cont
        handler_frags: List[_Frag] = []
        for h in stmt.handlers:
            h_frame = _Frame(handler_exc, parent=frame, fin_frag=fin)
            h_frame.break_out = None
            hf = self._seq(h.body, h_frame)
            frame.saw_return |= h_frame.saw_return
            handler_frags.append(hf)

        # the protected body: raises go to the first handler, else the
        # finally, else out
        if handler_frags and handler_frags[0].entry is not None:
            body_exc = handler_frags[0].entry
        elif fin is not None and fin.entry is not None:
            body_exc = fin.entry
        else:
            body_exc = frame.exc_cont
        body_frame = _Frame(body_exc, parent=frame, fin_frag=fin)
        body = self._seq(stmt.body + (stmt.orelse or []), body_frame)

        # multiple handlers: a raising body statement may enter any
        for extra in handler_frags[1:]:
            if extra.entry is not None:
                for n in self.cfg.nodes:
                    if n.kind != "stmt":
                        continue
                    if body_exc in n.exc_succ:
                        n.exc_succ.add(extra.entry)
                    if body_exc in n.succ:
                        n.succ.add(extra.entry)

        outs: List[CFGNode] = []
        if fin is not None and fin.entry is not None:
            for o in body.outs:
                o.succ.add(fin.entry)
            for hf in handler_frags:
                for o in hf.outs:
                    o.succ.add(fin.entry)
            # the finally's open ends continue: normally (caller
            # connects), exceptionally (body could raise past the
            # handlers), and to the function exit for returns routed
            # through
            if body_frame.saw_raise or self._body_may_raise(stmt):
                if not stmt.handlers:
                    for o in fin.outs:
                        o.succ.add(frame.exc_cont)
            if body_frame.saw_return:
                for o in fin.outs:
                    o.succ.add(self._return_target(frame))
            outs = list(fin.outs)
        else:
            outs = list(body.outs)
            for hf in handler_frags:
                outs.extend(hf.outs)
        frame.saw_raise |= body_frame.saw_raise and not stmt.handlers
        entry = body.entry
        if entry is None:
            entry = fin.entry if fin is not None else None
        if entry is None:
            n = self.node(stmt)
            return _Frag(n, [n])
        return _Frag(entry, outs)

    # ------------------------------------------------------ plumbing
    def _exc_edge(self, node: CFGNode, stmt, frame: _Frame,
                  walk: bool = False):
        raising = any(isinstance(n, _RAISERS)
                      for n in ast.walk(stmt)) \
            if walk else _may_raise(stmt)
        if raising:
            frame.saw_raise = True
            node.exc_succ.add(frame.exc_cont)

    @staticmethod
    def _body_may_raise(stmt: ast.Try) -> bool:
        return any(_may_raise(s) for s in stmt.body)

    def _return_target(self, frame: _Frame) -> CFGNode:
        f = frame
        while f is not None:
            if f.fin_frag is not None and f.fin_frag.entry is not None:
                f.saw_return = True
                return f.fin_frag.entry
            f.saw_return = True
            f = f.parent
        return self.cfg.exit


def build_cfg(func_node) -> CFG:
    """CFG for one function def (or lambda)."""
    return _Builder().build(func_node)


# ===================================================================
# jit entry detection (shared by tracer_safety / recompile_risk)
# ===================================================================
_JIT_NAMES = {"jit", "to_static", "pjit"}


def jit_identifier(node: ast.AST) -> Optional[str]:
    """'jit'/'to_static'/'pjit' when this expression names a jit
    wrapper (Name, dotted attribute, or
    ``functools.partial(jax.jit, ...)``)."""
    if isinstance(node, ast.Call):       # partial(jax.jit, ...)
        for sub in [node.func] + list(node.args):
            got = jit_identifier(sub)
            if got:
                return got
        return None
    d = dotted_name(node)
    if d is None:
        return None
    last = d.split(".")[-1]
    return last if last in _JIT_NAMES else None


def decorated_entry(node) -> Optional[str]:
    for dec in node.decorator_list:
        got = jit_identifier(dec)
        if got:
            return got
    return None


def jit_entries(cg: CallGraph) -> List[Tuple[Tuple[str, str], str]]:
    """Trace entry points across the whole graph: jit-decorated
    functions, functions named ``train_step``, and functions passed to
    a jit wrapper at a call site (``jax.jit(fn)``, ``jit(self.step)``,
    ``jit(partial(step, ...))``). Marks ``FuncNode.entry_via`` and
    returns ``[(key, via)]`` roots for ``CallGraph.reachable``.

    The scan marks nodes as it goes (``mark`` skips already-marked
    functions), so a second pass over the same graph would see nothing
    — the roots are cached on the graph so every analyzer sharing it
    gets the same answer."""
    cached = getattr(cg, "_jit_entries", None)
    if cached is not None:
        return list(cached)
    roots: List[Tuple[Tuple[str, str], str]] = []

    def mark(fn: FuncNode, via: str):
        if fn.entry_via is None:
            fn.entry_via = via
            roots.append((fn.key, via))

    for mi in cg.modules.values():
        for qual, fn in mi.funcs.items():
            node = fn.node
            if isinstance(node, ast.Lambda):
                continue
            via = decorated_entry(node)
            if via is None and node.name == "train_step":
                via = "train_step"
            if via is not None:
                mark(fn, via)
        # call-site entries: jit(<target>) anywhere in the module
        for n in ast.walk(mi.sf.tree):
            if not isinstance(n, ast.Call) or not n.args:
                continue
            via = jit_identifier(n.func)
            if via is None:
                continue
            tgt = n.args[0]
            if isinstance(tgt, ast.Call):  # jit(partial(step, ...))
                if dotted_name(tgt.func) and \
                        dotted_name(tgt.func).split(".")[-1] == \
                        "partial" and tgt.args:
                    tgt = tgt.args[0]
                else:
                    continue
            if isinstance(tgt, ast.Name):
                for q in mi.by_last.get(tgt.id, ()):
                    mark(mi.funcs[q], via)
            elif isinstance(tgt, ast.Attribute) and \
                    isinstance(tgt.value, ast.Name) and \
                    tgt.value.id in ("self", "cls"):
                for q in mi.by_last.get(tgt.attr, ()):
                    if mi.funcs[q].is_method:
                        mark(mi.funcs[q], via)
    cg._jit_entries = list(roots)
    return roots
