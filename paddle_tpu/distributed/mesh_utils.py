"""Global device-mesh management — the spine of the distributed design.

The reference composes dp/mp/pp/sharding process groups from an N-D rank grid
(HybridCommunicateGroup,
/root/reference/python/paddle/distributed/fleet/base/topology.py:140). Here
the same topology IS a jax.sharding.Mesh whose axes are named after the
paddle axes ("dp", "pp", "sharding", "mp", optionally "sep"); collectives are
XLA collectives over mesh axes, and "process groups" are views over mesh
axes (paddle_tpu/distributed/group.py).
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

_state = threading.local()


def build_mesh(axes: Dict[str, int], devices=None) -> Mesh:
    """axes: ordered {axis_name: degree}. Degrees must multiply to #devices
    (axes with degree 1 are kept so PartitionSpecs stay stable)."""
    devices = devices if devices is not None else jax.devices()
    names = list(axes.keys())
    degrees = [int(axes[n]) for n in names]
    total = int(np.prod(degrees))
    if total > len(devices):
        raise ValueError(
            f"mesh {axes} needs {total} devices, only {len(devices)} visible")
    dev_array = np.asarray(devices[:total]).reshape(degrees)
    return Mesh(dev_array, names)


def set_global_mesh(mesh: Mesh):
    _state.mesh = mesh


def get_global_mesh() -> Optional[Mesh]:
    return getattr(_state, "mesh", None)


def mesh_axis_size(axis: str) -> int:
    mesh = get_global_mesh()
    if mesh is None or axis not in mesh.axis_names:
        return 1
    return mesh.shape[axis]


def named_sharding(*spec) -> Optional[NamedSharding]:
    mesh = get_global_mesh()
    if mesh is None:
        return None
    return NamedSharding(mesh, PartitionSpec(*spec))


def shard_tensor_data(arr, *spec):
    """Place a jax array with the given PartitionSpec on the global mesh."""
    sh = named_sharding(*spec)
    if sh is None:
        return arr
    return jax.device_put(arr, sh)


def with_constraint(arr, *spec):
    mesh = get_global_mesh()
    if mesh is None:
        return arr
    # degrade axes the mesh doesn't have (or has at size 1) to replication:
    # TP-annotated layers must compose with any mesh (e.g. a pure
    # 'sharding' ZeRO mesh runs ColumnParallelLinear unsharded). A tuple
    # entry shards one dim over several axes; surviving members keep order.
    def _norm(s):
        if isinstance(s, (tuple, list)):
            kept = tuple(a for a in s
                         if a in mesh.axis_names and mesh.shape[a] > 1)
            return kept if kept else None
        return s if (s in mesh.axis_names and mesh.shape[s] > 1) else None

    spec = tuple(_norm(s) for s in spec)
    sharding = NamedSharding(mesh, PartitionSpec(*spec))
    if isinstance(arr, jax.core.Tracer):
        return jax.lax.with_sharding_constraint(arr, sharding)
    # Eager path: a committed single-device array can't take a sharding
    # constraint; reshard by placement instead.
    return jax.device_put(arr, sharding)


def batch_axis_constraint(h):
    """Pin activations to batch-axis sharding (dim 0 over dp and/or the
    ZeRO 'sharding' axis) — kept as the historical name; the
    implementation is the unified surface's ``shard.constrain_batch``
    (see that docstring for the GSPMD ZeRO rationale). No-op without a
    mesh. Accepts a Tensor (dispatched, so it records) or a raw array."""
    from .shard import constrain_batch
    return constrain_batch(h)


def manual_shard_map(f, mesh, in_specs, out_specs):
    """shard_map in fully-manual mode (no varying-mode-agreement checking)
    across jax versions: the pipeline/ring bodies manage their own
    collective reductions explicitly, which the vma checker rejects."""
    try:
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    except (TypeError, AttributeError):  # pragma: no cover — older jax
        from jax.experimental.shard_map import shard_map as _sm
        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=False)
