"""Process groups as mesh-axis views.

The reference's ProcessGroup (/root/reference/paddle/fluid/distributed/
collective/process_group.h:53) manages transport comms per rank list. On TPU
the transport is XLA over ICI; a "group" is metadata: the ranks it contains
and (when it corresponds to a mesh axis) the axis name collectives reduce
over. The Python API surface (new_group, group.process_ids, task.wait())
is preserved.
"""
from __future__ import annotations

from typing import List, Optional

from . import env


class Task:
    """Completed-collective handle (ProcessGroup::Task analog). XLA dispatch
    is async already; wait() is a device sync."""

    def __init__(self, result=None):
        self._result = result

    def is_completed(self):
        return True

    def wait(self, timeout=None):
        if self._result is not None:
            import jax
            jax.block_until_ready(self._result)
        return True

    def synchronize(self):
        self.wait()


class Group:
    def __init__(self, ranks: List[int], gid: int = 0,
                 mesh_axis: Optional[str] = None):
        self.ranks = list(ranks)
        self.id = gid
        self.mesh_axis = mesh_axis

    @property
    def nranks(self):
        return len(self.ranks)

    @property
    def world_size(self):
        return len(self.ranks)

    @property
    def process_ids(self):
        return self.ranks

    @property
    def rank(self):
        return self.get_group_rank(env.global_rank())

    def get_group_rank(self, rank):
        try:
            return self.ranks.index(rank)
        except ValueError:
            return -1

    def is_member(self):
        return env.global_rank() in self.ranks

    def __repr__(self):
        return f"Group(id={self.id}, ranks={self.ranks}, axis={self.mesh_axis})"


_groups = {}
_next_gid = [1]
_default_group: Optional[Group] = None


def _get_or_create_default() -> Group:
    global _default_group
    if _default_group is None:
        _default_group = Group(list(range(env.get_world_size())), gid=0)
    return _default_group


def get_group(gid: int = 0) -> Group:
    if gid == 0:
        return _get_or_create_default()
    return _groups[gid]


def new_group(ranks=None, backend=None, timeout=None,
              mesh_axis: Optional[str] = None) -> Group:
    """paddle.distributed.new_group
    (reference: python/paddle/distributed/collective.py:185)."""
    if ranks is None:
        ranks = list(range(env.get_world_size()))
    gid = _next_gid[0]
    _next_gid[0] += 1
    g = Group(sorted(ranks), gid, mesh_axis=mesh_axis)
    _groups[gid] = g
    return g


def destroy_process_group(group=None):
    global _default_group
    if group is None:
        _groups.clear()
        _default_group = None
    else:
        _groups.pop(group.id, None)
