"""auto.Engine (reference: /root/reference/python/paddle/distributed/
auto_parallel/engine.py:56; _build/_plan/_parallel/_initialize at
:513,670,698,734, fit :811).

TPU-native collapse (SURVEY §3.4): trace the model functionally, let GSPMD do
completion/partitioning/resharding. Engine.fit compiles ONE pjit step with
parameter shardings taken from `param.dist_spec` annotations (or replicated),
batch sharded over "dp"-like first mesh axis when a mesh is present.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor
from ...framework import random as random_mod
from ...jit.functional import _swapped_state, state_arrays


class Engine:
    def __init__(self, model=None, loss=None, optimizer=None, metrics=None,
                 cluster=None, strategy=None):
        self.model = model
        self.loss = loss
        self.optimizer = optimizer
        self.metrics = metrics
        self.strategy = strategy
        self._step_fn = None
        self.history = {"loss": []}

    def _build_step(self):
        model, loss_fn, opt = self.model, self.loss, self.optimizer
        trainable = {n: p for n, p in model.named_parameters()
                     if not p.stop_gradient}
        names = list(trainable.keys())

        def pure_step(params, buffers, opt_state, lr, t, key, x, y):
            def loss_of(tp):
                allp = {**params, **tp}
                from ...core import autograd as ag
                with _swapped_state(model, allp, buffers), ag.no_grad(), \
                        random_mod.traced_key_scope(key):
                    out = model(Tensor(x, stop_gradient=True))
                    l = loss_fn(out, Tensor(y, stop_gradient=True))
                return l._data if isinstance(l, Tensor) else l

            tp = {n: params[n] for n in names}
            loss, grads = jax.value_and_grad(loss_of)(tp)
            new_params = dict(params)
            new_state = {}
            for n in names:
                g = grads[n].astype(params[n].dtype)
                p_new, s_new = opt._update_rule(
                    params[n], g, lr, t, jnp.asarray(0.0, jnp.float32),
                    opt_state[n])
                new_params[n] = p_new
                new_state[n] = s_new
            return loss, new_params, new_state

        self._step_fn = jax.jit(pure_step, donate_argnums=(0, 2))

    def fit(self, train_data=None, train_sample_split=None, batch_size=1,
            epochs=1, steps_per_epoch=None, log_freq=10, valid_data=None,
            **kwargs):
        from ...io import DataLoader
        if isinstance(train_data, DataLoader):
            loader = train_data
        else:
            loader = DataLoader(train_data, batch_size=batch_size,
                                shuffle=True, drop_last=True)
        if self._step_fn is None:
            self._build_step()
        model, opt = self.model, self.optimizer
        trainable = {n: p for n, p in model.named_parameters()
                     if not p.stop_gradient}
        for epoch in range(epochs):
            for step, batch in enumerate(loader):
                if steps_per_epoch and step >= steps_per_epoch:
                    break
                x, y = batch[0], batch[1]
                params, buffers = state_arrays(model)
                opt_state = {n: {an: opt._get_accum(an, p)
                                 for an in opt._accum_names}
                             for n, p in trainable.items()}
                opt._step_count += 1
                loss, new_params, new_state = self._step_fn(
                    params, buffers, opt_state,
                    jnp.asarray(opt.get_lr(), jnp.float32),
                    jnp.asarray(opt._step_count, jnp.int32),
                    random_mod.next_key(),
                    x._data if isinstance(x, Tensor) else jnp.asarray(x),
                    y._data if isinstance(y, Tensor) else jnp.asarray(y))
                for n, p in model.named_parameters():
                    p._data = new_params[n]
                for n, p in trainable.items():
                    for an in opt._accum_names:
                        opt._set_accum(an, p, new_state[n][an])
                self.history["loss"].append(float(np.asarray(loss)))
        return self.history

    def evaluate(self, valid_data=None, batch_size=1, steps=None, **kwargs):
        from ...io import DataLoader
        loader = valid_data if isinstance(valid_data, DataLoader) else \
            DataLoader(valid_data, batch_size=batch_size)
        self.model.eval()
        losses = []
        for step, batch in enumerate(loader):
            if steps and step >= steps:
                break
            x, y = batch[0], batch[1]
            out = self.model(x)
            losses.append(float(self.loss(out, y).numpy()))
        self.model.train()
        return {"loss": float(np.mean(losses)) if losses else 0.0}

    def predict(self, test_data=None, batch_size=1, steps=None, **kwargs):
        from ...io import DataLoader
        loader = test_data if isinstance(test_data, DataLoader) else \
            DataLoader(test_data, batch_size=batch_size)
        self.model.eval()
        outs = []
        for step, batch in enumerate(loader):
            if steps and step >= steps:
                break
            x = batch[0] if isinstance(batch, (list, tuple)) else batch
            outs.append(self.model(x))
        self.model.train()
        return outs

    def save(self, path, training=True):
        import paddle_tpu as P
        P.save(self.model.state_dict(), path + ".pdparams")

    def load(self, path):
        import paddle_tpu as P
        self.model.set_state_dict(P.load(path + ".pdparams"))
