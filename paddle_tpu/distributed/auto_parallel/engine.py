"""auto.Engine (reference: /root/reference/python/paddle/distributed/
auto_parallel/engine.py:56; _build/_plan/_parallel/_initialize at
:513,670,698,734, fit :811).

TPU-native collapse (SURVEY §3.4): trace the model functionally, let GSPMD do
completion/partitioning/resharding. Engine.fit compiles ONE pjit step with
parameter shardings taken from `param.dist_spec` annotations (or replicated),
batch sharded over "dp"-like first mesh axis when a mesh is present.
"""
from __future__ import annotations

import numpy as np


class Engine:
    def __init__(self, model=None, loss=None, optimizer=None, metrics=None,
                 cluster=None, strategy=None):
        self.model = model
        self.loss = loss
        self.optimizer = optimizer
        self.metrics = metrics
        self.strategy = strategy
        self._step_fn = None
        self.history = {"loss": []}

    def _build_step(self):
        """One compiled SPMD step: delegate to TrainStep, which already
        does mesh placement from dist_spec/opt_state_spec annotations,
        AMP, grad clip and weight decay — the Completer/Partitioner/
        Resharder stack collapses into these annotations + GSPMD."""
        from ...jit.train_step import TrainStep

        amp_level = None
        scaler = None
        strat = self.strategy
        if strat is not None and getattr(strat, "amp", False):
            cfg = getattr(strat, "amp_configs", {}) or {}
            amp_level = "O2" if cfg.get("use_pure_fp16") else "O1"
            amp_dtype = "bfloat16" if cfg.get("use_bf16", True) \
                else "float16"
            if amp_dtype == "float16":
                # fp16 always gets a scaler: static scaling (dynamic off)
                # still multiplies the loss by init_loss_scaling — no
                # scaler at all would underflow small grads
                from ...amp.grad_scaler import GradScaler
                scaler = GradScaler(
                    init_loss_scaling=cfg.get("init_loss_scaling",
                                              2.0 ** 15),
                    incr_ratio=cfg.get("incr_ratio", 2.0),
                    decr_ratio=cfg.get("decr_ratio", 0.5),
                    incr_every_n_steps=cfg.get("incr_every_n_steps", 1000),
                    decr_every_n_nan_or_inf=cfg.get(
                        "decr_every_n_nan_or_inf", 2),
                    use_dynamic_loss_scaling=cfg.get(
                        "use_dynamic_loss_scaling", True))
            self._amp_dtype = amp_dtype
        else:
            self._amp_dtype = "bfloat16"
        self._step_fn = TrainStep(self.model, self.loss, self.optimizer,
                                  amp_level=amp_level,
                                  amp_dtype=self._amp_dtype, scaler=scaler)

    def prepare(self, inputs_spec=None, labels_spec=None, mode="train"):
        """Build the compiled step without data (reference engine.py:1385
        Engine.prepare). Records the input/label specs for cost()."""
        self._inputs_spec = inputs_spec
        self._labels_spec = labels_spec
        if self._step_fn is None:
            self._build_step()
        return self

    def dataloader(self, dataset, batch_size=1, shuffle=False,
                   drop_last=True, mode="train", **kwargs):
        """Create the distributed DataLoader for this engine (reference
        engine.py:1270). Batch sharding over the mesh's dp axis happens
        inside the compiled step, so one plain host loader suffices."""
        from ...io import DataLoader
        return DataLoader(dataset, batch_size=batch_size, shuffle=shuffle,
                          drop_last=drop_last, **kwargs)

    def cost(self, inputs_spec=None, labels_spec=None, mode="train"):
        """Estimated per-step cost from XLA's own analysis of the lowered
        step — forward + backward wrt every parameter (reference
        engine.py:1576 delegates to a hand-built cost model; on TPU the
        compiler's cost_analysis is the ground truth). Returns
        {"flops", "bytes accessed", ...}; {} only when no specs were
        given (failures warn and re-raise nothing silently)."""
        import warnings

        import jax
        import jax.numpy as jnp

        from ...core.tensor import Tensor
        from ...jit.api import _specs_from_input_spec
        from ...jit.functional import _swapped_state, state_arrays

        inputs_spec = inputs_spec or getattr(self, "_inputs_spec", None)
        labels_spec = labels_spec or getattr(self, "_labels_spec", None)
        if inputs_spec is None:
            return {}
        in_specs = list(inputs_spec if isinstance(inputs_spec, (list, tuple))
                        else [inputs_spec])
        n_in = len(in_specs)
        all_specs = in_specs + (
            list(labels_spec if isinstance(labels_spec, (list, tuple))
                 else [labels_spec]) if labels_spec is not None else [])

        try:
            sds, _ = _specs_from_input_spec(all_specs)
            # cost needs concrete shapes: collapse symbolic (variable-
            # batch) dims to 1
            abstract = [jax.ShapeDtypeStruct(
                [d if isinstance(d, int) else 1 for d in s.shape],
                s.dtype) for s in sds]
            params, buffers = state_arrays(self.model)

            def step_cost(p, *arrs):
                def loss_of(train_p):
                    from ...core import autograd as ag
                    with _swapped_state(self.model, train_p, buffers), \
                            ag.no_grad():
                        ts = [Tensor(a, stop_gradient=True) for a in arrs]
                        out = self.model(*ts[:n_in])
                        l = self.loss(out, *ts[n_in:]) if self.loss else out
                    arr = l._data if hasattr(l, "_data") else l
                    return arr.astype(jnp.float32)
                return jax.value_and_grad(loss_of)(p)

            compiled = jax.jit(step_cost).lower(params, *abstract).compile()
            analysis = compiled.cost_analysis()
            if isinstance(analysis, (list, tuple)):
                analysis = analysis[0] if analysis else {}
            return dict(analysis or {})
        except Exception as e:  # noqa: BLE001
            warnings.warn(f"Engine.cost failed to lower the step: "
                          f"{type(e).__name__}: {e}")
            return {}

    def fit(self, train_data=None, train_sample_split=None, batch_size=1,
            epochs=1, steps_per_epoch=None, log_freq=10, valid_data=None,
            **kwargs):
        from ...io import DataLoader
        if isinstance(train_data, DataLoader):
            loader = train_data
        else:
            loader = DataLoader(train_data, batch_size=batch_size,
                                shuffle=True, drop_last=True)
        if self._step_fn is None:
            self._build_step()
        for epoch in range(epochs):
            for step, batch in enumerate(loader):
                if steps_per_epoch and step >= steps_per_epoch:
                    break
                x, y = batch[0], batch[1]
                loss = self._step_fn(x, y)
                self.history["loss"].append(float(np.asarray(loss.numpy())))
        return self.history

    def evaluate(self, valid_data=None, batch_size=1, steps=None, **kwargs):
        from ...io import DataLoader
        loader = valid_data if isinstance(valid_data, DataLoader) else \
            DataLoader(valid_data, batch_size=batch_size)
        self.model.eval()
        losses = []
        for step, batch in enumerate(loader):
            if steps and step >= steps:
                break
            x, y = batch[0], batch[1]
            out = self.model(x)
            losses.append(float(self.loss(out, y).numpy()))
        self.model.train()
        return {"loss": float(np.mean(losses)) if losses else 0.0}

    def predict(self, test_data=None, batch_size=1, steps=None, **kwargs):
        from ...io import DataLoader
        loader = test_data if isinstance(test_data, DataLoader) else \
            DataLoader(test_data, batch_size=batch_size)
        self.model.eval()
        outs = []
        for step, batch in enumerate(loader):
            if steps and step >= steps:
                break
            x = batch[0] if isinstance(batch, (list, tuple)) else batch
            outs.append(self.model(x))
        self.model.train()
        return outs

    def save(self, path, training=True):
        import paddle_tpu as P
        P.save(self.model.state_dict(), path + ".pdparams")

    def load(self, path):
        import paddle_tpu as P
        self.model.set_state_dict(P.load(path + ".pdparams"))
