"""auto_parallel Strategy (reference: python/paddle/distributed/auto_parallel/strategy.py)."""
from __future__ import annotations


class _Config:
    def __init__(self, **defaults):
        self.__dict__.update(defaults)
        self.enable = False


class Strategy:
    def __init__(self, config=None):
        self.auto_mode = "semi"
        self.amp = _Config(dtype="bfloat16", level="o1")
        self.sharding = _Config(stage=1, degree=8)
        self.recompute = _Config(checkpoints=[])
        self.pipeline = _Config(schedule_mode="1F1B", micro_batch_size=1,
                                accumulate_steps=1)
        self.gradient_merge = _Config(k_steps=1, avg=True)
        self.dataset = _Config()
        if config:
            for k, v in config.items():
                if hasattr(self, k) and isinstance(v, dict):
                    getattr(self, k).__dict__.update(v)
