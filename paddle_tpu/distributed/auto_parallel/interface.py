"""Auto-parallel user interface: ProcessMesh / shard_tensor / shard_op.

Reference: /root/reference/python/paddle/distributed/auto_parallel/
process_mesh.py:45 + interface.py. The reference propagates DistAttrs through
ProgramDesc (Completer/Partitioner/Resharder, SURVEY §3.4); here ProcessMesh
maps 1:1 onto jax.sharding.Mesh and shard_tensor attaches a PartitionSpec —
GSPMD does completion, partitioning, and resharding in the XLA compiler.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ...core.tensor import Tensor


class ProcessMesh:
    def __init__(self, mesh=None, dim_names=None, shape=None,
                 process_ids=None):
        if mesh is not None:
            arr = np.asarray(mesh)
            self.shape = list(arr.shape)
            self.process_ids = arr.reshape(-1).tolist()
        else:
            self.shape = list(shape)
            self.process_ids = list(process_ids)
        self.dim_names = list(dim_names) if dim_names else [
            f"d{i}" for i in range(len(self.shape))]
        self._jax_mesh = None

    @property
    def ndim(self):
        return len(self.shape)

    @property
    def processes(self):
        return self.process_ids

    def get_jax_mesh(self) -> Mesh:
        if self._jax_mesh is None:
            devs = jax.devices()
            picked = [devs[i % len(devs)] for i in self.process_ids]
            arr = np.asarray(picked).reshape(self.shape)
            self._jax_mesh = Mesh(arr, tuple(self.dim_names))
        return self._jax_mesh

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh) and self.shape == other.shape
                and self.process_ids == other.process_ids)

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dim_names={self.dim_names})"


def shard_tensor(x, process_mesh: Optional[ProcessMesh] = None,
                 shard_spec: Optional[List] = None, **kwargs):
    """Annotate (and place) a tensor with a sharding over the mesh."""
    if process_mesh is None or shard_spec is None:
        return x
    mesh = process_mesh.get_jax_mesh()
    spec = PartitionSpec(*[s if s is not None else None for s in shard_spec])
    if isinstance(x, Tensor):
        try:
            x._data = jax.device_put(x._data, NamedSharding(mesh, spec))
        except Exception:
            pass  # placement best-effort (e.g. uneven shapes)
        x.dist_spec = tuple(shard_spec)
        x.process_mesh = process_mesh
        return x
    return x


def shard_op(op_fn, process_mesh=None, in_shard_specs=None,
             out_shard_specs=None, **kwargs):
    """Run an op with sharding constraints on inputs/outputs."""
    def wrapper(*args, **kw):
        out = op_fn(*args, **kw)
        if process_mesh is not None and out_shard_specs is not None:
            outs = out if isinstance(out, (list, tuple)) else [out]
            for o, spec in zip(outs, out_shard_specs):
                if isinstance(o, Tensor) and spec is not None:
                    shard_tensor(o, process_mesh, spec)
        return out
    return wrapper
