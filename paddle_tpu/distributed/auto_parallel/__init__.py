from .interface import ProcessMesh, shard_op, shard_tensor  # noqa: F401
from .engine import Engine  # noqa: F401
from .strategy import Strategy  # noqa: F401
