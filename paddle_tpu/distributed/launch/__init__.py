"""paddle.distributed.launch package (reference: python/paddle/distributed/launch)."""
from .main import launch, parse_args  # noqa: F401
