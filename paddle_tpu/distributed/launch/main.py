"""python -m paddle_tpu.distributed.launch — multi-process trainer launcher.

Reference: /root/reference/python/paddle/distributed/launch/main.py:18
(collective controller at launch/controllers/collective.py; env contract
from fleet/base/role_maker.py:848-972). The TPU-native launcher keeps that
env contract verbatim:

  PADDLE_TRAINER_ID        rank of this process
  PADDLE_TRAINERS_NUM      world size
  PADDLE_TRAINER_ENDPOINTS comma list host:port, one per rank
  PADDLE_CURRENT_ENDPOINT  this rank's endpoint
  PADDLE_RANK_IN_NODE      local rank
  PADDLE_MASTER            host:port of the TCPStore rendezvous
  TRAINING_ROLE            TRAINER

Rendezvous runs over the native C++ TCPStore (rank 0 hosts it inside
init_parallel_env). On TPU hosts one process per host is the norm (all
local chips belong to one process); --nproc_per_node exists for CPU
testing and host-sharded data work.
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time


def _free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def parse_args(argv=None):
    ap = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="spawn one training process per device/worker")
    ap.add_argument("--nproc_per_node", type=int,
                    default=int(os.environ.get("PADDLE_NPROC_PER_NODE", 1)))
    ap.add_argument("--nnodes", type=int, default=1)
    ap.add_argument("--node_rank", type=int, default=0)
    ap.add_argument("--master", default=None,
                    help="host:port of the rendezvous store (rank 0 hosts)")
    ap.add_argument("--log_dir", default=None)
    ap.add_argument("--devices", default=None,
                    help="accepted for reference-CLI parity")
    ap.add_argument("--jax_distributed", action="store_true",
                    help="initialize jax.distributed in each worker BEFORE "
                         "the script runs (required for compiled multi-host "
                         "SPMD: the coordinator handshake must precede any "
                         "XLA backend use, which importing the framework "
                         "already triggers)")
    ap.add_argument("--elastic_level", type=int, default=0,
                    help=">0 enables restart-on-failure (reference "
                         "elastic/manager.py; TPU-native = full-job "
                         "restart + checkpoint resume, SURVEY §5.3)")
    ap.add_argument("--max_restarts", type=int, default=3)
    ap.add_argument("--np", dest="np_range", default=None,
                    help="elastic world-size range MIN:MAX (reference "
                         "elastic --np syntax). Starts at MAX; scales IN "
                         "when a rank fails repeatedly (lost resource) "
                         "and honors operator elastic/scale_to requests "
                         "— each relaunch re-lowers onto the new mesh "
                         "via checkpoint resume (SURVEY §5.3)")
    ap.add_argument("training_script")
    ap.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return ap.parse_args(argv)


SCALE_RC = -1000  # sentinel: attempt ended by a scale request, not failure


def launch(argv=None) -> int:
    args = parse_args(argv)
    min_np = max_np = None
    if args.np_range:
        lo, _, hi = str(args.np_range).partition(":")
        min_np = int(lo)
        max_np = int(hi or lo)
        if args.elastic_level <= 0:
            args.elastic_level = 1
    max_failures = args.max_restarts if args.elastic_level > 0 else 0
    current_np = max_np or args.nproc_per_node
    rc = 1
    attempt = 0       # counts every relaunch (workers' resume signal)
    failures = 0      # only genuine failures consume restart budget
    scale_events = 0  # bounded so a misbehaving operator can't loop us
    last_failed_rank = None
    while True:
        rc, failed_rank, scale_to = _launch_once(args, attempt,
                                                 nproc=current_np)
        if rc == 0 or args.elastic_level <= 0:
            return rc
        if rc == SCALE_RC:
            # operator-requested resize: relaunch on the new mesh without
            # consuming restart budget (membership change, not failure)
            scale_events += 1
            if scale_events > 16:
                print("elastic: too many resize requests; giving up",
                      file=sys.stderr)
                return 1
            new_np = max(min_np or 1, min(scale_to, max_np or scale_to))
            if args.nnodes > 1:
                print("elastic: live resize is single-node only; "
                      "ignoring request", file=sys.stderr)
                new_np = current_np
            if new_np == current_np:
                print(f"elastic: resize request {scale_to} clamps to the "
                      f"current world {current_np}; continuing unchanged",
                      file=sys.stderr)
            else:
                print(f"elastic: scaling {current_np} -> {new_np} "
                      f"workers (operator request); re-lowering onto "
                      f"the new mesh", file=sys.stderr)
                current_np = new_np
            attempt += 1  # workers read RESTARTS>0 to resume checkpoints
            continue
        failures += 1
        if failures > max_failures:
            return rc
        if min_np is not None and failed_rank is not None \
                and failed_rank == last_failed_rank \
                and current_np - 1 >= min_np:
            # the same rank died twice in a row: treat its slot as a lost
            # resource and scale in (the reference's membership-shrink on
            # node loss, elastic/manager.py:126)
            current_np -= 1
            print(f"elastic: rank {failed_rank} failed repeatedly; "
                  f"scaling in to {current_np} workers", file=sys.stderr)
            last_failed_rank = None
        else:
            last_failed_rank = failed_rank
        attempt += 1
        print(f"elastic: job failed (rc={rc}); restart "
              f"{failures}/{max_failures}", file=sys.stderr)


class _HeartbeatWatcher:
    """Launcher half of elastic fault detection: hosts a LAUNCHER-owned
    TCPStore (so its life doesn't depend on any worker — the rank-0
    rendezvous store dies with rank 0) and reads the ``hb/<rank>`` keys
    workers bump (distributed/env.py ``_start_heartbeat``). Reports a
    rank whose beat has not advanced for ``timeout`` seconds: a
    SIGSTOPped or livelocked worker never exits, so exit-code monitoring
    alone misses it (reference: ElasticManager watchdog,
    fleet/elastic/manager.py:126). Ranks are armed only after their first
    beat — startup/compile time cannot false-trigger. Scope is per node:
    each node's launcher watches its own workers."""

    def __init__(self, ranks):
        from ...native.tcp_store import TCPStore
        self.ranks = list(ranks)
        self.timeout = float(os.environ.get(
            "PADDLE_ELASTIC_HEARTBEAT_TIMEOUT", "30"))
        self.interval = max(0.5, float(os.environ.get(
            "PADDLE_ELASTIC_HEARTBEAT_INTERVAL", "2")))
        # a pinned port lets operators/tooling connect for membership
        # queries and elastic/scale_to requests
        port = int(os.environ.get("PADDLE_ELASTIC_HB_PORT", 0) or 0) \
            or _free_port()
        self._store = TCPStore(host="127.0.0.1", port=port,
                               is_master=True, timeout=10.0)
        self.endpoint = f"127.0.0.1:{self._store.port}"
        self._last = {}       # rank -> (value, wall time it changed)
        self._next_check = 0.0

    def publish_world(self, world):
        """Membership view for operators (reference: etcd node list)."""
        try:
            self._store.set("elastic/world", str(world).encode())
        except Exception:
            pass

    def scale_request(self, current_world):
        """An operator-set elastic/scale_to value != current world, or
        None. Throttled to the heartbeat interval — an unthrottled call
        would hammer the store ~20x/sec from the 50ms monitor loop."""
        now = time.time()
        if now < getattr(self, "_next_scale_check", 0.0):
            return None
        self._next_scale_check = now + self.interval
        try:
            val = int(self._store.get("elastic/scale_to").decode())
        except Exception:
            return None
        if val and val != current_world:
            try:
                self._store.delete("elastic/scale_to")
            except Exception:
                pass
            return val
        return None

    def poll(self, live_ranks=None):
        """Return a stale rank id among ``live_ranks`` (default: all), or
        None. A rank that already exited keeps a frozen key — only ranks
        still running can be declared silent."""
        now = time.time()
        if now < self._next_check:
            return None
        self._next_check = now + self.interval
        ranks = self.ranks if live_ranks is None else \
            [r for r in self.ranks if r in live_ranks]
        for r in ranks:
            try:
                val = self._store.get(f"hb/{r}")
            except KeyError:
                continue  # rank hasn't started heartbeating yet
            except Exception:
                return None  # transient store error; retry next round
            prev = self._last.get(r)
            if prev is None or prev[0] != val:
                self._last[r] = (val, now)
            elif now - prev[1] > self.timeout:
                return r
        return None

    def close(self):
        try:
            self._store.close()
        except Exception:
            pass


def _launch_once(args, attempt: int = 0, nproc=None):
    """Run one job attempt. Returns (rc, failed_rank, scale_to):
    rc==SCALE_RC means the attempt was stopped by an operator resize
    request (scale_to holds the target world)."""
    nproc = nproc or args.nproc_per_node
    world = nproc * args.nnodes
    if args.nnodes > 1:
        # multi-node: rank 0 (node 0) hosts the store; every node must be
        # told where it is, and must advertise a reachable address
        if not args.master:
            raise SystemExit(
                "--master host:port is required when --nnodes > 1 "
                "(node 0 hosts the rendezvous store there)")
        host = os.environ.get("POD_IP") or socket.gethostbyname(
            socket.gethostname())
    else:
        host = "127.0.0.1"
    master = args.master or f"{host}:{_free_port()}"
    # the jax coordination service gets its own PROBED port (master+1 was
    # assumed free before — sequential kernel port handout made collisions
    # with base_port likely)
    jax_coord_port = _free_port()
    base_port = _free_port()
    # single-node endpoints are exact; multi-node lists this node's span
    # (the env contract only requires PADDLE_MASTER to be globally correct)
    endpoints = ",".join(f"{host}:{base_port + i}" for i in range(world))

    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)

    watcher = None
    if args.elastic_level > 0:
        try:
            watcher = _HeartbeatWatcher(
                [args.node_rank * nproc + i for i in range(nproc)])
        except Exception as e:  # heartbeat is best-effort; restarts still
            print(f"elastic: heartbeat store unavailable ({e}); "
                  f"exit-code monitoring only", file=sys.stderr)

    procs = []
    for local in range(nproc):
        rank = args.node_rank * nproc + local
        env = dict(os.environ)
        # workers resolve imports against the launch cwd (the script's own
        # directory is what python puts on sys.path otherwise)
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in (os.getcwd(), env.get("PYTHONPATH", "")) if p)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(world),
            "PADDLE_TRAINER_ENDPOINTS": endpoints,
            "PADDLE_CURRENT_ENDPOINT":
                endpoints.split(",")[rank],
            "PADDLE_RANK_IN_NODE": str(local),
            "PADDLE_MASTER": master,
            "TRAINING_ROLE": "TRAINER",
            "FLAGS_selected_tpus": str(local),
        })
        if args.elastic_level > 0:
            # which elastic attempt this is — workers use it to decide
            # whether to resume from checkpoint (ElasticManager.restarts).
            # Only set when THIS launcher owns the restart loop, so an
            # outer orchestrator's values are never clobbered.
            env["PADDLE_ELASTIC_RESTARTS"] = str(attempt)
            env["PADDLE_ELASTIC_LEVEL"] = str(args.elastic_level)
            if watcher is not None:
                env["PADDLE_ELASTIC_HB_ENDPOINT"] = watcher.endpoint
        if args.log_dir:
            out = open(os.path.join(args.log_dir,
                                    f"workerlog.{rank}"), "w")
        else:
            out = None
        if args.jax_distributed:
            mhost = master.partition(":")[0]
            env["PADDLE_JAX_COORDINATOR"] = \
                f"{mhost}:{jax_coord_port}"
            env["PADDLE_JAX_DISTRIBUTED"] = "1"
            boot = (
                "import os, sys, runpy, jax\n"
                "plat = os.environ.get('JAX_PLATFORMS')\n"
                "if plat:\n"
                "    jax.config.update('jax_platforms', plat)\n"
                "jax.distributed.initialize(\n"
                "    coordinator_address=os.environ['PADDLE_JAX_COORDINATOR'],\n"
                "    num_processes=int(os.environ['PADDLE_TRAINERS_NUM']),\n"
                "    process_id=int(os.environ['PADDLE_TRAINER_ID']))\n"
                "sys.argv = sys.argv[1:]\n"
                "runpy.run_path(sys.argv[0], run_name='__main__')\n")
            cmd = [sys.executable, "-u", "-c", boot,
                   args.training_script, *args.training_script_args]
        else:
            cmd = [sys.executable, "-u", args.training_script,
                   *args.training_script_args]
        procs.append((rank, subprocess.Popen(
            cmd, env=env, stdout=out,
            stderr=subprocess.STDOUT if out else None),
            out))

    rc = 0
    failed_rank = None
    scale_to = None
    if watcher is not None:
        watcher.publish_world(world)
    try:
        live = {r: p for r, p, _ in procs}

        def _kill_all(reason, code, force=False):
            """Stop remaining ranks. Default: SIGTERM + 10s grace then
            SIGKILL (peers get to flush checkpoints/logs). ``force``
            SIGKILLs immediately — required for the heartbeat path, where
            a SIGSTOPped process ignores SIGTERM forever."""
            nonlocal rc, live
            print(reason, file=sys.stderr)
            rc = code
            for q in live.values():
                if q.poll() is None:
                    q.kill() if force else q.terminate()
            deadline = time.time() + 10
            for q in live.values():
                try:
                    q.wait(max(0.1, deadline - time.time()))
                except subprocess.TimeoutExpired:
                    q.kill()
            live = {}

        while live:
            for r, p in list(live.items()):
                code = p.poll()
                if code is None:
                    continue
                del live[r]
                if code != 0:
                    failed_rank = r
                    _kill_all(f"rank {r} exited with code {code}; "
                              f"terminating peers", code)
                    break
            if live and watcher is not None:
                stale = watcher.poll(set(live))
                if stale is not None:
                    failed_rank = stale
                    _kill_all(
                        f"elastic: rank {stale} heartbeat silent for "
                        f">{watcher.timeout:.0f}s (hung or stopped); "
                        f"restarting job", 1, force=True)
                elif args.np_range:
                    req = watcher.scale_request(world)
                    if req is not None:
                        scale_to = req
                        _kill_all(
                            f"elastic: resize to {req} requested; "
                            f"checkpoint-stop for mesh change", SCALE_RC)
            time.sleep(0.05)
    except KeyboardInterrupt:
        for r, p, _ in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGINT)
        rc = 130
    finally:
        if watcher is not None:
            watcher.close()
        for _, p, out in procs:
            if out is not None:
                out.close()
    return rc, failed_rank, scale_to


if __name__ == "__main__":
    sys.exit(launch())
