"""paddle.distributed.rpc — minimal RPC over the TCPStore rendezvous.

Reference: /root/reference/python/paddle/distributed/rpc/rpc.py
(init_rpc/rpc_sync/rpc_async/shutdown, brpc-backed). TPU-native: requests
ride the same native TCPStore the collectives use — each worker runs a
server thread polling its request mailbox; callables must be picklable
(module-level functions), the reference's contract too.
"""
from __future__ import annotations

import itertools
import pickle
import threading
import time
from typing import Any, Optional

from . import env

__all__ = ["init_rpc", "rpc_sync", "rpc_async", "shutdown",
           "get_current_worker_info", "get_worker_info", "get_all_worker_infos"]

_state = {"running": False, "thread": None, "name": None, "names": {}}
_req_seq = itertools.count()
_TIMEOUT = 120.0


class WorkerInfo:
    def __init__(self, name, rank):
        self.name = name
        self.rank = rank

    def __repr__(self):
        return f"WorkerInfo(name={self.name}, rank={self.rank})"


def _store():
    s = env.get_store()
    if s is None:
        raise RuntimeError("init_rpc requires the multi-process bootstrap "
                           "(init_parallel_env / the launcher)")
    return s


def _serve(rank, start):
    # The server thread gets its OWN store connection: a blocking wait()
    # holds a connection's request mutex for the full round-trip, so
    # sharing the main thread's connection deadlocks when two ranks
    # rpc_sync at each other (both mains parked in wait, both servers
    # starved behind that mutex).
    from ..native.tcp_store import TCPStore
    main = _store()
    store = TCPStore(host=main.host, port=main.port, is_master=False)
    n = start
    while _state["running"]:
        key = f"rpc/req/{rank}/{n}"
        try:
            payload = store.wait(key, 1.0)
        except TimeoutError:
            continue
        except Exception:
            return
        # a malformed/unpicklable message must not kill the serve loop —
        # every later RPC to this rank would then hang to timeout
        caller = seq = None
        try:
            caller, seq, fn, args, kwargs = pickle.loads(payload)
            result = (True, fn(*args, **kwargs))
        except Exception as e:  # noqa: BLE001 — marshalled to caller
            result = (False, repr(e))
        try:
            blob = pickle.dumps(result)
        except Exception as e:  # noqa: BLE001 — unpicklable return value
            blob = pickle.dumps((False, f"unpicklable rpc result: {e!r}"))
        if caller is not None:
            store.set(f"rpc/res/{caller}/{seq}", blob)
        store.delete(key)
        n += 1


def init_rpc(name: str, rank: Optional[int] = None,
             world_size: Optional[int] = None, master_endpoint=None):
    env.init_parallel_env()
    rank = env.global_rank() if rank is None else rank
    store = _store()
    # read the mailbox resume point BEFORE becoming addressable (name
    # publish / end-of-init barrier): a peer's first send must not land
    # between the read and the server start, or its index gets skipped
    start = int(store.add(f"rpc/next/{rank}", 0))
    _state.update(running=True, name=name)
    t = threading.Thread(target=_serve, args=(rank, start), daemon=True)
    _state["thread"] = t
    t.start()
    store.set(f"rpc/name/{rank}", name.encode())
    # resolve peer names
    world = env.get_world_size() if world_size is None else world_size
    for r in range(world):
        _state["names"][store.wait(f"rpc/name/{r}", _TIMEOUT).decode()] = r
    # all servers live before anyone issues an rpc
    from .communication.collective import barrier
    barrier()


class _Future:
    def __init__(self, caller_rank, seq):
        self._key = f"rpc/res/{caller_rank}/{seq}"
        self._value = None
        self._done = False

    def wait(self, timeout=_TIMEOUT) -> Any:
        if self._done:
            return self._value
        store = _store()
        ok, value = pickle.loads(store.wait(self._key, timeout))
        store.delete(self._key)
        if not ok:
            raise RuntimeError(f"rpc target raised: {value}")
        self._value = value
        self._done = True
        return value


def _target_rank(to: str) -> int:
    if to in _state["names"]:
        return _state["names"][to]
    try:
        return int(to)
    except ValueError:
        raise ValueError(f"unknown rpc worker {to!r}")


def rpc_async(to: str, fn, args=(), kwargs=None, timeout=_TIMEOUT):
    store = _store()
    me = env.global_rank()
    seq = next(_req_seq)
    dst = _target_rank(to)
    # per-destination mailbox index: the server consumes in order
    idx = store.add(f"rpc/next/{dst}", 1) - 1
    store.set(f"rpc/req/{dst}/{idx}",
              pickle.dumps((me, seq, fn, tuple(args), kwargs or {})))
    return _Future(me, seq)


def rpc_sync(to: str, fn, args=(), kwargs=None, timeout=_TIMEOUT):
    return rpc_async(to, fn, args, kwargs, timeout).wait(timeout)


def shutdown(graceful=True):
    if graceful:
        from .communication.collective import barrier
        barrier()
    _state["running"] = False
    t = _state.get("thread")
    if t is not None:
        t.join(timeout=3)
    _state["thread"] = None


def get_current_worker_info() -> WorkerInfo:
    return WorkerInfo(_state["name"], env.global_rank())


def get_worker_info(name: str) -> WorkerInfo:
    return WorkerInfo(name, _target_rank(name))


def get_all_worker_infos():
    return [WorkerInfo(n, r) for n, r in sorted(_state["names"].items(),
                                                key=lambda kv: kv[1])]
