"""paddle.distributed.io (reference distributed/io.py): save/load for
distributed training — on TPU the sharded checkpoint module
(framework/checkpoint.py) is the real mechanism; these wrappers keep the
reference entry points."""
from __future__ import annotations


def save_persistables(executor=None, dirname=None, main_program=None,
                      filename=None):
    """reference io.py save_persistables: persist trainable state. The
    static Program tracks its layers; delegate to paddle.save."""
    import paddle_tpu as paddle
    if main_program is None or not hasattr(main_program, "state_dict"):
        raise TypeError(
            "save_persistables needs a program/layer exposing "
            "state_dict(); got "
            f"{type(main_program).__name__} (silently writing an empty "
            "checkpoint would lose the training state)")
    state = main_program.state_dict()
    paddle.save(state, (dirname or ".") + "/" + (filename or "__params__"))


def load_persistables(executor=None, dirname=None, main_program=None,
                      filename=None):
    import paddle_tpu as paddle
    return paddle.load((dirname or ".") + "/" + (filename or "__params__"))


def is_persistable(var):
    return getattr(var, "persistable", True)
