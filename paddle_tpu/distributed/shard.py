"""paddle_tpu.distributed.shard — the unified sharding API.

One surface that turns a parameter pytree + device mesh into a
``NamedSharding``/``PartitionSpec`` tree and applies it consistently
across training (``TrainStep``), serving (``Predictor`` /
``CachedDecoder``) and planning (``tools/shardcheck.py``):

- **Spec inference** (``spec_tree``): a rule table over parameter
  paths and shapes encodes the repo's embedding/attention/MLP axis
  conventions (GSPMD, Xu et al. 2021: a small set of annotations plus
  propagation covers data/model/pipeline parallelism). Unrecognized
  shapes fall back to replication — never a wrong guess.
- **Declarative overrides** (``annotate`` / ``Layer.shard_spec``):
  per-layer annotations or a glob spec-map by parameter path; explicit
  overrides always beat rules, rules beat the replicated fallback.
- **ZeRO composition** (``zero=`` levels ``os``/``os_g``/``p_g_os``):
  optimizer/parameter sharding is a spec-tree decision (Rajbhandari et
  al. 2020), not a per-model rewrite — dim 0 shards over the
  ``sharding`` axis wherever it divides evenly.
- **Placement** (``shard_params``/``shard_tree``/``sharding_tree``)
  and **activation constraints** (``constrain``/``constrain_batch``/
  ``constrain_seq``) that degrade to no-ops on meshless or 1-device
  runs, so the same model code runs everywhere.
- **Cache coherence**: every annotation bump increments a process-wide
  generation (``specs_generation``) that the compiled-step memos key
  on, and ``spec_tree_hash`` folds the spec tree into the persistent
  compile-cache fingerprint — two spec trees can never share an
  executable.
- **Observability**: ``paddle_shard_*`` gauges (spec-tree hash, spec
  counts, per-chip projected model-state bytes) on the metric registry
  so ``/statusz``//``/metrics`` show what sharding a live process runs.

Thread-safety: the generation counter and metric publication are
guarded by ``_lock``; spec inference itself is pure.
"""
from __future__ import annotations

import fnmatch
import hashlib
import json
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "REPLICATED", "ShardingRules", "default_rules",
    "normalize_spec", "spec_tree", "model_spec_tree", "spec_tree_hash",
    "annotate", "mark_param", "apply_sharding",
    "shard_tree", "shard_params", "sharding_tree", "param_shardings",
    "constrain", "constrain_batch", "constrain_seq",
    "batch_axes", "batch_spec",
    "specs_generation", "projected_bytes_per_chip", "publish_metrics",
    "ZERO_LEVELS",
]

# Explicitly-replicated spec (PartitionSpec() — every dim unsharded).
REPLICATED: Tuple = ()

ZERO_LEVELS = ("os", "os_g", "p_g_os")

_lock = threading.Lock()
_generation = 0
_metrics = None  # lazily-built {gauge-name: Gauge} dict


def specs_generation() -> int:
    """Process-wide sharding-annotation generation. Bumped by every
    ``annotate``/``mark_param``/``apply_sharding`` call; compiled-step
    signature memos include it so a spec change mid-process can never
    serve a stale executable (the flags_generation pattern)."""
    with _lock:
        return _generation


def _bump_generation():
    global _generation
    with _lock:
        _generation += 1


# --------------------------------------------------------------- specs
def _canon_spec(spec) -> Tuple:
    """Canonical tuple form of a spec: entries are None, an axis name,
    or a tuple of axis names. Accepts PartitionSpec, list/tuple, or
    None (replicated)."""
    if spec is None:
        return REPLICATED
    out = []
    for s in tuple(spec):
        if s is None or isinstance(s, str):
            out.append(s)
        elif isinstance(s, (tuple, list)):
            out.append(tuple(str(a) for a in s))
        else:
            raise TypeError(f"spec entry must be None, an axis name or "
                            f"a tuple of axis names, got {s!r}")
    return tuple(out)


def normalize_spec(spec, mesh, shape: Optional[Sequence[int]] = None
                   ) -> Tuple:
    """Degrade a spec against a mesh: axes the mesh doesn't have (or
    has at size 1) become replication, and — when ``shape`` is given —
    any dim the surviving axes don't divide evenly falls back to
    replication for that dim. A 1-device mesh therefore degrades every
    spec to the no-op, which is what lets tier-1 CPU runs exercise the
    full path."""
    spec = _canon_spec(spec)
    if mesh is None:
        return REPLICATED

    def _axis_size(a):
        return mesh.shape[a] if a in mesh.axis_names else 1

    out = []
    for i, s in enumerate(spec):
        if isinstance(s, tuple):
            kept = tuple(a for a in s if _axis_size(a) > 1)
            size = 1
            for a in kept:
                size *= _axis_size(a)
            s = (kept if len(kept) > 1 else (kept[0] if kept else None))
        else:
            size = _axis_size(s) if s is not None else 1
            s = s if size > 1 else None
        if s is not None and shape is not None:
            if i >= len(shape) or shape[i] % size != 0:
                s = None
        out.append(s)
    return tuple(out)


def _spec_shards(spec, mesh_axes: Dict[str, int]) -> int:
    """Number of shards a spec splits a buffer into over ``mesh_axes``
    (a {axis: degree} dict)."""
    n = 1
    for s in _canon_spec(spec):
        for a in (s if isinstance(s, tuple) else (s,)):
            if a is not None:
                n *= int(mesh_axes.get(a, 1))
    return n


# --------------------------------------------------------------- rules
class ShardingRules:
    """Rule table: ordered (glob-pattern, spec) name rules over the
    parameter path, then shape heuristics, then the replicated
    fallback. ``spec`` may also be a callable ``shape -> spec`` for
    shape-dependent rules."""

    def __init__(self, name_rules: Sequence[Tuple[str, Any]] = (),
                 use_shape_heuristics: bool = True):
        self.name_rules = list(name_rules)
        self.use_shape_heuristics = use_shape_heuristics

    def with_rules(self, *rules: Tuple[str, Any]) -> "ShardingRules":
        """A copy with extra name rules PREPENDED (first match wins, so
        later additions take precedence over the defaults)."""
        return ShardingRules(list(rules) + self.name_rules,
                             self.use_shape_heuristics)

    def spec_for(self, path: str, shape: Sequence[int]) -> Tuple:
        for pattern, spec in self.name_rules:
            if fnmatch.fnmatchcase(path, pattern):
                if callable(spec):
                    spec = spec(tuple(shape))
                return _canon_spec(spec)
        if self.use_shape_heuristics:
            return _canon_spec(self._shape_spec(tuple(shape)))
        return REPLICATED

    @staticmethod
    def _shape_spec(shape: Tuple[int, ...]):
        """Shape heuristics for the transformer weight classes this repo
        trains (the GSPMD/Megatron conventions):

        - embedding table [V, H], vocab much larger than hidden
          -> vocab-dim over 'mp'
        - column-parallel up-projection [H, k*H] (qkv k=3, mlp k=4)
          -> output dim over 'mp'
        - row-parallel down-projection [k*H, H]
          -> input dim over 'mp'
        - everything else (layernorm scales, biases, scalars, conv
          kernels, square projections — ambiguous) -> replicated.
        """
        if len(shape) != 2 or 0 in shape:
            return None
        d0, d1 = shape
        if d1 < 8:
            return None                        # classifier heads and the
        if d0 >= 8 * d1:                       # like: too small to split
            return ("mp", None)                # vocab/position-style table
        if d1 > d0 and d1 % d0 == 0 and d1 // d0 in (2, 3, 4, 8):
            return (None, "mp")                # qkv / mlp up
        if d0 > d1 and d0 % d1 == 0 and d0 // d1 in (2, 3, 4, 8):
            return ("mp", None)                # attention-out / mlp down
        return None


# The repo's layer-name conventions (GPT/BERT/ERNIE share them: see
# models/gpt.py, models/bert.py, fleet/meta_parallel/mp_layers.py).
# First match wins; `annotate` overrides beat all of these.
_DEFAULT_NAME_RULES: List[Tuple[str, Any]] = [
    ("*word_embeddings.weight", ("mp", None)),
    ("*position_embeddings", REPLICATED),
    ("*token_type_embeddings", REPLICATED),
    ("*task_type_embeddings", REPLICATED),
    ("*qkv_proj.weight", (None, "mp")),
    ("*qkv_proj.bias", ("mp",)),
    ("*fc_in.weight", (None, "mp")),
    ("*fc_in.bias", ("mp",)),
    ("*out_proj.weight", ("mp", None)),
    ("*out_proj.bias", REPLICATED),
    ("*fc_out.weight", ("mp", None)),
    ("*fc_out.bias", REPLICATED),
    ("*ln_*.weight", REPLICATED), ("*ln_*.bias", REPLICATED),
    ("*ln1.weight", REPLICATED), ("*ln1.bias", REPLICATED),
    ("*ln2.weight", REPLICATED), ("*ln2.bias", REPLICATED),
    ("*layer_norm.weight", REPLICATED), ("*layer_norm.bias", REPLICATED),
]


def default_rules() -> ShardingRules:
    return ShardingRules(_DEFAULT_NAME_RULES, use_shape_heuristics=True)


# ---------------------------------------------------------- annotation
def mark_param(param, spec, opt_state_spec="__unset__"):
    """Attach a sharding spec to one parameter (sets ``dist_spec``, the
    attribute every compiled-step builder reads) and bump the spec
    generation. The single supported write path — direct ``dist_spec``
    assignment still works but does not invalidate compiled-step
    memos."""
    param.dist_spec = _canon_spec(spec) if spec is not None else None
    if opt_state_spec != "__unset__":
        param.opt_state_spec = (_canon_spec(opt_state_spec)
                                if opt_state_spec is not None else None)
    _bump_generation()
    return param


def annotate(layer, spec_map: Optional[Dict[str, Any]] = None,
             **attr_specs) -> Dict[str, Tuple]:
    """Declarative per-layer override (``Layer.shard_spec`` delegates
    here). Two forms, composable:

    - keyword per direct attribute: ``layer.shard_spec(weight=(None,
      "mp"), bias=("mp",))``
    - glob spec-map over the layer's ``named_parameters`` paths:
      ``model.shard_spec({"encoder.*.qkv_proj.weight": (None, "mp")})``

    Overrides take precedence over the rule table in ``spec_tree``;
    pass ``None`` for an explicit replicated override. Returns the
    {path: spec} overrides that were recorded."""
    recorded: Dict[str, Tuple] = {}
    for attr, spec in attr_specs.items():
        p = getattr(layer, attr, None)
        if p is None or not hasattr(p, "shape"):
            raise AttributeError(
                f"{type(layer).__name__}.{attr} is not a parameter")
        p._shard_override = _canon_spec(spec)
        recorded[attr] = p._shard_override
    if spec_map:
        named = dict(layer.named_parameters())
        for pattern, spec in spec_map.items():
            hit = False
            for path, p in named.items():
                if fnmatch.fnmatchcase(path, pattern):
                    p._shard_override = _canon_spec(spec)
                    recorded[path] = p._shard_override
                    hit = True
            if not hit:
                raise KeyError(
                    f"shard_spec pattern {pattern!r} matches no "
                    f"parameter (have e.g. "
                    f"{sorted(named)[:3]}...)")
    if recorded:
        _bump_generation()
    return recorded


# ----------------------------------------------------------- inference
def _zero_compose(spec: Tuple, shape: Sequence[int], mesh,
                  axis: str = "sharding") -> Tuple:
    """Fold ZeRO parameter sharding into a spec: dim 0 shards over
    ``axis`` when it divides evenly. Composes with TP — if dim 0 is
    already sharded (after normalizing against the mesh) the ZeRO axis
    joins it only when the product still divides."""
    if not shape:
        return spec
    size = mesh.shape.get(axis, 1) if (mesh is not None
                                       and axis in mesh.axis_names) else 1
    if size <= 1:
        return spec
    spec = tuple(spec) + (None,) * (len(shape) - len(spec))
    d0 = spec[0]
    if d0 is None:
        if shape[0] % size == 0:
            return (axis,) + spec[1:]
        return spec
    existing = d0 if isinstance(d0, tuple) else (d0,)
    total = size
    for a in existing:
        total *= (mesh.shape.get(a, 1)
                  if mesh is not None and a in mesh.axis_names else 1)
    if shape[0] % total == 0:
        return (existing + (axis,),) + spec[1:]
    return spec


def spec_tree(model, mesh="__global__", rules: Optional[ShardingRules]
              = None, overrides: Optional[Dict[str, Any]] = None,
              zero: Optional[str] = None) -> Dict[str, Tuple]:
    """Infer the {param-path: PartitionSpec-tuple} tree for a model.

    Precedence per parameter (first source that answers wins):

    1. ``overrides`` argument (glob patterns over the path),
    2. ``annotate``/``Layer.shard_spec`` annotations
       (``p._shard_override``),
    3. an existing ``dist_spec`` (the TP layers self-annotate),
    4. the rule table (name rules, then shape heuristics),
    5. replicated.

    With ``zero`` set, dim 0 additionally shards over the ``sharding``
    mesh axis (level ``p_g_os``; ``os``/``os_g`` affect only the
    optimizer-state tree — see ``apply_sharding``). Specs are
    normalized against ``mesh`` (default: the global mesh), so a
    1-device mesh yields all-replicated."""
    if mesh == "__global__":
        from .mesh_utils import get_global_mesh
        mesh = get_global_mesh()
    if zero is not None and zero not in ZERO_LEVELS:
        raise ValueError(f"zero must be one of {ZERO_LEVELS}, got {zero!r}")
    rules = rules or default_rules()
    out: Dict[str, Tuple] = {}
    for path, p in model.named_parameters():
        shape = tuple(p.shape)
        spec = None
        if overrides:
            for pattern, s in overrides.items():
                if fnmatch.fnmatchcase(path, pattern):
                    spec = _canon_spec(s)
                    break
        if spec is None:
            ov = getattr(p, "_shard_override", None)
            if ov is not None:
                spec = _canon_spec(ov)
        if spec is None:
            # a model already passed through apply_sharding reads its
            # PRE-application annotation (saved as _base_dist_spec), not
            # the applied result — re-inference with different options
            # (e.g. dropping ZeRO) must not see its own prior output
            existing = getattr(p, "_base_dist_spec", "__unset__")
            if existing == "__unset__":
                existing = getattr(p, "dist_spec", None)
            if existing is not None:
                spec = _canon_spec(existing)
        if spec is None:
            spec = rules.spec_for(path, shape)
        spec = normalize_spec(spec, mesh, shape)
        if zero == "p_g_os":
            spec = _zero_compose(spec, shape, mesh)
        out[path] = spec
    return out


def model_spec_tree(model) -> Dict[str, Dict[str, Optional[Tuple]]]:
    """The CURRENT annotations of a model (no inference): per path the
    ``dist_spec`` and ``opt_state_spec`` attributes, for hashing and
    display."""
    out: Dict[str, Dict[str, Optional[Tuple]]] = {}
    for path, p in model.named_parameters():
        ds = getattr(p, "dist_spec", None)
        os_ = getattr(p, "opt_state_spec", None)
        out[path] = {
            "dist_spec": _canon_spec(ds) if ds is not None else None,
            "opt_state_spec": _canon_spec(os_) if os_ is not None else None,
        }
    return out


def spec_tree_hash(specs) -> str:
    """Stable sha256 of a spec tree (any JSON-able nesting of specs);
    folded into compiled-step fingerprints and exported as the
    ``paddle_shard_spec_tree_info`` gauge label so a live process's
    sharding is identifiable."""
    def _enc(v):
        if isinstance(v, dict):
            return {str(k): _enc(x) for k, x in sorted(v.items())}
        if isinstance(v, (list, tuple)):
            return [_enc(x) for x in v]
        return v if (v is None or isinstance(v, (str, int, float, bool))) \
            else repr(v)
    blob = json.dumps(_enc(specs), sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()


def apply_sharding(model, mesh="__global__",
                   rules: Optional[ShardingRules] = None,
                   overrides: Optional[Dict[str, Any]] = None,
                   zero: Optional[str] = None,
                   publish: bool = True) -> Dict[str, Tuple]:
    """Compute the spec tree and WRITE it onto the model's parameters
    (``dist_spec`` + ``opt_state_spec``) — the one-call replacement for
    manual ZeRO wiring (``group_sharded_parallel``) and hand-placed
    ``dist_spec`` assignments:

    - ``zero=None``: TP/rule placement only; optimizer state follows
      the parameter layout (TrainStep default).
    - ``zero="os"``/``"os_g"``: parameters keep their placement,
      optimizer state (and, via the TrainStep grad pin, gradients)
      shard dim 0 over the ``sharding`` axis.
    - ``zero="p_g_os"``: full ZeRO-3 — parameters, gradients and
      optimizer state all shard.

    Returns the parameter spec tree. Bumps ``specs_generation`` and
    (by default) publishes the ``paddle_shard_*`` gauges."""
    if mesh == "__global__":
        from .mesh_utils import get_global_mesh
        mesh = get_global_mesh()
    p_specs = spec_tree(model, mesh=mesh, rules=rules,
                        overrides=overrides, zero=zero)
    os_specs = p_specs if zero in (None, "p_g_os") else spec_tree(
        model, mesh=mesh, rules=rules, overrides=overrides, zero="p_g_os")
    named = dict(model.named_parameters())
    for path, spec in p_specs.items():
        p = named[path]
        if not hasattr(p, "_base_dist_spec"):
            p._base_dist_spec = getattr(p, "dist_spec", None)
        p.dist_spec = spec
        if zero is None:
            if getattr(p, "opt_state_spec", None) is not None:
                p.opt_state_spec = None
        else:
            p.opt_state_spec = os_specs[path]
    _bump_generation()
    if publish:
        publish_metrics(p_specs, named, mesh)
    return p_specs


# ----------------------------------------------------------- placement
def sharding_tree(specs, mesh="__global__"):
    """Map a pytree of specs to a pytree of ``NamedSharding`` over the
    mesh (``None`` without a mesh) — the ``get_sharding_tree`` surface
    over arbitrary trees."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec
    if mesh == "__global__":
        from .mesh_utils import get_global_mesh
        mesh = get_global_mesh()
    if mesh is None:
        return jax.tree_util.tree_map(lambda s: None, specs,
                                      is_leaf=_is_spec_leaf)
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, PartitionSpec(
            *normalize_spec(s, mesh))),
        specs, is_leaf=_is_spec_leaf)


def _is_spec_leaf(x) -> bool:
    """A spec tuple (or None/REPLICATED) is a leaf of a spec tree."""
    if x is None or x == ():
        return True
    return isinstance(x, tuple) and all(
        s is None or isinstance(s, (str, tuple)) for s in x)


def shard_tree(tree, specs, mesh="__global__"):
    """Place a pytree of arrays by a matching pytree of specs
    (``jax.device_put`` per leaf). Leaves whose spec is None/absent are
    replicated; without a mesh the tree is returned unchanged."""
    import jax
    if mesh == "__global__":
        from .mesh_utils import get_global_mesh
        mesh = get_global_mesh()
    if mesh is None:
        return tree
    shardings = sharding_tree(specs, mesh)

    def _put(a, sh):
        if sh is None or not hasattr(a, "shape"):
            return a
        return jax.device_put(a, sh)

    return jax.tree_util.tree_map(_put, tree, shardings)


def shard_params(model, mesh="__global__",
                 specs: Optional[Dict[str, Any]] = None):
    """Place a model's parameter arrays by their spec tree (inferred
    via ``spec_tree`` when not given), writing the placed arrays back
    into the parameters. The committed-placement sibling of
    ``apply_sharding`` — annotate first, then place. No-op without a
    mesh; returns the model."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec
    if mesh == "__global__":
        from .mesh_utils import get_global_mesh
        mesh = get_global_mesh()
    if mesh is None:
        return model
    if specs is None:
        specs = {path: getattr(p, "dist_spec", None)
                 for path, p in model.named_parameters()}
    for path, p in model.named_parameters():
        spec = normalize_spec(specs.get(path), mesh, tuple(p.shape))
        data = getattr(p, "_data", None)
        if data is None:            # LazyGuard abstract param: spec only
            continue
        p._data = jax.device_put(
            data, NamedSharding(mesh, PartitionSpec(*spec)))
    return model


def param_shardings(mesh, named_params) -> Dict[str, Any]:
    """{name: NamedSharding} for a named-parameter mapping from each
    param's ``dist_spec`` — the TrainStep/aot_lower layout source."""
    from jax.sharding import NamedSharding, PartitionSpec
    out = {}
    for n, p in dict(named_params).items():
        spec = normalize_spec(getattr(p, "dist_spec", None), mesh,
                              tuple(p.shape))
        out[n] = NamedSharding(mesh, PartitionSpec(*spec))
    return out


# --------------------------------------------------------- constraints
def batch_axes(mesh) -> Tuple[str, ...]:
    """Mesh axes the input batch dim shards over: dp and the ZeRO
    'sharding' axis (the standard GSPMD ZeRO recipe)."""
    if mesh is None:
        return ()
    return tuple(a for a in ("dp", "sharding")
                 if a in mesh.axis_names and mesh.shape[a] > 1)


def batch_spec(mesh):
    """PartitionSpec for a batch-major input on ``mesh``."""
    from jax.sharding import PartitionSpec
    axes = batch_axes(mesh)
    return PartitionSpec(axes if axes else None)


def constrain(x, *spec, mesh="__global__"):
    """Activation sharding constraint — the one surface model code and
    step builders use instead of per-model ``with_sharding_constraint``
    hacks. ``spec`` entries are axis names / None / tuples (or a single
    PartitionSpec / spec tuple). Accepts a framework ``Tensor``
    (dispatched, so it records under tracing) or a raw array; degrades
    per mesh (absent axes -> replication; meshless -> identity)."""
    if mesh == "__global__":
        from .mesh_utils import get_global_mesh
        mesh = get_global_mesh()
    if mesh is None:
        return x
    if len(spec) == 1 and _is_spec_leaf(spec[0]):
        spec = tuple(spec[0])
    from .mesh_utils import with_constraint

    def fn(a):
        s = spec + (None,) * (getattr(a, "ndim", len(spec)) - len(spec))
        return with_constraint(a, *s)

    from ..core.tensor import Tensor
    if isinstance(x, Tensor):
        from ..core.dispatch import apply_op
        return apply_op("shard_constraint", fn, x)
    return fn(x)


def constrain_batch(x, mesh="__global__"):
    """Pin dim 0 to batch-axis sharding (dp + ZeRO 'sharding').
    Without this GSPMD can propagate a ZeRO parameter sharding into
    activations (full global batch replicated per chip with hidden-dim
    all-gathers — measured 2 GB/buffer on the ERNIE-10B v5e-64 plan).
    No-op without a mesh."""
    if mesh == "__global__":
        from .mesh_utils import get_global_mesh
        mesh = get_global_mesh()
    axes = batch_axes(mesh)
    if mesh is None or not axes:
        return x
    nshards = 1
    for a in axes:
        nshards *= mesh.shape[a]
    shape = tuple(getattr(x, "shape", ()))
    if not shape or shape[0] % nshards != 0:
        return x                       # ragged batch: leave placement free
    # one dim-0 entry sharded over BOTH axes (PartitionSpec tuple entry)
    return constrain(x, (("dp", "sharding"),), mesh=mesh)


def constrain_seq(x, mesh="__global__"):
    """Sequence-parallel constraint for [B, S, ...] activations: batch
    over dp, sequence over the 'sep' axis. No-op without a mesh or sep
    axis."""
    if mesh == "__global__":
        from .mesh_utils import get_global_mesh
        mesh = get_global_mesh()
    if mesh is None or "sep" not in mesh.axis_names \
            or mesh.shape["sep"] == 1:
        return x
    return constrain(x, "dp", "sep", mesh=mesh)


# ------------------------------------------------------- observability
def projected_bytes_per_chip(named_params, specs: Dict[str, Tuple],
                             mesh_axes: Dict[str, int],
                             opt_bytes_per_param: int = 0,
                             opt_specs: Optional[Dict[str, Tuple]] = None
                             ) -> Dict[str, int]:
    """Analytic per-chip model-state projection for a TARGET topology
    (a {axis: degree} dict — no devices needed): for each parameter,
    bytes divide by the number of shards its spec yields on that
    topology. ``opt_bytes_per_param`` adds optimizer-state bytes per
    element laid out by ``opt_specs`` (default: the param specs).
    Returns {"param_bytes", "opt_bytes", "total_bytes"} — the number
    shardcheck gates and the ``paddle_shard_projected_*`` gauges
    export."""
    import numpy as np
    param_b = 0
    opt_b = 0
    for name, p in dict(named_params).items():
        shape = tuple(p.shape)
        n_elem = int(np.prod(shape)) if shape else 1
        dt = getattr(getattr(p, "_data", None), "dtype", None) or \
            getattr(p, "dtype", "float32")
        itemsize = np.dtype(str(dt).replace("paddle.", "")).itemsize
        spec = specs.get(name, REPLICATED)
        param_b += (n_elem * itemsize) // max(_spec_shards(
            spec, mesh_axes), 1)
        if opt_bytes_per_param:
            ospec = (opt_specs or specs).get(name, spec)
            if not getattr(p, "stop_gradient", False):
                opt_b += (n_elem * opt_bytes_per_param) // max(
                    _spec_shards(ospec, mesh_axes), 1)
    return {"param_bytes": int(param_b), "opt_bytes": int(opt_b),
            "total_bytes": int(param_b + opt_b)}


def _get_metrics():
    """Lazily register the paddle_shard_* gauge families (once per
    process, like the serving/runtime metric modules)."""
    global _metrics
    with _lock:
        if _metrics is None:
            from ..observability.registry import default_registry
            reg = default_registry()
            _metrics = {
                "info": reg.gauge(
                    "paddle_shard_spec_tree_info",
                    "Spec-tree identity of the live process's sharding "
                    "(value 1; the hash label identifies the tree)",
                    labelnames=("hash",)),
                "sharded": reg.gauge(
                    "paddle_shard_spec_params_sharded",
                    "Parameters carrying a non-replicated spec"),
                "replicated": reg.gauge(
                    "paddle_shard_spec_params_replicated",
                    "Parameters whose spec is fully replicated"),
                "projected": reg.gauge(
                    "paddle_shard_projected_bytes_per_chip",
                    "Projected per-chip model-state bytes from the "
                    "spec tree on the current mesh",
                    labelnames=("component",)),
            }
        return _metrics


def publish_metrics(specs: Dict[str, Tuple], named_params,
                    mesh) -> None:
    """Export the spec tree to the metric registry: identity hash,
    sharded/replicated counts, per-chip projected bytes on ``mesh``
    (skipped without a mesh)."""
    m = _get_metrics()
    h = spec_tree_hash(specs)
    m["info"].clear()
    m["info"].labels(hash=h).set(1)
    sharded = sum(1 for s in specs.values()
                  if any(a is not None for a in s))
    m["sharded"].set(sharded)
    m["replicated"].set(len(specs) - sharded)
    if mesh is not None:
        axes = {str(k): int(v) for k, v in dict(mesh.shape).items()}
        proj = projected_bytes_per_chip(named_params, specs, axes)
        m["projected"].labels(component="params").set(
            proj["param_bytes"])
        m["projected"].labels(component="total").set(
            proj["total_bytes"])
