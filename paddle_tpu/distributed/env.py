"""Distributed environment & bootstrap.

Reference contract: ranks discover each other via env vars set by the
launcher (PaddleCloudRoleMaker,
/root/reference/python/paddle/distributed/fleet/base/role_maker.py:848-972 —
PADDLE_TRAINER_ENDPOINTS / PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ID). The
TPU-native bootstrap keeps those env names and maps them onto
jax.distributed.initialize (coordination service = the TCPStore analog).
"""
from __future__ import annotations

import os

import jax


_initialized = False
_jax_distributed = False
_store = None


def _env_int(name, default=0):
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def get_rank(group=None) -> int:
    if group is not None:
        return group.get_group_rank(global_rank())
    return global_rank()


def global_rank() -> int:
    if _jax_distributed:
        return jax.process_index()
    return _env_int("PADDLE_TRAINER_ID", 0)


def get_world_size(group=None) -> int:
    if group is not None:
        return group.nranks
    if _jax_distributed:
        return jax.process_count()
    return _env_int("PADDLE_TRAINERS_NUM", 1)


def jax_distributed_active() -> bool:
    """True when jax.distributed.initialize ran for this world — eager
    collectives can then execute as compiled XLA collectives over the
    global device set instead of host TCPStore exchanges."""
    return _jax_distributed


def get_store():
    """The rendezvous TCPStore (native C++ server on rank 0; see
    paddle_tpu/native/csrc/tcp_store.cc). None in single-process mode."""
    return _store


def init_parallel_env():
    """paddle.distributed.init_parallel_env
    (reference: python/paddle/distributed/parallel.py:921).

    Multi-process bootstrap: every rank rendezvouses through the native
    TCPStore hosted by rank 0 (the reference's ncclUniqueId-exchange store,
    phi/core/distributed/store/tcp_store.h). Eager `paddle.distributed.*`
    collectives then run over the store; optionally (PADDLE_JAX_DISTRIBUTED=1)
    jax.distributed.initialize is also called so compiled multi-host SPMD
    sees one global device set.
    """
    global _initialized, _jax_distributed, _store
    if _initialized:
        return ParallelEnv()
    n = _env_int("PADDLE_TRAINERS_NUM", 1)
    rank = _env_int("PADDLE_TRAINER_ID", 0)
    if n > 1:
        from ..native.tcp_store import TCPStore

        master = os.environ.get("PADDLE_MASTER", "")
        if not master:
            eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
            first = eps.split(",")[0] if eps else ""
            host, _, port = first.partition(":")
            if not port or not int(port):
                raise RuntimeError(
                    "multi-process bootstrap needs PADDLE_MASTER=host:port "
                    "(or PADDLE_TRAINER_ENDPOINTS with concrete ports) so "
                    "every rank can find the rank-0 TCPStore; use "
                    "`python -m paddle_tpu.distributed.launch`, which sets "
                    "both")
            # store lives one port above the first trainer endpoint
            master = f"{host}:{int(port) + 1}"
        host, _, port = master.partition(":")
        timeout = float(os.environ.get("PADDLE_STORE_TIMEOUT", "120"))
        _store = TCPStore(host=host or "127.0.0.1", port=int(port or 0),
                          is_master=(rank == 0), timeout=timeout,
                          world_size=n)
        _store.barrier("init_parallel_env", n, timeout)
        if os.environ.get("PADDLE_JAX_DISTRIBUTED") == "1":
            from jax._src import distributed as _jd
            if getattr(_jd.global_state, "client", None) is not None:
                # the launcher's --jax_distributed bootstrap initialized
                # the coordination service before any framework import
                # (mandatory: initialize() must precede backend use)
                _jax_distributed = True
            else:
                coordinator = os.environ.get(
                    "PADDLE_JAX_COORDINATOR",
                    f"{host or '127.0.0.1'}:{int(port or 0) + 1}")
                jax.distributed.initialize(coordinator_address=coordinator,
                                           num_processes=n,
                                           process_id=rank)
                _jax_distributed = True
            # AFTER distributed init (local_devices touches the backend):
            # fresh host tensors must land on a PROCESS-LOCAL device — the
            # global default (jax.devices()[0]) belongs to process 0, and
            # arrays created there from other ranks can't feed compiled
            # multi-host steps (cross-host reshard is unsupported)
            jax.config.update("jax_default_device", jax.local_devices()[0])
        if int(os.environ.get("PADDLE_ELASTIC_LEVEL", "0") or 0) > 0:
            _start_heartbeat(_store, rank,
                             rendezvous=(host or "127.0.0.1",
                                         int(port or 0)))
        _initialized = True
    return ParallelEnv()


def _start_heartbeat(store, rank, rendezvous=None):
    """Elastic fault DETECTION, worker half (reference: ElasticManager's
    etcd heartbeat, fleet/elastic/manager.py:126): a daemon thread bumps
    ``hb/<rank>`` every interval, preferably in the LAUNCHER-owned
    heartbeat store (PADDLE_ELASTIC_HB_ENDPOINT — independent of any
    worker's life), else the rank-0 rendezvous store. The launcher
    watches the keys and restarts the job when one goes silent — which
    catches hangs and SIGSTOP-style silent deaths that the exit-code
    monitor cannot see."""
    import threading
    import time as _time

    shared = store  # the main thread's client — true last resort only
    store = None
    hb_ep = os.environ.get("PADDLE_ELASTIC_HB_ENDPOINT")
    if hb_ep:
        try:
            from ..native.tcp_store import TCPStore
            host, _, port = hb_ep.partition(":")
            store = TCPStore(host=host or "127.0.0.1", port=int(port),
                             is_master=False, timeout=10.0)
        except Exception:
            store = None  # fall through to a dedicated rendezvous client
    if store is None and rendezvous is not None:
        # open a DEDICATED connection for the heartbeat thread: the main
        # thread's client has one unsynchronized socket, and interleaved
        # set()/wait() framing from two threads corrupts the protocol
        # (round-3 advisor finding)
        try:
            from ..native.tcp_store import TCPStore
            store = TCPStore(host=rendezvous[0], port=rendezvous[1],
                             is_master=False, timeout=10.0)
        except Exception:
            store = None
    if store is None:
        store = shared  # single-socket risk beats no heartbeat at all
    if store is None:
        return
    interval = float(os.environ.get(
        "PADDLE_ELASTIC_HEARTBEAT_INTERVAL", "2"))

    def beat():
        n = 0
        while True:
            try:
                store.set(f"hb/{rank}", str(n))
            except Exception:
                return  # store gone (teardown) — stop quietly
            n += 1
            _time.sleep(interval)

    t = threading.Thread(target=beat, daemon=True,
                         name=f"paddle-elastic-hb-{rank}")
    t.start()


class ParallelEnv:
    @property
    def rank(self):
        return global_rank()

    @property
    def local_rank(self):
        return _env_int("PADDLE_RANK_IN_NODE", global_rank())

    @property
    def world_size(self):
        return get_world_size()

    @property
    def nranks(self):
        return get_world_size()

    @property
    def dev_id(self):
        return _env_int("FLAGS_selected_tpus", 0)

    @property
    def current_endpoint(self):
        eps = self.trainer_endpoints
        r = self.rank
        return eps[r] if r < len(eps) else ""

    @property
    def trainer_endpoints(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return eps.split(",") if eps else []


def is_initialized() -> bool:
    return _initialized


def parallel_device_count() -> int:
    """Devices visible for sharding (real chips, or virtual CPU devices when
    XLA_FLAGS=--xla_force_host_platform_device_count is set for testing)."""
    return len(jax.devices())
