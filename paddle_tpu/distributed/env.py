"""Distributed environment & bootstrap.

Reference contract: ranks discover each other via env vars set by the
launcher (PaddleCloudRoleMaker,
/root/reference/python/paddle/distributed/fleet/base/role_maker.py:848-972 —
PADDLE_TRAINER_ENDPOINTS / PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ID). The
TPU-native bootstrap keeps those env names and maps them onto
jax.distributed.initialize (coordination service = the TCPStore analog).
"""
from __future__ import annotations

import os

import jax


_initialized = False


def _env_int(name, default=0):
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def get_rank(group=None) -> int:
    if group is not None:
        return group.get_group_rank(global_rank())
    return global_rank()


def global_rank() -> int:
    if _initialized:
        return jax.process_index()
    return _env_int("PADDLE_TRAINER_ID", 0)


def get_world_size(group=None) -> int:
    if group is not None:
        return group.nranks
    if _initialized:
        return jax.process_count()
    return _env_int("PADDLE_TRAINERS_NUM", 1)


def init_parallel_env():
    """paddle.distributed.init_parallel_env
    (reference: python/paddle/distributed/parallel.py:921)."""
    global _initialized
    if _initialized:
        return ParallelEnv()
    endpoints = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
    n = _env_int("PADDLE_TRAINERS_NUM", 1)
    rank = _env_int("PADDLE_TRAINER_ID", 0)
    if n > 1 and endpoints:
        coordinator = endpoints.split(",")[0]
        jax.distributed.initialize(
            coordinator_address=coordinator, num_processes=n, process_id=rank)
        _initialized = True
    return ParallelEnv()


class ParallelEnv:
    @property
    def rank(self):
        return global_rank()

    @property
    def local_rank(self):
        return _env_int("PADDLE_RANK_IN_NODE", global_rank())

    @property
    def world_size(self):
        return get_world_size()

    @property
    def nranks(self):
        return get_world_size()

    @property
    def dev_id(self):
        return _env_int("FLAGS_selected_tpus", 0)

    @property
    def current_endpoint(self):
        eps = self.trainer_endpoints
        r = self.rank
        return eps[r] if r < len(eps) else ""

    @property
    def trainer_endpoints(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return eps.split(",") if eps else []


def is_initialized() -> bool:
    return _initialized


def parallel_device_count() -> int:
    """Devices visible for sharding (real chips, or virtual CPU devices when
    XLA_FLAGS=--xla_force_host_platform_device_count is set for testing)."""
    return len(jax.devices())
