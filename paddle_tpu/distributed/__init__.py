"""paddle.distributed equivalent — mesh-first distributed layer."""
from . import fleet  # noqa: F401
from . import auto_parallel as auto  # noqa: F401
from .communication import *  # noqa: F401,F403
from .communication.collective import ReduceOp  # noqa: F401
from .env import (  # noqa: F401
    ParallelEnv, get_rank, get_world_size, init_parallel_env, is_initialized,
)
from .group import Group, destroy_process_group, get_group, new_group  # noqa: F401
from .mesh_utils import (  # noqa: F401
    build_mesh, get_global_mesh, set_global_mesh, shard_tensor_data,
    with_constraint,
)
from .parallel import DataParallel  # noqa: F401
from .auto_parallel.interface import ProcessMesh, shard_op, shard_tensor  # noqa: F401

import types as _types
from .fleet.meta_parallel.sharding import (  # noqa: F401
    group_sharded_parallel, save_group_sharded_model,
)

sharding = _types.SimpleNamespace(
    group_sharded_parallel=group_sharded_parallel,
    save_group_sharded_model=save_group_sharded_model,
)


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """paddle.distributed.spawn — multiprocess launch on one host."""
    import multiprocessing as mp
    import os
    n = nprocs if nprocs > 0 else 1
    ctx = mp.get_context("spawn")
    procs = []
    for rank in range(n):
        env_update = {
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(n),
        }

        def target(r=rank, upd=env_update):
            os.environ.update(upd)
            func(*args)
        p = ctx.Process(target=target, daemon=daemon)
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            p.join()
    return procs

from . import rpc  # noqa: F401
