"""paddle.distributed equivalent — mesh-first distributed layer."""
from . import fleet  # noqa: F401
from . import auto_parallel as auto  # noqa: F401
from .communication import *  # noqa: F401,F403
from .communication.collective import ReduceOp  # noqa: F401
from .env import (  # noqa: F401
    ParallelEnv, get_rank, get_world_size, init_parallel_env, is_initialized,
)
from .group import Group, destroy_process_group, get_group, new_group  # noqa: F401
from .mesh_utils import (  # noqa: F401
    build_mesh, get_global_mesh, set_global_mesh, shard_tensor_data,
    with_constraint,
)
from .parallel import DataParallel  # noqa: F401
from .auto_parallel.interface import ProcessMesh, shard_op, shard_tensor  # noqa: F401
from . import shard  # noqa: F401  (the unified sharding API)
from .shard import (  # noqa: F401
    apply_sharding, constrain, constrain_batch, constrain_seq,
    shard_params, spec_tree, spec_tree_hash,
)

import types as _types
from .fleet.meta_parallel.sharding import (  # noqa: F401
    group_sharded_parallel, save_group_sharded_model,
)

sharding = _types.SimpleNamespace(
    group_sharded_parallel=group_sharded_parallel,
    save_group_sharded_model=save_group_sharded_model,
)


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """paddle.distributed.spawn — multiprocess launch on one host."""
    import multiprocessing as mp
    import os
    n = nprocs if nprocs > 0 else 1
    ctx = mp.get_context("spawn")
    procs = []
    for rank in range(n):
        env_update = {
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(n),
        }

        def target(r=rank, upd=env_update):
            os.environ.update(upd)
            func(*args)
        p = ctx.Process(target=target, daemon=daemon)
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            p.join()
    return procs

from . import rpc  # noqa: F401

# ---- reference __all__ completions (python/paddle/distributed/__init__.py)
from .communication.collective import (  # noqa: F401,E402
    all_to_all as alltoall, all_to_all_single as alltoall_single,
)
from . import launch  # noqa: F401,E402  (the runnable launcher package)


class ParallelMode:
    """reference parallel.py ParallelMode constants."""
    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3


def is_available():
    """reference: whether the distributed package can be used. Always
    true here — single-process SPMD works without any env setup."""
    return True


def get_backend(group=None):
    """reference parallel.py get_backend: the communication backend
    name. XLA collectives ride ICI/DCN; the store-backed eager path is
    the gloo analog."""
    return "xla"


def gloo_init_parallel_env(rank_id, rank_num, server_endpoint):
    """reference: bootstrap the gloo CPU barrier backend. Subsumed by
    init_parallel_env's TCPStore rendezvous; provided for API parity."""
    import os
    # explicit arguments OVERRIDE the environment — a stale
    # PADDLE_TRAINER_ID from a prior launch must not win over the
    # caller's rank
    os.environ["PADDLE_TRAINER_ID"] = str(rank_id)
    os.environ["PADDLE_TRAINERS_NUM"] = str(rank_num)
    os.environ["PADDLE_MASTER"] = server_endpoint
    return init_parallel_env()


def gloo_barrier():
    from .communication.collective import barrier
    return barrier()


def gloo_release():
    """Tear down the rendezvous resources (reference gloo_release)."""
    return None


# PS / recsys dataset surface: out of core scope (SURVEY §2.3 excludes
# the parameter-server stack); names exist and fail loudly with the
# reason rather than silently missing.
def _ps_out_of_scope(name):
    class _PS:
        def __init__(self, *a, **k):
            raise NotImplementedError(
                f"{name} belongs to the parameter-server/recsys stack, "
                "which SURVEY §2.3 excludes from the TPU core scope; "
                "use paddle.io.Dataset/DataLoader for data feeding")
    _PS.__name__ = name
    return _PS


InMemoryDataset = _ps_out_of_scope("InMemoryDataset")
QueueDataset = _ps_out_of_scope("QueueDataset")
CountFilterEntry = _ps_out_of_scope("CountFilterEntry")
ProbabilityEntry = _ps_out_of_scope("ProbabilityEntry")
ShowClickEntry = _ps_out_of_scope("ShowClickEntry")

from . import io  # noqa: F401,E402


def wait(tensor, group=None, use_calc_stream=True):
    """reference communication wait: block until the tensor's pending
    collective lands. XLA orders by data dependence; a device sync is
    the strongest equivalent."""
    arr = tensor._data if hasattr(tensor, "_data") else tensor
    try:
        arr.block_until_ready()
    except AttributeError:
        pass
    return tensor


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """reference distributed.split (parallel layers helper): run a
    linear/embedding with its weight split over model-parallel ranks.
    GSPMD subsumes the manual partitioning — the fleet TP layers
    (Column/RowParallelLinear, VocabParallelEmbedding) are the
    TPU-native implementation; this wrapper instantiates the right one."""
    from .fleet.meta_parallel import (ColumnParallelLinear,
                                      RowParallelLinear,
                                      VocabParallelEmbedding)
    if operation == "embedding" and axis != 0:
        raise ValueError(
            "split(operation='embedding') only supports axis=0 "
            "(vocab-dimension partitioning), matching the reference")
    has_bias = bias_attr is not False
    if operation == "linear":
        if axis == 0:
            layer = RowParallelLinear(size[0], size[1],
                                      input_is_parallel=False,
                                      weight_attr=weight_attr,
                                      has_bias=has_bias)
        else:
            layer = ColumnParallelLinear(size[0], size[1],
                                         gather_output=gather_out,
                                         weight_attr=weight_attr,
                                         has_bias=has_bias)
        return layer(x)
    if operation == "embedding":
        layer = VocabParallelEmbedding(size[0], size[1],
                                       weight_attr=weight_attr)
        return layer(x)
    raise ValueError(f"unsupported split operation {operation!r}")
