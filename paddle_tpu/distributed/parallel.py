"""DataParallel wrapper
(reference: /root/reference/python/paddle/distributed/parallel.py:202 — wraps
model with the C++ EagerReducer for bucketed fused allreduce overlapped with
backward, reducer.cc).

TPU-native: DP gradient sync is a mesh reduction inside the compiled step —
there is no reducer protocol to run. This wrapper preserves the API and marks
the model for batch-axis sharding ("dp") so the TrainStep/pjit path shards
inputs and averages grads via psum automatically. Single-process eager
behavior is identical to bare model.
"""
from __future__ import annotations

from ..nn.layer.layers import Layer
from . import env


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self.add_sublayer("_layers", layers)
        self.find_unused_parameters = find_unused_parameters
        for p in layers.parameters():
            if not hasattr(p, "dist_spec"):
                p.dist_spec = None  # replicated params, dp-sharded batch

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        pass

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    @property
    def parameters_(self):
        return self._layers.parameters()


def get_rank(group=None):
    return env.get_rank(group)


def get_world_size(group=None):
    return env.get_world_size(group)


init_parallel_env = env.init_parallel_env
ParallelEnv = env.ParallelEnv
