from .collective import (  # noqa: F401
    ReduceOp, all_gather, all_gather_object, all_reduce, all_to_all,
    all_to_all_single, barrier, broadcast, broadcast_object_list, gather,
    irecv, isend, recv, reduce, reduce_scatter, scatter, scatter_object_list,
    send, stream,
)
