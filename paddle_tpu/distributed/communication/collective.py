"""Eager collective API (paddle.distributed.*).

Reference surface: /root/reference/python/paddle/distributed/communication/
(all_reduce.py:19 etc.), backed there by ProcessGroupNCCL. TPU-native
semantics, three regimes:

- traced (shard_map/pjit): jax.lax collectives over the group's mesh axis —
  compiled into the XLA program, riding ICI (the performance path).
- eager single-process: world_size==1 ≡ identity (reference behavior for a
  1-rank group).
- eager multi-process: host-side collectives over the native TCPStore
  rendezvous (paddle_tpu/native/csrc/tcp_store.cc) — every rank posts its
  numpy payload under a sequenced key and reads its peers'. Correct and
  portable (no device interconnect assumptions); traced collectives remain
  the way to make communication fast. Matches the reference's contract that
  `paddle.distributed.*` works in eager mode (process_group.h:53).
"""
from __future__ import annotations

import io
import itertools
import types
from collections import defaultdict

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import apply_op
from ...core.tensor import Tensor
from .. import env
from ..group import Group, Task, get_group

# ------------------------------------------------------------------
# store-backed eager transport

_coll_seq = defaultdict(itertools.count)  # group tag -> counter
_p2p_seq = defaultdict(itertools.count)   # (src, dst) -> counter
_TIMEOUT = 120.0


def _dumps(arr) -> bytes:
    buf = io.BytesIO()
    np.save(buf, np.asarray(arr), allow_pickle=False)
    return buf.getvalue()


def _loads(b: bytes) -> np.ndarray:
    return np.load(io.BytesIO(b), allow_pickle=False)


def _group_info(group):
    """(ranks list, my index, key tag) for a group or the world."""
    if group is not None and getattr(group, "ranks", None):
        ranks = list(group.ranks)
        tag = "g" + "_".join(map(str, ranks))
    else:
        ranks = list(range(env.get_world_size()))
        tag = "w"
    me = env.global_rank()
    if me not in ranks:
        raise RuntimeError(
            f"rank {me} called a collective on a group it is not a member "
            f"of (group ranks: {ranks})")
    return ranks, ranks.index(me), tag


def _require_store():
    store = env.get_store()
    if store is None:
        raise RuntimeError(
            "eager multi-rank collectives need paddle.distributed."
            "init_parallel_env() (TCPStore rendezvous) first")
    return store


def _ckey(tag, op):
    """Sequenced key. The counter is PER GROUP TAG so subgroup collectives
    don't desynchronize the world sequence (each group's members issue the
    same ordered stream of collectives — the standard contract)."""
    return f"c/{tag}/{op}/{next(_coll_seq[tag])}"


def _gc_keys(store, key, payload_keys, n_readers):
    """Refcounted cleanup: the last reader deletes the payload keys (the
    C++ store keeps every SET forever otherwise — unbounded rank-0 memory
    across a long eager loop)."""
    if store.add(f"{key}/ack", 1) == n_readers:
        for k in payload_keys:
            store.delete(k)
        store.delete(f"{key}/ack")


def _exchange(op, arr, group):
    """Post my payload, collect every group member's, in group-rank order.
    All ranks must issue collectives in the same order (the standard
    collective-call contract; the sequence number enforces pairing)."""
    store = _require_store()
    ranks, idx, tag = _group_info(group)
    key = _ckey(tag, op)
    store.set(f"{key}/{idx}", _dumps(arr))
    out = [_loads(store.wait(f"{key}/{i}", _TIMEOUT))
           for i in range(len(ranks))]
    _gc_keys(store, key, [f"{key}/{i}" for i in range(len(ranks))],
             len(ranks))
    return out


def _unwrap_np(tensor):
    a = tensor._data if isinstance(tensor, Tensor) else tensor
    return np.asarray(a)


def _check_consistent(op, vals, ranks):
    """Cross-rank dtype/shape agreement check at dispatch time — the
    reference's CommDynamicCheck (phi/core/distributed/check/
    nccl_dynamic_check.cc): a rank calling a collective with a mismatched
    tensor gets a clear diagnostic naming the offending ranks instead of
    a downstream np.stack/reshape error."""
    shapes = [getattr(v, "shape", None) for v in vals]
    dtypes = [getattr(v, "dtype", None) for v in vals]
    if len(set(shapes)) > 1 or len(set(map(str, dtypes))) > 1:
        detail = ", ".join(
            f"rank {r}: shape={s} dtype={d}"
            for r, s, d in zip(ranks, shapes, dtypes))
        raise RuntimeError(
            f"collective '{op}' called with mismatched tensors across "
            f"ranks ({detail}); every member of the group must pass the "
            f"same shape/dtype")


def _eager_multirank(group) -> bool:
    n = group.nranks if group else env.get_world_size()
    return n > 1


def _np_reduce(stacked, op):
    if op in (ReduceOp.SUM, "sum"):
        return stacked.sum(0)
    if op in (ReduceOp.MAX, "max"):
        return stacked.max(0)
    if op in (ReduceOp.MIN, "min"):
        return stacked.min(0)
    if op in (ReduceOp.AVG, "avg"):
        return stacked.mean(0)
    if op in (ReduceOp.PROD, "prod"):
        return stacked.prod(0)
    raise ValueError(f"unknown reduce op {op}")


def _root_index(group, root):
    """Group-local index of a root rank, validated (Group.get_group_rank
    returns -1 for non-members, which would otherwise hang every member
    in store.wait for the full timeout)."""
    idx = group.get_group_rank(root) if group else root
    n = group.nranks if group else env.get_world_size()
    if idx is None or idx < 0 or idx >= n:
        raise ValueError(
            f"root rank {root} is not a member of the group")
    return idx


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


def _is_traced(x) -> bool:
    arr = x._data if isinstance(x, Tensor) else x
    return isinstance(arr, jax.core.Tracer)


def _axis_or_none(group):
    if group is not None and group.mesh_axis:
        return group.mesh_axis
    return None


def _apply_reduce(arr, op, axis_name):
    if op in (ReduceOp.SUM, "sum"):
        return jax.lax.psum(arr, axis_name)
    if op in (ReduceOp.MAX, "max"):
        return jax.lax.pmax(arr, axis_name)
    if op in (ReduceOp.MIN, "min"):
        return jax.lax.pmin(arr, axis_name)
    if op in (ReduceOp.AVG, "avg"):
        return jax.lax.pmean(arr, axis_name)
    if op in (ReduceOp.PROD, "prod"):
        return jnp.exp(jax.lax.psum(jnp.log(arr), axis_name))
    raise ValueError(f"unknown reduce op {op}")


_device_ar_cache = {}  # (kind, ...) -> jitted collective


def _np_red_fn(op):
    return {ReduceOp.SUM: jnp.sum, "sum": jnp.sum,
            ReduceOp.MAX: jnp.max, "max": jnp.max,
            ReduceOp.MIN: jnp.min, "min": jnp.min,
            ReduceOp.AVG: jnp.mean, "avg": jnp.mean,
            ReduceOp.PROD: jnp.prod, "prod": jnp.prod}[op]


def _device_eligible(arr_np, group) -> bool:
    """Whether the eager XLA device path can carry this collective.
    Decided from WORLD-GLOBAL facts only (every member computes the same
    branch; a per-rank fallback would desync/deadlock): jax.distributed
    liveness, the one-device-per-process world shape, and the tensor's
    dtype/shape — which the collective contract requires to agree across
    ranks. float64 routes to the host exchange so it reduces in full
    precision (XLA:TPU has no f64; a silent downcast would give the same
    call different numerics depending on eligibility)."""
    return (env.jax_distributed_active()
            and len(jax.devices()) == env.get_world_size()
            and arr_np.dtype != np.float64)


def _device_collective(kind, arr, group, op=None, src_idx=None):
    """Eager collective as a compiled XLA operation over the GROUP's
    device subset (one device per process; the submesh is the group's
    global ranks) — data rides ICI/DCN, not the host TCPStore (which
    remains the control/bootstrap path). Every group member calls in
    lockstep, forming one group-global array with one shard per member:

      ar: reduce over the member axis, replicated out
      ag: identity, replicated out (each member reads all shards)
      bc: member src_idx's shard, replicated out
      rs: reduce over members then re-shard rows back to members
      a2a: transpose (member, piece) -> (piece, member), sharded out
    """
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    ranks, idx, tag = _group_info(group)
    n = len(ranks)
    devs = [jax.devices()[r] for r in ranks]
    local = arr if isinstance(arr, jax.Array) else jnp.asarray(arr)
    mesh = Mesh(np.array(devs), ("g",))
    gshape = (n,) + tuple(local.shape)
    sh = NamedSharding(mesh, PartitionSpec("g"))
    garr = jax.make_array_from_single_device_arrays(
        gshape, sh, [jax.device_put(local[None], jax.local_devices()[0])])
    key = (kind, gshape, str(local.dtype), str(op), tag, src_idx)
    fn = _device_ar_cache.get(key)
    if fn is None:
        rep = NamedSharding(mesh, PartitionSpec())
        if kind == "ar":
            red = _np_red_fn(op)
            fn = jax.jit(lambda x: red(x, axis=0), out_shardings=rep)
        elif kind == "ag":
            # identity; out_shardings=replicated is what inserts the
            # gather (x + 0 would promote bool to int32)
            fn = jax.jit(lambda x: x, out_shardings=rep)
        elif kind == "bc":
            fn = jax.jit(lambda x: x[src_idx], out_shardings=rep)
        elif kind == "rs":
            red = _np_red_fn(op)
            chunk = local.shape[0] // n

            def _rs(x):
                total = red(x, axis=0)
                return total.reshape((n, chunk) + total.shape[1:])
            fn = jax.jit(_rs, out_shardings=sh)
        elif kind == "a2a":
            fn = jax.jit(lambda x: jnp.swapaxes(x, 0, 1),
                         out_shardings=sh)
        else:
            raise ValueError(kind)
        _device_ar_cache[key] = fn
    out = fn(garr)
    shard = jnp.asarray(out.addressable_shards[0].data)
    if kind in ("ar", "bc"):
        return shard
    if kind == "ag":
        return shard  # replicated (n, ...) — full gather
    # rs / a2a: my row of the resharded output
    return shard[0]


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """In traced (shard_map) context: psum over the group's mesh axis.
    Eager multi-rank: XLA device collective when jax.distributed is live
    and the group is the world; TCPStore host exchange otherwise.
    Eager 1-rank: identity (matches reference for single-rank groups)."""
    axis = _axis_or_none(group)
    if _is_traced(tensor) and axis is not None:
        r = apply_op("all_reduce", lambda a: _apply_reduce(a, op, axis), tensor)
        tensor._data = r._data
        return Task(tensor._data)
    n = group.nranks if group else env.get_world_size()
    if n <= 1:
        return Task(tensor._data if isinstance(tensor, Tensor) else tensor)
    arr = _unwrap_np(tensor)
    if _device_eligible(arr, group):
        out = _device_collective("ar", arr, group, op=op)
        if isinstance(tensor, Tensor):
            tensor._data = out.astype(tensor._data.dtype)
            return Task(tensor._data)
        return Task(out)
    vals = _exchange("ar", arr, group)
    _check_consistent("ar", vals, _group_info(group)[0])
    out = _np_reduce(np.stack(vals), op)
    tensor._data = jnp.asarray(out.astype(arr.dtype))
    return Task(tensor._data)


def all_gather(tensor_list, tensor, group=None, sync_op=True):
    axis = _axis_or_none(group)
    if _is_traced(tensor) and axis is not None:
        gathered = apply_op(
            "all_gather",
            lambda a: jax.lax.all_gather(a, axis, axis=0, tiled=False), tensor)
        n = group.nranks
        for i in range(n):
            tensor_list.append(gathered[i])
        return Task()
    n = group.nranks if group else env.get_world_size()
    if n <= 1:
        tensor_list.append(tensor)
        return Task()
    arr = _unwrap_np(tensor)
    if _device_eligible(arr, group):
        full = _device_collective("ag", arr, group)
        tensor_list.extend(Tensor(full[i]) for i in range(n))
        return Task()
    vals = _exchange("ag", arr, group)
    _check_consistent("ag", vals, _group_info(group)[0])
    tensor_list.extend(Tensor(jnp.asarray(v)) for v in vals)
    return Task()


def all_gather_object(object_list, obj, group=None):
    import pickle
    n = group.nranks if group else env.get_world_size()
    if n <= 1:
        object_list.append(obj)
        return Task()
    store = _require_store()
    ranks, idx, tag = _group_info(group)
    key = _ckey(tag, "ago")
    store.set(f"{key}/{idx}", pickle.dumps(obj))
    object_list.extend(pickle.loads(store.wait(f"{key}/{i}", _TIMEOUT))
                       for i in range(len(ranks)))
    _gc_keys(store, key, [f"{key}/{i}" for i in range(len(ranks))],
             len(ranks))
    return Task()


def broadcast(tensor, src, group=None, sync_op=True):
    axis = _axis_or_none(group)
    if _is_traced(tensor) and axis is not None:
        src_local = group.get_group_rank(src) if group else src

        def _bcast(a):
            # select src's shard on the axis for everyone
            full = jax.lax.all_gather(a, axis, axis=0)
            return full[src_local]
        r = apply_op("broadcast", _bcast, tensor)
        tensor._data = r._data
        return Task(tensor._data)
    n = group.nranks if group else env.get_world_size()
    if n <= 1:
        return Task()
    arr = _unwrap_np(tensor)
    src_idx = _root_index(group, src)
    if _device_eligible(arr, group):
        out = _device_collective("bc", arr, group, src_idx=src_idx)
        tensor._data = out.astype(tensor._data.dtype) \
            if isinstance(tensor, Tensor) else out
        return Task(tensor._data)
    store = _require_store()
    ranks, idx, tag = _group_info(group)
    key = _ckey(tag, "bc")
    if idx == src_idx:
        store.set(key, _dumps(arr))
    tensor._data = jnp.asarray(_loads(store.wait(key, _TIMEOUT)))
    _gc_keys(store, key, [key], len(ranks))
    return Task(tensor._data)


def broadcast_object_list(object_list, src=0, group=None):
    import pickle
    n = group.nranks if group else env.get_world_size()
    if n <= 1:
        return Task()
    store = _require_store()
    ranks, idx, tag = _group_info(group)
    src_idx = _root_index(group, src)
    key = _ckey(tag, "bco")
    if idx == src_idx:
        store.set(key, pickle.dumps(list(object_list)))
    got = pickle.loads(store.wait(key, _TIMEOUT))
    object_list[:] = got
    _gc_keys(store, key, [key], len(ranks))
    return Task()


def reduce(tensor, dst, op=ReduceOp.SUM, group=None, sync_op=True):
    # psum everywhere ≡ reduce + broadcast; dst semantics preserved logically
    return all_reduce(tensor, op, group, sync_op)


def reduce_scatter(tensor, tensor_list_or_input, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    axis = _axis_or_none(group)
    inp = tensor_list_or_input
    if isinstance(inp, (list, tuple)):
        from ...tensor.manipulation import concat
        inp = concat(list(inp), axis=0)
    if _is_traced(inp) and axis is not None:
        r = apply_op(
            "reduce_scatter",
            lambda a: jax.lax.psum_scatter(a, axis, scatter_dimension=0,
                                           tiled=True), inp)
        tensor._data = r._data
        return Task(tensor._data)
    n = group.nranks if group else env.get_world_size()
    if n <= 1:
        tensor._data = inp._data if isinstance(inp, Tensor) else inp
        return Task()
    arr = _unwrap_np(inp)
    if arr.shape[0] % n == 0 and _device_eligible(arr, group):
        tensor._data = _device_collective("rs", arr, group, op=op)
        return Task(tensor._data)
    ranks, idx, _ = _group_info(group)
    vals = _exchange("rs", arr, group)
    _check_consistent("rs", vals, ranks)
    total = _np_reduce(np.stack(vals), op)
    chunk = total.shape[0] // len(ranks)
    tensor._data = jnp.asarray(total[idx * chunk:(idx + 1) * chunk])
    return Task(tensor._data)


def all_to_all(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    axis = _axis_or_none(group)
    n = group.nranks if group else env.get_world_size()
    if in_tensor_list and _is_traced(in_tensor_list[0]) and axis is not None:
        from ...tensor.manipulation import stack, unbind
        stacked = stack(list(in_tensor_list), axis=0)
        r = apply_op(
            "all_to_all",
            lambda a: jax.lax.all_to_all(a, axis, split_axis=0, concat_axis=0,
                                         tiled=False), stacked)
        out_tensor_list.extend(unbind(r, axis=0))
        return Task()
    if n <= 1:
        out_tensor_list.extend(in_tensor_list)
        return Task()
    stacked = np.stack([_unwrap_np(t) for t in in_tensor_list])
    if _device_eligible(stacked, group):
        mine = _device_collective("a2a", stacked, group)
        out_tensor_list.extend(Tensor(mine[i]) for i in range(n))
        return Task()
    ranks, idx, _ = _group_info(group)
    vals = _exchange("a2a", stacked, group)
    _check_consistent("a2a", vals, ranks)
    out_tensor_list.extend(Tensor(jnp.asarray(vals[i][idx]))
                           for i in range(len(ranks)))
    return Task()


def all_to_all_single(out_tensor, in_tensor, out_split_sizes=None,
                      in_split_sizes=None, group=None, sync_op=True):
    axis = _axis_or_none(group)
    if _is_traced(in_tensor) and axis is not None:
        r = apply_op(
            "all_to_all_single",
            lambda a: jax.lax.all_to_all(a, axis, split_axis=0, concat_axis=0,
                                         tiled=True), in_tensor)
        out_tensor._data = r._data
        return Task(out_tensor._data)
    n = group.nranks if group else env.get_world_size()
    if n <= 1:
        out_tensor._data = in_tensor._data
        return Task()
    if out_split_sizes or in_split_sizes:
        raise NotImplementedError(
            "eager all_to_all_single with explicit split sizes is not "
            "supported; equal splits only")
    arr = _unwrap_np(in_tensor)
    ranks, idx, _ = _group_info(group)
    if arr.shape[0] % len(ranks) != 0:
        raise ValueError(
            f"all_to_all_single dim 0 ({arr.shape[0]}) must divide the "
            f"group size ({len(ranks)})")
    if _device_eligible(arr, group):
        chunk = arr.shape[0] // len(ranks)
        stacked = arr.reshape((len(ranks), chunk) + arr.shape[1:])
        mine = _device_collective("a2a", stacked, group)
        out_tensor._data = mine.reshape((-1,) + tuple(arr.shape[1:]))
        return Task(out_tensor._data)
    vals = _exchange("a2as", arr, group)
    chunk = vals[0].shape[0] // len(ranks)
    out_tensor._data = jnp.asarray(np.concatenate(
        [v[idx * chunk:(idx + 1) * chunk] for v in vals]))
    return Task(out_tensor._data)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    n = group.nranks if group else env.get_world_size()
    if n <= 1:
        if tensor_list:
            tensor._data = tensor_list[0]._data
        return Task()
    store = _require_store()
    ranks, idx, tag = _group_info(group)
    src_idx = _root_index(group, src)
    key = _ckey(tag, "sc")
    if idx == src_idx:
        for i in range(len(ranks)):
            store.set(f"{key}/{i}", _dumps(_unwrap_np(tensor_list[i])))
    tensor._data = jnp.asarray(_loads(store.wait(f"{key}/{idx}", _TIMEOUT)))
    store.delete(f"{key}/{idx}")  # sole consumer of this slot
    return Task(tensor._data)


def scatter_object_list(out_object_list, in_object_list=None, src=0,
                        group=None):
    import pickle
    n = group.nranks if group else env.get_world_size()
    if n <= 1:
        out_object_list.extend(in_object_list or [])
        return Task()
    store = _require_store()
    ranks, idx, tag = _group_info(group)
    src_idx = _root_index(group, src)
    key = _ckey(tag, "sco")
    if idx == src_idx:
        for i in range(len(ranks)):
            store.set(f"{key}/{i}", pickle.dumps(in_object_list[i]))
    out_object_list.append(pickle.loads(store.wait(f"{key}/{idx}",
                                                   _TIMEOUT)))
    store.delete(f"{key}/{idx}")
    return Task()


def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True):
    n = group.nranks if group else env.get_world_size()
    if n <= 1:
        if gather_list is not None:
            gather_list.append(tensor)
        return Task()
    store = _require_store()
    ranks, idx, tag = _group_info(group)
    dst_idx = _root_index(group, dst)
    key = _ckey(tag, "ga")
    store.set(f"{key}/{idx}", _dumps(_unwrap_np(tensor)))
    if idx == dst_idx:
        for i in range(len(ranks)):
            v = _loads(store.wait(f"{key}/{i}", _TIMEOUT))
            if gather_list is not None:
                gather_list.append(Tensor(jnp.asarray(v)))
            store.delete(f"{key}/{i}")
    return Task()


def send(tensor, dst=0, group=None, sync_op=True):
    """P2P send. Inside shard_map this is a ppermute; eager multi-process
    routes through the store under a per-(src,dst) sequence so repeated
    sends pair with recvs in order."""
    if env.get_world_size() <= 1 and not _is_traced(tensor):
        return Task()
    store = env.get_store()
    if store is None:
        raise RuntimeError("eager p2p send needs init_parallel_env()")
    me = env.global_rank()
    k = next(_p2p_seq[(me, dst)])
    store.set(f"p2p/{me}to{dst}/{k}", _dumps(_unwrap_np(tensor)))
    return Task()


def recv(tensor, src=0, group=None, sync_op=True):
    if env.get_world_size() <= 1 and not _is_traced(tensor):
        return Task()
    store = env.get_store()
    if store is None:
        raise RuntimeError("eager p2p recv needs init_parallel_env()")
    me = env.global_rank()
    k = next(_p2p_seq[(src, me)])
    tensor._data = jnp.asarray(_loads(
        store.wait(f"p2p/{src}to{me}/{k}", _TIMEOUT)))
    store.delete(f"p2p/{src}to{me}/{k}")
    return Task(tensor._data)


def isend(tensor, dst=0, group=None):
    return send(tensor, dst, group, sync_op=False)


def irecv(tensor, src=0, group=None):
    return recv(tensor, src, group, sync_op=False)


def barrier(group=None):
    store = env.get_store()
    if store is not None and _eager_multirank(group):
        ranks, _, tag = _group_info(group)
        s = next(_coll_seq[tag])
        name = f"__barrier/{tag}/{s}"
        world = len(ranks)
        if store.add(name, 1) == world:
            store.set(f"{name}/done", b"1")
        store.wait(f"{name}/done", _TIMEOUT)
        # the last rank to pass the barrier garbage-collects its keys
        if store.add(f"{name}/ack", 1) == world:
            for k in (name, f"{name}/done", f"{name}/ack"):
                store.delete(k)
        return Task()
    import jax as _jax
    (_jax.device_put(0.0) + 0).block_until_ready()
    return Task()


class P2POp:
    def __init__(self, op, tensor, peer, group=None):
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group


def batch_isend_irecv(p2p_op_list):
    return [Task() for _ in p2p_op_list]


# stream.* variants (reference python/paddle/distributed/communication/stream/)
def _stream_variant(fn):
    def wrapper(*args, **kwargs):
        kwargs.pop("use_calc_stream", None)
        return fn(*args, **kwargs)
    return wrapper


stream = types.SimpleNamespace(
    all_reduce=_stream_variant(all_reduce),
    all_gather=_stream_variant(all_gather),
    all_to_all=_stream_variant(all_to_all),
    all_to_all_single=_stream_variant(all_to_all_single),
    broadcast=_stream_variant(broadcast),
    reduce=_stream_variant(reduce),
    reduce_scatter=_stream_variant(reduce_scatter),
    scatter=_stream_variant(scatter),
    send=_stream_variant(send),
    recv=_stream_variant(recv),
)
