"""Eager collective API (paddle.distributed.*).

Reference surface: /root/reference/python/paddle/distributed/communication/
(all_reduce.py:19 etc.), backed there by ProcessGroupNCCL. TPU-native
semantics: inside traced code (shard_map/pjit) use the `inside_shard_map`
forms (jax.lax collectives over mesh axes); in eager single-process mode the
collectives operate on the local tensor (world_size==1 ≡ identity, which is
exactly the reference behavior for a 1-rank group). Multi-host eager
collectives go through jax.experimental.multihost_utils when initialized.
"""
from __future__ import annotations

import types

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import apply_op
from ...core.tensor import Tensor
from .. import env
from ..group import Group, Task, get_group


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


def _is_traced(x) -> bool:
    arr = x._data if isinstance(x, Tensor) else x
    return isinstance(arr, jax.core.Tracer)


def _axis_or_none(group):
    if group is not None and group.mesh_axis:
        return group.mesh_axis
    return None


def _apply_reduce(arr, op, axis_name):
    if op in (ReduceOp.SUM, "sum"):
        return jax.lax.psum(arr, axis_name)
    if op in (ReduceOp.MAX, "max"):
        return jax.lax.pmax(arr, axis_name)
    if op in (ReduceOp.MIN, "min"):
        return jax.lax.pmin(arr, axis_name)
    if op in (ReduceOp.AVG, "avg"):
        return jax.lax.pmean(arr, axis_name)
    if op in (ReduceOp.PROD, "prod"):
        return jnp.exp(jax.lax.psum(jnp.log(arr), axis_name))
    raise ValueError(f"unknown reduce op {op}")


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """In traced (shard_map) context: psum over the group's mesh axis.
    Eager 1-rank: identity (matches reference for single-rank groups)."""
    axis = _axis_or_none(group)
    if _is_traced(tensor) and axis is not None:
        r = apply_op("all_reduce", lambda a: _apply_reduce(a, op, axis), tensor)
        tensor._data = r._data
        return Task(tensor._data)
    n = group.nranks if group else env.get_world_size()
    if n <= 1:
        return Task(tensor._data if isinstance(tensor, Tensor) else tensor)
    raise NotImplementedError(
        "eager multi-rank all_reduce outside traced code requires "
        "jax.distributed multi-host mode; wrap the step in shard_map/pjit "
        "(fleet.distributed_model does this) or use world_size==1")


def all_gather(tensor_list, tensor, group=None, sync_op=True):
    axis = _axis_or_none(group)
    if _is_traced(tensor) and axis is not None:
        gathered = apply_op(
            "all_gather",
            lambda a: jax.lax.all_gather(a, axis, axis=0, tiled=False), tensor)
        n = group.nranks
        for i in range(n):
            tensor_list.append(gathered[i])
        return Task()
    n = group.nranks if group else env.get_world_size()
    if n <= 1:
        tensor_list.append(tensor)
        return Task()
    raise NotImplementedError("eager multi-rank all_gather: use traced path")


def all_gather_object(object_list, obj, group=None):
    n = group.nranks if group else env.get_world_size()
    if n <= 1:
        object_list.append(obj)
        return Task()
    raise NotImplementedError


def broadcast(tensor, src, group=None, sync_op=True):
    axis = _axis_or_none(group)
    if _is_traced(tensor) and axis is not None:
        src_local = group.get_group_rank(src) if group else src

        def _bcast(a):
            # select src's shard on the axis for everyone
            full = jax.lax.all_gather(a, axis, axis=0)
            return full[src_local]
        r = apply_op("broadcast", _bcast, tensor)
        tensor._data = r._data
        return Task(tensor._data)
    n = group.nranks if group else env.get_world_size()
    if n <= 1:
        return Task()
    raise NotImplementedError("eager multi-rank broadcast: use traced path")


def broadcast_object_list(object_list, src=0, group=None):
    n = group.nranks if group else env.get_world_size()
    if n <= 1:
        return Task()
    raise NotImplementedError


def reduce(tensor, dst, op=ReduceOp.SUM, group=None, sync_op=True):
    # psum everywhere ≡ reduce + broadcast; dst semantics preserved logically
    return all_reduce(tensor, op, group, sync_op)


def reduce_scatter(tensor, tensor_list_or_input, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    axis = _axis_or_none(group)
    inp = tensor_list_or_input
    if isinstance(inp, (list, tuple)):
        from ...tensor.manipulation import concat
        inp = concat(list(inp), axis=0)
    if _is_traced(inp) and axis is not None:
        r = apply_op(
            "reduce_scatter",
            lambda a: jax.lax.psum_scatter(a, axis, scatter_dimension=0,
                                           tiled=True), inp)
        tensor._data = r._data
        return Task(tensor._data)
    n = group.nranks if group else env.get_world_size()
    if n <= 1:
        tensor._data = inp._data if isinstance(inp, Tensor) else inp
        return Task()
    raise NotImplementedError("eager multi-rank reduce_scatter: use traced path")


def all_to_all(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    axis = _axis_or_none(group)
    n = group.nranks if group else env.get_world_size()
    if in_tensor_list and _is_traced(in_tensor_list[0]) and axis is not None:
        from ...tensor.manipulation import stack, unbind
        stacked = stack(list(in_tensor_list), axis=0)
        r = apply_op(
            "all_to_all",
            lambda a: jax.lax.all_to_all(a, axis, split_axis=0, concat_axis=0,
                                         tiled=False), stacked)
        out_tensor_list.extend(unbind(r, axis=0))
        return Task()
    if n <= 1:
        out_tensor_list.extend(in_tensor_list)
        return Task()
    raise NotImplementedError("eager multi-rank all_to_all: use traced path")


def all_to_all_single(out_tensor, in_tensor, out_split_sizes=None,
                      in_split_sizes=None, group=None, sync_op=True):
    axis = _axis_or_none(group)
    if _is_traced(in_tensor) and axis is not None:
        r = apply_op(
            "all_to_all_single",
            lambda a: jax.lax.all_to_all(a, axis, split_axis=0, concat_axis=0,
                                         tiled=True), in_tensor)
        out_tensor._data = r._data
        return Task(out_tensor._data)
    n = group.nranks if group else env.get_world_size()
    if n <= 1:
        out_tensor._data = in_tensor._data
        return Task()
    raise NotImplementedError


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    n = group.nranks if group else env.get_world_size()
    if n <= 1:
        if tensor_list:
            tensor._data = tensor_list[0]._data
        return Task()
    raise NotImplementedError("eager multi-rank scatter: use traced path")


def scatter_object_list(out_object_list, in_object_list=None, src=0,
                        group=None):
    n = group.nranks if group else env.get_world_size()
    if n <= 1:
        out_object_list.extend(in_object_list or [])
        return Task()
    raise NotImplementedError


def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True):
    n = group.nranks if group else env.get_world_size()
    if n <= 1:
        if gather_list is not None:
            gather_list.append(tensor)
        return Task()
    raise NotImplementedError


def send(tensor, dst=0, group=None, sync_op=True):
    """P2P send — inside shard_map this is a ppermute; eager 1-rank no-op."""
    if env.get_world_size() <= 1 and not _is_traced(tensor):
        return Task()
    raise NotImplementedError(
        "eager p2p send: use the pipeline-parallel traced path "
        "(fleet.meta_parallel.PipelineParallel)")


def recv(tensor, src=0, group=None, sync_op=True):
    if env.get_world_size() <= 1 and not _is_traced(tensor):
        return Task()
    raise NotImplementedError(
        "eager p2p recv: use the pipeline-parallel traced path")


def isend(tensor, dst=0, group=None):
    return send(tensor, dst, group, sync_op=False)


def irecv(tensor, src=0, group=None):
    return recv(tensor, src, group, sync_op=False)


def barrier(group=None):
    import jax as _jax
    (_jax.device_put(0.0) + 0).block_until_ready()
    return Task()


class P2POp:
    def __init__(self, op, tensor, peer, group=None):
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group


def batch_isend_irecv(p2p_op_list):
    return [Task() for _ in p2p_op_list]


# stream.* variants (reference python/paddle/distributed/communication/stream/)
def _stream_variant(fn):
    def wrapper(*args, **kwargs):
        kwargs.pop("use_calc_stream", None)
        return fn(*args, **kwargs)
    return wrapper


stream = types.SimpleNamespace(
    all_reduce=_stream_variant(all_reduce),
    all_gather=_stream_variant(all_gather),
    all_to_all=_stream_variant(all_to_all),
    all_to_all_single=_stream_variant(all_to_all_single),
    broadcast=_stream_variant(broadcast),
    reduce=_stream_variant(reduce),
    reduce_scatter=_stream_variant(reduce_scatter),
    scatter=_stream_variant(scatter),
    send=_stream_variant(send),
    recv=_stream_variant(recv),
)
