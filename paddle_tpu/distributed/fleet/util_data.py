"""fleet UtilBase + MultiSlot data generators + Role.

Reference: python/paddle/distributed/fleet/utils/fleet_util.py
(UtilBase), fleet/data_generator/data_generator.py, base/role_maker.py
(Role). The data generators are PS-feed TEXT formatters — standalone
logic with no server dependency, so they are implemented faithfully
(slot lines readable by MultiSlotDataFeed); UtilBase's collective
helpers ride this framework's collective layer.
"""
from __future__ import annotations

import os
import sys


class Role:
    """(role_maker.py:31)."""

    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4
    COORDINATOR = 5


class UtilBase:
    """(fleet_util.py UtilBase): small cross-worker utilities."""

    def all_reduce(self, input, mode="sum", comm_world="worker"):  # noqa: A002
        import numpy as np

        from .. import all_reduce as _ar
        from ..communication.collective import ReduceOp
        from ... import to_tensor

        op = {"sum": ReduceOp.SUM, "max": ReduceOp.MAX,
              "min": ReduceOp.MIN}.get(mode)
        if op is None:
            raise ValueError(f"all_reduce mode {mode!r} (sum|max|min)")
        t = to_tensor(np.asarray(input))
        _ar(t, op=op)
        return t.numpy()

    def barrier(self, comm_world="worker"):
        from ..communication.collective import barrier as _barrier

        _barrier()

    def all_gather(self, input, comm_world="worker"):  # noqa: A002
        import numpy as np

        from .. import all_gather as _ag
        from ... import to_tensor

        out = []
        _ag(out, to_tensor(np.asarray(input)))
        return [o.numpy() for o in out]

    def get_file_shard(self, files):
        """Split ``files`` contiguously over workers, earlier workers
        taking the remainder (fleet_util.py get_file_shard)."""
        if not isinstance(files, list):
            raise TypeError("files should be a list of file paths")
        from .. import env

        trainer_id = env.global_rank()
        trainers = env.get_world_size()
        remainder = len(files) % trainers
        blocksize = len(files) // trainers
        begin = trainer_id * blocksize + min(trainer_id, remainder)
        end = begin + blocksize + (1 if trainer_id < remainder else 0)
        return files[begin:end]

    def print_on_rank(self, message, rank_id):
        from .. import env

        if env.global_rank() == rank_id:
            print(message)


class DataGenerator:
    """(data_generator.py DataGenerator): user overrides generate();
    run_from_stdin/run_from_memory stream formatted slot lines."""

    def __init__(self):
        self.batch_size_ = 1
        self._proto_info = None

    def set_batch(self, batch_size):
        self.batch_size_ = batch_size

    def generate_sample(self, line):
        raise NotImplementedError(
            "Please rewrite this function to return a list or tuple: "
            "[(name, [feasign, ...]), ...]")

    generate = generate_sample

    def generate_batch(self, samples):
        def local_iter():
            for s in samples:
                yield s
        return local_iter

    def run_from_stdin(self):
        for line in sys.stdin:
            line_iter = self.generate_sample(line)
            for user_parsed_line in line_iter():
                if user_parsed_line is None:
                    continue
                sys.stdout.write(self._gen_str(user_parsed_line))

    def run_from_memory(self):
        batch_samples = []
        line_iter = self.generate_sample(None)
        for user_parsed_line in line_iter():
            if user_parsed_line is None:
                continue
            batch_samples.append(user_parsed_line)
            if len(batch_samples) == self.batch_size_:
                batch_iter = self.generate_batch(batch_samples)
                for sample in batch_iter():
                    sys.stdout.write(self._gen_str(sample))
                batch_samples = []
        if batch_samples:
            batch_iter = self.generate_batch(batch_samples)
            for sample in batch_iter():
                sys.stdout.write(self._gen_str(sample))

    def _gen_str(self, line):
        raise NotImplementedError


def _validate_slots(line):
    if isinstance(line, zip):
        line = list(line)
    if not isinstance(line, (list, tuple)):
        raise ValueError(
            "the output of process() must be in list or tuple type, "
            "Example: [('words', [1926, 8, 17]), ('label', [1])]")
    return line


class MultiSlotDataGenerator(DataGenerator):
    """Formats [(name, [feasign...]), ...] into the MultiSlotDataFeed
    line ``<n> id1 .. idn <m> id1 .. idm`` (data_generator.py:285)."""

    def _gen_str(self, line):
        line = _validate_slots(line)
        out = []
        if self._proto_info is None:
            self._proto_info = []
            first = True
        else:
            first = False
            if len(line) != len(self._proto_info):
                raise ValueError(
                    f"the complete field set of two given line are "
                    f"inconsistent: {len(line)} vs "
                    f"{len(self._proto_info)}")
        for i, (name, elements) in enumerate(line):
            if not isinstance(name, str):
                raise ValueError(f"name {type(name)} must be in str type")
            if not isinstance(elements, list):
                raise ValueError(
                    f"elements {type(elements)} must be in list type")
            if not elements:
                raise ValueError(
                    "the elements of each field can not be empty; pad "
                    "it in process()")
            dtype = "uint64"
            for e in elements:
                if isinstance(e, float):
                    dtype = "float"
                elif not isinstance(e, int):
                    raise ValueError(
                        "the type of element must be int or float")
            if first:
                self._proto_info.append((name, dtype))
            else:
                if self._proto_info[i][0] != name:
                    raise ValueError(
                        f"the field name of two given line are not "
                        f"matched: {name} vs {self._proto_info[i][0]}")
                if dtype == "float" and self._proto_info[i][1] == "uint64":
                    self._proto_info[i] = (name, "float")
            out.append(str(len(elements)))
            out.extend(str(e) for e in elements)
        return " ".join(out) + "\n"


class MultiSlotStringDataGenerator(DataGenerator):
    """String-feasign variant (data_generator.py
    MultiSlotStringDataGenerator): no proto typing, plain join."""

    def _gen_str(self, line):
        line = _validate_slots(line)
        out = []
        for name, elements in line:
            if not isinstance(name, str):
                raise ValueError(f"name {type(name)} must be in str type")
            if not isinstance(elements, (list, tuple)):
                raise ValueError(
                    f"elements {type(elements)} must be list/tuple")
            out.append(str(len(elements)))
            out.extend(str(e) for e in elements)
        return " ".join(out) + "\n"
