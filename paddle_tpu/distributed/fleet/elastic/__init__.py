"""fleet.elastic (reference: python/paddle/distributed/fleet/elastic/
manager.py:126 — etcd-watched membership, scale in/out, restart).

TPU-native stance (SURVEY §5.3): mid-program ICI failures are not
survivable, so elasticity = job-level restart + checkpoint resume,
with FAULT DETECTION split across:

- the launcher's restart loop (`--elastic_level`/`--max_restarts`,
  distributed/launch/main.py) catching non-zero exits;
- the heartbeat watchdog (the reference's etcd heartbeat analog):
  workers bump ``hb/<rank>`` in a LAUNCHER-owned TCPStore
  (distributed/env.py ``_start_heartbeat``) and the launcher's
  ``_HeartbeatWatcher`` SIGKILLs + relaunches when a rank goes silent
  (catches hangs/SIGSTOP that never exit; e2e:
  tests/test_launch.py::test_elastic_heartbeat_detects_silent_hang).

ElasticManager is the thin status surface workers read (attempt count →
checkpoint-resume decision).
"""
from __future__ import annotations

import os


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class ElasticManager:
    def __init__(self, args=None, etcd_client=None):
        self.args = args
        self.restarts = int(os.environ.get("PADDLE_ELASTIC_RESTARTS", 0))

    def enabled(self) -> bool:
        return int(os.environ.get("PADDLE_ELASTIC_LEVEL", 0)) > 0

    def exit(self, completed=True):
        return ElasticStatus.COMPLETED if completed else ElasticStatus.ERROR


def launch_elastic(args=None, distribute_mode=None):
    """reference elastic/__init__.py:49 — delegate to the launcher's
    restart loop."""
    from ..launch.main import launch
    argv = ["--elastic_level", "1"] + (args or [])
    return launch(argv)
