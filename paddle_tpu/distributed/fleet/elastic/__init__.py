"""fleet.elastic (reference: python/paddle/distributed/fleet/elastic/
manager.py:126 — etcd-watched membership, scale in/out, restart).

TPU-native stance (SURVEY §5.3): mid-program ICI failures are not
survivable, so elasticity = job-level restart + checkpoint resume. The
launcher implements the restart loop (`--elastic_level`/`--max_restarts`,
paddle_tpu.distributed.launch); ElasticManager is the thin status surface
over it.
"""
from __future__ import annotations

import os


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class ElasticManager:
    def __init__(self, args=None, etcd_client=None):
        self.args = args
        self.restarts = int(os.environ.get("PADDLE_ELASTIC_RESTARTS", 0))

    def enabled(self) -> bool:
        return int(os.environ.get("PADDLE_ELASTIC_LEVEL", 0)) > 0

    def exit(self, completed=True):
        return ElasticStatus.COMPLETED if completed else ElasticStatus.ERROR


def launch_elastic(args=None, distribute_mode=None):
    """reference elastic/__init__.py:49 — delegate to the launcher's
    restart loop."""
    from ..launch.main import launch
    argv = ["--elastic_level", "1"] + (args or [])
    return launch(argv)
