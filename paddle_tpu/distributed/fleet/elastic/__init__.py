"""fleet.elastic (reference: python/paddle/distributed/fleet/elastic/
manager.py:126 — etcd-watched membership, scale in/out, restart).

TPU-native stance (SURVEY §5.3): mid-program ICI failures are not
survivable, so elasticity = job-level restart + checkpoint resume,
with FAULT DETECTION split across:

- the launcher's restart loop (`--elastic_level`/`--max_restarts`,
  distributed/launch/main.py) catching non-zero exits;
- the heartbeat watchdog (the reference's etcd heartbeat analog):
  workers bump ``hb/<rank>`` in a LAUNCHER-owned TCPStore
  (distributed/env.py ``_start_heartbeat``) and the launcher's
  ``_HeartbeatWatcher`` SIGKILLs + relaunches when a rank goes silent
  (catches hangs/SIGSTOP that never exit; e2e:
  tests/test_launch.py::test_elastic_heartbeat_detects_silent_hang).

ElasticManager is the thin status surface workers read (attempt count →
checkpoint-resume decision).
"""
from __future__ import annotations

import os


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class ElasticManager:
    """Worker-side elastic surface: attempt count (checkpoint-resume
    decision), the current membership view, and scale requests. The
    launcher-owned heartbeat TCPStore plays the reference's etcd:
    workers register liveness there (``hb/<rank>``), the launcher
    publishes ``elastic/world``, and an operator (or a worker) sets
    ``elastic/scale_to`` to resize — the launcher checkpoints-stops the
    job and relaunches on the new mesh (--np MIN:MAX)."""

    def __init__(self, args=None, etcd_client=None):
        self.args = args
        self.restarts = int(os.environ.get("PADDLE_ELASTIC_RESTARTS", 0))
        self._client = None

    def enabled(self) -> bool:
        return int(os.environ.get("PADDLE_ELASTIC_LEVEL", 0)) > 0

    @property
    def world_size(self) -> int:
        return int(os.environ.get("PADDLE_TRAINERS_NUM", 1))

    def _store(self):
        if self._client is None:
            ep = os.environ.get("PADDLE_ELASTIC_HB_ENDPOINT")
            if not ep:
                raise RuntimeError(
                    "no elastic membership store (launch with "
                    "--elastic_level/--np so the launcher hosts one)")
            from ....native.tcp_store import TCPStore
            host, _, port = ep.partition(":")
            self._client = TCPStore(host=host or "127.0.0.1",
                                    port=int(port), is_master=False,
                                    timeout=10.0)
        return self._client

    def members(self):
        """Ranks with a registered heartbeat (the etcd node-list analog)."""
        store = self._store()
        out = []
        for r in range(self.world_size):
            try:
                store.get(f"hb/{r}")
                out.append(r)
            except Exception:
                pass
        return out

    def scale_to(self, n: int):
        """Request a resize: the launcher checkpoints-stops the job and
        relaunches with ``n`` workers (clamped to its --np MIN:MAX)."""
        self._store().set("elastic/scale_to", str(int(n)).encode())

    def exit(self, completed=True):
        return ElasticStatus.COMPLETED if completed else ElasticStatus.ERROR


def launch_elastic(args=None, distribute_mode=None):
    """reference elastic/__init__.py:49 — delegate to the launcher's
    restart loop."""
    from ...launch.main import launch
    argv = ["--elastic_level", "1"] + (args or [])
    return launch(argv)
