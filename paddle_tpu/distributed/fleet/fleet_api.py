"""fleet.init / distributed_model / distributed_optimizer
(reference: /root/reference/python/paddle/distributed/fleet/fleet.py:100,168,1060).

TPU-native: fleet.init reads strategy.hybrid_configs and builds the device
mesh (topology.py); distributed_model attaches sharding metadata (DP batch
axis, TP layer PartitionSpecs already set by mp_layers); distributed_optimizer
wraps the optimizer so TrainStep/pjit runs sharded. Single-process eager
training continues to work unchanged (world_size==1 collectives are identity).
"""
from __future__ import annotations

from typing import Optional

from .. import env
from .distributed_strategy import DistributedStrategy
from .topology import CommunicateTopology, HybridCommunicateGroup

_fleet_state = {
    "initialized": False,
    "strategy": None,
    "hcg": None,
}


def init(role_maker=None, is_collective=False, strategy: Optional[DistributedStrategy] = None):
    strategy = strategy or DistributedStrategy()
    hc = strategy.hybrid_configs
    dims = [hc.get("dp_degree", 1), hc.get("pp_degree", 1),
            hc.get("sharding_degree", 1), hc.get("mp_degree", 1)]
    names = ["data", "pipe", "sharding", "model"]
    if hc.get("sep_degree", 1) > 1:
        dims.insert(3, hc["sep_degree"])
        names.insert(3, "sep")
    if hc.get("ep_degree", 1) > 1:
        # expert-parallel mesh axis (the reference routes MoE through its
        # own NCCL group, moe_layer.py:261; here it is a first-class axis)
        dims.insert(3, hc["ep_degree"])
        names.insert(3, "expert")
    topo = CommunicateTopology(names, dims)
    hcg = HybridCommunicateGroup(topo)
    _fleet_state.update(initialized=True, strategy=strategy, hcg=hcg)
    env.init_parallel_env()
    return _FleetAPI


def get_hybrid_communicate_group() -> HybridCommunicateGroup:
    return _fleet_state["hcg"]


def is_initialized():
    return _fleet_state["initialized"]


def distributed_model(model):
    """Wrap for hybrid parallel. DP grads are averaged by the mesh (psum in
    the compiled step); PP wraps in PipelineParallel when pp_degree>1."""
    hcg = _fleet_state["hcg"]
    if hcg is None:
        return model
    if hcg.get_pipe_parallel_world_size() > 1:
        from .meta_parallel.pipeline_parallel import PipelineParallel
        return PipelineParallel(model, hcg,
                                _fleet_state["strategy"])
    model._fleet_hcg = hcg
    return model


def _apply_meta_optimizers(optimizer, strategy):
    """Algorithm toggles with a real implementation are applied; ones
    without one WARN loudly (reference meta-optimizer zoo,
    python/paddle/distributed/fleet/meta_optimizers/)."""
    if strategy is None:
        return optimizer
    import warnings

    if getattr(strategy, "lars", False):
        from ...optimizer import LarsMomentum, Momentum
        if isinstance(optimizer, LarsMomentum):
            pass
        elif isinstance(optimizer, Momentum):
            cfg = strategy.lars_configs or {}
            if getattr(optimizer, "_nesterov", False):
                warnings.warn(
                    "strategy.lars replaces Momentum with LarsMomentum, "
                    "which has no Nesterov variant (reference "
                    "lars_momentum op) — use_nesterov is dropped")
            if getattr(optimizer, "_l2_coeff", 0.0) or \
                    getattr(optimizer, "_wd_obj", None) is not None:
                warnings.warn(
                    "strategy.lars supersedes the inner Momentum's "
                    "weight_decay with lars_configs['lars_weight_decay'] "
                    "(the LARS trust ratio folds decay into local_lr)")
            optimizer = LarsMomentum(
                learning_rate=optimizer._lr,
                momentum=optimizer._momentum,
                lars_coeff=cfg.get("lars_coeff", 0.001),
                lars_weight_decay=cfg.get("lars_weight_decay", 0.0005),
                parameters=optimizer._parameters,
                grad_clip=optimizer._grad_clip,
                exclude_from_weight_decay=cfg.get(
                    "exclude_from_weight_decay", []),
                epsilon=cfg.get("epsilon", 0.0),
                rescale_grad=getattr(optimizer, "_rescale", 1.0))
        else:
            warnings.warn(
                "DistributedStrategy.lars applies to a Momentum "
                f"optimizer (reference lars_optimizer.py contract); got "
                f"{type(optimizer).__name__} — running it unchanged")
    from .meta_parallel.dgc_localsgd import (DGCMomentum, _dp_mesh,
                                             make_localsgd_optimizer)

    if getattr(strategy, "dgc", False):
        from ...optimizer import Momentum
        if _dp_mesh() is None:
            warnings.warn(
                "strategy.dgc: no dp>1 mesh active — gradient compression "
                "needs data-parallel replicas (reference _can_apply "
                "worker_num>1 gate); running the optimizer unchanged")
        elif isinstance(optimizer, DGCMomentum):
            pass
        elif isinstance(optimizer, Momentum) and \
                not getattr(optimizer, "_nesterov", False):
            cfg = strategy.dgc_configs or {}
            optimizer = DGCMomentum(
                learning_rate=optimizer._lr,
                momentum=optimizer._momentum,
                parameters=optimizer._parameters,
                rampup_begin_step=cfg.get("rampup_begin_step", 0),
                rampup_step=cfg.get("rampup_step", 1),
                sparsity=cfg.get("sparsity", [0.999]),
                weight_decay=getattr(optimizer, "_l2_coeff", 0.0) or None,
                grad_clip=optimizer._grad_clip)
        else:
            warnings.warn(
                "DistributedStrategy.dgc applies to a (non-Nesterov) "
                "Momentum optimizer (reference DGCMomentumOptimizer "
                f"contract); got {type(optimizer).__name__} — running it "
                f"unchanged")
    for toggle, adaptive in (("localsgd", False),
                             ("adaptive_localsgd", True)):
        if getattr(strategy, toggle, False):
            if _dp_mesh() is None:
                warnings.warn(
                    f"strategy.{toggle}: no dp>1 mesh active — local SGD "
                    f"needs data-parallel replicas (reference _can_apply "
                    f"worker_num>1 gate); running the optimizer unchanged")
                continue
            cfg = (strategy.adaptive_localsgd_configs if adaptive
                   else strategy.localsgd_configs) or {}
            optimizer = make_localsgd_optimizer(
                optimizer,
                k_steps=cfg.get("k_steps", 1),
                begin_step=cfg.get("begin_step", 1),
                adaptive=adaptive,
                init_k_steps=cfg.get("init_k_steps", 1))
            break
    return optimizer


def distributed_optimizer(optimizer, strategy=None):
    strategy = strategy or _fleet_state["strategy"]
    optimizer = _apply_meta_optimizers(optimizer, strategy)
    hcg = _fleet_state["hcg"]
    if hcg is None:
        return optimizer
    from .meta_parallel.hybrid_optimizer import HybridParallelOptimizer
    return HybridParallelOptimizer(optimizer, hcg, strategy)


def worker_index():
    return env.global_rank()


def worker_num():
    return env.get_world_size()


def is_first_worker():
    return env.global_rank() == 0


def barrier_worker():
    from ..communication.collective import barrier
    barrier()


def save_persistables(executor=None, dirname=None, main_program=None, **kw):
    import os
    import paddle_tpu as P
    if main_program is not None and hasattr(main_program, "all_parameters"):
        state = {p.name: p for p in main_program.all_parameters()}
        P.save(state, os.path.join(dirname, "persistables.pdparams"))


def save_inference_model(executor=None, dirname=None, feeded_var_names=None,
                         target_vars=None, main_program=None, **kw):
    from ...static.io import save_inference_model as _sim
    import os
    return _sim(os.path.join(dirname or ".", "model"), feeded_var_names or [],
                target_vars or [], executor, program=main_program)


class _FleetAPIType:
    init = staticmethod(init)
    distributed_model = staticmethod(distributed_model)
    distributed_optimizer = staticmethod(distributed_optimizer)
    worker_index = staticmethod(worker_index)
    worker_num = staticmethod(worker_num)
    is_first_worker = staticmethod(is_first_worker)
    barrier_worker = staticmethod(barrier_worker)
    save_persistables = staticmethod(save_persistables)
    save_inference_model = staticmethod(save_inference_model)
    get_hybrid_communicate_group = staticmethod(get_hybrid_communicate_group)
    is_initialized = staticmethod(is_initialized)
    DistributedStrategy = DistributedStrategy

    @property
    def worker_endpoints(self):
        return env.ParallelEnv().trainer_endpoints


_FleetAPI = _FleetAPIType()
