"""Pipeline-parallel execution.

Reference: /root/reference/python/paddle/distributed/fleet/meta_parallel/
pipeline_parallel.py:31 (PipelineParallel.train_batch → 1F1B
forward_backward_pipeline at :117, p2p via batched isend/irecv).

TPU-native design: instead of rank-local p2p processes, the microbatch loop
is GSPMD-compiled. `train_batch` builds ONE jitted step in which microbatches
flow through stage weights laid out on the "pp" mesh axis. Round-1 scheme is
a scan-over-microbatches with stage-sharded weights (compute of different
stages overlaps across microbatches thanks to XLA async collectives over
ICI); an explicit shard_map 1F1B with ppermute is the planned upgrade.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ....core.tensor import Tensor
from ....jit.functional import _swapped_state, state_arrays
from ....framework import random as random_mod
from ....nn.layer.layers import Layer
from .pp_layers import PipelineLayer


class PipelineParallel(Layer):
    def __init__(self, layers: PipelineLayer, hcg, strategy):
        super().__init__()
        self._model = layers
        self.add_sublayer("model", layers)
        self._hcg = hcg
        self._strategy = strategy
        pc = strategy.pipeline_configs if strategy is not None else {}
        self.micro_batch_size = pc.get("micro_batch_size", 1)
        self.accumulate_steps = pc.get("accumulate_steps", 1)
        self._train_step = None

    def forward(self, x):
        return self._model(x)

    def _build_step(self, optimizer, scaler):
        model = self._model
        loss_fn = model._loss_fn
        n_micro = self.accumulate_steps
        opt = optimizer._inner_opt if hasattr(optimizer, "_inner_opt") else optimizer
        trainable = {n: p for n, p in model.named_parameters()
                     if not p.stop_gradient}
        trainable_names = list(trainable.keys())
        update_rule = opt._update_rule
        accum_names = opt._accum_names

        def pure_step(params, buffers, opt_state, lr, t, key, data, labels):
            def loss_of(tp):
                all_p = {**params, **tp}
                from ....core import autograd as ag
                with _swapped_state(model, all_p, buffers), ag.no_grad(), \
                        random_mod.traced_key_scope(key):
                    # microbatch loop: scan carries the running loss sum
                    def micro(b_idx, acc):
                        xb = jax.lax.dynamic_index_in_dim(data, b_idx, 0,
                                                          keepdims=False)
                        yb = jax.lax.dynamic_index_in_dim(labels, b_idx, 0,
                                                          keepdims=False)
                        out = model(Tensor(xb, stop_gradient=True))
                        lo = loss_fn(out, Tensor(yb, stop_gradient=True))
                        return acc + (lo._data if isinstance(lo, Tensor) else lo)
                    acc = jnp.zeros((), jnp.float32)
                    for i in range(n_micro):
                        acc = micro(i, acc)
                return acc / n_micro

            tp = {n: params[n] for n in trainable_names}
            loss, grads = jax.value_and_grad(loss_of)(tp)
            new_params = dict(params)
            new_state = {}
            for n in trainable_names:
                g = grads[n].astype(params[n].dtype)
                p_new, s_new = update_rule(
                    params[n], g, lr, t, jnp.asarray(0.0, jnp.float32),
                    opt_state[n])
                new_params[n] = p_new
                new_state[n] = s_new
            return loss, new_params, new_state

        return jax.jit(pure_step, donate_argnums=(0, 2))

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """data = [inputs, labels]; runs accumulate_steps microbatches."""
        x, y = data
        opt = optimizer._inner_opt if hasattr(optimizer, "_inner_opt") else optimizer
        if self._train_step is None:
            self._train_step = self._build_step(optimizer, scaler)
        model = self._model
        params, buffers = state_arrays(model)
        trainable = {n: p for n, p in model.named_parameters()
                     if not p.stop_gradient}
        opt_state = {n: {an: opt._get_accum(an, p)
                         for an in opt._accum_names}
                     for n, p in trainable.items()}
        opt._step_count += 1
        lr = jnp.asarray(opt.get_lr(), jnp.float32)
        t = jnp.asarray(opt._step_count, jnp.int32)
        key = random_mod.next_key()
        xd = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        yd = y._data if isinstance(y, Tensor) else jnp.asarray(y)
        n_micro = self.accumulate_steps
        # reshape batch into [n_micro, micro_bsz, ...]
        xd = xd.reshape((n_micro, xd.shape[0] // n_micro) + xd.shape[1:])
        yd = yd.reshape((n_micro, yd.shape[0] // n_micro) + yd.shape[1:])
        loss, new_params, new_state = self._train_step(
            params, buffers, opt_state, lr, t, key, xd, yd)
        for n, p in model.named_parameters():
            p._data = new_params[n]
        for n, p in trainable.items():
            for an in opt._accum_names:
                opt._set_accum(an, p, new_state[n][an])
        if lr_scheduler is not None:
            lr_scheduler.step()
        return Tensor(loss)

    def eval_batch(self, data, compute_loss=True):
        x, y = data
        out = self._model(x)
        if compute_loss and self._model._loss_fn is not None:
            return self._model._loss_fn(out, y)
        return out


class PipelineParallelWithInterleave(PipelineParallel):
    """Interleaved virtual-stage schedule (reference pipeline_parallel.py:461).
    Under GSPMD the schedule is XLA's concern; this subclass preserves API."""
    pass
