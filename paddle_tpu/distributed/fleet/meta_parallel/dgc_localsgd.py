"""DGC and LocalSGD — communication-reducing DP training schedules.

Reference:
- DGC: /root/reference/python/paddle/distributed/fleet/meta_optimizers/
  dgc_optimizer.py (DGCMomentumOptimizer wrapping the dgc/dgc_momentum ops,
  paddle/fluid/operators/dgc_op.h) — Deep Gradient Compression (Lin et al.
  2018): per-worker top-k gradient sparsification with momentum correction
  and momentum factor masking; transmitted mass is the error-feedback
  accumulator, untransmitted mass stays local.
- LocalSGD: /root/reference/python/paddle/distributed/fleet/meta_optimizers/
  localsgd_optimizer.py (param snapshots + allreduce of param deltas every
  k steps; AdaptiveLocalSGDOptimizer adapts k from the loss ratio,
  localsgd_optimizer.py:452-479).

TPU-native design. The reference implements both as NCCL-op program
rewrites. Here they are alternative *compiled step structures* built by
TrainStep when the fleet strategy toggle is on:

- DGC wraps the grad computation in ``shard_map`` over the 'dp' mesh axis
  so each data-parallel shard materializes its own LOCAL gradient (plain
  GSPMD fuses the cross-replica sum into the backward, so no local grad
  exists to compress). Per-rank u/v accumulators ride the optimizer state
  as (D, *shape) arrays sharded over 'dp'. The transmitted tensor is the
  error accumulator masked by a |v|-quantile threshold (== top-k selection,
  and the ramping sparsity schedule stays jit-static because the threshold
  is data-dependent rather than a shape), reduced with a single pmean —
  numerically identical to sparse aggregation, and the masked reduction is
  what XLA can actually ship over ICI.
- LocalSGD keeps each dp rank's params (and velocity) as (D, *shape)
  'dp'-sharded optimizer state, runs the whole local update inside
  shard_map, and only pays a cross-replica pmean of the parameters at sync
  steps — the canonical (user-visible) params update at syncs and stay
  stale in between, exactly the LocalSGD contract. k_steps is adapted
  in-graph for adaptive_localsgd with the reference's
  ceil(sqrt(lr0*loss/(lr*loss0))*k0) rule clipped to [1, 16].

Both require an active dp>1 mesh (the reference's _can_apply worker_num>1
gate lives in fleet._apply_meta_optimizers, which declines the swap and
warns when there is none).
"""
from __future__ import annotations

import warnings

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ....optimizer.optimizer import SGD, Momentum
from ...mesh_utils import get_global_mesh, manual_shard_map

__all__ = ["DGCMomentum", "make_localsgd_optimizer",
           "build_dgc_pure_step", "build_localsgd_pure_step"]


def _dp_mesh():
    """The active mesh when it has a non-trivial 'dp' axis, else None."""
    mesh = get_global_mesh()
    if mesh is not None and "dp" in mesh.axis_names and \
            mesh.shape["dp"] > 1:
        return mesh
    return None


def _dp_degree():
    mesh = _dp_mesh()
    return mesh.shape["dp"] if mesh is not None else 1


def _require_pure_dp(mesh, what):
    if mesh is None:
        raise RuntimeError(
            f"{what} requires an active dp>1 mesh (fleet.init with "
            f"dp_degree>1); none is set — the fleet strategy gate should "
            f"have declined the optimizer swap")
    if any(mesh.shape[a] > 1 for a in mesh.axis_names if a != "dp"):
        raise NotImplementedError(
            f"{what} composes with pure data parallelism only (reference "
            f"meta-optimizer black/white lists); found non-trivial mesh "
            f"axes {dict(mesh.shape)}")


class DGCMomentum(Momentum):
    """Momentum whose post-rampup update is plain SGD — the momentum lives
    in the per-worker DGC ``u`` accumulator (momentum correction), matching
    the reference dgc_momentum kernel's step<rampup?momentum:sgd branch
    (dgc_optimizer.py:143-166, dgc_momentum_op.h)."""

    _accum_names = ["velocity", "dgc_u", "dgc_v"]

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 rampup_begin_step=0, rampup_step=1, sparsity=(0.999,),
                 weight_decay=None, grad_clip=None, **kw):
        from ....nn.clip import ClipGradByNorm
        if grad_clip is not None and not isinstance(grad_clip,
                                                    ClipGradByNorm):
            # reference contract (dgc_optimizer.py:83-91): only
            # ClipGradByNorm composes with sparsified grads
            raise ValueError(
                "DGC only supports ClipGradByNorm (reference "
                "DGCMomentumOptimizer contract); got "
                f"{type(grad_clip).__name__}")
        super().__init__(learning_rate, momentum, parameters,
                         weight_decay=weight_decay, grad_clip=grad_clip)
        self._dgc_cfg = {
            "momentum": float(momentum),
            "rampup_begin_step": int(rampup_begin_step),
            "rampup_step": int(rampup_step),
            "sparsity": [float(s) for s in sparsity],
        }

    def _accum_spec(self, name, p):
        if name in ("dgc_u", "dgc_v"):
            return (_dp_degree(),) + tuple(p.shape), jnp.float32
        return super()._accum_spec(name, p)

    def _get_accum(self, name, p, init=None):
        if name in ("dgc_u", "dgc_v") and init is None:
            shape, dtype = self._accum_spec(name, p)
            init = jnp.zeros(shape, dtype)
        return super()._get_accum(name, p, init)

    def step(self):
        raise RuntimeError(
            "DGCMomentum runs through the compiled TrainStep (gradient "
            "compression needs the shard_mapped per-rank grads); eager "
            ".step() would silently train uncompressed SGD")

    def _update_rule(self, p, g, lr, t, wd, state):
        begin = self._dgc_cfg["rampup_begin_step"]
        lr = lr.astype(p.dtype)
        v = state["velocity"]
        v_new = self._momentum * v + g
        in_dgc = t >= begin
        p_out = jnp.where(in_dgc, p - lr * g, p - lr * v_new)
        out = {"velocity": jnp.where(in_dgc, v, v_new)}
        # u/v are updated by the shard_mapped gradient transform; the
        # update rule threads them through unchanged
        for k in ("dgc_u", "dgc_v"):
            if k in state:
                out[k] = state[k]
        return p_out, out


def _sparsity_at(t, cfg):
    """Ramping sparsity schedule: step through cfg['sparsity'] stages over
    rampup_step steps starting at rampup_begin_step (reference dgc op's
    rampup_begin_step/rampup_step/sparsity attrs). Traced scalar in
    [0, 1)."""
    sched = jnp.asarray(cfg["sparsity"], jnp.float32)
    n_stage = len(cfg["sparsity"])
    span = max(cfg["rampup_step"], 1)
    rel = jnp.maximum(t - cfg["rampup_begin_step"], 0)
    stage = jnp.clip((rel * n_stage) // span, 0, n_stage - 1)
    return sched[stage]


def _local_clip(gf, clip_thr):
    """Per-tensor local-grad clip at clip_norm * D^-0.5 — the reference
    applies it in BOTH phases (dgc_optimizer.py:91 _append_clip_norm runs
    unconditionally in apply_gradients)."""
    if clip_thr is None:
        return gf
    norm = jnp.sqrt(jnp.sum(jnp.square(gf)))
    return jnp.where(norm > clip_thr,
                     gf * (clip_thr / jnp.maximum(norm, 1e-12)), gf)


def _dgc_compress(g, u, v, t, cfg):
    """One DGC step for ONE parameter on ONE dp rank (runs inside
    shard_map; u/v enter as the (1, *shape) local slice of the stacked
    accumulator, g arrives already locally clipped).

    Lin et al. 2018 with momentum correction + momentum factor masking:
        u <- m*u + g_local ; v <- v + u
        mask = |v| >= quantile(|v|, sparsity)       (== top-k)
        send = v*mask ; v <- v*(1-mask) ; u <- u*(1-mask)
        G = pmean over ranks of send
    """
    m = cfg["momentum"]
    u0, v0 = u[0], v[0]
    gf = g.astype(jnp.float32)
    u1 = m * u0 + gf
    v1 = v0 + u1
    s = _sparsity_at(t, cfg)
    absv = jnp.abs(v1)
    thr = jnp.quantile(absv.reshape(-1), jnp.clip(s, 0.0, 1.0 - 1e-7))
    mask = (absv >= thr).astype(jnp.float32)
    send = v1 * mask
    g_agg = jax.lax.pmean(send, "dp")
    return g_agg, (u1 * (1.0 - mask))[None], (v1 * (1.0 - mask))[None]


def build_dgc_pure_step(ts):
    """DGC variant of TrainStep._make_pure_step: shard_map'd local grads +
    compressed aggregation over the 'dp' axis."""
    from ....nn.clip import ClipGradByNorm

    mesh = _dp_mesh()
    _require_pure_dp(mesh, "DGC")
    if ts._scaler is not None:
        raise NotImplementedError("DGC + dynamic loss scaling is not "
                                  "supported (use AMP without a scaler)")

    opt = ts.optimizer
    cfg = opt._dgc_cfg
    grad_clip = getattr(opt, "_grad_clip", None)
    clip_thr = (grad_clip.clip_norm * mesh.shape["dp"] ** -0.5
                if isinstance(grad_clip, ClipGradByNorm) else None)
    trainable_names = list(ts._trainable.keys())
    loss_of = _make_loss_of(ts)
    wd_by_name = {n: opt._wd_for(p) for n, p in ts._trainable.items()}
    lr_mult = {n: getattr(p, "optimize_attr", {"learning_rate": 1.0})[
        "learning_rate"] for n, p in ts._trainable.items()}
    update_rule = opt._update_rule

    def pure_step(params, buffers, opt_state, sc_state, lr, t, key, *batch):
        train_params = {n: params[n] for n in trainable_names}
        u = {n: opt_state[n]["dgc_u"] for n in trainable_names}
        v = {n: opt_state[n]["dgc_v"] for n in trainable_names}
        bspecs = tuple(P("dp") if getattr(b, "ndim", 0) >= 1 else P()
                       for b in batch)

        def local(tp, allp, bufs, u, v, key, t, *batch_local):
            loss_r, g = jax.value_and_grad(
                lambda q: loss_of(q, allp, bufs, key, batch_local))(tp)
            loss = jax.lax.pmean(loss_r, "dp")
            # local clip runs in BOTH phases (reference _append_clip_norm)
            g = {n: _local_clip(g[n].astype(jnp.float32), clip_thr)
                 for n in g}

            def dense(g, u, v):
                return ({n: jax.lax.pmean(g[n], "dp") for n in g}, u, v)

            def dgc(g, u, v):
                out_g, out_u, out_v = {}, {}, {}
                for n in g:
                    out_g[n], out_u[n], out_v[n] = _dgc_compress(
                        g[n], u[n], v[n], t, cfg)
                return out_g, out_u, out_v

            return (loss,) + jax.lax.cond(
                t >= cfg["rampup_begin_step"], dgc, dense, g, u, v)

        loss, g_agg, u2, v2 = manual_shard_map(
            local, mesh=mesh,
            in_specs=(P(), P(), P(), P("dp"), P("dp"), P(), P()) + bspecs,
            out_specs=(P(), P(), P("dp"), P("dp")))(
            train_params, params, buffers, u, v, key, t, *batch)

        new_params = dict(params)
        new_state = {}
        for n in trainable_names:
            g = g_agg[n]
            p_arr = params[n]
            if g.dtype != p_arr.dtype:
                g = g.astype(p_arr.dtype)
            if opt._l2_coeff and not opt._decoupled_wd():
                g = g + opt._l2_coeff * p_arr
            state_n = dict(opt_state[n], dgc_u=u2[n], dgc_v=v2[n])
            p_new, s_new = update_rule(
                p_arr, g, lr * lr_mult[n], t,
                jnp.asarray(wd_by_name[n], jnp.float32), state_n)
            new_params[n] = p_new
            new_state[n] = s_new
        loss, new_params, new_state = jax.lax.optimization_barrier(
            (loss, new_params, new_state))
        return loss, new_params, new_state, sc_state

    return pure_step


# ---------------------------------------------------------------- LocalSGD

def make_localsgd_optimizer(inner, k_steps=1, begin_step=1, adaptive=False,
                            init_k_steps=1):
    """Swap a SGD/Momentum optimizer for its LocalSGD variant (reference
    LocalSGDOptimizer._can_apply restricts to exactly these two,
    localsgd_optimizer.py:47-53). The returned optimizer carries
    ``_localsgd_cfg`` which TrainStep reads to build the k-step-sync
    compiled schedule; params and velocity become per-dp-rank state."""
    if not isinstance(inner, (SGD, Momentum)):
        warnings.warn(
            "DistributedStrategy.localsgd applies to SGD/Momentum "
            f"optimizers only (reference _can_apply contract); got "
            f"{type(inner).__name__} — running it unchanged")
        return inner
    if isinstance(inner, DGCMomentum):
        # reference meta-optimizer black lists forbid this composition
        # (LocalSGDOptimizer.meta_optimizers_black_list)
        warnings.warn(
            "strategy.localsgd cannot compose with strategy.dgc "
            "(reference meta-optimizer black list); keeping DGC")
        return inner
    wd = inner._wd_obj if inner._wd_obj is not None else \
        (inner._l2_coeff or None)
    if isinstance(inner, Momentum):
        opt = _LocalSGDMomentum(
            learning_rate=inner._lr, momentum=inner._momentum,
            parameters=inner._parameters,
            use_nesterov=getattr(inner, "_nesterov", False),
            weight_decay=wd, grad_clip=inner._grad_clip)
    else:
        opt = _LocalSGDSGD(learning_rate=inner._lr,
                           parameters=inner._parameters,
                           weight_decay=wd, grad_clip=inner._grad_clip)
    opt._localsgd_cfg = {
        "k_steps": int(k_steps), "begin_step": int(begin_step),
        "adaptive": bool(adaptive), "init_k_steps": int(init_k_steps),
    }
    opt._ls_scalars = None      # persisted {"k","last","loss0","lr0"}
    return opt


class _LocalSGDStateMixin:
    """Per-rank stacked (D, *shape) accumulators for the LocalSGD step."""

    def _accum_spec(self, name, p):
        if name == "ls_p":
            return ((_dp_degree(),) + tuple(p.shape),
                    getattr(p._data, "dtype", jnp.float32))
        shape, dtype = super()._accum_spec(name, p)
        return (_dp_degree(),) + tuple(shape), dtype

    def _get_accum(self, name, p, init=None):
        if init is None:
            if name == "ls_p":
                init = jnp.broadcast_to(
                    p._data, (_dp_degree(),) + tuple(p.shape))
            else:
                shape, dtype = self._accum_spec(name, p)
                init = jnp.zeros(shape, dtype)
        return super()._get_accum(name, p, init)

    def step(self):
        raise RuntimeError(
            "LocalSGD optimizers run through the compiled TrainStep "
            "(their state is per-dp-rank); eager .step() has no local "
            "rank to act on")

    # the sync-schedule scalars (k / last-sync / loss0 / lr0) must survive
    # checkpoint save/resume or an adaptive run resumes on fabricated
    # baselines and fires syncs off-schedule
    def state_dict(self):
        sd = super().state_dict()
        if getattr(self, "_ls_scalars", None) is not None:
            for k, val in self._ls_scalars.items():
                sd[f"@localsgd_{k}"] = jnp.asarray(val)
        return sd

    def set_state_dict(self, state_dict):
        super().set_state_dict(state_dict)
        keys = ("k", "last", "loss0", "lr0")
        if all(f"@localsgd_{k}" in state_dict for k in keys):
            self._ls_scalars = {
                k: jnp.asarray(getattr(state_dict[f"@localsgd_{k}"],
                                       "_data",
                                       state_dict[f"@localsgd_{k}"]))
                for k in keys}

    set_dict = set_state_dict


class _LocalSGDSGD(_LocalSGDStateMixin, SGD):
    _accum_names = ["ls_p"]


class _LocalSGDMomentum(_LocalSGDStateMixin, Momentum):
    _accum_names = ["velocity", "ls_p"]


def localsgd_scalar_init(cfg):
    k0 = cfg["init_k_steps"] if cfg["adaptive"] else cfg["k_steps"]
    return {"k": jnp.asarray(k0, jnp.int32),
            "last": jnp.asarray(0, jnp.int32),
            "loss0": jnp.asarray(1.0, jnp.float32),
            "lr0": jnp.asarray(1.0, jnp.float32)}


def build_localsgd_pure_step(ts):
    """LocalSGD variant of TrainStep._make_pure_step: every dp rank updates
    its own parameter copy inside shard_map; a cross-replica pmean of the
    params runs only at sync steps. Canonical (user-visible) params update
    at syncs and stay stale in between (the reference's per-worker params
    likewise diverge between snapshot allreduces)."""
    mesh = _dp_mesh()
    opt = ts.optimizer
    _require_pure_dp(mesh, "LocalSGD")
    if ts._scaler is not None:
        raise NotImplementedError("LocalSGD + dynamic loss scaling is "
                                  "not supported")

    cfg = opt._localsgd_cfg
    trainable_names = list(ts._trainable.keys())
    loss_of = _make_loss_of(ts)
    wd_by_name = {n: opt._wd_for(p) for n, p in ts._trainable.items()}
    lr_mult = {n: getattr(p, "optimize_attr", {"learning_rate": 1.0})[
        "learning_rate"] for n, p in ts._trainable.items()}
    update_rule = opt._update_rule
    accum_names = [a for a in opt._accum_names if a != "ls_p"]
    from ....jit.train_step import _functional_clip
    grad_clip = getattr(opt, "_grad_clip", None)

    def pure_step(params, buffers, opt_state, sc_state, lr, t, key, *batch):
        ls = opt_state["__ls__"]
        bspecs = tuple(P("dp") if getattr(b, "ndim", 0) >= 1 else P()
                       for b in batch)

        def local(allp, bufs, stacked_p, stacked_acc, ls, key, lr, t,
                  *batch_local):
            p_loc = {n: stacked_p[n][0] for n in trainable_names}
            loss_r, g = jax.value_and_grad(
                lambda q: loss_of(q, allp, bufs, key, batch_local))(p_loc)
            avg_loss = jax.lax.pmean(loss_r, "dp")
            g = _functional_clip(grad_clip, g)
            p2, acc2 = {}, {}
            for n in trainable_names:
                gn = g[n]
                if gn.dtype != p_loc[n].dtype:
                    gn = gn.astype(p_loc[n].dtype)
                if opt._l2_coeff and not opt._decoupled_wd():
                    gn = gn + opt._l2_coeff * p_loc[n]
                state_n = {a: stacked_acc[n][a][0] for a in accum_names}
                p2[n], acc2[n] = update_rule(
                    p_loc[n], gn, lr * lr_mult[n], t,
                    jnp.asarray(wd_by_name[n], jnp.float32), state_n)

            # sync schedule (reference: communicate() every step until
            # begin_step, then every k; adaptive re-derives k from the
            # loss ratio at syncs, localsgd_optimizer.py:481-488)
            begin = cfg["begin_step"]
            do_sync = jnp.where(t <= begin, True,
                                (t - ls["last"]) >= ls["k"])

            def sync(p2):
                avg = {n: jax.lax.pmean(p2[n], "dp")
                       for n in trainable_names}
                return avg, avg

            def nosync(p2):
                return p2, {n: allp[n] for n in trainable_names}

            p_next, canon_next = jax.lax.cond(do_sync, sync, nosync, p2)

            if cfg["adaptive"]:
                # next_k = ceil(sqrt(lr0*loss/(lr*loss0) * k0)) in [1,16]
                # (localsgd_optimizer.py:456-479)
                next_k = jnp.clip(jnp.ceil(jnp.sqrt(
                    ls["lr0"] * avg_loss
                    / jnp.maximum(lr * ls["loss0"], 1e-12)
                    * float(cfg["init_k_steps"]))), 1, 16).astype(jnp.int32)
                in_warmup = t <= begin
                k_new = jnp.where(
                    in_warmup, jnp.int32(cfg["init_k_steps"]),
                    jnp.where(do_sync, next_k, ls["k"]))
                loss0 = jnp.where(in_warmup, avg_loss, ls["loss0"])
                lr0 = jnp.where(in_warmup, lr, ls["lr0"])
            else:
                k_new = jnp.asarray(cfg["k_steps"], jnp.int32)
                loss0, lr0 = ls["loss0"], ls["lr0"]
            ls_new = {"k": k_new,
                      "last": jnp.where(do_sync, t, ls["last"]),
                      "loss0": loss0, "lr0": lr0}
            stacked_p2 = {n: p_next[n][None] for n in trainable_names}
            stacked_acc2 = {n: {a: acc2[n][a][None] for a in accum_names}
                            for n in trainable_names}
            return avg_loss, canon_next, stacked_p2, stacked_acc2, ls_new

        stacked_p = {n: opt_state[n]["ls_p"] for n in trainable_names}
        stacked_acc = {n: {a: opt_state[n][a] for a in accum_names}
                       for n in trainable_names}
        loss, canon, sp2, sa2, ls2 = manual_shard_map(
            local, mesh=mesh,
            in_specs=(P(), P(), P("dp"), P("dp"), P(), P(), P(), P())
            + bspecs,
            out_specs=(P(), P(), P("dp"), P("dp"), P()))(
            params, buffers, stacked_p, stacked_acc, ls, key, lr, t,
            *batch)

        new_params = dict(params)
        new_state = {"__ls__": ls2}
        for n in trainable_names:
            new_params[n] = canon[n]
            new_state[n] = dict({a: sa2[n][a] for a in accum_names},
                                ls_p=sp2[n])
        loss, new_params, new_state = jax.lax.optimization_barrier(
            (loss, new_params, new_state))
        return loss, new_params, new_state, sc_state

    return pure_step


def _make_loss_of(ts):
    """Shared with the plain step (train_step._make_loss_of) so the AMP /
    functional-state / key semantics cannot drift between schedules."""
    from ....jit.train_step import _make_loss_of as make
    return make(ts)
