"""Megatron f/g collective ops with explicit custom VJPs.

Reference: mp_ops.py (_c_identity / _mp_allreduce at
/root/reference/python/paddle/distributed/fleet/layers/mpu/mp_ops.py) — the
tensor-parallel conjugate pair:

- ``mp_identity`` ('f'): identity forward, all-reduce backward. Marks the
  point where a replicated activation fans out into column-sharded compute;
  the backward sums the per-rank partial cotangents.
- ``mp_allreduce`` ('g'): all-reduce forward, identity backward. Closes a
  row-sharded matmul; the cotangent is already replicated.

These are REQUIRED (not a convenience) inside manual-SPMD bodies that are
differentiated with in-body ``jax.vjp`` (the 1F1B pipeline backward): the
raw transpose of ``lax.psum`` there scales cotangents by the axis size,
whereas these pairs encode the correct Megatron transposes explicitly.
"""
from __future__ import annotations

import functools

import jax


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def mp_identity(x, axis: str):
    """'f': identity fwd; psum over ``axis`` in bwd."""
    return x


def _mp_identity_fwd(x, axis):
    return x, None


def _mp_identity_bwd(axis, _, ct):
    return (jax.lax.psum(ct, axis),)


mp_identity.defvjp(_mp_identity_fwd, _mp_identity_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def mp_allreduce(x, axis: str):
    """'g': psum over ``axis`` fwd; identity bwd."""
    return jax.lax.psum(x, axis)


def _mp_allreduce_fwd(x, axis):
    return jax.lax.psum(x, axis), None


def _mp_allreduce_bwd(axis, _, ct):
    return (ct,)


mp_allreduce.defvjp(_mp_allreduce_fwd, _mp_allreduce_bwd)
