"""SPMD pipeline parallelism: GPipe schedule inside shard_map.

The reference implements PP as rank-local Python schedules exchanging
activations over NCCL p2p (1F1B at
/root/reference/python/paddle/distributed/fleet/meta_parallel/
pipeline_parallel.py:117, p2p via batched isend/irecv). The TPU-native
equivalent compiles the WHOLE schedule into one XLA program: stage weights
live sharded over the 'pp' mesh axis (leading stacked-layer dim), microbatch
activations flow stage-to-stage with `lax.ppermute` over ICI, and autodiff
through the schedule yields the reverse pipeline automatically (grad
accumulation over microbatches falls out of the sum over the unrolled loop).

Layout contract inside the body (manual SPMD — all collectives explicit):
- stacked layer params: leading dim = total layers, sharded over 'pp'
- activations: [micro_batch, seq, hidden] with batch dp-sharded and seq
  sep-sharded by the caller's in_specs
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def spmd_pipeline(layer_fn: Callable, stacked_params, x, mesh: Mesh,
                  n_micro: int, param_specs, x_spec,
                  axis: str = "pp", remat: bool = True):
    """Run ``x`` through all stacked layers with a GPipe pipeline over
    ``axis``.

    layer_fn(params_slice, x_mb) -> x_mb — ONE layer, manual-SPMD (any
    collectives inside must use mesh axis names; it runs inside shard_map).
    stacked_params: pytree of arrays with leading dim L (total layers).
    x: [batch, seq, hidden] global activations (already embedded).
    param_specs: pytree of PartitionSpec matching stacked_params (dim 0 must
    be ``axis``). x_spec: PartitionSpec for x (batch/seq sharding).
    """
    from jax.experimental.shard_map import shard_map

    pp = mesh.shape[axis]
    batch = x.shape[0]
    assert batch % n_micro == 0, (batch, n_micro)
    mb = batch // n_micro
    x_mb = x.reshape((n_micro, mb) + x.shape[1:])
    xm_spec = P(*((None,) + tuple(x_spec)))

    one_layer = jax.checkpoint(layer_fn) if remat else layer_fn

    def stage_fn(params_local, h):
        # scan over this stage's local layers (leading dim L/pp)
        def step(c, p_slice):
            return one_layer(p_slice, c), None
        h, _ = jax.lax.scan(step, h, params_local)
        return h

    def body(params_local, xm):
        # xm: [n_micro, mb_local, s_local, hidden]
        stage = jax.lax.axis_index(axis)
        state = jnp.zeros(xm.shape[1:], xm.dtype)
        out = jnp.zeros_like(xm)
        perm = [(i, i + 1) for i in range(pp - 1)]
        for t in range(n_micro + pp - 1):
            if pp > 1:
                prev = jax.lax.ppermute(state, axis, perm)
            else:
                prev = state
            feed = xm[min(t, n_micro - 1)]
            inp = jnp.where(stage == 0, feed, prev)
            state = stage_fn(params_local, inp)
            o_idx = t - (pp - 1)
            if o_idx >= 0:
                out = out.at[o_idx].set(
                    jnp.where(stage == pp - 1, state, jnp.zeros_like(state)))
        # only the last stage holds real outputs; sum-broadcast over the ring
        if pp > 1:
            out = jax.lax.psum(out, axis)
        return out

    y = shard_map(body, mesh=mesh, in_specs=(param_specs, xm_spec),
                  out_specs=xm_spec, check_rep=False)(stacked_params, x_mb)
    return y.reshape(x.shape)
