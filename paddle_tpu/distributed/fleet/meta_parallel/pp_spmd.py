"""SPMD pipeline parallelism: GPipe / 1F1B / interleaved schedules in shard_map.

The reference implements PP as rank-local Python schedules exchanging
activations over NCCL p2p (1F1B at
/root/reference/python/paddle/distributed/fleet/meta_parallel/
pipeline_parallel.py:117, interleaved virtual stages at :461, p2p via batched
isend/irecv). The TPU-native equivalent compiles the WHOLE schedule into one
XLA program: stage weights live sharded over the 'pp' mesh axis (leading
stacked-layer dim), microbatch activations flow stage-to-stage with
`lax.ppermute` over ICI.

Three schedules:
- ``spmd_pipeline`` (GPipe / "F-then-B"): forward loop only; autodiff through
  the unrolled schedule yields the reverse pipeline. Stores every
  microbatch's stage activations — memory grows with n_micro.
- ``spmd_pipeline_1f1b`` ("1F1B"): custom-VJP whose backward re-runs the
  forward interleaved one-forward/one-backward per tick, so each stage keeps
  at most ~2*pp microbatch inputs alive (memory bounded by pipeline DEPTH,
  not microbatch count — the property 1F1B exists for). Costs one extra
  forward of the schedule, the same trade remat makes.
- ``spmd_pipeline_interleaved``: virtual pipeline stages (Megatron "VPP") —
  each rank owns ``v`` non-adjacent layer chunks; microbatches cycle the
  ring v times, shrinking the bubble from (pp-1)/(n+pp-1) toward
  (pp-1)/(v*pp+pp-1) per group of pp microbatches.

Layout contract inside the body (manual SPMD — all collectives explicit):
- stacked layer params: leading dim = total layers, sharded over 'pp'
- activations: [micro_batch, seq, hidden] with batch dp-sharded and seq
  sep-sharded by the caller's in_specs
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def spmd_pipeline(layer_fn: Callable, stacked_params, x, mesh: Mesh,
                  n_micro: int, param_specs, x_spec,
                  axis: str = "pp", remat: bool = True):
    """Run ``x`` through all stacked layers with a GPipe pipeline over
    ``axis``.

    layer_fn(params_slice, x_mb) -> x_mb — ONE layer, manual-SPMD (any
    collectives inside must use mesh axis names; it runs inside shard_map).
    stacked_params: pytree of arrays with leading dim L (total layers).
    x: [batch, seq, hidden] global activations (already embedded).
    param_specs: pytree of PartitionSpec matching stacked_params (dim 0 must
    be ``axis``). x_spec: PartitionSpec for x (batch/seq sharding).
    """
    from ...mesh_utils import manual_shard_map as shard_map

    pp = mesh.shape[axis]
    batch = x.shape[0]
    assert batch % n_micro == 0, (batch, n_micro)
    mb = batch // n_micro
    x_mb = x.reshape((n_micro, mb) + x.shape[1:])
    xm_spec = P(*((None,) + tuple(x_spec)))

    stage_fn = _make_stage_fn(layer_fn, remat)

    def body(params_local, xm):
        # xm: [n_micro, mb_local, s_local, hidden]
        stage = jax.lax.axis_index(axis)
        state = jnp.zeros(xm.shape[1:], xm.dtype)
        out = jnp.zeros_like(xm)
        perm = [(i, i + 1) for i in range(pp - 1)]
        for t in range(n_micro + pp - 1):
            if pp > 1:
                prev = jax.lax.ppermute(state, axis, perm)
            else:
                prev = state
            feed = xm[min(t, n_micro - 1)]
            inp = jnp.where(stage == 0, feed, prev)
            state = stage_fn(params_local, inp)
            o_idx = t - (pp - 1)
            if o_idx >= 0:
                out = out.at[o_idx].set(
                    jnp.where(stage == pp - 1, state, jnp.zeros_like(state)))
        # only the last stage holds real outputs; sum-broadcast over the ring
        if pp > 1:
            out = jax.lax.psum(out, axis)
        return out

    y = shard_map(body, mesh=mesh, in_specs=(param_specs, xm_spec),
                  out_specs=xm_spec)(stacked_params, x_mb)
    return y.reshape(x.shape)


def _make_stage_fn(layer_fn, remat):
    one_layer = jax.checkpoint(layer_fn) if remat else layer_fn

    def stage_fn(params_local, h):
        def step(c, p_slice):
            return one_layer(p_slice, c), None
        h, _ = jax.lax.scan(step, h, params_local)
        return h

    return stage_fn


def spmd_pipeline_1f1b(layer_fn: Callable, stacked_params, x, mesh: Mesh,
                       n_micro: int, param_specs, x_spec,
                       axis: str = "pp", remat: bool = True):
    """1F1B pipeline schedule (reference: forward_backward_pipeline,
    pipeline_parallel.py:117 — startup/steady/cooldown) as a custom-VJP
    SPMD program.

    Forward = the GPipe loop (nothing saved beyond inputs). Backward
    re-runs the forward interleaved with the backward: at tick ``t`` stage
    ``s`` forwards microbatch ``t - s`` and backwards microbatch
    ``t - 2*(pp-1) + s``; activations live in a circular buffer of depth
    min(2*pp, n_micro), so peak memory is bounded by pipeline depth while
    GPipe's grows with n_micro. Gradient math is identical (same sum over
    microbatches) — only the evaluation order differs.
    """
    from ...mesh_utils import manual_shard_map as shard_map

    pp = mesh.shape[axis]
    batch = x.shape[0]
    assert batch % n_micro == 0, (batch, n_micro)
    mb = batch // n_micro
    xm_shape = (n_micro, mb) + x.shape[1:]
    xm_spec = P(*((None,) + tuple(x_spec)))
    stage_fn = _make_stage_fn(layer_fn, remat)
    perm_dn = [(i, i + 1) for i in range(pp - 1)]
    perm_up = [(i + 1, i) for i in range(pp - 1)]

    def fwd_body(params_local, xm):
        stage = jax.lax.axis_index(axis)
        state = jnp.zeros(xm.shape[1:], xm.dtype)
        out = jnp.zeros_like(xm)
        for t in range(n_micro + pp - 1):
            prev = jax.lax.ppermute(state, axis, perm_dn)
            feed = xm[min(t, n_micro - 1)]
            inp = jnp.where(stage == 0, feed, prev)
            state = stage_fn(params_local, inp)
            o_idx = t - (pp - 1)
            if o_idx >= 0:
                out = out.at[o_idx].set(
                    jnp.where(stage == pp - 1, state, jnp.zeros_like(state)))
        return jax.lax.psum(out, axis)

    def bwd_body(params_local, xm, dym):
        stage = jax.lax.axis_index(axis)
        D = min(2 * pp, n_micro)
        ibuf = jnp.zeros((D,) + xm.shape[1:], xm.dtype)
        h_state = jnp.zeros(xm.shape[1:], xm.dtype)
        g_state = jnp.zeros(xm.shape[1:], dym.dtype)
        dxm = jnp.zeros(xm.shape, dym.dtype)
        dp_acc = jax.tree_util.tree_map(
            lambda a: jnp.zeros(a.shape, jnp.float32), params_local)
        for t in range(n_micro + 2 * (pp - 1)):
            prev = jax.lax.ppermute(h_state, axis, perm_dn)
            gin = jax.lax.ppermute(g_state, axis, perm_up)
            # -- forward part: microbatch f = t - stage
            f = t - stage
            f_ok = (f >= 0) & (f < n_micro)
            f_c = jnp.clip(f, 0, n_micro - 1)
            feed = jax.lax.dynamic_index_in_dim(xm, f_c, 0, keepdims=False)
            inp = jnp.where(stage == 0, feed, prev)
            slot = f_c % D
            old = jax.lax.dynamic_index_in_dim(ibuf, slot, 0, keepdims=False)
            ibuf = jax.lax.dynamic_update_index_in_dim(
                ibuf, jnp.where(f_ok, inp, old), slot, 0)
            h_new = stage_fn(params_local, inp)
            h_state = jnp.where(f_ok, h_new, h_state)
            # -- backward part: microbatch g = t - 2*(pp-1) + stage
            g = t - 2 * (pp - 1) + stage
            g_ok = (g >= 0) & (g < n_micro)
            g_c = jnp.clip(g, 0, n_micro - 1)
            dy_g = jax.lax.dynamic_index_in_dim(dym, g_c, 0, keepdims=False)
            dout = jnp.where(stage == pp - 1, dy_g, gin).astype(xm.dtype)
            binp = jax.lax.dynamic_index_in_dim(ibuf, g_c % D, 0,
                                                keepdims=False)
            _, vjp_fn = jax.vjp(stage_fn, params_local, binp)
            dp, dinp = vjp_fn(dout)
            dp_acc = jax.tree_util.tree_map(
                lambda a, d: a + jnp.where(g_ok, d.astype(jnp.float32), 0.0),
                dp_acc, dp)
            g_state = jnp.where(g_ok, dinp.astype(dym.dtype), g_state)
            dxm = dxm.at[g_c].add(
                jnp.where(g_ok & (stage == 0), dinp.astype(dym.dtype), 0.0))
        # reduce param grads over the batch axes the activations were
        # sharded on (dp/sep): those axes are unmapped in param_specs, and
        # with check_rep=False shard_map takes rank-local output values
        batch_axes = tuple(a for a in x_spec if a is not None)
        if batch_axes:
            dp_acc = jax.tree_util.tree_map(
                lambda a: jax.lax.psum(a, batch_axes), dp_acc)
        dp_acc = jax.tree_util.tree_map(
            lambda a, p: a.astype(p.dtype), dp_acc, params_local)
        return dp_acc, jax.lax.psum(dxm, axis)

    fwd_sm = shard_map(fwd_body, mesh=mesh, in_specs=(param_specs, xm_spec),
                       out_specs=xm_spec)
    bwd_sm = shard_map(bwd_body, mesh=mesh,
                       in_specs=(param_specs, xm_spec, xm_spec),
                       out_specs=(param_specs, xm_spec))

    @jax.custom_vjp
    def pipe(params, xx):
        return fwd_sm(params, xx.reshape(xm_shape)).reshape(x.shape)

    def pipe_fwd(params, xx):
        return pipe(params, xx), (params, xx)

    def pipe_bwd(res, gy):
        params, xx = res
        dp, dxm = bwd_sm(params, xx.reshape(xm_shape),
                         gy.reshape(xm_shape))
        return dp, dxm.reshape(x.shape).astype(xx.dtype)

    pipe.defvjp(pipe_fwd, pipe_bwd)
    return pipe(stacked_params, x)


def spmd_pipeline_interleaved(layer_fn: Callable, stacked_params, x,
                              mesh: Mesh, n_micro: int, v: int, param_specs,
                              x_spec, axis: str = "pp", remat: bool = True):
    """Interleaved virtual-stage pipeline (reference:
    PipelineParallelWithInterleave, pipeline_parallel.py:461).

    Each rank owns ``v`` non-adjacent layer chunks (virtual stage
    ``c*pp + s`` on rank ``s``); microbatches travel the stage ring ``v``
    times. Processed in serial groups of ``pp`` microbatches (the reference
    imposes the same ``accumulate_steps % pp == 0`` constraint); within a
    group, chunk passes chain seamlessly through the ring wraparound, so
    the per-group bubble is (pp-1)/(v*pp + pp - 1). Backward is autodiff
    through the schedule (GPipe memory profile).
    """
    from ...mesh_utils import manual_shard_map as shard_map

    pp = mesh.shape[axis]
    batch = x.shape[0]
    assert batch % n_micro == 0, (batch, n_micro)
    if n_micro % pp != 0:
        raise ValueError(
            f"interleaved schedule requires n_micro % pp == 0 (got "
            f"{n_micro} % {pp}); the reference imposes the same constraint "
            f"(pipeline_parallel.py:492 accumulate_steps % num_stages)")
    mb = batch // n_micro
    xm_spec = P(*((None,) + tuple(x_spec)))
    stage_fn = _make_stage_fn(layer_fn, remat)

    n_layers = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if n_layers % (v * pp) != 0 or n_layers // (v * pp) == 0:
        raise ValueError(
            f"interleaved schedule requires num_layers divisible by "
            f"virtual_pp_degree*pp_degree (got {n_layers} layers, "
            f"v={v} * pp={pp} = {v * pp})")

    # reshape [L, ...] -> [v, pp, Lc, ...]: virtual stage vs = c*pp + s owns
    # layers [vs*Lc, (vs+1)*Lc); shard dim 1 over 'pp'
    def _reshape_param(a):
        return a.reshape((v, pp, a.shape[0] // (v * pp)) + a.shape[1:])

    vparams = jax.tree_util.tree_map(_reshape_param, stacked_params)
    vspecs = jax.tree_util.tree_map(
        lambda s: P(None, axis, None, *tuple(s)[1:]), param_specs,
        is_leaf=lambda s: isinstance(s, P))

    ring = [(i, (i + 1) % pp) for i in range(pp)]

    def body(params_local, xm):
        # params_local: [v, 1, Lc, ...] -> [v, Lc, ...]
        pl = jax.tree_util.tree_map(lambda a: a[:, 0], params_local)
        stage = jax.lax.axis_index(axis)
        out = jnp.zeros_like(xm)
        n_groups = n_micro // pp
        ticks = v * pp + pp - 1
        for grp in range(n_groups):
            state = jnp.zeros(xm.shape[1:], xm.dtype)
            for r in range(ticks):
                moved = jax.lax.ppermute(state, axis, ring)
                q = r - stage                     # flow position
                ok = (q >= 0) & (q < v * pp)
                q_c = jnp.clip(q, 0, v * pp - 1)
                c = q_c // pp                     # chunk index (traced)
                j = q_c % pp                      # within-group microbatch
                f = grp * pp + j
                feed = jax.lax.dynamic_index_in_dim(xm, f, 0, keepdims=False)
                inp = jnp.where((stage == 0) & (c == 0), feed, moved)
                chunk_p = jax.tree_util.tree_map(
                    lambda a: jax.lax.dynamic_index_in_dim(
                        a, c, 0, keepdims=False), pl)
                h = stage_fn(chunk_p, inp)
                state = jnp.where(ok, h, state)
                done = ok & (stage == pp - 1) & (c == v - 1)
                out = jax.lax.dynamic_update_index_in_dim(
                    out,
                    jnp.where(
                        done, state,
                        jax.lax.dynamic_index_in_dim(out, f, 0,
                                                     keepdims=False)),
                    f, 0)
        return jax.lax.psum(out, axis)

    x_mb = x.reshape((n_micro, mb) + x.shape[1:])
    y = shard_map(body, mesh=mesh, in_specs=(vspecs, xm_spec),
                  out_specs=xm_spec)(vparams, x_mb)
    return y.reshape(x.shape)
