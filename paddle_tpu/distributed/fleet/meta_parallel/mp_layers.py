"""Tensor-parallel (Megatron-style) layers.

Reference: /root/reference/python/paddle/distributed/fleet/layers/mpu/
mp_layers.py:35,173,343,558 (VocabParallelEmbedding / ColumnParallelLinear /
RowParallelLinear / ParallelCrossEntropy built from explicit c_ops).

TPU-native mechanism: the layers hold logically-full parameters annotated
with a PartitionSpec (`param.dist_spec`); under a mesh the pjit/GSPMD
compiler shards the matmuls and inserts the identity/allreduce collectives
the reference codes by hand (column: no fwd comm; row: psum fwd). Sharding
constraints on activations steer XLA to the Megatron pattern. Eager
single-chip execution is exact (full weights).
"""
from __future__ import annotations

from ....nn import functional as F
from ....nn import initializer as I
from ....nn.initializer_utils import create_parameter_with_attr
from ....nn.layer.layers import Layer
from ...mesh_utils import get_global_mesh
from ...shard import constrain, mark_param


def _mark(param, *spec):
    # unified-surface annotation: sets dist_spec AND bumps the spec
    # generation so compiled-step memos see the change
    return mark_param(param, spec)


class VocabParallelEmbedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.weight = _mark(create_parameter_with_attr(
            [num_embeddings, embedding_dim], self._dtype, weight_attr, False,
            default_initializer=I.XavierNormal()), "mp", None)

    def forward(self, x):
        out = F.embedding(x, self.weight)
        return out


class ColumnParallelLinear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.gather_output = gather_output
        self.weight = _mark(create_parameter_with_attr(
            [in_features, out_features], self._dtype, weight_attr, False,
            default_initializer=I.XavierNormal()), None, "mp")
        if has_bias or has_bias is None:
            self.bias = _mark(create_parameter_with_attr(
                [out_features], self._dtype, None, True,
                default_initializer=I.Constant(0.0)), "mp")
        else:
            self.bias = None

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if get_global_mesh() is not None:
            spec = (None,) * (out.ndim - 1)
            if self.gather_output:
                out = constrain(out, *spec, None)
            else:
                out = constrain(out, *spec, "mp")
        return out


class RowParallelLinear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.input_is_parallel = input_is_parallel
        self.weight = _mark(create_parameter_with_attr(
            [in_features, out_features], self._dtype, weight_attr, False,
            default_initializer=I.XavierNormal()), "mp", None)
        if has_bias:
            self.bias = _mark(create_parameter_with_attr(
                [out_features], self._dtype, None, True,
                default_initializer=I.Constant(0.0)), None)
        else:
            self.bias = None

    def forward(self, x):
        # contraction over the mp-sharded dim → GSPMD inserts the allreduce
        out = F.linear(x, self.weight, self.bias)
        if get_global_mesh() is not None:
            spec = (None,) * (out.ndim - 1)
            out = constrain(out, *spec, None)
        return out


class ParallelCrossEntropy(Layer):
    """Vocab-parallel softmax CE (reference mp_layers.py:558 →
    c_softmax_with_cross_entropy). With GSPMD the plain CE over the sharded
    logits axis compiles to the same pattern."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):  # noqa: A002
        return F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)


def get_rng_state_tracker():
    """TP-rank dropout determinism helper (reference:
    fleet/meta_parallel/parallel_layers/random.py). Keys already derive from
    the traced base key per step; expose the paddle API."""
    class _Tracker:
        def add(self, name, seed):
            pass

        def rng_state(self, name="global_seed"):
            import contextlib

            @contextlib.contextmanager
            def _s():
                yield
            return _s()

    return _Tracker()
