from .dgc_localsgd import DGCMomentum, make_localsgd_optimizer  # noqa: F401
from .hybrid_optimizer import HybridParallelGradScaler, HybridParallelOptimizer  # noqa: F401
from .mp_layers import (  # noqa: F401
    ColumnParallelLinear, ParallelCrossEntropy, RowParallelLinear,
    VocabParallelEmbedding, get_rng_state_tracker,
)
from .pipeline_parallel import PipelineParallel, PipelineParallelWithInterleave  # noqa: F401
from .pp_layers import LayerDesc, PipelineLayer, SegmentLayers, SharedLayerDesc  # noqa: F401
from .sharding import (  # noqa: F401
    GroupShardedOptimizerStage2, GroupShardedStage2, GroupShardedStage3,
    group_sharded_parallel, save_group_sharded_model,
)
