"""Group-sharded (ZeRO) training.

Reference: /root/reference/python/paddle/distributed/fleet/meta_parallel/
sharding/group_sharded_optimizer_stage2.py:53, group_sharded_stage2.py:46,
group_sharded_stage3.py:59; entry group_sharded_parallel at
/root/reference/python/paddle/distributed/sharding/group_sharded.py:37.

TPU-native: ZeRO is a sharding-spec choice, not a runtime protocol. Stage 1/2
shard optimizer state (and grads) over the "sharding"/"dp" mesh axis; stage 3
also shards parameters. These wrappers are the paddle-API shims over the
unified surface — ``paddle_tpu.distributed.shard`` owns the spec decision
(``apply_sharding(zero=...)`` is the direct form); GSPMD then emits
reduce-scatter/all-gather exactly where the reference does them by hand.
"""
from __future__ import annotations

from ....nn.layer.layers import Layer


def _flat_axis_spec(p, axis="sharding"):
    """Shard dim 0 of the param over the sharding axis when it divides
    evenly; fall back to replicated (scalars and non-divisible dims would
    otherwise fail placement). Delegates to the unified surface's ZeRO
    composition (shard._zero_compose over a replicated base)."""
    from ...mesh_utils import get_global_mesh
    from ...shard import _zero_compose
    shape = tuple(p.shape)
    if not shape:
        return None
    return _zero_compose((None,) * len(shape), shape, get_global_mesh(),
                         axis=axis)


class GroupShardedStage2(Layer):
    def __init__(self, layer, optimizer, group=None, sync_buffers=False,
                 buffer_max_size=2 ** 23, auto_refresh_trainable=True,
                 device="tpu", dp_group=None):
        super().__init__()
        # bypass Layer.__setattr__ for the private ref: assigning a Layer
        # attribute auto-registers it as a sublayer, and together with the
        # explicit add_sublayer the SAME parameters would appear twice in
        # named_parameters() — the compiled TrainStep then donates each
        # underlying buffer twice (Execute() error)
        object.__setattr__(self, "_layer", layer)
        self.add_sublayer("layer", layer)
        self._optimizer = optimizer
        # mark optimizer state sharding: the TrainStep builder reads
        # p.opt_state_spec when laying out accumulators
        from ...shard import mark_param
        for p in layer.parameters():
            mark_param(p, getattr(p, "dist_spec", None),
                       opt_state_spec=_flat_axis_spec(p))

    def forward(self, *args, **kwargs):
        return self._layer(*args, **kwargs)


class GroupShardedStage3(Layer):
    def __init__(self, layer, optimizer, group=None, sync_buffers=False,
                 device="tpu", segment_size=2 ** 20, pertrain_sync_models=True,
                 offload=False, sync_comm=False, dp_group=None,
                 exclude_layer=None):
        super().__init__()
        object.__setattr__(self, "_layer", layer)  # see GroupShardedStage2
        self.add_sublayer("layer", layer)
        self._optimizer = optimizer
        from ...shard import mark_param
        for p in layer.parameters():
            spec = _flat_axis_spec(p)
            mark_param(p, spec, opt_state_spec=spec)

    def forward(self, *args, **kwargs):
        return self._layer(*args, **kwargs)


class GroupShardedOptimizerStage2:
    def __init__(self, params, optim, group=None, offload=False, device="tpu",
                 **kwargs):
        self._optim = optim
        from ...shard import mark_param
        for p in params:
            mark_param(p, getattr(p, "dist_spec", None),
                       opt_state_spec=_flat_axis_spec(p))

    def __getattr__(self, item):
        return getattr(self.__dict__["_optim"], item)

    def __setattr__(self, item, value):
        # writes (TrainStep's _step_count bump, LR changes) must reach the
        # inner optimizer, or its serialized state drifts from reality
        if item == "_optim":
            self.__dict__[item] = value
        else:
            setattr(self.__dict__["_optim"], item, value)

    def step(self):
        self._optim.step()

    def clear_grad(self, set_to_zero=True):
        self._optim.clear_grad(set_to_zero)


def group_sharded_parallel(model, optimizer, level, scaler=None, group=None,
                           offload=False, sync_buffers=False, buffer_max_size=2 ** 23,
                           segment_size=2 ** 20, sync_comm=False, dp_group=None,
                           exclude_layer=None):
    """paddle.distributed.sharding.group_sharded_parallel."""
    if level in ("os", "os_g", "p_g_os"):
        pass
    else:
        raise ValueError(f"level must be os/os_g/p_g_os, got {level}")
    if level == "p_g_os":
        model = GroupShardedStage3(model, optimizer)
    else:
        model = GroupShardedStage2(model, optimizer)
        optimizer = GroupShardedOptimizerStage2(
            list(model.parameters()), optimizer)
    return model, optimizer, scaler


def save_group_sharded_model(model, output, optimizer=None):
    import os
    import paddle_tpu as P
    inner = model._layer if hasattr(model, "_layer") else model
    P.save(inner.state_dict(), os.path.join(output, "model.pdparams"))
    if optimizer is not None:
        P.save(optimizer.state_dict(), os.path.join(output, "model.pdopt"))
