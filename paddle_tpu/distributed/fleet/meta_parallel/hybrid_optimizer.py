"""HybridParallelOptimizer + GradScaler wrapper
(reference: /root/reference/python/paddle/distributed/fleet/meta_optimizers/
dygraph_optimizer/hybrid_parallel_optimizer.py:226,290).

Under the mesh/pjit design, DP gradient averaging and cross-group global-norm
clipping happen inside the compiled step (reductions are global over the
mesh), so this wrapper mainly preserves the API and guards clip semantics.
"""
from __future__ import annotations


class HybridParallelOptimizer:
    def __init__(self, optimizer, hcg, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy

    def __getattr__(self, item):
        return getattr(self.__dict__["_inner_opt"], item)

    def step(self):
        self._inner_opt.step()

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        return self._inner_opt.minimize(loss, startup_program, parameters,
                                        no_grad_set)

    def clear_grad(self, set_to_zero=True):
        self._inner_opt.clear_grad(set_to_zero)

    clear_gradients = clear_grad


class HybridParallelGradScaler:
    def __init__(self, scaler, hcg):
        self._scaler = scaler
        self._hcg = hcg

    def __getattr__(self, item):
        return getattr(self.__dict__["_scaler"], item)

    def scale(self, var):
        return self._scaler.scale(var)

    def minimize(self, optimizer, scaled_loss):
        return self._scaler.minimize(optimizer, scaled_loss)
