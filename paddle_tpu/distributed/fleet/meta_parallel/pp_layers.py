"""PipelineLayer / LayerDesc — pipeline model description.

Reference: /root/reference/python/paddle/distributed/fleet/meta_parallel/
parallel_layers/pp_layers.py:57,77,209 (LayerDesc / SharedLayerDesc /
PipelineLayer with segmentation). The description API is preserved; execution
maps stages onto the "pp" mesh axis via the shard_map microbatch loop in
pipeline_parallel.py.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ....nn.layer.layers import Layer


class LayerDesc:
    def __init__(self, layer_cls, *inputs, **kwargs):
        self.layer_cls = layer_cls
        self.inputs = inputs
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_cls(*self.inputs, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_cls, forward_func=None, shared_weight_attr="weight",
                 *inputs, **kwargs):
        super().__init__(layer_cls, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """Split N layers into num_parts (reference pp_layers.py:93)."""

    def __init__(self, layers_desc, num_parts, method="uniform"):
        self.descs = layers_desc
        self.num_parts = num_parts
        self.method = method

    def do_segment(self) -> List[int]:
        n = len(self.descs)
        if self.method == "uniform":
            per = n // self.num_parts
            extra = n % self.num_parts
            bounds = [0]
            for i in range(self.num_parts):
                bounds.append(bounds[-1] + per + (1 if i < extra else 0))
            return bounds
        raise NotImplementedError(f"segment method {self.method}")


class PipelineLayer(Layer):
    def __init__(self, layers, num_stages=None, topology=None, loss_fn=None,
                 seg_method="uniform", recompute_interval=0,
                 recompute_ctx=None, num_virtual_pipeline_stages=None):
        super().__init__()
        self._layers_desc = list(layers)
        self._loss_fn = loss_fn
        self._topo = topology
        self._num_stages = num_stages or 1
        self._seg_method = seg_method

        # materialize all layers (single-process holds the full model; stage
        # assignment becomes a mesh placement concern at compile time)
        self.segment_bounds = SegmentLayers(
            self._layers_desc, self._num_stages, seg_method).do_segment()
        self._shared = {}
        built = []
        from .mp_layers import _mark  # noqa: F401
        for i, item in enumerate(self._layers_desc):
            if isinstance(item, SharedLayerDesc):
                if item.layer_name in self._shared:
                    built.append(("shared", item, self._shared[item.layer_name]))
                    continue
                layer = item.build_layer()
                self._shared[item.layer_name] = layer
                self.add_sublayer(str(i), layer)
                built.append(("shared_first", item, layer))
            elif isinstance(item, LayerDesc):
                layer = item.build_layer()
                self.add_sublayer(str(i), layer)
                built.append(("layer", item, layer))
            elif isinstance(item, Layer):
                self.add_sublayer(str(i), item)
                built.append(("layer", None, item))
            elif callable(item):
                built.append(("func", None, item))
            else:
                raise TypeError(f"bad pipeline item {item}")
        self._built = built

    def get_stage_of_layer(self, layer_idx):
        for s in range(self._num_stages):
            if self.segment_bounds[s] <= layer_idx < self.segment_bounds[s + 1]:
                return s
        return self._num_stages - 1

    def stage_layers(self, stage):
        lo, hi = self.segment_bounds[stage], self.segment_bounds[stage + 1]
        return self._built[lo:hi]

    def forward(self, x):
        out = x
        for kind, desc, layer in self._built:
            if kind == "func":
                out = layer(out)
            elif kind == "shared" and desc.forward_func is not None:
                out = desc.forward_func(layer, out)
            else:
                out = layer(out)
        return out
