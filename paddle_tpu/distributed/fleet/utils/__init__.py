"""fleet.utils — filesystem helpers, recompute, DP grad fusion.

Reference: python/paddle/distributed/fleet/utils/{fs.py (LocalFS:113,
HDFSClient:424), hybrid_parallel_util.py (fused_allreduce_gradients:211),
__init__.py recompute:30}. TPU-native: recompute is jax.checkpoint on the
traced segment; fused DP grad sync is a single batched all_reduce sweep
(XLA fuses it; the reference's Reducer bucketing exists to overlap NCCL,
which GSPMD handles inside compiled steps).
"""
from __future__ import annotations

import os
import shutil

import numpy as _np

__all__ = ["LocalFS", "HDFSClient", "recompute", "recompute_sequential",
           "fused_allreduce_gradients", "HybridParallelInferenceHelper"]

from .hybrid_parallel_inference import HybridParallelInferenceHelper  # noqa: F401,E402
from . import tensor_parallel_utils  # noqa: F401,E402


class ExecuteError(Exception):
    pass


class FSFileExistsError(Exception):
    pass


class FSFileNotExistsError(Exception):
    pass


class LocalFS:
    """Local filesystem with the reference FS interface (fs.py:113)."""

    def ls_dir(self, fs_path):
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for e in os.listdir(fs_path):
            (dirs if os.path.isdir(os.path.join(fs_path, e))
             else files).append(e)
        return dirs, files

    def is_file(self, fs_path):
        return os.path.isfile(fs_path)

    def is_dir(self, fs_path):
        return os.path.isdir(fs_path)

    def is_exist(self, fs_path):
        return os.path.exists(fs_path)

    def mkdirs(self, fs_path):
        os.makedirs(fs_path, exist_ok=True)

    def delete(self, fs_path):
        if self.is_file(fs_path):
            os.remove(fs_path)
        elif self.is_dir(fs_path):
            shutil.rmtree(fs_path)

    def rename(self, fs_src_path, fs_dst_path):
        os.rename(fs_src_path, fs_dst_path)

    def need_upload_download(self):
        return False

    def upload(self, local_path, fs_path):
        if not self.is_exist(local_path):
            raise FSFileNotExistsError(local_path)
        shutil.copy(local_path, fs_path)

    def download(self, fs_path, local_path):
        if not self.is_exist(fs_path):
            raise FSFileNotExistsError(fs_path)
        shutil.copy(fs_path, local_path)

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path):
            if exist_ok:
                return
            raise FSFileExistsError(fs_path)
        open(fs_path, "a").close()

    def mv(self, src_path, dst_path, overwrite=False, test_exists=False):
        if not self.is_exist(src_path):
            raise FSFileNotExistsError(src_path)
        if not overwrite and self.is_exist(dst_path):
            raise FSFileExistsError(dst_path)
        shutil.move(src_path, dst_path)

    def list_dirs(self, fs_path):
        if not self.is_exist(fs_path):
            return []
        return [e for e in os.listdir(fs_path)
                if os.path.isdir(os.path.join(fs_path, e))]

    def cat(self, fs_path=None):
        with open(fs_path, "rb") as f:
            return f.read().decode()


class HDFSClient:
    """HDFS interface placeholder: requires a hadoop client binary the
    reference shells out to (fs.py:424); not available here."""

    def __init__(self, hadoop_home=None, configs=None, *a, **kw):
        raise RuntimeError(
            "HDFSClient needs a local hadoop installation (the reference "
            "shells out to `hadoop fs`); none exists in this environment. "
            "Use LocalFS, or mount the data locally.")


def recompute(function, *args, **kwargs):
    """Activation-recompute wrapper (reference recompute.py:330): inside
    traced/jit execution the segment is wrapped in jax.checkpoint; eager
    calls just run the function (eager autograd stores activations
    per-op, there is nothing to discard ahead of time)."""
    import jax

    from ....core.tensor import Tensor

    preserve = kwargs.pop("preserve_rng_state", True)  # noqa: F841
    use_reentrant = kwargs.pop("use_reentrant", True)  # noqa: F841

    def _traced(v):
        return isinstance(v, jax.core.Tracer) or (
            isinstance(v, Tensor) and isinstance(v._data, jax.core.Tracer))

    def _in_trace_context():
        # A segment can close over traced values while every explicit arg
        # is concrete (e.g. a module whose params are traced by TrainStep);
        # checking only the args would silently skip jax.checkpoint and
        # lose the memory savings. The trace context catches that case.
        try:
            from jax._src.core import EvalTrace
            return not isinstance(jax.core.trace_ctx.trace, EvalTrace)
        except (AttributeError, ImportError, TypeError):  # pragma: no cover
            return False

    if not any(_traced(v) for v in list(args) + list(kwargs.values())) \
            and not _in_trace_context():
        # eager: per-op autograd stores activations anyway, just run it
        return function(*args, **kwargs)

    # Tensor is not a jax pytree: pass raw arrays through checkpoint and
    # rewrap at the boundary so the segment sees Tensors again. Only
    # array leaves (positional or keyword) become checkpoint operands;
    # everything else (flags, scalars, strings) is closed over as a
    # static — a bool operand would become a tracer and break `if flag:`
    # control flow inside the segment.
    def _arrayish(v):
        return isinstance(v, (Tensor, jax.Array, jax.core.Tracer,
                              _np.ndarray))
    arr_pos = [i for i, v in enumerate(args) if _arrayish(v)]
    arr_keys = [k for k, v in kwargs.items() if _arrayish(v)]
    leaves = [args[i] for i in arr_pos] + [kwargs[k] for k in arr_keys]
    out_meta = []

    def seg(*raw):
        pos = list(args)
        for j, i in enumerate(arr_pos):
            pos[i] = (Tensor(raw[j]) if isinstance(args[i], Tensor)
                      else raw[j])
        kw = dict(kwargs)
        for j, k in enumerate(arr_keys):
            r = raw[len(arr_pos) + j]
            kw[k] = Tensor(r) if isinstance(kwargs[k], Tensor) else r
        out = function(*pos, **kw)
        outs = out if isinstance(out, (tuple, list)) else (out,)
        out_meta[:] = [(isinstance(out, (tuple, list)),
                        [isinstance(o, Tensor) for o in outs])]
        return tuple(o._data if isinstance(o, Tensor) else o for o in outs)

    raw = [v._data if isinstance(v, Tensor) else v for v in leaves]
    res = jax.checkpoint(seg)(*raw)
    is_seq, tensor_flags = out_meta[0]
    wrapped = tuple(Tensor(r) if f else r
                    for r, f in zip(res, tensor_flags))
    return wrapped if is_seq else wrapped[0]


def recompute_sequential(ctx, functions, *args, **kwargs):
    """Segmented sequential recompute (reference recompute.py:454):
    splits a Sequential into `segments` chunks, recomputing each."""
    segments = max((ctx or {}).get("segments", 1), 1)
    fns = list(functions)
    seg_len = max(-(-len(fns) // segments), 1)   # ceil: exactly `segments`
    out = args
    for i in range(0, len(fns), seg_len):
        chunk = fns[i:i + seg_len]

        def seg(*a, _chunk=chunk):
            for f in _chunk:
                a = (f(*a),)
            return a[0]
        out = (recompute(seg, *out if isinstance(out, tuple) else (out,),
                         **kwargs),)
    return out[0]


def fused_allreduce_gradients(parameter_list, hcg=None):
    """Sum-allreduce every parameter gradient over the data-parallel
    group in one sweep (reference hybrid_parallel_util.py:211, used by PP
    to sync grads after the microbatch loop). With an hcg the reduction
    stays inside the dp group — never across tp/pp ranks — and grads are
    AVERAGED over the group, matching the reference's 1/nranks scaling
    around its sum-allreduce (_apply_collective_grads)."""
    from ...communication.collective import ReduceOp, all_reduce

    group = hcg.get_data_parallel_group() if hcg is not None else None
    if group is not None and group.nranks <= 1:
        return
    pairs = [(p, p.grad) for p in parameter_list
             if getattr(p, "grad", None) is not None]
    if not pairs:
        return
    import jax.numpy as jnp

    from ....core.tensor import Tensor

    # one fused sweep per dtype: flatten+concat, one collective, split
    by_dtype = {}
    for p, g in pairs:
        by_dtype.setdefault(str(g._data.dtype), []).append((p, g))
    for grp in by_dtype.values():
        flat = Tensor(jnp.concatenate([g._data.reshape(-1)
                                       for _, g in grp]))
        all_reduce(flat, op=ReduceOp.AVG, group=group)
        off = 0
        for _, g in grp:
            n = int(_np.prod(g._data.shape)) if g._data.shape else 1
            g._data = flat._data[off:off + n].reshape(g._data.shape)
            off += n
