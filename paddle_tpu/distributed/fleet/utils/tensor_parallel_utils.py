"""Tensor-parallel checkpoint conversion utilities.

Reference: python/paddle/distributed/fleet/utils/tensor_parallel_utils.py
(parameter conversion between tensor-parallel degrees). TPU-native: a
state_dict trained at one mp degree is resharded to another by splitting
or concatenating each parameter along its 'mp'-annotated dimension from
the layer's dist_spec (mp_layers.py / GPTStackedTransformer.SPECS). The
head-major qkv layout guarantees contiguous splits are per-head, so these
conversions are exact.
"""
from __future__ import annotations

import numpy as np

__all__ = ["mp_axis_of", "split_mp_state_dict", "merge_mp_state_dicts"]


def mp_axis_of(spec, axis_name: str = "mp"):
    """Index of the dimension sharded over ``axis_name`` in a dist_spec
    tuple (e.g. ('pp', None, 'mp') -> 2), or None if replicated."""
    if spec is None:
        return None
    for i, a in enumerate(spec):
        if a == axis_name:
            return i
    return None


def split_mp_state_dict(state_dict, specs, mp_degree: int,
                        axis_name: str = "mp"):
    """Split a full (mp=1) state_dict into ``mp_degree`` per-rank dicts.

    ``specs`` maps param name -> dist_spec tuple; names missing from it
    (or replicated over ``axis_name``) are shared by every rank.
    """
    if mp_degree < 1:
        raise ValueError(f"mp_degree must be >= 1, got {mp_degree}")
    shards = [dict() for _ in range(mp_degree)]
    for name, value in state_dict.items():
        arr = np.asarray(value.numpy() if hasattr(value, "numpy") else value)
        dim = mp_axis_of(specs.get(name), axis_name)
        # copies throughout: per-rank checkpoints must not alias each
        # other or the source (np.split returns views)
        if dim is None or mp_degree == 1:
            for s in shards:
                s[name] = arr.copy()
            continue
        if arr.shape[dim] % mp_degree != 0:
            raise ValueError(
                f"{name}: dim {dim} ({arr.shape[dim]}) not divisible by "
                f"mp_degree={mp_degree}")
        for rank, piece in enumerate(np.split(arr, mp_degree, axis=dim)):
            shards[rank][name] = piece.copy()
    return shards


def merge_mp_state_dicts(shards, specs, axis_name: str = "mp"):
    """Inverse of split_mp_state_dict: concatenate per-rank dicts back
    into the full (mp=1) state_dict."""
    if not shards:
        raise ValueError("no shards given")
    merged = {}
    for name in shards[0]:
        arrs = [np.asarray(s[name].numpy() if hasattr(s[name], "numpy")
                           else s[name]) for s in shards]
        dim = mp_axis_of(specs.get(name), axis_name)
        if dim is None or len(shards) == 1:
            merged[name] = arrs[0]
        else:
            merged[name] = np.concatenate(arrs, axis=dim)
    return merged
