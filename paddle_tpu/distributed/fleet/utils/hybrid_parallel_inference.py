"""Hybrid-parallel autoregressive inference helper.

Reference: python/paddle/distributed/fleet/utils/hybrid_parallel_inference.py
(HybridParallelInferenceHelper:27) rewrites a static Program so an
autoregressive decode loop runs pipeline-parallel. TPU-native collapse:
the model forward is already one SPMD program under the global mesh, so
the helper only has to run the decode loop.

Decode path: models that support the paged KV cache (``forward(ids,
cache=...)`` — see serving.generation.model_fns) run prefill once and
then one ``[batch, 1]`` cached decode step per emitted token, so the
per-token cost is O(T·L) instead of the old full-window O(T²·L)
recompute. Models without cache support fall back to the original
fixed-padded-window forward (``_full_window_generate`` — also the
measured baseline in tools/bench_decode.py). Token selection is one
vectorized host pass either way (serving.generation.sampling).
"""
from __future__ import annotations

import numpy as np

__all__ = ["HybridParallelInferenceHelper"]


class HybridParallelInferenceHelper:
    """Greedy/sampling decode driver over a causal-LM ``Layer``.

    ``model(ids)`` must return logits ``[batch, seq, vocab]`` (optionally
    wrapped in a tuple/list, first element used). Works on a single chip
    and unchanged under a fleet mesh — sharding comes from the params'
    dist_spec annotations, not from this class. (The KV-cached fast path
    is single-shard; a live pp/mp/sep mesh routes to the full-window
    fallback.)
    """

    def __init__(self, model, max_length: int = 128, eos_token_id=None,
                 pad_token_id: int = 0):
        self.model = model
        self.max_length = int(max_length)
        self.eos_token_id = eos_token_id
        self.pad_token_id = int(pad_token_id)
        self._decoders = {}     # batch -> CachedDecoder (+ page geometry)

    def _logits(self, ids_tensor):
        out = self.model(ids_tensor)
        if isinstance(out, (tuple, list)):
            out = out[0]
        return out

    # ------------------------------------------------------ entry point
    def generate(self, input_ids, max_new_tokens: int = 32,
                 temperature: float = 0.0, seed: int = 0):
        """Decode ``max_new_tokens`` tokens. temperature 0 = greedy;
        otherwise softmax sampling (vectorized inverse-CDF over the
        batch, numpy RNG seeded with ``seed``)."""
        ids = np.asarray(input_ids.numpy() if hasattr(input_ids, "numpy")
                         else input_ids).astype("int64")
        if ids.ndim == 1:
            ids = ids[None, :]
        prompt_len = ids.shape[1]
        if prompt_len >= self.max_length:
            raise ValueError(
                f"prompt length {prompt_len} leaves no room to generate "
                f"within max_length={self.max_length}")
        total = min(self.max_length, prompt_len + int(max_new_tokens))
        was_training = getattr(self.model, "training", False)
        if hasattr(self.model, "eval"):
            self.model.eval()
        try:
            if self._cached_decode_ok():
                return self._generate_cached(ids, total, temperature,
                                             seed)
            return self._full_window_generate(ids, total, temperature,
                                              seed)
        finally:
            if was_training:
                self.model.train()

    def _cached_decode_ok(self) -> bool:
        from ....serving.generation.model_fns import supports_cached_decode
        if not supports_cached_decode(self.model):
            return False
        from ...mesh_utils import get_global_mesh
        mesh = get_global_mesh()
        return mesh is None or not any(
            mesh.shape.get(a, 1) > 1 for a in ("pp", "mp", "sep"))

    # ------------------------------------------------------ cached path
    def _decoder_for(self, batch: int):
        entry = self._decoders.get(batch)
        if entry is None:
            from ....serving.generation.model_fns import CachedDecoder
            page_size = 16 if self.max_length >= 16 else self.max_length
            pages_per_seq = -(-self.max_length // page_size)
            dec = CachedDecoder(self.model, max_batch=batch,
                                page_size=page_size,
                                pages_per_seq=pages_per_seq)
            entry = self._decoders[batch] = (dec, page_size,
                                             pages_per_seq)
        return entry

    def _generate_cached(self, ids: np.ndarray, total: int,
                         temperature: float, seed: int):
        from ....serving.generation.sampling import sample_next_tokens

        b, prompt_len = ids.shape
        dec, page_size, pages_per_seq = self._decoder_for(b)
        dec.refresh_params()    # pick up weight updates between calls
        # contiguous per-row page ranges (page 0 is the trash page)
        tables = (1 + np.arange(b * pages_per_seq, dtype=np.int32)
                  .reshape(b, pages_per_seq))
        k, v = self.model.init_kv_pools(1 + b * pages_per_seq, page_size)
        lens = np.full(b, prompt_len, np.int32)
        last, k, v, _ = dec.prefill(ids, lens, tables, k, v)
        rng = np.random.RandomState(seed)
        done = np.zeros(b, bool)
        buf = np.full((b, total), self.pad_token_id, "int64")
        buf[:, :prompt_len] = ids
        step_logits = np.asarray(last)
        for pos in range(prompt_len, total):
            nxt = sample_next_tokens(step_logits, temperature, rng=rng)
            buf[:, pos] = np.where(done, self.pad_token_id, nxt)
            if self.eos_token_id is not None:
                done |= (nxt == self.eos_token_id)
                if done.all():
                    return buf[:, :pos + 1]
            if pos + 1 >= total:
                break
            # cache the chosen token at `pos`, get logits for pos+1;
            # finished rows keep decoding masked-off via `active`
            active = ~done
            logits, k, v, _ = dec.decode(
                buf[:, pos], np.full(b, pos, np.int32), active,
                np.where(active, pos + 1, pos).astype(np.int32),
                tables, k, v)
            step_logits = np.asarray(logits)
        return buf[:, :total]

    # ------------------------------------------------------ fallback
    def _full_window_generate(self, ids: np.ndarray, total: int,
                              temperature: float, seed: int):
        """The pre-KV-cache path: one full padded-window forward per
        emitted token (ONE compiled shape for all steps). Kept for
        models without cache support and as the decode-bench baseline."""
        import paddle_tpu as paddle

        from ....serving.generation.sampling import sample_next_tokens

        b, prompt_len = ids.shape
        buf = np.full((b, total), self.pad_token_id, "int64")
        buf[:, :prompt_len] = ids
        rng = np.random.RandomState(seed)
        done = np.zeros(b, bool)
        for pos in range(prompt_len, total):
            logits = self._logits(paddle.to_tensor(buf))
            # slice the one needed row ON DEVICE before the host
            # transfer — the full [b, total, vocab] tensor is ~200MB
            # at realistic vocab sizes
            step_logits = np.asarray(logits[:, pos - 1, :].numpy())
            nxt = sample_next_tokens(step_logits, temperature, rng=rng)
            buf[:, pos] = np.where(done, self.pad_token_id, nxt)
            if self.eos_token_id is not None:
                done |= (nxt == self.eos_token_id)
                if done.all():
                    return buf[:, :pos + 1]
        return buf[:, :total]
