"""Hybrid-parallel autoregressive inference helper.

Reference: python/paddle/distributed/fleet/utils/hybrid_parallel_inference.py
(HybridParallelInferenceHelper:27) rewrites a static Program so an
autoregressive decode loop runs pipeline-parallel. TPU-native collapse:
the model forward is already one SPMD program under the global mesh
(GSPMD handles tp/pp placement), so the helper only has to run the decode
loop — one jitted forward per emitted token at a fixed padded length
(a single compiled shape; XLA caches it), greedy or sampled selection on
the final-position logits.
"""
from __future__ import annotations

import numpy as np

__all__ = ["HybridParallelInferenceHelper"]


class HybridParallelInferenceHelper:
    """Greedy/sampling decode driver over a causal-LM ``Layer``.

    ``model(ids)`` must return logits ``[batch, seq, vocab]`` (optionally
    wrapped in a tuple/list, first element used). Works on a single chip
    and unchanged under a fleet mesh — sharding comes from the params'
    dist_spec annotations, not from this class.
    """

    def __init__(self, model, max_length: int = 128, eos_token_id=None,
                 pad_token_id: int = 0):
        self.model = model
        self.max_length = int(max_length)
        self.eos_token_id = eos_token_id
        self.pad_token_id = int(pad_token_id)

    def _logits(self, ids_tensor):
        out = self.model(ids_tensor)
        if isinstance(out, (tuple, list)):
            out = out[0]
        return out

    def generate(self, input_ids, max_new_tokens: int = 32,
                 temperature: float = 0.0, seed: int = 0):
        """Decode ``max_new_tokens`` tokens. temperature 0 = greedy;
        otherwise softmax sampling with a numpy RNG (host-side choice,
        device-side forward)."""
        import paddle_tpu as paddle

        ids = np.asarray(input_ids.numpy() if hasattr(input_ids, "numpy")
                         else input_ids).astype("int64")
        if ids.ndim == 1:
            ids = ids[None, :]
        b, prompt_len = ids.shape
        if prompt_len >= self.max_length:
            raise ValueError(
                f"prompt length {prompt_len} leaves no room to generate "
                f"within max_length={self.max_length}")
        total = min(self.max_length, prompt_len + int(max_new_tokens))
        # fixed padded window -> ONE compiled forward shape for all steps
        buf = np.full((b, total), self.pad_token_id, "int64")
        buf[:, :prompt_len] = ids
        rng = np.random.RandomState(seed)
        done = np.zeros(b, bool)
        was_training = getattr(self.model, "training", False)
        self.model.eval()
        try:
            for pos in range(prompt_len, total):
                logits = self._logits(paddle.to_tensor(buf))
                # slice the one needed row ON DEVICE before the host
                # transfer — the full [b, total, vocab] tensor is ~200MB
                # at realistic vocab sizes
                step_logits = np.asarray(logits[:, pos - 1, :].numpy())
                if temperature and temperature > 0.0:
                    z = step_logits / float(temperature)
                    z = z - z.max(-1, keepdims=True)
                    p = np.exp(z) / np.exp(z).sum(-1, keepdims=True)
                    nxt = np.array([rng.choice(p.shape[-1], p=p[i])
                                    for i in range(b)])
                else:
                    nxt = step_logits.argmax(-1)
                buf[:, pos] = np.where(done, self.pad_token_id, nxt)
                if self.eos_token_id is not None:
                    done |= (nxt == self.eos_token_id)
                    if done.all():
                        total = pos + 1
                        break
        finally:
            if was_training:
                self.model.train()
        return buf[:, :total]
