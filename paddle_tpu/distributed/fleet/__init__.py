"""paddle.distributed.fleet equivalent."""
from . import meta_parallel  # noqa: F401
from .distributed_strategy import DistributedStrategy  # noqa: F401
from .fleet_api import (  # noqa: F401
    barrier_worker, distributed_model, distributed_optimizer,
    get_hybrid_communicate_group, init, is_first_worker, is_initialized,
    save_inference_model, save_persistables, worker_index, worker_num,
)
from .topology import CommunicateTopology, HybridCommunicateGroup  # noqa: F401
from .util_data import (  # noqa: F401
    DataGenerator, MultiSlotDataGenerator, MultiSlotStringDataGenerator,
    Role, UtilBase,
)
from .fleet_api import _FleetAPIType as Fleet  # noqa: F401

PaddleCloudRoleMaker = None


class UserDefinedRoleMaker:
    def __init__(self, *a, **k):
        pass
from . import elastic  # noqa: F401
from . import utils  # noqa: F401
