"""Hybrid-parallel topology
(reference: /root/reference/python/paddle/distributed/fleet/base/topology.py:54,140).

CommunicateTopology / HybridCommunicateGroup keep the reference's exact rank
math (axis order "data","pipe","sharding","sep","model") but each axis group
is a mesh-axis view rather than an NCCL communicator; the same object also
owns the jax.sharding.Mesh used by the pjit training path.
"""
from __future__ import annotations

import itertools
from functools import reduce
from typing import Dict, List

import numpy as np

from .. import env
from ..group import Group, new_group
from ..mesh_utils import build_mesh, set_global_mesh

_AXIS_TO_MESH_NAME = {"data": "dp", "pipe": "pp", "sharding": "sharding",
                      "sep": "sep", "expert": "ep", "model": "mp"}


class CommunicateTopology:
    def __init__(self, hybrid_group_names=("data", "pipe", "sharding", "model"),
                 dims=(1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self.coordinate = list(itertools.product(*[range(d) for d in dims]))
        self._world_size = reduce(lambda x, y: x * y, self._dims, 1)
        self._coord2rank = {c: i for i, c in enumerate(self.coordinate)}
        self._rank2coord = {i: c for c, i in self._coord2rank.items()}

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return self._world_size

    def get_rank(self, **args):
        key = tuple(args[name] for name in self._parallel_names)
        return self._coord2rank[key]

    def get_coord(self, rank):
        return self._rank2coord[rank]

    def get_axis_list(self, axis_name, index):
        axis = self._parallel_names.index(axis_name)
        return [rank for coord, rank in self._coord2rank.items()
                if coord[axis] == index]

    def get_comm_list(self, axis_name):
        """All rank-lists along axis_name (one per setting of other axes)."""
        axis = self._parallel_names.index(axis_name)
        other = [n for i, n in enumerate(self._parallel_names) if i != axis]
        ranges = [range(self.get_dim(n)) for n in other]
        out = []
        for combo in itertools.product(*ranges):
            grp = []
            for i in range(self._dims[axis]):
                coord = {}
                for n, v in zip(other, combo):
                    coord[n] = v
                coord[axis_name] = i
                grp.append(self.get_rank(**coord))
            out.append(grp)
        return out

    def get_rank_from_stage(self, global_rank, **kwargs):
        coord = list(self.get_coord(global_rank))
        for k, v in kwargs.items():
            coord[self._parallel_names.index(k)] = v
        return self._coord2rank[tuple(coord)]


class HybridCommunicateGroup:
    def __init__(self, topology: CommunicateTopology):
        self._topo = topology
        self.global_rank = env.global_rank()
        self._dp_degree = self._topo.get_dim("data")
        self._pp_degree = self._topo.get_dim("pipe")
        self._sharding_degree = self._topo.get_dim("sharding")
        self._mp_degree = self._topo.get_dim("model")
        self._sep_degree = (self._topo.get_dim("sep")
                            if "sep" in self._topo.get_hybrid_group_names()
                            else 1)

        # groups per axis (mesh-axis views)
        self._dp_group = self._make_group("data", "dp")
        self._pp_group = self._make_group("pipe", "pp")
        self._sharding_group = self._make_group("sharding", "sharding")
        self._mp_group = self._make_group("model", "mp")
        if "expert" in self._topo.get_hybrid_group_names():
            self._ep_group = self._make_group("expert", "ep")
        else:
            self._ep_group = None

        # the device mesh for compiled parallelism (only when enough devices)
        try:
            axes = {}
            for name in self._topo.get_hybrid_group_names():
                axes[_AXIS_TO_MESH_NAME[name]] = self._topo.get_dim(name)
            self.mesh = build_mesh(axes)
            set_global_mesh(self.mesh)
        except ValueError:
            self.mesh = None

    def _make_group(self, axis_name, mesh_axis) -> Group:
        comm_lists = self._topo.get_comm_list(axis_name)
        my = [g for g in comm_lists if self.global_rank in g]
        ranks = my[0] if my else [self.global_rank]
        return new_group(ranks, mesh_axis=mesh_axis)

    # paddle topology API surface
    def get_parallel_mode(self):
        if self._mp_degree == 1 and self._pp_degree == 1 and \
                self._sharding_degree == 1:
            return "data_parallel" if self._dp_degree > 1 else "single"
        return "hybrid_parallel"

    def topology(self):
        return self._topo

    def get_global_rank(self):
        return self.global_rank

    # data parallel
    def get_data_parallel_rank(self):
        return self._dp_group.rank if self._dp_group.nranks > 1 else 0

    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_data_parallel_group(self):
        return self._dp_group

    def get_data_parallel_group_src_rank(self):
        return self._dp_group.ranks[0]

    # model (tensor) parallel
    def get_model_parallel_rank(self):
        return self._mp_group.rank if self._mp_group.nranks > 1 else 0

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_model_parallel_group(self):
        return self._mp_group

    def get_model_parallel_group_src_rank(self):
        return self._mp_group.ranks[0]

    # pipeline
    def get_stage_id(self):
        return self._pp_group.rank if self._pp_group.nranks > 1 else 0

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_pipe_parallel_group(self):
        return self._pp_group

    def is_first_stage(self):
        return self.get_stage_id() == 0

    def is_last_stage(self):
        return self.get_stage_id() == self._pp_degree - 1

    # sharding
    def get_sharding_parallel_rank(self):
        return self._sharding_group.rank if self._sharding_group.nranks > 1 else 0

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sharding_parallel_group(self):
        return self._sharding_group

    def get_sharding_parallel_group_src_rank(self):
        return self._sharding_group.ranks[0]

    def get_sep_parallel_world_size(self):
        return self._sep_degree
