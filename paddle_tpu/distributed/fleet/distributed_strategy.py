"""DistributedStrategy — the fleet feature-toggle surface.

Reference: /root/reference/python/paddle/distributed/fleet/base/
distributed_strategy.py:117 (protobuf-backed, ~40 toggles; SURVEY §2.6 lists
the property lines). Here it is a plain typed object with the same names;
toggles that XLA subsumes (fuse_all_reduce_ops, nccl knobs, ...) are accepted
and recorded but change nothing — documented inert.
"""
from __future__ import annotations

import copy
import json


_DEFAULT_CONFIGS = {
    "amp_configs": {
        "init_loss_scaling": 32768.0, "incr_every_n_steps": 1000,
        "decr_every_n_nan_or_inf": 2, "incr_ratio": 2.0, "decr_ratio": 0.5,
        "use_dynamic_loss_scaling": True, "custom_white_list": [],
        "custom_black_list": [], "use_pure_fp16": False, "use_bf16": True,
        "use_fp16_guard": True,
    },
    "recompute_configs": {"checkpoints": [], "enable_offload": False,
                          "checkpoint_shape": []},
    "pipeline_configs": {"micro_batch_size": 1, "accumulate_steps": 1,
                         "schedule_mode": "1F1B", "p2p_cache_shape": True},
    "hybrid_configs": {"dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
                       "sharding_degree": 1, "sep_degree": 1},
    "sharding_configs": {"sharding_segment_strategy": "segment_broadcast_MB",
                         "segment_broadcast_MB": 32, "sharding_degree": 8,
                         "mp_degree": 1, "stage": 1, "offload": False},
    "gradient_merge_configs": {"k_steps": 1, "avg": True},
    "localsgd_configs": {"k_steps": 1, "begin_step": 1},
    "adaptive_localsgd_configs": {"init_k_steps": 1, "begin_step": 1},
    "dgc_configs": {"rampup_begin_step": 0, "rampup_step": 1,
                    "sparsity": [0.999]},
    "lars_configs": {"lars_coeff": 0.001, "lars_weight_decay": 0.0005,
                     "epsilon": 0, "exclude_from_weight_decay": []},
    "lamb_configs": {"lamb_weight_decay": 0.01,
                     "exclude_from_weight_decay": []},
    "a_sync_configs": {"k_steps": -1, "max_merge_var_num": 1,
                       "send_queue_size": 16,
                       "independent_recv_thread": False},
    "qat_configs": {"channel_wise_abs_max": True, "weight_bits": 8,
                    "activation_bits": 8, "not_quant_pattern": []},
    "tensor_parallel_configs": {"tensor_parallel_degree": 1,
                                "tensor_init_seed": -1},
}

_BOOL_TOGGLES = [
    "amp", "asp", "recompute", "sync_nccl_allreduce",
    "use_hierarchical_allreduce", "sync_batch_norm", "fuse_all_reduce_ops",
    "find_unused_parameters", "sharding", "without_graph_optimization",
    "fuse_grad_merge", "pipeline", "tensor_parallel", "localsgd",
    "adaptive_localsgd", "dgc", "gradient_merge", "lars", "lamb", "elastic",
    "auto", "semi_auto", "auto_search", "qat", "heter_ccl_mode", "a_sync",
    "fp16_allreduce", "adam_d2sum", "is_fl_ps_mode", "is_with_coordinator",
    "cudnn_exhaustive_search", "cudnn_batchnorm_spatial_persistent",
    "_calc_comm_same_stream", "split_data",
]

# inert numeric/str knobs accepted with reference defaults (the full
# property surface of distributed_strategy.py:117; XLA subsumes the
# behavior, the names must not AttributeError — SURVEY §2.6)
_SCALAR_DEFAULTS = {
    "nccl_comm_num": 1,
    "fuse_grad_size_in_MB": 32,
    "fuse_grad_size_in_num": 8,
    "last_comm_group_size_MB": 1,
    "_fuse_grad_size_in_TFLOPS": 50.0,
    "conv_workspace_size_limit": 512,
    "hierarchical_allreduce_inter_nranks": 1,
    "fs_client_param": None,
    "sparse_table_configs": None,
    "trainer_desc_configs": None,
    "gradient_scale_configs": {"scale_strategy": "avg"},
}


class DistributedStrategy:
    def __init__(self):
        self.__dict__["_flags"] = {t: False for t in _BOOL_TOGGLES}
        self.__dict__["_configs"] = copy.deepcopy(_DEFAULT_CONFIGS)
        self.__dict__["_scalars"] = copy.deepcopy(_SCALAR_DEFAULTS)
        # execution/build strategy accepted for compat
        self.__dict__["execution_strategy"] = None
        self.__dict__["build_strategy"] = None

    def __getattr__(self, name):
        flags = self.__dict__["_flags"]
        configs = self.__dict__["_configs"]
        scalars = self.__dict__["_scalars"]
        if name in flags:
            return flags[name]
        if name in configs:
            return configs[name]
        if name in scalars:
            return scalars[name]
        raise AttributeError(name)

    def __setattr__(self, name, value):
        flags = self.__dict__["_flags"]
        configs = self.__dict__["_configs"]
        scalars = self.__dict__["_scalars"]
        if name in flags:
            flags[name] = bool(value)
        elif name in configs:
            merged = dict(configs[name])
            merged.update(value or {})
            configs[name] = merged
        elif name in ("execution_strategy", "build_strategy"):
            self.__dict__[name] = value
        else:
            scalars[name] = value

    def to_json(self):
        return json.dumps({"flags": self._flags, "configs": self._configs,
                           "scalars": {k: v for k, v in self._scalars.items()
                                       if isinstance(v, (int, float, str,
                                                         list, dict))}})

    def __repr__(self):
        on = [k for k, v in self._flags.items() if v]
        return f"DistributedStrategy(enabled={on})"
