"""Wheel build (reference: /root/reference/setup.py building the
paddlepaddle wheel embedding libpaddle.so). The native C++ components
(TCPStore, shm ring, host tracer — paddle_tpu/native/csrc) are compiled
on first use against the host toolchain rather than shipped as a binary,
so the wheel is pure-python + sources; `python -m build` or
`pip install .` both work from this file alone."""
from setuptools import setup

setup()
