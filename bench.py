"""Benchmark: GPT causal-LM training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
The reference publishes no in-repo numbers (SURVEY §6/BASELINE.md); the
headline target is MFU-based (>=45% on the GPT config), so vs_baseline is
measured_MFU / 0.45.

Usage: python bench.py [--smoke]
"""
import argparse
import json
import os
import sys
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny config on CPU for CI/verify")
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--no-amp", action="store_true",
                    help="disable bf16 autocast (default: O1 bf16, the "
                         "reference's AMP GPT configuration)")
    args = ap.parse_args()

    import jax

    if args.smoke:
        jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as paddle
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models import (GPTForCausalLM, GPTPretrainingCriterion,
                                   gpt_tiny, gpt2_small)

    paddle.seed(0)
    if args.smoke:
        cfg = gpt_tiny(use_flash_attention=False)
        batch, seq = 2, 64
    else:
        cfg = gpt2_small(max_seq_len=512)
        batch, seq = 8, 512

    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion()
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    amp_level = None if (args.smoke or args.no_amp) else "O1"
    step = TrainStep(model, lambda out, y: crit(out, y), opt,
                     amp_level=amp_level)

    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, seq)).astype("int64"))

    loss = step(ids, ids)  # compile + first step
    for _ in range(max(args.warmup - 1, 0)):
        loss = step(ids, ids)
    float(loss.numpy())  # sync

    t0 = time.perf_counter()
    for _ in range(args.steps):
        loss = step(ids, ids)
    final = float(loss.numpy())  # sync
    dt = time.perf_counter() - t0

    steps_per_sec = args.steps / dt
    tokens_per_sec = steps_per_sec * batch * seq

    n_params = model.num_params()
    # 6*N FLOPs/token (fwd+bwd) + attention term 12*L*H*S per token
    attn_flops = 12 * cfg.num_layers * cfg.hidden_size * seq
    flops_per_token = 6 * n_params + attn_flops
    achieved = tokens_per_sec * flops_per_token
    peak = float(os.environ.get("BENCH_PEAK_FLOPS", 197e12))  # v5e bf16
    mfu = achieved / peak
    assert np.isfinite(final), "loss diverged"

    print(json.dumps({
        "metric": "gpt2s_train_tokens_per_sec" if not args.smoke
                  else "gpt_tiny_smoke_tokens_per_sec",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.45, 4) if not args.smoke else 1.0,
    }))


if __name__ == "__main__":
    sys.exit(main())
