"""Benchmark: GPT causal-LM training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
The reference publishes no in-repo numbers (SURVEY §6/BASELINE.md); the
headline target is MFU-based (>=45% on the GPT config), so vs_baseline is
measured_MFU / 0.45. See PERF.md for the measured decomposition and the
machine ceiling analysis.

Methodology: K training steps run inside ONE compiled program
(TrainStep.run_steps — lax.scan over the step), the only host sync is the
final loss fetch, and the best of several windows is reported: the runtime
tunnel on this host adds multi-ms, high-variance per-dispatch overhead
that would otherwise dominate the measurement.

Usage: python bench.py [--smoke]
       [--config small|medium|large|1.3b|bert|resnet50]
       [--batch N] [--moment-dtype float32|bfloat16] [--amp O1|O2]
       [--recompute full|dots|none] [--steps K] [--windows W] [--no-amp]
"""
import argparse
import json
import os
import sys
import time

import numpy as np


# The classifier AND the wedge-safe subprocess probes live in
# tools/_bench_common.py (shared by every tools/bench_*.py and by
# tools/shardcheck.py's topology probe); the BENCH_r04 root cause —
# probe succeeds, tunnel wedges, the FIRST in-process eager op (a
# convert_element_type on the 1.3B path) surfaces backend-unavailable
# looking like a dtype bug — is documented there. The aliases keep
# this bench's public shape (tests monkeypatch bench._probe_backend).
from tools._bench_common import (  # noqa: E402
    backend_unavailable as _backend_unavailable,
    probe_backend as _probe_backend,
    skip_record as _skip_record,
)


def _bench_resnet(args, paddle, TrainStep):
    """BASELINE config 2: ResNet-50 training images/s (vs_baseline is
    images/s / 2000 — a round v5e single-chip waypoint, no published
    reference number exists). Default layout is NHWC, the MXU-native
    fast path (round-4 measured +11% over NCHW; the input pipeline
    produces channels-last directly — a real TPU training setup decodes
    HWC images anyway). ``--layout nchw`` re-measures the reference's
    layout. The extra "mfu" key uses 3x the 4.089 GFLOP/img fwd cost
    (fwd + 2x bwd, conv-dominated)."""
    import paddle_tpu.nn.functional as F
    from paddle_tpu.vision.models import resnet50

    layout = (args.layout or "nhwc").upper()
    model = resnet50(num_classes=1000, data_format=layout)
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    parameters=model.parameters())
    amp = None if args.no_amp else (args.amp or "O2")
    step = TrainStep(model, lambda o, y: F.cross_entropy(o, y), opt,
                     amp_level=amp)
    batch = args.batch or 128
    rng = np.random.RandomState(0)
    shape = (batch, 3, 224, 224) if layout == "NCHW" \
        else (batch, 224, 224, 3)
    x = paddle.to_tensor(rng.randn(*shape).astype("float32"))
    y = paddle.to_tensor(rng.randint(0, 1000, (batch,)).astype("int64"))
    K = max(args.steps, 1)
    loss = step.run_steps(K, x, y)
    assert np.isfinite(float(loss.numpy()))
    best = 0.0
    for _ in range(max(args.windows, 1)):
        t0 = time.perf_counter()
        loss = step.run_steps(K, x, y)
        float(loss.numpy())
        best = max(best, K * batch / (time.perf_counter() - t0))
    peak = float(os.environ.get("BENCH_PEAK_FLOPS", 197e12))
    mfu = best * 3 * 4.089e9 / peak
    print(json.dumps({"metric": "resnet50_train_images_per_sec",
                      "skipped": False,
                      "value": round(best, 1), "unit": "images/s",
                      "vs_baseline": round(best / 2000.0, 4),
                      "mfu": round(mfu, 4), "layout": layout}))


def _bench_bert(args, paddle, TrainStep):
    """BASELINE config 3: BERT-base MLM+NSP pretraining tokens/s
    (measured ~124,000 / 45.2% MFU at b=32 s=512 AMP O2, 40-step
    windows; MFU-based vs_baseline like the GPT configs)."""
    from paddle_tpu.models import (BertConfig, BertForPretraining,
                                   BertPretrainingCriterion)

    cfg = BertConfig(hidden_dropout_prob=0.0,
                     attention_probs_dropout_prob=0.0)
    model = BertForPretraining(cfg)
    crit = BertPretrainingCriterion(ignore_index=-1000)  # bench labels
    # are dense random ids, none ignored

    def loss_fn(out, labels, nsp_labels):
        return crit(out, labels, nsp_labels)

    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 moment_dtype=args.moment_dtype
                                 or "float32")
    amp = None if args.no_amp else (args.amp or "O2")
    step = TrainStep(model, loss_fn, opt, amp_level=amp)
    batch, seq = (args.batch or 32), 512
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, seq)).astype("int64"))
    nsp = paddle.to_tensor(rng.randint(0, 2, (batch,)).astype("int64"))
    K = max(args.steps, 1)
    loss = step.run_steps(K, ids, ids, nsp, n_inputs=1)
    assert np.isfinite(float(loss.numpy()))
    best = 0.0
    for _ in range(max(args.windows, 1)):
        t0 = time.perf_counter()
        loss = step.run_steps(K, ids, ids, nsp, n_inputs=1)
        float(loss.numpy())
        best = max(best, K * batch * seq / (time.perf_counter() - t0))
    n = model.num_params()
    fpt = 6 * n + 12 * cfg.num_layers * cfg.hidden_size * seq
    peak = float(os.environ.get("BENCH_PEAK_FLOPS", 197e12))
    print(json.dumps({"metric": "bert_base_pretrain_tokens_per_sec",
                      "skipped": False,
                      "value": round(best, 1), "unit": "tokens/s",
                      "vs_baseline": round(best * fpt / peak / 0.45, 4)}))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny config on CPU for CI/verify")
    ap.add_argument("--config", default="1.3b",
                    choices=["small", "medium", "large", "1.3b",
                             "resnet50", "bert"],
                    help="default is the BASELINE north-star (GPT-3 1.3B "
                         "b=2 s=2048 single chip, measured 49.9%% MFU); "
                         "medium is the short-seq headline (51.8%%)")
    ap.add_argument("--batch", type=int, default=0,
                    help="override batch size (0 = config default)")
    ap.add_argument("--seq", type=int, default=0,
                    help="override sequence length (gpt configs; 0 = "
                         "config default). Long-context rows: "
                         "--config medium --seq 4096 --batch 2")
    ap.add_argument("--moment-dtype", default=None,
                    choices=["float32", "bfloat16"])
    ap.add_argument("--layout", default=None, choices=["nhwc", "nchw"],
                    help="resnet50 activation layout (default nhwc, the "
                         "MXU-native fast path)")
    ap.add_argument("--recompute", default=None,
                    choices=["full", "dots", "attn", "none"],
                    help="stacked-decoder recompute policy (large and "
                         "1.3b configs; their default 'full' is the only "
                         "policy that fits HBM)")
    ap.add_argument("--steps", type=int, default=40,
                    help="steps per compiled window (40 amortizes the "
                         "host dispatch tunnel to <0.5%%; saturated by 80)")
    ap.add_argument("--windows", type=int, default=3)
    ap.add_argument("--input-pipeline", action="store_true",
                    help="feed every step from io.DataLoader (shm_ring "
                         "workers) instead of one resident synthetic "
                         "batch — measures the real ingestion path "
                         "(PERF.md 'with input pipeline' row)")
    ap.add_argument("--workers", type=int, default=2,
                    help="DataLoader workers for --input-pipeline")
    ap.add_argument("--amp", default="O2", choices=["O1", "O2"],
                    help="autocast level (default O2 pure-bf16 with f32 "
                         "master params: measured 43.0%% vs O1's 40.8%% "
                         "MFU at gpt2-medium, identical loss trajectory)")
    ap.add_argument("--no-amp", action="store_true",
                    help="disable bf16 autocast entirely")
    args = ap.parse_args()

    if args.smoke:
        import jax

        jax.config.update("jax_platforms", "cpu")
        return _run(args)
    # never touch jax in-process until a subprocess probe confirms the
    # backend initializes: a wedged tunnel would hang us unrecoverably
    platform, diag, probe = _probe_backend()
    if platform is not None and platform not in ("tpu", "axon"):
        # jax can fall back to CPU silently when TPU init fails
        # non-fatally — a 1-core CPU "bench" would hang the driver
        # or report a meaningless number, so treat it as unavailable
        platform, diag = None, f"probe fell back to {platform!r}"
    if platform is None:
        # the shared structured skip record (tools/_bench_common.py):
        # "no measurement" stays distinguishable from "measured zero",
        # and the probe record says how the retry budget was spent
        print(json.dumps(_skip_record(
            f"TPU backend unreachable, bench skipped: {diag}",
            probe=probe)))
        return 0
    try:
        return _run(args)
    except Exception as e:  # noqa: BLE001 - the probe-to-first-op race:
        # the backend can wedge AFTER a successful subprocess probe, in
        # which case the first in-process eager dispatch (whatever op it
        # happens to be — BENCH_r04 died inside convert_element_type)
        # raises backend-unavailable. That is a skip, not a crash.
        if not _backend_unavailable(e):
            raise
        print(json.dumps(_skip_record(
            ("TPU backend wedged after a successful probe, "
             f"bench skipped: {type(e).__name__}: {str(e)[:300]}"),
            probe=probe)))
        return 0


# hand-vs-cost-model agreement bound: divergence beyond this from BOTH
# analytic candidates (plain 6N, full-remat ~8N) fails the record
_COST_AGREE_TOL = 0.15


def _train_cost_model_check(batch, seq, n_params, attn_flops):
    """XLA cost-model FLOPs of the train executable that actually ran
    (xstats registry) vs the hand formula. Returns the record section;
    ``available`` is False when no analysis could be read (the bench
    then reports the hand number alone instead of failing)."""
    out = {"available": False}
    try:
        from paddle_tpu.observability import xstats
        reg = xstats.default_exec_registry()
        ents = [e for e in reg.entries()
                if e.site == "train_step" and e.dispatches]
        if not ents:
            return out
        ent = max(ents, key=lambda e: e.last_dispatch_unix_ms or 0)
        ana = reg.ensure_analysis(ent)
        if not ana or not ana.get("flops"):
            out["error"] = ent.analysis_error
            return out
        # a run_steps window executable wraps K steps in a lax.scan;
        # XLA's HLO cost analysis counts the while BODY once (it does
        # not multiply by trip count), so the per-token normalization
        # tries both readings and keeps the closer one — either way a
        # real model-shape drift moves the FLOPs far beyond the bound
        tag = ent.signature[0][1] if ent.signature else "tag:single"
        steps = int(tag.rsplit(":", 1)[1]) if "multi" in tag else 1
        per_token = {"body_once": ana["flops"] / (batch * seq),
                     "times_steps":
                     ana["flops"] / (steps * batch * seq)}
        hand = 6 * n_params + attn_flops
        # full remat re-runs the forward inside the backward: ~one
        # extra model forward (2N) and a second attention pass
        hand_remat = 8 * n_params + 2 * attn_flops
        ratios = {f"{k}_vs_{h}": cm / hv
                  for k, cm in per_token.items()
                  for h, hv in (("plain", hand), ("remat", hand_remat))}
        best_key = min(ratios, key=lambda k: abs(ratios[k] - 1.0))
        out.update({
            "available": True,
            "flops_per_token": round(
                per_token["body_once" if "body_once" in best_key
                          else "times_steps"], 1),
            "hand_flops_per_token": float(hand),
            "hand_remat_flops_per_token": float(hand_remat),
            "ratios": {k: round(v, 4) for k, v in ratios.items()},
            "best": best_key,
            "agrees": abs(ratios[best_key] - 1.0) <= _COST_AGREE_TOL,
            "exec_flops": ana["flops"],
            "window_steps": steps,
        })
    except Exception as e:  # noqa: BLE001 - the cross-check must not
        out["error"] = f"{type(e).__name__}: {e}"  # sink a bench run
    return out


def _run(args):
    import jax  # noqa: F401 - the backend may init at first op below

    import paddle_tpu as paddle
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models import (GPTForCausalLM, GPTPretrainingCriterion,
                                   gpt_tiny, gpt2_large, gpt2_medium,
                                   gpt2_small, gpt3_1p3b)

    paddle.seed(0)
    if args.config in ("resnet50", "bert"):
        if args.smoke:
            raise SystemExit(
                f"--smoke runs the gpt-tiny CPU config only; run "
                f"--config {args.config} without --smoke (real chip)")
        if args.config == "resnet50":
            return _bench_resnet(args, paddle, TrainStep)
        return _bench_bert(args, paddle, TrainStep)
    if args.smoke:
        cfg = gpt_tiny(use_flash_attention=False)
        batch, seq = 2, 64
        metric = "gpt_tiny_smoke_tokens_per_sec"
    elif args.config == "small":
        cfg = gpt2_small(max_seq_len=512)
        batch, seq = 8, 512
        metric = "gpt2s_train_tokens_per_sec"
    elif args.config == "large":
        # 774M: stacked scan decoder; at b=8 s=1024 only full recompute +
        # bf16 optimizer moments fit the 15.75 GB chip ("dots" saves ~7.5GB
        # of matmul outputs across 36 layers and OOMs). Measured 25.5% MFU
        # vs medium's 30.6% — the +33% recompute FLOPs outweigh the better
        # H=1280 matmul shapes, which is why medium stays the default.
        cfg = gpt2_large(stacked=True,
                         recompute=args.recompute or "full")
        batch, seq = 8, 1024
        metric = "gpt2l_train_tokens_per_sec"
        if args.moment_dtype is None:
            args.moment_dtype = "bfloat16"
    elif args.config == "1.3b":
        # BASELINE north-star model on ONE chip: stacked scan + full
        # remat + bf16 moments + flash attention (s>=2048) fit 1.3B in
        # 15.75 GB; measured 7,313 tok/s (33.8% MFU) b=2 s=2048
        cfg = gpt3_1p3b(stacked=True, recompute=args.recompute or "full")
        batch, seq = 2, 2048
        metric = "gpt3_1p3b_train_tokens_per_sec"
        if args.moment_dtype is None:
            args.moment_dtype = "bfloat16"
    else:
        cfg = gpt2_medium(max_seq_len=512)
        batch, seq = 16, 512
        metric = "gpt2m_train_tokens_per_sec"
    if args.batch:
        batch = args.batch
    if args.seq and not args.smoke:
        seq = args.seq
        # rebuild the config with a matching context window (and stacked
        # full-remat for the long-context rows, which need O(S) memory)
        base = {"small": gpt2_small, "medium": gpt2_medium,
                "large": gpt2_large, "1.3b": gpt3_1p3b}.get(args.config)
        if base is not None:
            kw = dict(max_seq_len=seq)
            if seq >= 4096 or args.config in ("large", "1.3b"):
                kw.update(stacked=True, recompute=args.recompute or "full")
                if args.moment_dtype is None:
                    args.moment_dtype = "bfloat16"
            cfg = base(**kw)
            metric = f"{metric[:metric.index('_train')]}_s{seq}" \
                     "_train_tokens_per_sec"

    from paddle_tpu.framework.flags import flag_value
    if not args.smoke and getattr(cfg, "use_flash_attention", True) and \
            seq >= int(flag_value("FLAGS_flash_min_seqlen")):
        # flash kicks in at FLAGS_flash_min_seqlen (2048): autotune the
        # block sizes for THIS attention shape eagerly (fwd+bwd timing,
        # persisted) — the traced TrainStep picks the winner up through
        # the "mha_step" cache instead of the static 512x1024 default
        from paddle_tpu.ops import flash_attention
        # key the tuning on the dtype attention will actually run in
        # (bf16 under AMP autocast, f32 under --no-amp) or the cache
        # entry can never be hit by the traced dispatch
        tune_dtype = "float32" if args.no_amp else "bfloat16"
        picked = flash_attention.pretune(
            batch, cfg.num_heads, seq, cfg.hidden_size // cfg.num_heads,
            dtype=tune_dtype)
        if picked:
            print(f"# flash pretune s={seq}: block_q={picked[0]} "
                  f"block_k={picked[1]}", file=sys.stderr)

    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion()
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 moment_dtype=args.moment_dtype or "float32")
    amp_level = None if (args.smoke or args.no_amp) else args.amp
    step = TrainStep(model, lambda out, y: crit(out, y), opt,
                     amp_level=amp_level)

    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, seq)).astype("int64"))

    K = max(args.steps, 1)
    if args.input_pipeline:
        # real ingestion: every step's batch comes through io.DataLoader
        # (multiprocess workers + shm_ring transport). Steps dispatch
        # asynchronously; the loss fetch at window end is the only sync,
        # so host-side loading overlaps device compute.
        import paddle_tpu.io as io

        class TokenDataset(io.Dataset):
            def __init__(self, n):
                self.n = n

            def __len__(self):
                return self.n

            def __getitem__(self, i):
                r = np.random.RandomState(i)
                return r.randint(0, cfg.vocab_size, (seq,)).astype("int64")

        n_batches = K * (args.windows + 1) + 2
        loader = io.DataLoader(TokenDataset(n_batches * batch),
                               batch_size=batch, shuffle=False,
                               num_workers=args.workers, drop_last=True)
        it = iter(loader)

        def one_window():
            loss = None
            for _ in range(K):
                b = next(it)
                if isinstance(b, (list, tuple)):
                    b = b[0]
                loss = step(b, b)
            return float(loss.numpy())     # single sync per window

        final = one_window()               # compile + warm
        best = 0.0
        for _ in range(max(args.windows, 1)):
            t0 = time.perf_counter()
            final = one_window()
            dt = time.perf_counter() - t0
            best = max(best, K * batch * seq / dt)
        metric += "_pipelined"
    else:
        loss = step.run_steps(K, ids, ids)     # compile + warm window
        final = float(loss.numpy())

        best = 0.0
        for _ in range(max(args.windows, 1)):
            t0 = time.perf_counter()
            loss = step.run_steps(K, ids, ids)
            final = float(loss.numpy())        # the only sync point
            dt = time.perf_counter() - t0
            best = max(best, K * batch * seq / dt)

    n_params = model.num_params()
    # 6*N FLOPs/token (fwd+bwd) + attention term 12*L*H*S per token
    attn_flops = 12 * cfg.num_layers * cfg.hidden_size * seq
    flops_per_token = 6 * n_params + attn_flops
    achieved = best * flops_per_token
    peak = float(os.environ.get("BENCH_PEAK_FLOPS", 197e12))  # v5e bf16
    mfu = achieved / peak
    assert np.isfinite(final), "loss diverged"

    # cost-model cross-check: the XLA-counted FLOPs of the executable
    # that actually ran (xstats registry) against the hand formula the
    # MFU headline is derived from — silent model-shape drift in the
    # hand 6ND would show up here as divergence. Full-remat configs
    # legitimately execute ~an extra forward (8N-ish), so agreement is
    # judged against the closer of the two analytic candidates.
    cost_model = _train_cost_model_check(batch, seq, n_params,
                                         attn_flops)

    print(json.dumps({
        "metric": metric,
        "skipped": False,
        "value": round(best, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.45, 4) if not args.smoke else 1.0,
        "cost_model": cost_model,
    }))
    if cost_model.get("available") and not cost_model["agrees"]:
        print(f"# FAIL: cost-model FLOPs/token "
              f"{cost_model['flops_per_token']:.3e} diverges "
              f">{int(_COST_AGREE_TOL * 100)}% from the hand formula "
              f"({cost_model['hand_flops_per_token']:.3e} plain / "
              f"{cost_model['hand_remat_flops_per_token']:.3e} remat)",
              file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
