"""Build and run the native C++ unit tests (SURVEY §4.6: the reference
colocates C++ gtests with each native library; here assert-style checks
in native/csrc/native_test.cc cover TCPStore, shm_ring, host tracer)."""
import os
import shutil
import subprocess

import pytest

CSRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "paddle_tpu", "native", "csrc")


@pytest.mark.skipif(shutil.which("g++") is None, reason="no C++ toolchain")
def test_native_cc_suites(tmp_path):
    exe = str(tmp_path / "native_test")
    build = subprocess.run(
        ["g++", "-O1", "-std=c++17", "-pthread",
         os.path.join(CSRC, "native_test.cc"),
         os.path.join(CSRC, "tcp_store.cc"),
         os.path.join(CSRC, "shm_ring.cc"),
         os.path.join(CSRC, "host_tracer.cc"),
         "-lrt", "-o", exe],
        capture_output=True, text=True, timeout=180)
    assert build.returncode == 0, build.stderr[-2000:]
    run = subprocess.run([exe], capture_output=True, text=True, timeout=120)
    assert run.returncode == 0, (run.stdout[-1000:], run.stderr[-2000:])
    assert "3 suites passed" in run.stdout
