"""Decode serving: paged KV cache, cached decode correctness, and the
continuous-batching GenerationServer (paddle_tpu/serving/generation)."""
import os
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import GPTForCausalLM, GPTKVCache, gpt_tiny
from paddle_tpu.serving import DeadlineExceededError, QueueFullError
from paddle_tpu.serving.generation import (GenerationServer, PagedKVCache,
                                           sample_next_tokens)
from paddle_tpu.serving.generation.model_fns import (CachedDecoder,
                                                     supports_cached_decode)


def make_model(**kw):
    paddle.seed(0)
    cfg = gpt_tiny(use_flash_attention=False, **kw)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m, cfg


def make_tables(batch, pages_per_seq):
    """Contiguous per-row page ranges skipping trash page 0."""
    return (1 + np.arange(batch * pages_per_seq, dtype=np.int32)
            .reshape(batch, pages_per_seq))


# ---------------------------------------------------------------- ops
class TestPagedOps:
    def test_write_gather_roundtrip(self):
        import jax.numpy as jnp

        from paddle_tpu.ops import paged_attention as pa
        pool = jnp.zeros((5, 4, 2, 3))
        tables = np.array([[2, 4], [1, 3]], np.int32)
        kv = np.arange(2 * 8 * 2 * 3, dtype=np.float32).reshape(2, 8, 2, 3)
        positions = np.broadcast_to(np.arange(8, dtype=np.int32), (2, 8))
        valid = np.ones((2, 8), bool)
        slots = pa.flat_slots(jnp.asarray(tables), jnp.asarray(positions),
                              jnp.asarray(valid), 4)
        pool = pa.write_pool(pool, np.asarray(slots).reshape(-1),
                             kv.reshape(-1, 2, 3))
        out = np.asarray(pa.gather_pool(pool, jnp.asarray(tables)))
        np.testing.assert_array_equal(out, kv)

    def test_invalid_positions_hit_trash_page_only(self):
        import jax.numpy as jnp

        from paddle_tpu.ops import paged_attention as pa
        pool = jnp.full((3, 4, 1, 2), -7.0)
        tables = np.array([[1, 2]], np.int32)
        positions = np.broadcast_to(np.arange(8, dtype=np.int32), (1, 8))
        valid = np.zeros((1, 8), bool)     # everything masked
        slots = pa.flat_slots(jnp.asarray(tables), jnp.asarray(positions),
                              jnp.asarray(valid), 4)
        assert int(np.asarray(slots).max()) < 4   # all in page 0
        pool2 = pa.write_pool(pool, np.asarray(slots).reshape(-1),
                              np.ones((8, 1, 2), np.float32))
        np.testing.assert_array_equal(np.asarray(pool2[1:]),
                                      np.asarray(pool[1:]))


# ------------------------------------------------- allocator/kv cache
class TestPagedKVCache:
    def test_alloc_free_reuse(self):
        m, _ = make_model()
        kv = PagedKVCache(m, num_pages=5, page_size=4)
        assert kv.capacity == 4 and kv.free_pages == 4
        a = kv.alloc(3)
        assert len(a) == 3 and 0 not in a
        assert kv.alloc(2) is None          # all-or-nothing
        assert kv.free_pages == 1           # failed alloc took nothing
        kv.free(a)
        assert kv.free_pages == 4
        assert kv.evicted_pages_total == 3
        b = kv.alloc(4)
        assert sorted(b) == [1, 2, 3, 4]    # freed pages reused
        assert kv.pages_for(1) == 1 and kv.pages_for(9) == 3

    def test_trash_page_never_allocated_and_double_free_caught(self):
        m, _ = make_model()
        kv = PagedKVCache(m, num_pages=3, page_size=2)
        pages = kv.alloc(2)
        assert 0 not in pages
        with pytest.raises(ValueError):
            kv.free([0])
        kv.free(pages)
        with pytest.raises(RuntimeError):
            kv.free(pages)


# ------------------------------------------------------ cache numerics
class TestCacheEquivalence:
    @pytest.mark.parametrize("stacked", [False, True])
    def test_eager_prefill_is_bit_identical(self, stacked):
        """The cache-threaded forward runs the SAME attention math as
        the uncached path for prefill, so eagerly (no jit refusion) the
        logits are bit-identical."""
        m, cfg = make_model(stacked=stacked)
        b, prompt, ps, pps = 2, 5, 4, 8
        ids = np.random.RandomState(0).randint(
            0, cfg.vocab_size, (b, prompt)).astype("int64")
        full = m(paddle.to_tensor(ids)).numpy()
        k, v = m.init_kv_pools(1 + b * pps, ps)
        t = paddle.to_tensor
        if not stacked:
            k = [t(x) for x in k]
            v = [t(x) for x in v]
        else:
            k, v = t(k), t(v)
        pos = np.broadcast_to(np.arange(prompt, dtype=np.int32),
                              (b, prompt)).copy()
        cache = GPTKVCache(
            "prefill", ps, k, v, t(make_tables(b, pps)),
            t(np.full(b, prompt, np.int32)),
            t(np.ones((b, prompt), bool)), t(pos))
        logits, _ = m(t(ids), cache=cache)
        np.testing.assert_array_equal(logits.numpy(), full)

    @pytest.mark.parametrize("stacked", [False, True])
    def test_prefill_exact_and_decode_tight(self, stacked):
        """Jitted prefill matches the uncached forward within fp noise
        (XLA refusion); decode matches within tight fp tolerance."""
        m, cfg = make_model(stacked=stacked)
        b, prompt, ps, pps = 2, 5, 4, 8
        ids = np.random.RandomState(0).randint(
            0, cfg.vocab_size, (b, prompt)).astype("int64")
        full = m(paddle.to_tensor(ids)).numpy()
        dec = CachedDecoder(m, max_batch=b, page_size=ps,
                            pages_per_seq=pps)
        k, v = m.init_kv_pools(1 + b * pps, ps)
        tables = make_tables(b, pps)
        last, k, v, _ = dec.prefill(
            ids, np.full(b, prompt, np.int32), tables, k, v)
        np.testing.assert_allclose(np.asarray(last), full[:, -1, :],
                                   rtol=1e-5, atol=1e-6)
        # 4 greedy decode steps vs the growing full forward
        cur = full[:, -1, :].argmax(-1)
        ref_ids = ids
        for step in range(4):
            pos = prompt + step
            logits, k, v, _ = dec.decode(
                cur, np.full(b, pos, np.int32), np.ones(b, bool),
                np.full(b, pos + 1, np.int32), tables, k, v)
            ref_ids = np.concatenate([ref_ids, cur[:, None]], 1)
            ref = m(paddle.to_tensor(ref_ids)).numpy()[:, -1]
            np.testing.assert_allclose(np.asarray(logits), ref,
                                       rtol=1e-4, atol=1e-5)
            assert (np.asarray(logits).argmax(-1) == ref.argmax(-1)).all()
            cur = ref.argmax(-1)

    def test_dead_lanes_do_not_perturb_live_lanes(self):
        """Slot masking: a garbage dead lane must not change a live
        lane's logits (the continuous-batching invariant)."""
        m, cfg = make_model()
        ps, pps = 4, 8
        ids = np.random.RandomState(1).randint(
            0, cfg.vocab_size, (1, 6)).astype("int64")
        outs = []
        for b in (1, 4):
            dec = CachedDecoder(m, max_batch=b, page_size=ps,
                                pages_per_seq=pps)
            k, v = m.init_kv_pools(1 + b * pps, ps)
            tables = make_tables(b, pps)
            ids_b = np.zeros((b, 6), np.int64)
            ids_b[0] = ids[0]
            lens = np.zeros(b, np.int32)
            lens[0] = 6
            last, k, v, _ = dec.prefill(ids_b, lens, tables, k, v)
            tok = np.zeros(b, np.int64)
            tok[0] = int(np.asarray(last)[0].argmax())
            active = np.zeros(b, bool)
            active[0] = True
            logits, k, v, _ = dec.decode(
                tok, np.full(b, 6, np.int32), active,
                np.where(active, 7, 0).astype(np.int32), tables, k, v)
            outs.append(np.asarray(logits)[0])
        np.testing.assert_allclose(outs[0], outs[1], rtol=1e-5,
                                   atol=1e-6)

    def test_supports_cached_decode_contract(self):
        m, _ = make_model()
        assert supports_cached_decode(m)
        from paddle_tpu.models import BertModel, bert_tiny
        assert not supports_cached_decode(BertModel(bert_tiny()))

    def test_decode_step_compiles_once(self):
        m, cfg = make_model()
        with GenerationServer(m, max_batch=4, page_size=8,
                              name="once") as srv:
            futs = [srv.submit_generate([1 + i, 2, 3],
                                        max_new_tokens=4 + i)
                    for i in range(6)]
            for f in futs:
                f.result(timeout=60)
            decode_sigs = [s for s in srv.decoder.compiled_signatures
                           if s[0] == "generate_decode"]
            assert len(decode_sigs) == 1


# ------------------------------------------------------------ sampling
class TestSampling:
    def test_greedy_matches_argmax(self):
        logits = np.random.RandomState(0).randn(4, 9)
        np.testing.assert_array_equal(
            sample_next_tokens(logits, 0.0), logits.argmax(-1))

    def test_mixed_rows_and_determinism(self):
        logits = np.random.RandomState(0).randn(4, 9)
        temps = [0.0, 1.0, 0.0, 0.5]
        a = sample_next_tokens(logits, temps,
                               rng=np.random.RandomState(7))
        b = sample_next_tokens(logits, temps,
                               rng=np.random.RandomState(7))
        np.testing.assert_array_equal(a, b)
        assert a[0] == logits[0].argmax() and a[2] == logits[2].argmax()

    def test_matches_multinomial_distribution(self):
        """Inverse-CDF selection reproduces the softmax distribution."""
        logits = np.log(np.array([[0.7, 0.2, 0.1]]))
        rng = np.random.RandomState(0)
        draws = np.array([
            sample_next_tokens(logits, 1.0, rng=rng)[0]
            for _ in range(3000)])
        freq = np.bincount(draws, minlength=3) / 3000.0
        np.testing.assert_allclose(freq, [0.7, 0.2, 0.1], atol=0.03)


# ----------------------------------------------------- the engine
class TestGenerationServer:
    def _reference(self, m, cfg, prompt, n):
        from paddle_tpu.distributed.fleet.utils import (
            HybridParallelInferenceHelper)
        helper = HybridParallelInferenceHelper(
            m, max_length=cfg.max_seq_len)
        out = helper._full_window_generate(
            np.asarray(prompt, np.int64)[None, :],
            min(cfg.max_seq_len, len(prompt) + n), 0.0, 0)
        return list(out[0, len(prompt):])

    def test_greedy_matches_full_window_reference(self):
        m, cfg = make_model()
        with GenerationServer(m, max_batch=4, page_size=8,
                              name="ref") as srv:
            p = [5, 7, 9, 2]
            got = srv.generate(p, max_new_tokens=6)
            assert got == self._reference(m, cfg, p, 6)

    def test_continuous_join_and_evict_ordering(self):
        """Different-length requests share the in-flight batch; a late
        request joins mid-decode; every stream still matches its
        single-request reference."""
        m, cfg = make_model()
        prompts = [[5, 7, 9], [3, 1, 4, 1, 5], [2, 2]]
        new = [12, 4, 8]
        refs = [self._reference(m, cfg, p, n)
                for p, n in zip(prompts, new)]
        with GenerationServer(m, max_batch=4, page_size=8,
                              name="join") as srv:
            f0 = srv.submit_generate(prompts[0], max_new_tokens=new[0])
            f1 = srv.submit_generate(prompts[1], max_new_tokens=new[1])
            # wait until the first stream is visibly mid-decode, then
            # JOIN a third sequence into the live batch
            deadline = time.monotonic() + 30
            while len(f0.tokens()) < 2 and time.monotonic() < deadline:
                time.sleep(0.002)
            assert not f0.done() or len(f0.tokens()) >= 2
            f2 = srv.submit_generate(prompts[2], max_new_tokens=new[2])
            outs = [f.result(timeout=60) for f in (f0, f1, f2)]
            assert outs == refs
            assert f1.finish_reason == "length"
            snap = srv.metrics_snapshot()
            # overlapped execution: fewer decode iterations than the
            # serial sum of per-sequence steps
            assert snap["batch_occupancy"]["steps"] < sum(new)
            assert snap["counters"]["completed"] == 3
            assert snap["tokens_total"] == sum(new)

    def test_page_reuse_after_eviction(self):
        """Pool sized for ONE sequence: the second request reuses the
        first one's evicted pages and still decodes correctly.
        (prefix_cache off: this pins the LEGACY eager-free accounting;
        the cached-page variant lives in test_prefix_spec.py.)"""
        m, cfg = make_model()
        p1, p2 = [5, 7, 9], [8, 6, 4]
        r1 = self._reference(m, cfg, p1, 6)
        r2 = self._reference(m, cfg, p2, 6)
        # capacity: pages for one sequence of 3+6=9 tokens @ page 4 = 3
        with GenerationServer(m, max_batch=2, page_size=4, num_pages=4,
                              max_seq_len=16, prefix_cache=False,
                              name="reuse") as srv:
            f1 = srv.submit_generate(p1, max_new_tokens=6)
            f2 = srv.submit_generate(p2, max_new_tokens=6)
            assert f1.result(timeout=60) == r1
            assert f2.result(timeout=60) == r2
            assert srv.kv.evicted_pages_total == 6
            assert srv.kv.free_pages == srv.kv.capacity
            snap = srv.metrics_snapshot()
            assert snap["kv_pages"]["evicted_total"] == 6
            assert snap["kv_pages"]["used"] == 0

    def test_streaming_iteration_and_eos(self):
        m, cfg = make_model()
        # use a greedy token as eos: the stream must stop at its FIRST
        # occurrence with reason "eos", eos token included
        ref = self._reference(m, cfg, [5, 7, 9], 8)
        eos = int(ref[2])
        stop = ref.index(eos) + 1
        with GenerationServer(m, max_batch=2, page_size=8,
                              eos_token_id=eos, name="eos") as srv:
            fut = srv.submit_generate([5, 7, 9], max_new_tokens=8)
            streamed = list(fut)
            assert streamed == fut.result(timeout=10)
            assert streamed == ref[:stop]
            assert fut.finish_reason == "eos"

    def test_cancel_mid_stream(self):
        m, cfg = make_model()
        with GenerationServer(m, max_batch=2, page_size=8,
                              name="cancel") as srv:
            fut = srv.submit_generate([5, 7, 9], max_new_tokens=120)
            deadline = time.monotonic() + 30
            while len(fut.tokens()) < 2 and time.monotonic() < deadline:
                time.sleep(0.002)
            assert fut.cancel()
            toks = fut.result(timeout=30)
            assert 2 <= len(toks) < 120
            assert fut.finish_reason == "cancelled"
            assert fut.cancelled()
            assert srv.kv.free_pages == srv.kv.capacity
            # engine still serves after a cancellation
            assert srv.generate([1, 2], max_new_tokens=2) == \
                self._reference(m, cfg, [1, 2], 2)

    def test_deadline_matches_submit_semantics(self):
        m, cfg = make_model()
        srv = GenerationServer(m, max_batch=2, page_size=8,
                               name="deadline", start=False)
        fut = srv.submit_generate([5, 7], max_new_tokens=4,
                                  timeout_ms=5.0)
        time.sleep(0.05)
        srv.start()
        with pytest.raises(DeadlineExceededError):
            fut.result(timeout=30)
        assert fut.finish_reason == "timed_out"
        assert srv.metrics_snapshot()["counters"]["timed_out"] == 1
        srv.shutdown()

    def test_hard_deadline_evicts_inflight_stream(self):
        """Fleet deadline propagation, engine side: a stream whose
        HARD budget (deadline_ms) expires mid-generation is evicted
        at batch re-form — future fails typed, already-emitted tokens
        stay readable, and every page returns to the free list
        instead of the engine burning decode steps to the length
        cap."""
        m, cfg = make_model()
        with GenerationServer(m, max_batch=2, page_size=8,
                              prefix_cache=False,
                              name="hard_deadline") as srv:
            fut = srv.submit_generate([5, 7, 9], max_new_tokens=200,
                                      deadline_ms=120.0)
            with pytest.raises(DeadlineExceededError):
                fut.result(timeout=60)
            assert fut.finish_reason == "deadline"
            assert len(fut.tokens()) < 200     # evicted, not run out
            assert srv.kv.free_pages == srv.kv.capacity
            leak = srv.metrics_snapshot()["kv_leak_check"]
            assert not leak.get("leaked"), leak
            assert srv.metrics_snapshot()[
                "counters"]["timed_out"] == 1
            # the engine still serves after the eviction
            assert srv.generate([1, 2], max_new_tokens=2) == \
                self._reference(m, cfg, [1, 2], 2)

    def test_scheduling_timeout_still_never_evicts_inflight(self):
        """timeout_ms keeps its pre-deadline-propagation contract: it
        gates SCHEDULING only — once decoding, a stream with a tiny
        timeout_ms but no hard budget runs to completion."""
        m, cfg = make_model()
        ref = self._reference(m, cfg, [5, 7], 4)
        with GenerationServer(m, max_batch=2, page_size=8,
                              name="sched_only") as srv:
            fut = srv.submit_generate([5, 7], max_new_tokens=4,
                                      timeout_ms=30000.0)
            assert fut.result(timeout=60) == ref

    def test_queue_full_backpressure(self):
        m, cfg = make_model()
        srv = GenerationServer(m, max_batch=2, page_size=8,
                               queue_capacity=2, name="full",
                               start=False)
        srv.submit_generate([1], max_new_tokens=1)
        srv.submit_generate([2], max_new_tokens=1)
        with pytest.raises(QueueFullError):
            srv.submit_generate([3], max_new_tokens=1)
        assert srv.metrics_snapshot()["counters"]["rejected"] == 1
        srv.shutdown()   # inline drain resolves the two queued streams

    def test_fault_barrier_decode(self):
        """A model error mid-decode fails the in-flight streams only;
        the worker survives and serves the next request."""
        m, cfg = make_model()
        with GenerationServer(m, max_batch=2, page_size=8,
                              name="fault") as srv:
            real = srv.decoder.decode
            state = {"bombs": 1}

            def bomb(*a, **kw):
                if state["bombs"]:
                    state["bombs"] -= 1
                    raise RuntimeError("injected decode fault")
                return real(*a, **kw)

            srv.decoder.decode = bomb
            fut = srv.submit_generate([5, 7, 9], max_new_tokens=6)
            with pytest.raises(RuntimeError, match="injected"):
                fut.result(timeout=30)
            assert fut.finish_reason == "error"
            assert srv.kv.free_pages == srv.kv.capacity
            got = srv.generate([5, 7, 9], max_new_tokens=6,
                               timeout_ms=None)
            assert got == self._reference(m, cfg, [5, 7, 9], 6)
            snap = srv.metrics_snapshot()
            assert snap["counters"]["failed"] == 1
            assert snap["counters"]["completed"] == 1

    def test_fault_barrier_prefill(self):
        m, cfg = make_model()
        with GenerationServer(m, max_batch=2, page_size=8,
                              name="pfault") as srv:
            real = srv.decoder.prefill
            state = {"bombs": 1}

            def bomb(*a, **kw):
                if state["bombs"]:
                    state["bombs"] -= 1
                    raise RuntimeError("injected prefill fault")
                return real(*a, **kw)

            srv.decoder.prefill = bomb
            fut = srv.submit_generate([5, 7], max_new_tokens=2)
            with pytest.raises(RuntimeError, match="injected"):
                fut.result(timeout=30)
            assert srv.kv.free_pages == srv.kv.capacity
            assert srv.generate([5, 7], max_new_tokens=2) == \
                self._reference(m, cfg, [5, 7], 2)

    def test_shutdown_no_drain_fails_queued(self):
        from paddle_tpu.serving import ServerClosedError
        m, cfg = make_model()
        srv = GenerationServer(m, max_batch=2, page_size=8,
                               name="abort", start=False)
        fut = srv.submit_generate([5], max_new_tokens=4)
        srv.shutdown(drain=False)
        with pytest.raises(ServerClosedError):
            fut.result(timeout=10)
        with pytest.raises(ServerClosedError):
            srv.submit_generate([1], max_new_tokens=1)

    def test_validation(self):
        m, cfg = make_model()
        srv = GenerationServer(m, max_batch=2, page_size=8,
                               name="valid", start=False)
        with pytest.raises(ValueError, match="no room"):
            srv.submit_generate(np.arange(cfg.max_seq_len),
                                max_new_tokens=2)
        with pytest.raises(ValueError, match="empty"):
            srv.submit_generate([], max_new_tokens=2)
        with pytest.raises(ValueError):
            srv.submit_generate([1], max_new_tokens=0)
        srv.shutdown()

    def test_temperature_streams_are_request_deterministic(self):
        m, cfg = make_model()
        with GenerationServer(m, max_batch=4, page_size=8,
                              name="temp") as srv:
            a = srv.generate([5, 7, 9], max_new_tokens=8,
                             temperature=0.8, seed=3)
            b = srv.generate([5, 7, 9], max_new_tokens=8,
                             temperature=0.8, seed=3)
            assert a == b
            assert len(a) == 8

    def test_metrics_exposition(self):
        from paddle_tpu.observability import prometheus_text
        m, cfg = make_model()
        with GenerationServer(m, max_batch=2, page_size=8,
                              name="expo") as srv:
            srv.generate([5, 7], max_new_tokens=3)
            text = prometheus_text()
            for fam in ("paddle_decode_tokens_total",
                        "paddle_decode_inter_token_ms",
                        "paddle_decode_kv_pages",
                        "paddle_decode_batch_occupancy",
                        "paddle_decode_requests_total"):
                assert fam in text
            snap = srv.metrics_snapshot()
            assert snap["tokens_total"] == 3
            assert snap["step_ms"]["prefill"]["count"] == 1
            assert snap["step_ms"]["decode"]["count"] == 2


# ------------------------------------------------- warmup + manifest
class TestWarmupManifest:
    @pytest.fixture
    def cache_dir(self, tmp_path):
        from paddle_tpu.compile_cache import reset_default_cache
        paddle.set_flags({"FLAGS_compile_cache_dir": str(tmp_path)})
        reset_default_cache()
        yield str(tmp_path)
        paddle.set_flags({"FLAGS_compile_cache_dir": ""})
        reset_default_cache()

    def test_site_tagged_entries_and_filtering(self, tmp_path):
        from paddle_tpu.compile_cache import WarmupManifest
        man = WarmupManifest(str(tmp_path / "m.json"))
        man.record([((4, 16), "float32")])                 # predict
        man.record([((2, 8), "int64")], site="generate_prefill")
        man.record([((2,), "int64")], site="generate_decode")
        assert len(man) == 3
        assert len(man.specs(site="predict")) == 1
        assert len(man.specs(site="generate_prefill")) == 1
        # reload from disk keeps the tags
        man2 = WarmupManifest(str(tmp_path / "m.json"))
        assert {e["site"] for e in man2.specs()} == \
            {"predict", "generate_prefill", "generate_decode"}

    def test_pre_site_manifest_loads_as_predict(self, tmp_path):
        import json
        path = tmp_path / "old.json"
        path.write_text(json.dumps(
            {"version": 1,
             "entries": [{"feeds": [[[4, 16], "float32"]]}]}))
        from paddle_tpu.compile_cache import WarmupManifest
        man = WarmupManifest(str(path))
        assert len(man.specs(site="predict")) == 1

    def test_traffic_records_and_replay_warms(self, cache_dir):
        m, cfg = make_model()
        with GenerationServer(m, max_batch=2, page_size=8,
                              name="man1") as srv:
            srv.generate([5, 7, 9], max_new_tokens=3)
            man = srv.warmup_manifest
            assert man is not None
            sites = {e["site"] for e in man.specs()}
            assert sites == {"generate_prefill", "generate_decode"}
            path = man.path
        # a "restarted" engine replays exactly the observed lattice
        m2, _ = make_model()
        srv2 = GenerationServer(m2, max_batch=2, page_size=8,
                                name="man2", start=False)
        fresh = srv2.warmup_from_manifest(path)
        assert fresh == 2    # one prefill bucket + the decode step
        # traffic after replay adds no new signatures
        srv2.start()
        srv2.generate([5, 7, 9], max_new_tokens=3)
        sigs = srv2.decoder.compiled_signatures
        assert len(sigs) == 2
        srv2.shutdown()

    def test_flag_auto_replay(self, cache_dir):
        m, cfg = make_model()
        with GenerationServer(m, max_batch=2, page_size=8,
                              name="auto1") as srv:
            srv.generate([5, 7], max_new_tokens=2)
        m2, _ = make_model()
        paddle.set_flags({"FLAGS_decode_warmup_from_manifest": True})
        try:
            srv2 = GenerationServer(m2, max_batch=2, page_size=8,
                                    name="auto1", start=False)
            assert len(srv2.decoder.compiled_signatures) == 2
            srv2.shutdown()
        finally:
            paddle.set_flags(
                {"FLAGS_decode_warmup_from_manifest": False})

    def test_inference_server_skips_generate_sites(self, tmp_path):
        """InferenceServer.warmup_from_manifest must ignore decode-
        engine entries — their feeds mean nothing to the Predictor."""
        from paddle_tpu.compile_cache import WarmupManifest
        path = str(tmp_path / "mixed.json")
        man = WarmupManifest(path)
        man.record([((2,), "int64")], site="generate_decode")
        assert man.specs(site="predict") == []


# ------------------------------------------- helper migration (sat. 1)
class TestHybridHelperMigration:
    def test_cached_path_taken_and_matches_full_window(self):
        from paddle_tpu.distributed.fleet.utils import (
            HybridParallelInferenceHelper)
        m, cfg = make_model()
        h = HybridParallelInferenceHelper(m, max_length=32)
        ids = np.random.RandomState(2).randint(
            0, cfg.vocab_size, (3, 5)).astype("int64")
        out = h.generate(ids, max_new_tokens=8)
        assert h._decoders        # the cached decoder was built & used
        ref = h._full_window_generate(ids, 13, 0.0, 0)
        np.testing.assert_array_equal(out, ref)

    def test_eos_early_stop_parity(self):
        from paddle_tpu.distributed.fleet.utils import (
            HybridParallelInferenceHelper)
        m, cfg = make_model()
        probe = HybridParallelInferenceHelper(m, max_length=32)
        ids = np.array([[5, 7, 9]], "int64")
        greedy = probe.generate(ids, max_new_tokens=8)
        eos = int(greedy[0, 5])    # 3rd generated token (may repeat
        # earlier in the greedy stream; parity with the full-window
        # path is what matters, not the absolute stop position)
        h = HybridParallelInferenceHelper(m, max_length=32,
                                          eos_token_id=eos)
        out = h.generate(ids, max_new_tokens=8)
        ref = h._full_window_generate(ids, 11, 0.0, 0)
        np.testing.assert_array_equal(out, ref)
        assert out.shape[1] < 11   # stopped before the full budget

    def test_picks_up_weight_updates_between_calls(self):
        from paddle_tpu.distributed.fleet.utils import (
            HybridParallelInferenceHelper)
        m, cfg = make_model()
        h = HybridParallelInferenceHelper(m, max_length=24)
        ids = np.array([[5, 7, 9]], "int64")
        a = h.generate(ids, max_new_tokens=6)
        w = m.gpt.embeddings.word_embeddings.weight
        w.set_value(np.asarray(w.numpy()) * 0.5
                    + np.random.RandomState(0).randn(
                        *w.shape).astype("float32"))
        b = h.generate(ids, max_new_tokens=6)   # must see new weights
        ref = h._full_window_generate(ids, 9, 0.0, 0)
        np.testing.assert_array_equal(b, ref)
        assert not np.array_equal(a, b)

    def test_fallback_for_cacheless_models(self):
        from paddle_tpu.distributed.fleet.utils import (
            HybridParallelInferenceHelper)

        class Toy:
            """Minimal logits-only model without cache support."""

            def __init__(self):
                self.training = False

            def __call__(self, ids):
                b, s = ids.shape
                base = np.asarray(ids.numpy(), np.float32)[..., None]
                return paddle.to_tensor(
                    np.tile(base, (1, 1, 11)) +
                    np.arange(11, dtype=np.float32))

        h = HybridParallelInferenceHelper(Toy(), max_length=8)
        out = h.generate(np.array([[1, 2]], "int64"), max_new_tokens=3)
        assert out.shape == (1, 5)
