"""paddle.signal + paddle.vision.ops vs numpy / torch oracles.

Mirrors the reference OpTest pattern (numpy as the oracle); torch (CPU,
baked into the image) provides oracles for stft/roi_align/deform_conv2d
exactly as the reference's tests use scipy/opencv-computed references.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.tensor import to_tensor
from paddle_tpu.vision import ops as vops

torch = pytest.importorskip("torch")


# ------------------------------------------------------------------ signal

def test_frame_last_axis():
    x = np.arange(16, dtype=np.float32)
    out = paddle.signal.frame(to_tensor(x), frame_length=4, hop_length=2)
    ref = np.stack([x[i * 2:i * 2 + 4] for i in range(7)], axis=-1)
    np.testing.assert_allclose(out.numpy(), ref)


def test_frame_axis0_batched():
    x = np.random.randn(16, 3).astype(np.float32)
    out = paddle.signal.frame(to_tensor(x), frame_length=8, hop_length=4,
                              axis=0)
    assert out.shape == [3, 8, 3]
    np.testing.assert_allclose(out.numpy()[1, :, 2], x[4:12, 2], rtol=1e-6)


def test_overlap_add_inverts_frame_non_overlapping():
    x = np.random.randn(2, 12).astype(np.float32)
    f = paddle.signal.frame(to_tensor(x), frame_length=4, hop_length=4)
    y = paddle.signal.overlap_add(f, hop_length=4)
    np.testing.assert_allclose(y.numpy(), x, rtol=1e-6)


def test_overlap_add_matches_torch():
    frames = np.random.randn(6, 5).astype(np.float32)  # (frame_len, n)
    y = paddle.signal.overlap_add(to_tensor(frames), hop_length=2)
    ref = torch.nn.functional.fold(
        torch.tensor(frames)[None], output_size=(1, 4 * 2 + 6),
        kernel_size=(1, 6), stride=(1, 2))[0, 0, 0].numpy()
    np.testing.assert_allclose(y.numpy(), ref, rtol=1e-5)


@pytest.mark.parametrize("onesided", [True, False])
def test_stft_matches_torch(onesided):
    np.random.seed(0)
    x = np.random.randn(2, 256).astype(np.float32)
    win = np.hanning(64).astype(np.float32)
    out = paddle.signal.stft(to_tensor(x), n_fft=64, hop_length=16,
                             window=to_tensor(win), center=True,
                             onesided=onesided)
    ref = torch.stft(torch.tensor(x), n_fft=64, hop_length=16,
                     window=torch.tensor(win), center=True,
                     onesided=onesided, return_complex=True).numpy()
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-4)


def test_istft_roundtrip():
    np.random.seed(1)
    x = np.random.randn(1, 512).astype(np.float32)
    win = np.hanning(128).astype(np.float32)
    spec = paddle.signal.stft(to_tensor(x), n_fft=128, hop_length=32,
                              window=to_tensor(win))
    y = paddle.signal.istft(spec, n_fft=128, hop_length=32,
                            window=to_tensor(win), length=512)
    np.testing.assert_allclose(y.numpy(), x, rtol=1e-3, atol=1e-4)


# ------------------------------------------------------------- vision ops

def test_nms_matches_torchvision_algorithm():
    np.random.seed(2)
    n = 40
    wh = np.random.rand(n, 2).astype(np.float32) * 20 + 1
    xy = np.random.rand(n, 2).astype(np.float32) * 60
    boxes = np.concatenate([xy, xy + wh], axis=1)
    scores = np.random.rand(n).astype(np.float32)

    keep = vops.nms(to_tensor(boxes), 0.5, to_tensor(scores)).numpy()

    # greedy numpy oracle
    order = np.argsort(-scores)
    kept = []
    supp = np.zeros(n, bool)
    for i in order:
        if supp[i]:
            continue
        kept.append(i)
        x1 = np.maximum(boxes[i, 0], boxes[:, 0])
        y1 = np.maximum(boxes[i, 1], boxes[:, 1])
        x2 = np.minimum(boxes[i, 2], boxes[:, 2])
        y2 = np.minimum(boxes[i, 3], boxes[:, 3])
        inter = np.maximum(x2 - x1, 0) * np.maximum(y2 - y1, 0)
        a = (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])
        iou = inter / (a[i] + a - inter)
        supp |= iou > 0.5
    np.testing.assert_array_equal(np.sort(keep), np.sort(np.array(kept)))


def test_nms_categories():
    boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [0, 0, 10, 10]],
                     dtype=np.float32)
    scores = np.array([0.9, 0.8, 0.7], dtype=np.float32)
    cats = np.array([0, 0, 1], dtype=np.int64)
    keep = vops.nms(to_tensor(boxes), 0.5, to_tensor(scores),
                    category_idxs=to_tensor(cats),
                    categories=[0, 1]).numpy()
    # box1 suppressed by box0 (same cat); box2 survives (different cat)
    assert set(keep.tolist()) == {0, 2}


def test_roi_align_matches_torchvision():
    tv = pytest.importorskip("torchvision")
    np.random.seed(3)
    x = np.random.randn(2, 3, 16, 16).astype(np.float32)
    boxes = np.array([[1.0, 1.0, 9.0, 9.0], [0.0, 0.0, 15.0, 15.0],
                      [2.0, 3.0, 12.0, 10.0]], dtype=np.float32)
    boxes_num = np.array([2, 1], dtype=np.int32)
    out = vops.roi_align(to_tensor(x), to_tensor(boxes),
                         to_tensor(boxes_num), output_size=4,
                         spatial_scale=1.0, sampling_ratio=2,
                         aligned=True).numpy()
    rois = torch.tensor(
        np.concatenate([[[0], [0], [1]], boxes], axis=1).astype(np.float32))
    ref = tv.ops.roi_align(torch.tensor(x), rois, output_size=4,
                           spatial_scale=1.0, sampling_ratio=2,
                           aligned=True).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


def test_roi_pool_matches_torchvision():
    tv = pytest.importorskip("torchvision")
    np.random.seed(4)
    x = np.random.randn(1, 2, 12, 12).astype(np.float32)
    boxes = np.array([[0.0, 0.0, 11.0, 11.0], [2.0, 2.0, 8.0, 9.0]],
                     dtype=np.float32)
    boxes_num = np.array([2], dtype=np.int32)
    out = vops.roi_pool(to_tensor(x), to_tensor(boxes),
                        to_tensor(boxes_num), output_size=3).numpy()
    rois = torch.tensor(
        np.concatenate([[[0], [0]], boxes], axis=1).astype(np.float32))
    ref = tv.ops.roi_pool(torch.tensor(x), rois, output_size=3).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-5)


def test_deform_conv2d_matches_torchvision():
    tv = pytest.importorskip("torchvision")
    np.random.seed(5)
    x = np.random.randn(2, 4, 8, 8).astype(np.float32)
    w = np.random.randn(6, 4, 3, 3).astype(np.float32) * 0.2
    b = np.random.randn(6).astype(np.float32) * 0.1
    off = np.random.randn(2, 2 * 9, 8, 8).astype(np.float32) * 0.5
    mask = np.random.rand(2, 9, 8, 8).astype(np.float32)
    out = vops.deform_conv2d(
        to_tensor(x), to_tensor(off), to_tensor(w), to_tensor(b),
        stride=1, padding=1, mask=to_tensor(mask)).numpy()
    ref = tv.ops.deform_conv2d(
        torch.tensor(x), torch.tensor(off), torch.tensor(w),
        torch.tensor(b), stride=1, padding=1,
        mask=torch.tensor(mask)).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-3, atol=1e-4)


def _roi_align_numpy(x, boxes, batch_idx, out_size, scale, sr, aligned):
    """Loop-based RoIAlign oracle."""
    R = boxes.shape[0]
    C, H, W = x.shape[1:]
    out = np.zeros((R, C, out_size, out_size), np.float32)

    def bil(img, y, xx):
        if y < -1.0 or y > H or xx < -1.0 or xx > W:
            return np.zeros(img.shape[0], np.float32)
        y = min(max(y, 0.0), H - 1)
        xx = min(max(xx, 0.0), W - 1)
        y0, x0 = int(np.floor(y)), int(np.floor(xx))
        y1, x1 = min(y0 + 1, H - 1), min(x0 + 1, W - 1)
        wy, wx = y - y0, xx - x0
        return (img[:, y0, x0] * (1 - wy) * (1 - wx)
                + img[:, y0, x1] * (1 - wy) * wx
                + img[:, y1, x0] * wy * (1 - wx)
                + img[:, y1, x1] * wy * wx)

    off = 0.5 if aligned else 0.0
    for r in range(R):
        img = x[batch_idx[r]]
        x1, y1, x2, y2 = boxes[r] * scale
        x1, y1, x2, y2 = x1 - off, y1 - off, x2 - off, y2 - off
        rw, rh = x2 - x1, y2 - y1
        if not aligned:
            rw, rh = max(rw, 1.0), max(rh, 1.0)
        bw, bh = rw / out_size, rh / out_size
        for i in range(out_size):
            for j in range(out_size):
                acc = np.zeros(C, np.float32)
                for iy in range(sr):
                    for ix in range(sr):
                        yy = y1 + (i + (iy + 0.5) / sr) * bh
                        xx = x1 + (j + (ix + 0.5) / sr) * bw
                        acc += bil(img, yy, xx)
                out[r, :, i, j] = acc / (sr * sr)
    return out


def test_roi_align_matches_numpy_oracle():
    np.random.seed(9)
    x = np.random.randn(2, 3, 16, 16).astype(np.float32)
    # last box extends past the image (proposals can) — exercises the
    # "contribute 0 beyond 1px outside" rule
    boxes = np.array([[1.0, 1.0, 9.0, 9.0], [0.0, 0.0, 15.0, 15.0],
                      [-6.0, -4.0, 12.0, 10.0]], dtype=np.float32)
    boxes_num = np.array([2, 1], dtype=np.int32)
    for aligned in (True, False):
        out = vops.roi_align(to_tensor(x), to_tensor(boxes),
                             to_tensor(boxes_num), output_size=4,
                             spatial_scale=0.5, sampling_ratio=2,
                             aligned=aligned).numpy()
        ref = _roi_align_numpy(x, boxes, [0, 0, 1], 4, 0.5, 2, aligned)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


@pytest.mark.slow
def test_roi_align_adaptive_sampling_ratio():
    """sampling_ratio=-1 -> per-roi ceil(roi_size/output) density."""
    np.random.seed(10)
    x = np.random.randn(1, 2, 32, 32).astype(np.float32)
    # rois of very different sizes -> different adaptive densities
    boxes = np.array([[0.0, 0.0, 31.0, 31.0], [4.0, 4.0, 8.0, 8.0]],
                     dtype=np.float32)
    boxes_num = np.array([2], dtype=np.int32)
    out = vops.roi_align(to_tensor(x), to_tensor(boxes),
                         to_tensor(boxes_num), output_size=4,
                         sampling_ratio=-1, aligned=True).numpy()

    def oracle_one(box, sr):
        return _roi_align_numpy(x, box[None], [0], 4, 1.0, sr, True)[0]

    # roi0: 31/4 -> sr=8 ; roi1: 4/4 -> sr=1
    np.testing.assert_allclose(out[0], oracle_one(boxes[0], 8),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(out[1], oracle_one(boxes[1], 1),
                               rtol=1e-4, atol=1e-4)


def test_deform_conv2d_zero_offset_equals_conv():
    np.random.seed(6)
    x = np.random.randn(1, 3, 6, 6).astype(np.float32)
    w = np.random.randn(5, 3, 3, 3).astype(np.float32) * 0.3
    off = np.zeros((1, 18, 6, 6), dtype=np.float32)
    out = vops.deform_conv2d(to_tensor(x), to_tensor(off), to_tensor(w),
                             padding=1).numpy()
    ref = torch.nn.functional.conv2d(
        torch.tensor(x), torch.tensor(w), padding=1).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


def test_deform_conv2d_layer_and_grad():
    layer = vops.DeformConv2D(3, 4, 3, padding=1)
    x = to_tensor(np.random.randn(1, 3, 5, 5).astype(np.float32),
                  stop_gradient=False)
    off = to_tensor(np.zeros((1, 18, 5, 5), dtype=np.float32))
    y = layer(x, off)
    loss = y.sum()
    loss.backward()
    assert x.grad is not None
    assert layer.weight.grad is not None


def test_yolo_box_shapes_and_range():
    np.random.seed(7)
    s, cls = 3, 5
    x = np.random.randn(2, s * (5 + cls), 4, 4).astype(np.float32)
    img = np.array([[608, 608], [416, 416]], dtype=np.int32)
    boxes, scores = vops.yolo_box(
        to_tensor(x), to_tensor(img), anchors=[10, 13, 16, 30, 33, 23],
        class_num=cls, conf_thresh=0.01, downsample_ratio=32)
    assert boxes.shape == [2, 4 * 4 * s, 4]
    assert scores.shape == [2, 4 * 4 * s, cls]
    b = boxes.numpy()
    assert (b[0, :, 2] <= 608).all() and (b.min() >= 0)


def test_prior_box_basic():
    inp = np.zeros((1, 8, 4, 4), dtype=np.float32)
    img = np.zeros((1, 3, 32, 32), dtype=np.float32)
    boxes, var = vops.prior_box(
        to_tensor(inp), to_tensor(img), min_sizes=[8.0], max_sizes=[16.0],
        aspect_ratios=[2.0], flip=True, clip=True)
    # priors per location: 1 (ar=1,min) + 1 (sqrt(min*max)) + 2 (ar 2, 1/2)
    assert boxes.shape == [4, 4, 4, 4]
    bn = boxes.numpy()
    assert bn.min() >= 0.0 and bn.max() <= 1.0
    # center of cell (0,0) is at (4, 4) px -> normalized 0.125
    ctr = (bn[0, 0, 0, :2] + bn[0, 0, 0, 2:]) / 2
    np.testing.assert_allclose(ctr, [0.125, 0.125], atol=1e-6)
    assert var.shape == [4, 4, 4, 4]


def test_box_coder_decode_encode_roundtrip():
    np.random.seed(8)
    priors = np.array([[10, 10, 30, 30], [5, 5, 15, 25]], dtype=np.float32)
    var = [0.1, 0.1, 0.2, 0.2]
    targets = np.array([[12, 11, 28, 32], [4, 6, 18, 22]], dtype=np.float32)
    enc = vops.box_coder(to_tensor(priors), var, to_tensor(targets),
                         code_type="encode_center_size").numpy()
    # decode back the diagonal (target i vs prior i)
    deltas = np.stack([enc[i, i] for i in range(2)])[None]  # (1?,)
    deltas = np.broadcast_to(
        np.stack([enc[i, i] for i in range(2)])[:, None, :], (2, 2, 4))
    dec = vops.box_coder(to_tensor(priors), var,
                         to_tensor(np.ascontiguousarray(deltas)),
                         code_type="decode_center_size", axis=0).numpy()
    np.testing.assert_allclose(np.stack([dec[i, i] for i in range(2)]),
                               targets, rtol=1e-4, atol=1e-3)


def test_empty_inputs():
    empty_boxes = to_tensor(np.zeros((0, 4), np.float32))
    keep = vops.nms(empty_boxes, 0.5,
                    to_tensor(np.zeros((0,), np.float32)))
    assert keep.shape == [0]
    x = to_tensor(np.random.randn(1, 4, 8, 8).astype(np.float32))
    zero_num = to_tensor(np.array([0], np.int32))
    assert vops.roi_align(x, empty_boxes, zero_num, 2).shape == [0, 4, 2, 2]
    assert vops.roi_pool(x, empty_boxes, zero_num, 2).shape == [0, 4, 2, 2]


def test_roi_pool_matches_numpy_oracle():
    np.random.seed(11)
    x = np.random.randn(1, 2, 12, 12).astype(np.float32)
    boxes = np.array([[0.0, 0.0, 11.0, 11.0], [2.0, 2.0, 8.0, 9.0]],
                     dtype=np.float32)
    out = vops.roi_pool(to_tensor(x), to_tensor(boxes),
                        to_tensor(np.array([2], np.int32)),
                        output_size=3).numpy()
    # loop oracle (quantized-bin max, reference rule)
    ref = np.zeros((2, 2, 3, 3), np.float32)
    for r in range(2):
        xx1, yy1, xx2, yy2 = np.round(boxes[r]).astype(int)
        rh, rw = max(yy2 - yy1 + 1, 1), max(xx2 - xx1 + 1, 1)
        for i in range(3):
            hs = min(max(yy1 + int(np.floor(i * rh / 3)), 0), 12)
            he = min(max(yy1 + int(np.ceil((i + 1) * rh / 3)), 0), 12)
            for j in range(3):
                ws = min(max(xx1 + int(np.floor(j * rw / 3)), 0), 12)
                we = min(max(xx1 + int(np.ceil((j + 1) * rw / 3)), 0), 12)
                if he > hs and we > ws:
                    ref[r, :, i, j] = x[0, :, hs:he, ws:we].max(axis=(1, 2))
    np.testing.assert_allclose(out, ref, rtol=1e-6)


def test_deform_conv2d_border_zero_padding():
    """A sample point in (-1, 0) must blend with zeros, not clamp."""
    x = np.full((1, 1, 1, 1), 2.0, np.float32)
    w = np.ones((1, 1, 1, 1), np.float32)
    off = np.zeros((1, 2, 1, 1), np.float32)
    off[0, 0, 0, 0] = -0.5  # dy: sample at y=-0.5
    out = vops.deform_conv2d(to_tensor(x), to_tensor(off),
                             to_tensor(w)).numpy()
    np.testing.assert_allclose(out, [[[[1.0]]]], rtol=1e-6)


def test_psroi_pool_matches_numpy_oracle():
    np.random.seed(12)
    x = np.random.randn(1, 2 * 2 * 3, 8, 8).astype(np.float32)
    boxes = np.array([[0.0, 0.0, 7.0, 7.0], [1.2, 0.7, 5.4, 6.1]],
                     dtype=np.float32)
    out = vops.psroi_pool(to_tensor(x), to_tensor(boxes),
                          to_tensor(np.array([2], np.int32)),
                          output_size=2, spatial_scale=0.5).numpy()
    assert out.shape == (2, 3, 2, 2)
    # loop oracle following the reference kernel's quantization
    H = W = 8
    ref = np.zeros((2, 3, 2, 2), np.float32)
    for r in range(2):
        sx = np.floor(boxes[r, 0] + 0.5) * 0.5
        sy = np.floor(boxes[r, 1] + 0.5) * 0.5
        ex = (np.floor(boxes[r, 2] + 0.5) + 1.0) * 0.5
        ey = (np.floor(boxes[r, 3] + 0.5) + 1.0) * 0.5
        rh, rw = max(ey - sy, 0.1), max(ex - sx, 0.1)
        bh, bw = rh / 2, rw / 2
        for c in range(3):
            for i in range(2):
                for j in range(2):
                    hs = min(max(int(np.floor(i * bh + sy)), 0), H)
                    he = min(max(int(np.ceil((i + 1) * bh + sy)), 0), H)
                    ws = min(max(int(np.floor(j * bw + sx)), 0), W)
                    we = min(max(int(np.ceil((j + 1) * bw + sx)), 0), W)
                    ch = (c * 2 + i) * 2 + j
                    if he > hs and we > ws:
                        ref[r, c, i, j] = x[0, ch, hs:he, ws:we].mean()
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_yolo_box_anchor_major_order():
    """Row k of the output is anchor k//(h*w), cell (k%(h*w))//w, k%w."""
    s, cls, h, w = 2, 1, 2, 2
    x = np.zeros((1, s * (5 + cls), h, w), dtype=np.float32)
    xr = x.reshape(1, s, 5 + cls, h, w)
    # make anchor 1 cell (0,1) uniquely identifiable via a huge tw
    xr[0, 1, 2, 0, 1] = 2.0  # tw
    xr[0, :, 4] = 5.0  # all confident
    img = np.array([[64, 64]], dtype=np.int32)
    boxes, scores = vops.yolo_box(
        to_tensor(xr.reshape(1, -1, h, w)), to_tensor(img),
        anchors=[4, 4, 8, 8], class_num=cls, conf_thresh=0.01,
        downsample_ratio=16, clip_bbox=False)
    b = boxes.numpy()[0]
    widths = b[:, 2] - b[:, 0]
    # anchor-major row index: anchor1,row0,col1 -> 1*4 + 0*2 + 1 = 5
    assert widths.argmax() == 5


def test_distribute_fpn_proposals():
    rois = np.array([[0, 0, 16, 16], [0, 0, 224, 224], [0, 0, 448, 448]],
                    dtype=np.float32)
    multi, restore = vops.distribute_fpn_proposals(
        to_tensor(rois), 2, 5, 4, 224)
    assert len(multi) == 4
    total = sum(m.shape[0] for m in multi)
    assert total == 3
    r = restore.numpy().reshape(-1)
    cat = np.concatenate([m.numpy() for m in multi if m.shape[0]])
    np.testing.assert_allclose(cat[r], rois)


def test_matrix_nms_decays_scores():
    boxes = np.array([[[0, 0, 10, 10], [1, 1, 11, 11], [20, 20, 30, 30]]],
                     dtype=np.float32)
    scores = np.array([[[0.1, 0.1, 0.1], [0.9, 0.8, 0.6]]], dtype=np.float32)
    out, num, idx = vops.matrix_nms(
        to_tensor(boxes), to_tensor(scores), score_threshold=0.3,
        background_label=0, return_index=True)
    o = out.numpy()
    assert o.shape[1] == 6 and int(num.numpy()[0]) == o.shape[0]
    assert o.shape[0] == 3
    # rows sorted by decayed score; top box keeps its score exactly
    assert o[0, 1] == pytest.approx(0.9)
    assert (o[:, 1] <= np.array([0.9, 0.8, 0.6]) + 1e-6).all()
    # the overlapping lower-scored box (orig idx 1) must be decayed
    i = idx.numpy().tolist().index(1)
    assert o[i, 1] < 0.8
    # indices correspond row-by-row: idx row i is the box in out row i
    np.testing.assert_allclose(o[:, 2:],
                               boxes[0][idx.numpy()], rtol=1e-6)


def test_distribute_fpn_proposals_per_image_counts():
    rois = np.array([[0, 0, 16, 16], [0, 0, 224, 224], [0, 0, 448, 448],
                     [0, 0, 20, 20]], dtype=np.float32)
    rois_num = np.array([3, 1], dtype=np.int32)
    multi, restore, nums = vops.distribute_fpn_proposals(
        to_tensor(rois), 2, 5, 4, 224, rois_num=to_tensor(rois_num))
    assert all(n.shape[0] == 2 for n in nums)  # per-image counts
    # level of roi 0 (scale 16) == level of roi 3 (scale 20) == level 2
    lvl2 = nums[0].numpy()
    np.testing.assert_array_equal(lvl2, [1, 1])
    total = np.stack([n.numpy() for n in nums]).sum(axis=0)
    np.testing.assert_array_equal(total, rois_num)
