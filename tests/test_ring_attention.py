"""Ring attention / sequence parallelism tests (paddle_tpu/ops/ring_attention).

Capability beyond the reference snapshot (SURVEY §5.7: no SP/CP exists there).
"""
import numpy as np
import pytest

pytestmark = pytest.mark.slow

import paddle_tpu as paddle


def setup_module(m):
    import jax
    jax.config.update("jax_default_matmul_precision", "highest")


def _ref_causal(q, k, v):
    import jax.numpy as jnp
    scale = 1.0 / np.sqrt(q.shape[-1])
    qt, kt, vt = (np.swapaxes(a, 1, 2) for a in (q, k, v))
    logits = np.einsum("bhqd,bhkd->bhqk", qt, kt) * scale
    s = logits.shape[-1]
    mask = np.tril(np.ones((s, s), bool))
    logits = np.where(mask, logits, -1e30)
    e = np.exp(logits - logits.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    out = np.einsum("bhqk,bhkd->bhqd", p, vt)
    return np.swapaxes(out, 1, 2)


class TestRingAttention:
    def test_matches_full_attention(self):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.distributed.mesh_utils import build_mesh
        from paddle_tpu.ops.ring_attention import ring_attention

        mesh = build_mesh({"dp": 2, "sep": 4})
        rng = np.random.RandomState(0)
        q, k, v = (rng.randn(2, 128, 4, 16).astype("float32")
                   for _ in range(3))
        out = jax.jit(lambda a, b, c: ring_attention(
            jnp.asarray(a), jnp.asarray(b), jnp.asarray(c), mesh))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), _ref_causal(q, k, v),
                                   rtol=1e-4, atol=1e-5)

    def test_non_causal(self):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.distributed.mesh_utils import build_mesh
        from paddle_tpu.ops.ring_attention import ring_attention

        mesh = build_mesh({"sep": 8})
        rng = np.random.RandomState(1)
        q, k, v = (rng.randn(1, 64, 2, 8).astype("float32") for _ in range(3))
        out = jax.jit(lambda a, b, c: ring_attention(
            jnp.asarray(a), jnp.asarray(b), jnp.asarray(c), mesh,
            causal=False))(q, k, v)
        # non-causal oracle
        scale = 1.0 / np.sqrt(8)
        qt, kt, vt = (np.swapaxes(a, 1, 2) for a in (q, k, v))
        logits = np.einsum("bhqd,bhkd->bhqk", qt, kt) * scale
        e = np.exp(logits - logits.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        ref = np.swapaxes(np.einsum("bhqk,bhkd->bhqd", p, vt), 1, 2)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)

    def test_grad_matches_full(self):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.distributed.mesh_utils import build_mesh
        from paddle_tpu.ops.ring_attention import ring_attention
        from paddle_tpu.ops.pallas_attention import _mha_reference

        mesh = build_mesh({"sep": 4})
        rng = np.random.RandomState(2)
        q, k, v = (jnp.asarray(rng.randn(1, 64, 2, 8), jnp.float32)
                   for _ in range(3))

        def loss_ring(q, k, v):
            return jnp.sum(ring_attention(q, k, v, mesh) ** 2)

        def loss_ref(q, k, v):
            o = _mha_reference(jnp.transpose(q, (0, 2, 1, 3)),
                               jnp.transpose(k, (0, 2, 1, 3)),
                               jnp.transpose(v, (0, 2, 1, 3)), True,
                               1.0 / np.sqrt(8))
            return jnp.sum(o ** 2)

        g1 = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-4)


class TestGPTSequenceParallel:
    def test_gpt_sep_training_matches_single(self):
        import paddle_tpu.distributed.fleet as fleet
        from paddle_tpu.distributed.mesh_utils import set_global_mesh
        from paddle_tpu.jit import TrainStep
        from paddle_tpu.models import (GPTForCausalLM,
                                       GPTPretrainingCriterion, gpt_tiny)

        ids_np = np.random.RandomState(0).randint(0, 256, (4, 64)).astype("int64")

        def run(hybrid):
            paddle.seed(0)
            if hybrid:
                s = fleet.DistributedStrategy()
                s.hybrid_configs = hybrid
                fleet.init(is_collective=True, strategy=s)
            else:
                set_global_mesh(None)
            m = GPTForCausalLM(gpt_tiny(use_flash_attention=False))
            crit = GPTPretrainingCriterion()
            opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                         parameters=m.parameters())
            step = TrainStep(m, lambda o, y: crit(o, y), opt)
            ids = paddle.to_tensor(ids_np)
            losses = [float(step(ids, ids).numpy()) for _ in range(3)]
            set_global_mesh(None)
            return losses

        single = run(None)
        hybrid = run({"dp_degree": 2, "mp_degree": 2, "pp_degree": 1,
                      "sep_degree": 2})
        np.testing.assert_allclose(single, hybrid, rtol=1e-3, atol=1e-3)


class TestFlashRing:
    """Pallas-kernel-per-chunk ring attention (flash x sep composition)."""

    def _dense_oracle(self, q, k, v, causal, sc):
        import jax.numpy as jnp
        import jax
        lg = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * sc
        if causal:
            s = lg.shape[-1]
            lg = jnp.where(jnp.tril(jnp.ones((s, s), bool)), lg, -1e30)
        p = jax.nn.softmax(lg, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))

    @pytest.mark.parametrize("causal", [True, False])
    def test_flash_ring_matches_dense(self, causal):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh
        from paddle_tpu.ops.ring_attention import ring_attention
        B, S, H, D = 1, 256, 2, 64   # s_loc = 128 per sep rank
        rng = np.random.RandomState(0)
        q = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
        k = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
        v = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
        mesh = Mesh(np.array(jax.devices()[:2]), ("sep",))
        sc = 1.0 / np.sqrt(D)
        out = ring_attention(q, k, v, mesh, causal=causal, sm_scale=sc,
                             use_flash=True)
        ref = self._dense_oracle(q, k, v, causal, sc)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_flash_ring_grads_match_einsum_ring(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh
        from paddle_tpu.ops.ring_attention import ring_attention
        B, S, H, D = 1, 256, 2, 64
        rng = np.random.RandomState(1)
        q = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
        k = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
        v = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
        mesh = Mesh(np.array(jax.devices()[:2]), ("sep",))
        sc = 1.0 / np.sqrt(D)

        def loss(flash):
            def f(q, k, v):
                o = ring_attention(q, k, v, mesh, causal=True, sm_scale=sc,
                                   use_flash=flash)
                return jnp.sum(jnp.square(o.astype(jnp.float32)))
            return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

        gf = loss(True)
        ge = loss(False)
        for a, b, n in zip(gf, ge, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-4, atol=5e-4, err_msg=n)
