"""dy2static AST fallback (round-3 verdict item 5): tensor-dependent
Python if/while converts via AST rewrite when tracing fails.
Reference analog: python/paddle/jit/dy2static/ifelse_transformer.py."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn


class BranchyNet(nn.Layer):
    """Data-dependent branch + data-dependent while loop."""

    def __init__(self):
        super().__init__()
        self.lin = nn.Linear(4, 4)

    def forward(self, x):
        h = self.lin(x)
        if h.sum() > 0:
            out = h * 2.0
        else:
            out = h - 1.0
        return out


class LoopNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.lin = nn.Linear(4, 4)

    def forward(self, x):
        h = self.lin(x)
        # double h until its norm exceeds 100 (tensor-dependent while)
        while (h * h).sum() < 100.0:
            h = h * 2.0
        return h


def _eager_branchy(lin, x):
    h = lin(x)
    if float(h.sum().numpy()) > 0:
        return h * 2.0
    return h - 1.0


class TestAstFallback:
    def test_if_matches_eager(self):
        paddle.seed(0)
        net = BranchyNet()
        for sign in (+1.0, -1.0):
            x = paddle.to_tensor(
                sign * np.abs(np.random.RandomState(0)
                              .randn(2, 4)).astype("float32"))
            want = _eager_branchy(net.lin, x).numpy()
            snet = paddle.jit.to_static(BranchyNet())
            snet.set_state_dict(net.state_dict())
            got = snet(x).numpy()
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       rtol=1e-5)

    def test_while_matches_eager(self):
        paddle.seed(1)
        net = LoopNet()
        x = paddle.to_tensor(
            np.random.RandomState(1).randn(2, 4).astype("float32"))
        h = net.lin(x)
        while float((h * h).sum().numpy()) < 100.0:
            h = h * 2.0
        want = np.asarray(h.numpy())
        snet = paddle.jit.to_static(LoopNet())
        snet.set_state_dict(net.state_dict())
        got = np.asarray(snet(x).numpy())
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_transform_preserves_concrete_semantics(self):
        # the rewritten function must behave identically when called
        # eagerly (python branch selection, no lax)
        from paddle_tpu.jit.dy2static import ast_transform

        def f(a, flag):
            if flag:
                b = a + 1
            else:
                b = a - 1
            return b

        g = ast_transform(f)
        assert g(5, True) == 6 and g(5, False) == 4

    def test_trains_through_branch(self):
        # converted model must be differentiable end-to-end
        paddle.seed(0)
        snet = paddle.jit.to_static(BranchyNet())
        opt = paddle.optimizer.SGD(learning_rate=0.01,
                                   parameters=snet.parameters())
        x = paddle.to_tensor(
            np.random.RandomState(2).randn(2, 4).astype("float32"))
        losses = []
        for _ in range(5):
            loss = (snet(x) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0]

    def test_branch_local_names_do_not_break_concrete_branches(self):
        # a concrete `if` whose branch binds a name used only inside it
        # must keep working after the rewrite (no NameError from the
        # generated return of branch-local vars — _dy2s_get sentinel)
        from paddle_tpu.jit.dy2static import ast_transform

        def f(a, flag):
            out = a * 2
            if flag:
                extra = a + 10
                out = out + extra
            return out

        g = ast_transform(f)
        assert g(5, True) == 25 and g(5, False) == 10

    def test_loop_local_name_in_while(self):
        from paddle_tpu.jit.dy2static import ast_transform

        def f(n):
            i = 0
            while i < n:
                tmp = i * 2  # loop-local, unbound before the loop
                i = tmp // 2 + 1
            return i

        g = ast_transform(f)
        assert g(3) == 3 and g(0) == 0

    def test_side_effecting_test_evaluates_before_capture(self):
        # a walrus in the if-test rebinding an output name must run
        # BEFORE the branch functions snapshot enclosing values
        from paddle_tpu.jit.dy2static import ast_transform

        def f(x):
            out = 0
            if (out := x + 1) > 0:
                out = out * 2
            return out

        g = ast_transform(f)
        assert g(3) == f(3) == 8

    def test_unbound_use_raises_nameerror_family(self):
        from paddle_tpu.jit.dy2static import ast_transform

        def h(a, flag):
            if flag:
                extra = a + 10
            return extra + 1

        g = ast_transform(h)
        assert g(5, True) == 16
        import pytest as _pytest
        with _pytest.raises(NameError):  # UnboundLocalError ⊂ NameError
            g(5, False)

    def test_unsupported_constructs_left_alone(self):
        from paddle_tpu.jit.dy2static import ast_transform

        def f(a):
            # `return` inside the branch: transformer must leave this as
            # plain python (still fine for concrete predicates)
            if a > 0:
                return a * 2
            return a - 1

        g = ast_transform(f)
        assert g(3) == 6 and g(-3) == -4


# ---- round-4 verdict item 6: return / break / continue / for-range ----

class EarlyReturnNet(nn.Layer):
    """Early return from a tensor-dependent branch (the reference's
    return_transformer.py case)."""

    def __init__(self):
        super().__init__()
        self.lin = nn.Linear(4, 4)

    def forward(self, x):
        h = self.lin(x)
        if h.sum() > 0:
            return h * 2.0
        return h - 1.0


class BreakNet(nn.Layer):
    """break out of a tensor-bounded loop (break_continue_transformer)."""

    def __init__(self):
        super().__init__()
        self.lin = nn.Linear(4, 4)

    def forward(self, x):
        h = self.lin(x)
        n = paddle.to_tensor(np.float32(0.0))
        while n < 10.0:
            h = h * 1.5
            n = n + 1.0
            if (h * h).sum() > 50.0:
                break
        return h, n


class ContinueNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.lin = nn.Linear(4, 4)

    def forward(self, x):
        h = self.lin(x)
        n = paddle.to_tensor(np.float32(0.0))
        acc = paddle.zeros_like(h)
        while n < 6.0:
            n = n + 1.0
            if n.sum() % 2.0 < 0.5:
                continue
            acc = acc + h * n
        return acc


class NestedIfNet(nn.Layer):
    """Nested tensor-dependent if inside if (round-3 ADVICE: inner
    rewrites leaked __dy2s_* function objects into the outer carry)."""

    def __init__(self):
        super().__init__()
        self.lin = nn.Linear(4, 4)

    def forward(self, x):
        h = self.lin(x)
        if h.sum() > 0:
            if (h * h).sum() > 10.0:
                out = h * 3.0
            else:
                out = h * 2.0
        else:
            out = h - 1.0
        return out


class ForRangeNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.lin = nn.Linear(4, 4)

    def forward(self, x, steps):
        h = self.lin(x)
        for i in range(steps):
            h = h + float(1.0)
        return h


class TestStatementCoverage:
    def _compare(self, net_cls, eager_fn, xs, atol=1e-5):
        paddle.seed(0)
        net = net_cls()
        for x in xs:
            want = eager_fn(net, x)
            snet = paddle.jit.to_static(net_cls())
            snet.set_state_dict(net.state_dict())
            got = snet(x)
            want_t = want if isinstance(want, tuple) else (want,)
            got_t = got if isinstance(got, tuple) else (got,)
            for w, g in zip(want_t, got_t):
                np.testing.assert_allclose(np.asarray(g.numpy()),
                                           np.asarray(w.numpy()),
                                           rtol=1e-5, atol=atol)

    def test_early_return(self):
        def eager(net, x):
            h = net.lin(x)
            if float(h.sum().numpy()) > 0:
                return h * 2.0
            return h - 1.0
        rng = np.random.RandomState(0)
        xs = [paddle.to_tensor(s * np.abs(rng.randn(2, 4))
                               .astype("float32")) for s in (1.0, -1.0)]
        self._compare(EarlyReturnNet, eager, xs)

    def test_break(self):
        def eager(net, x):
            h = net.lin(x)
            n = 0.0
            while n < 10.0:
                h = h * 1.5
                n = n + 1.0
                if float((h * h).sum().numpy()) > 50.0:
                    break
            return h, paddle.to_tensor(np.float32(n))
        rng = np.random.RandomState(1)
        xs = [paddle.to_tensor(rng.randn(2, 4).astype("float32"))]
        self._compare(BreakNet, eager, xs)

    def test_continue(self):
        def eager(net, x):
            h = net.lin(x)
            n = 0.0
            acc = paddle.zeros_like(h)
            while n < 6.0:
                n = n + 1.0
                if n % 2.0 < 0.5:
                    continue
                acc = acc + h * n
            return acc
        rng = np.random.RandomState(2)
        xs = [paddle.to_tensor(rng.randn(2, 4).astype("float32"))]
        self._compare(ContinueNet, eager, xs)

    def test_nested_if(self):
        def eager(net, x):
            h = net.lin(x)
            if float(h.sum().numpy()) > 0:
                if float((h * h).sum().numpy()) > 10.0:
                    return h * 3.0
                return h * 2.0
            return h - 1.0
        rng = np.random.RandomState(3)
        xs = [paddle.to_tensor(s * np.abs(rng.randn(2, 4))
                               .astype("float32"))
              for s in (1.0, -1.0, 3.0)]
        self._compare(NestedIfNet, eager, xs)

    def test_for_range_tensor_bound(self):
        paddle.seed(0)
        net = ForRangeNet()
        x = paddle.to_tensor(
            np.random.RandomState(4).randn(2, 4).astype("float32"))
        want = net.lin(x).numpy() + 5.0
        snet = paddle.jit.to_static(ForRangeNet())
        snet.set_state_dict(net.state_dict())
        got = snet(x, paddle.to_tensor(np.int32(5))).numpy()
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5)


class ForContinueNet(nn.Layer):
    """continue inside for-range: the counter increment must advance
    even on skipped iterations (review regression: infinite loop)."""

    def __init__(self):
        super().__init__()
        self.lin = nn.Linear(4, 4)

    def forward(self, x, steps):
        h = self.lin(x)
        acc = paddle.zeros_like(h)
        for i in range(steps):
            if paddle.to_tensor(np.float32(1.0)) * i % 2.0 < 0.5:
                continue
            acc = acc + h
        return acc


class TestForContinue:
    def test_for_continue_terminates_and_matches(self):
        paddle.seed(0)
        net = ForContinueNet()
        x = paddle.to_tensor(
            np.random.RandomState(5).randn(2, 4).astype("float32"))
        # odd i in 0..5 -> 3 additions
        want = net.lin(x).numpy() * 3.0
        snet = paddle.jit.to_static(ForContinueNet())
        snet.set_state_dict(net.state_dict())
        got = snet(x, paddle.to_tensor(np.int32(6))).numpy()
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5)


class ReturnThenBindNet(nn.Layer):
    """Early return followed by trailing code that BINDS a local (the
    guard-if carries it one-sided; review regression)."""

    def __init__(self):
        super().__init__()
        self.lin = nn.Linear(4, 4)

    def forward(self, x):
        h = self.lin(x)
        if h.sum() > 0:
            return h * 2.0
        y = h + 1.0
        z = y * 3.0
        return z


class RangeFloatNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.lin = nn.Linear(4, 4)

    def forward(self, x):
        h = self.lin(x)
        for i in range(h.sum()):  # float bound: must raise like range()
            h = h + 1.0
        return h


class TestReviewRegressions2:
    def test_return_then_local_binding(self):
        paddle.seed(0)
        net = ReturnThenBindNet()
        rng = np.random.RandomState(0)
        for s in (1.0, -1.0):
            x = paddle.to_tensor(s * np.abs(rng.randn(2, 4))
                                 .astype("float32"))
            h = net.lin(x)
            want = (h * 2.0 if float(h.sum().numpy()) > 0
                    else (h + 1.0) * 3.0).numpy()
            snet = paddle.jit.to_static(ReturnThenBindNet())
            snet.set_state_dict(net.state_dict())
            got = snet(x).numpy()
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       rtol=1e-5)

    def test_range_float_bound_raises(self):
        import pytest
        paddle.seed(0)
        snet = paddle.jit.to_static(RangeFloatNet())
        x = paddle.to_tensor(
            np.abs(np.random.RandomState(0).randn(2, 4)).astype("float32"))
        with pytest.raises(TypeError, match="integer"):
            snet(x)
