"""MoE + expert parallelism tests.

Oracle pattern: the naive gate is a dense softmax mixture, checkable
against an explicit per-expert loop (reference test analog:
test_moe_api.py over moe_layer.py:261).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed.fleet as fleet
from paddle_tpu.distributed.mesh_utils import set_global_mesh
from paddle_tpu.incubate.distributed.models.moe import MoELayer
from paddle_tpu.jit import TrainStep

B, S, D, F, E = 4, 8, 16, 32, 4


def _x(seed=0):
    rng = np.random.RandomState(seed)
    return paddle.to_tensor(rng.randn(B, S, D).astype("float32"))


def _np(t):
    return np.asarray(t.numpy())


class TestGates:
    @pytest.mark.parametrize("gate", ["gshard", "switch", "naive"])
    def test_forward_shapes(self, gate):
        paddle.seed(0)
        moe = MoELayer(D, F, E, gate=gate)
        out = moe(_x())
        assert out.shape == [B, S, D]
        assert np.isfinite(_np(out)).all()
        assert moe.l_aux is not None
        assert np.isfinite(float(moe.l_aux.numpy()))

    def test_naive_gate_matches_dense_mixture(self):
        paddle.seed(0)
        moe = MoELayer(D, F, E, gate="naive")
        x = _x(1)
        out = _np(moe(x))

        xt = _np(x).reshape(-1, D)
        wg = _np(moe.gate_weight)
        logits = xt @ wg
        p = np.exp(logits - logits.max(1, keepdims=True))
        p /= p.sum(1, keepdims=True)
        ref = np.zeros_like(xt)

        def gelu(a):
            return 0.5 * a * (1 + np.tanh(
                np.sqrt(2 / np.pi) * (a + 0.044715 * a ** 3)))
        for e in range(E):
            h = gelu(xt @ _np(moe.w1)[e] + _np(moe.b1)[e])
            fe = h @ _np(moe.w2)[e] + _np(moe.b2)[e]
            ref += p[:, e:e + 1] * fe
        np.testing.assert_allclose(out.reshape(-1, D), ref, rtol=2e-4,
                                   atol=2e-4)

    def test_gshard_top2_combine_renormalized(self):
        paddle.seed(0)
        moe = MoELayer(D, F, E, gate="gshard", capacity_factor=100.0)
        x = _x(2)
        moe(x)  # no drops at huge capacity
        # re-derive combine weights: each token's two gate values sum to 1
        from paddle_tpu.incubate.distributed.models.moe import _gshard_gate
        import jax.numpy as jnp
        xt = jnp.asarray(_np(x).reshape(-1, D))
        wg = jnp.asarray(_np(moe.gate_weight))
        combine, aux = _gshard_gate(xt, wg, E, moe._capacity(B * S))
        sums = np.asarray(combine.sum(axis=(1, 2)))
        np.testing.assert_allclose(sums, np.ones_like(sums), atol=1e-5)

    def test_switch_capacity_drops_tokens(self):
        paddle.seed(0)
        # capacity 1 per expert: at most E tokens survive out of B*S
        moe = MoELayer(D, F, E, gate="switch", capacity_factor=E / (B * S))
        out = _np(moe(_x(3)))
        dropped = np.all(out.reshape(-1, D) == 0, axis=1).sum()
        assert dropped >= B * S - E

    def test_grads_flow_to_experts_and_gate(self):
        paddle.seed(0)
        moe = MoELayer(D, F, E, gate="gshard")
        out = moe(_x(4))
        out.sum().backward()
        for p in (moe.gate_weight, moe.w1, moe.w2, moe.b1):
            assert p.grad is not None
            assert np.abs(_np(p.grad)).sum() > 0, p.name

    def test_unknown_gate_raises(self):
        with pytest.raises(ValueError, match="unknown gate"):
            MoELayer(D, F, E, gate="bogus")


class _MoENet(paddle.nn.Layer):
    def __init__(self):
        super().__init__()
        self.proj = paddle.nn.Linear(D, D)
        self.moe = MoELayer(D, F, E, gate="gshard")

    def forward(self, x):
        return self.moe(self.proj(x))


class TestExpertParallel:
    def _run(self, hybrid, steps=3):
        paddle.seed(0)
        if hybrid:
            s = fleet.DistributedStrategy()
            s.hybrid_configs = hybrid
            fleet.init(is_collective=True, strategy=s)
        else:
            set_global_mesh(None)
        net = _MoENet()
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=net.parameters())
        step = TrainStep(net, lambda o, y: ((o - y) ** 2).mean(), opt)
        x = _x(5)
        y = _x(6)
        losses = [float(step(x, y).numpy()) for _ in range(steps)]
        net_params = {n: _np(p) for n, p in net.named_parameters()}
        set_global_mesh(None)
        return losses, net_params, net

    @pytest.mark.slow
    def test_ep4_matches_single(self):
        import jax
        jax.config.update("jax_default_matmul_precision", "highest")
        single, p1, _ = self._run(None)
        ep, p2, _ = self._run({"dp_degree": 1, "ep_degree": 4})
        np.testing.assert_allclose(single, ep, rtol=1e-4, atol=1e-4)
        for n in p1:
            np.testing.assert_allclose(p1[n], p2[n], rtol=1e-4, atol=1e-4,
                                       err_msg=n)

    @pytest.mark.slow
    def test_dp2_ep4_matches_single(self):
        import jax
        jax.config.update("jax_default_matmul_precision", "highest")
        single, p1, _ = self._run(None)
        hyb, p2, _ = self._run({"dp_degree": 2, "ep_degree": 4})
        np.testing.assert_allclose(single, hyb, rtol=1e-4, atol=1e-4)
        for n in p1:
            np.testing.assert_allclose(p1[n], p2[n], rtol=1e-4, atol=1e-4,
                                       err_msg=n)

    def test_expert_weights_sharded_over_ep(self):
        _, _, net = self._run({"dp_degree": 1, "ep_degree": 4}, steps=1)
        w1 = net.moe.w1._data
        shard_experts = {sh.data.shape[0] for sh in w1.addressable_shards}
        assert shard_experts == {E // 4}
