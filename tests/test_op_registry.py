"""Op registry: yaml source of truth + compat aliasing + coverage target
(reference: ops.yaml/op_compat.yaml; SURVEY §7.2 ~350-op target)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.ops import registry


def test_coverage_meets_target():
    assert len(registry.op_names()) >= 350


def test_every_entry_resolves_to_callable():
    bad = []
    for name in registry.op_names():
        try:
            fn = registry.resolve(name)
            if not callable(fn):
                bad.append(name)
        except Exception as e:  # noqa: BLE001
            bad.append(f"{name}: {e}")
    assert not bad, bad[:20]


def test_compat_aliases_resolve():
    # op_compat.yaml rename pairs must round-trip to live callables
    for old, new in [("elementwise_add", "add"),
                     ("reduce_sum", "sum"),
                     ("lookup_table_v2", "embedding"),
                     ("fill_constant", "full"),
                     ("expand_v2", "expand"),
                     ("hard_sigmoid", "hardsigmoid")]:
        assert registry.compat_name(old) == new, old
        assert callable(registry.resolve(old))


def test_resolved_op_computes():
    add = registry.resolve("elementwise_add")
    out = add(paddle.to_tensor(np.array([1.0], np.float32)),
              paddle.to_tensor(np.array([2.0], np.float32)))
    assert float(out.numpy()) == 3.0


def test_unknown_op_raises():
    import pytest
    with pytest.raises(KeyError, match="not in the registry"):
        registry.resolve("definitely_not_an_op")


class TestMiscCoverage:
    """Memory stats, monitor registry, callbacks, BERT, autotune cache,
    custom-op toolchain — VERDICT coverage rows 2, 12, 15, 44, 48."""

    def test_memory_stats_surface(self):
        cur = paddle.device.memory_allocated()
        peak = paddle.device.max_memory_allocated()
        assert peak >= cur >= 0
        assert paddle.device.cuda.memory_allocated() >= 0
        paddle.device.reset_peak_memory_stats()

    def test_monitor_registry(self):
        from paddle_tpu.framework import monitor
        monitor.stat_reset()
        monitor.stat_add("x", 2)
        monitor.stat_add("x", 3)
        assert monitor.stat_get("x") == 5
        assert "x" in monitor.stat_names()
        monitor.stat_reset("x")
        assert monitor.stat_get("x") == 0

    @pytest.mark.slow
    def test_hapi_callbacks_early_stopping(self, tmp_path):
        from paddle_tpu.vision.datasets import FakeMNIST
        from paddle_tpu.vision.models import LeNet
        from paddle_tpu.hapi.callbacks import EarlyStopping, ModelCheckpoint
        paddle.seed(0)
        m = paddle.Model(LeNet())
        m.prepare(paddle.optimizer.Adam(learning_rate=1e-3,
                                        parameters=m.parameters()),
                  paddle.nn.CrossEntropyLoss())
        es = EarlyStopping(monitor="loss", patience=0, verbose=0)
        ck = ModelCheckpoint(save_freq=1, save_dir=str(tmp_path))
        ds = FakeMNIST(n=32)
        m.fit(ds, eval_data=ds, epochs=4, batch_size=16, verbose=0,
              callbacks=[es, ck])
        assert (tmp_path / "final.pdparams").exists()

    @pytest.mark.slow
    def test_bert_family_trains(self):
        from paddle_tpu.models import (BertForSequenceClassification,
                                       bert_tiny)
        import numpy as np
        paddle.seed(0)
        m = BertForSequenceClassification(bert_tiny(), num_classes=3)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=m.parameters())
        rng = np.random.RandomState(0)
        ids = paddle.to_tensor(rng.randint(0, 256, (4, 32)).astype("int64"))
        y = paddle.to_tensor(rng.randint(0, 3, (4,)).astype("int64"))
        lossf = paddle.nn.CrossEntropyLoss()
        losses = []
        for _ in range(5):
            loss = lossf(m(ids), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0]

    def test_autotune_cache_roundtrip(self, tmp_path, monkeypatch):
        from paddle_tpu.ops import autotune
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_CACHE",
                           str(tmp_path / "at.json"))
        autotune._cache.clear()
        autotune._loaded = False
        autotune._cache["mha_fwd/test"] = [256, 128]
        autotune._save()
        autotune._cache.clear()
        autotune._loaded = False
        autotune._load()
        assert autotune._cache["mha_fwd/test"] == [256, 128]

    def test_custom_op_decorator(self):
        import numpy as np
        from paddle_tpu.utils import custom_op

        @custom_op("quad", backward=lambda res, g: (g * 4.0,))
        def quad(x):
            return x * 4.0

        x = paddle.to_tensor(np.array([1.5], np.float32),
                             stop_gradient=False)
        y = quad(x)
        y.sum().backward()
        assert float(y.numpy()) == 6.0
        assert float(x.grad.numpy()) == 4.0

    def test_fft_module(self):
        import numpy as np
        x = np.random.RandomState(0).randn(8).astype(np.float32)
        out = paddle.fft.fft(paddle.to_tensor(x))
        np.testing.assert_allclose(np.asarray(out.numpy()),
                                   np.fft.fft(x), rtol=1e-4, atol=1e-4)
        out2 = paddle.fft.irfft(paddle.fft.rfft(paddle.to_tensor(x)))
        np.testing.assert_allclose(np.asarray(out2.numpy()), x, rtol=1e-4,
                                   atol=1e-4)
