"""Config.set_precision serving-dtype rewrite (round-4 verdict item 3).

The reference rewrites the inference graph to fp16/bf16
(convert_to_mixed_precision.cc); here the PdProgram re-lowers the whole
program in the target dtype before the serving jit traces.
"""
import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import inference


def _export_lenet(tmp):
    from paddle_tpu.vision.models import LeNet

    paddle.seed(0)
    net = LeNet()
    prefix = os.path.join(tmp, "m")
    paddle.jit.save(net, prefix, input_spec=[
        paddle.static.InputSpec([2, 1, 28, 28], "float32")])
    return net, prefix


class TestServingPrecision:
    def test_bf16_within_tolerance_and_actually_lowered(self, tmp_path):
        net, prefix = _export_lenet(str(tmp_path))
        x = np.random.RandomState(0).randn(2, 1, 28, 28).astype("float32")

        cfg = inference.Config(prefix + ".pdmodel", prefix + ".pdiparams")
        pred32 = inference.create_predictor(cfg)
        out32 = pred32.run([x])[0]

        cfg16 = inference.Config(prefix + ".pdmodel",
                                 prefix + ".pdiparams")
        cfg16.set_precision(inference.PrecisionType.Bfloat16)
        pred16 = inference.create_predictor(cfg16)
        out16 = pred16.run([x])[0]

        assert out16.dtype == np.float32  # outputs come back f32
        np.testing.assert_allclose(out16, out32, rtol=0.05, atol=0.02)
        # the rewrite really happened: bf16 rounding must show
        assert not np.array_equal(out16, out32)
        # and the program's float params really carry the serving dtype
        prog = pred16._artifact._prog
        import jax.numpy as jnp
        assert prog.precision == "bfloat16"

    def test_fp16_precision(self, tmp_path):
        net, prefix = _export_lenet(str(tmp_path))
        x = np.random.RandomState(1).randn(2, 1, 28, 28).astype("float32")
        cfg = inference.Config(prefix + ".pdmodel", prefix + ".pdiparams")
        cfg.set_precision(inference.PrecisionType.Half)
        pred = inference.create_predictor(cfg)
        out = pred.run([x])[0]
        want = net(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(out, want, rtol=0.02, atol=0.01)

    def test_precision_needs_program_form(self, tmp_path):
        # only a .pdexec (no .pdmodel): reduced precision must refuse
        # loudly rather than silently serve f32
        from paddle_tpu.vision.models import LeNet

        paddle.seed(0)
        net = LeNet()
        prefix = os.path.join(str(tmp_path), "m")
        paddle.jit.save(net, prefix, input_spec=[
            paddle.static.InputSpec([2, 1, 28, 28], "float32")],
            pdmodel_format=False)
        assert not os.path.exists(prefix + ".pdmodel")
        cfg = inference.Config(prefix)
        cfg.set_precision(inference.PrecisionType.Bfloat16)
        with pytest.raises(ValueError, match="re-lower"):
            inference.create_predictor(cfg)

    def test_int8_routes_to_quantization(self, tmp_path):
        _, prefix = _export_lenet(str(tmp_path))
        cfg = inference.Config(prefix + ".pdmodel", prefix + ".pdiparams")
        cfg.set_precision(inference.PrecisionType.Int8)
        with pytest.raises(NotImplementedError, match="PTQ"):
            inference.create_predictor(cfg)

    def test_set_precision_survives_set_model(self):
        cfg = inference.Config()
        cfg.set_precision(inference.PrecisionType.Bfloat16)
        cfg.set_model("/tmp/nope")
        assert cfg.precision() == inference.PrecisionType.Bfloat16
