"""Namespace __all__ parity gate vs the reference's own package lists —
every symbol the reference exports at these surfaces must exist here
(round-5 sweep closed the last 52; this keeps them closed)."""
import importlib
import os
import re

import numpy as np
import pytest

REF = "/root/reference/python/paddle"

PAIRS = [
    ("__init__.py", "paddle_tpu"),
    ("nn/__init__.py", "paddle_tpu.nn"),
    ("nn/functional/__init__.py", "paddle_tpu.nn.functional"),
    ("distributed/__init__.py", "paddle_tpu.distributed"),
    ("static/__init__.py", "paddle_tpu.static"),
    ("incubate/__init__.py", "paddle_tpu.incubate"),
    ("io/__init__.py", "paddle_tpu.io"),
    ("optimizer/__init__.py", "paddle_tpu.optimizer"),
    ("vision/__init__.py", "paddle_tpu.vision"),
]


def _ref_all(path):
    with open(path) as f:
        src = f.read()
    m = re.search(r"__all__ = \[(.*?)\]", src, re.S)
    if not m:
        return []
    return re.findall(r"'([^']+)'", m.group(1)) + \
        re.findall(r'"([^"]+)"', m.group(1))


@pytest.mark.parametrize("ref_file,mod_name", PAIRS,
                         ids=[p[1] for p in PAIRS])
def test_reference_all_symbols_present(ref_file, mod_name):
    path = os.path.join(REF, ref_file)
    if not os.path.exists(path):
        pytest.skip("reference tree unavailable")
    want = _ref_all(path)
    assert want, f"no __all__ parsed from {path}"
    mod = importlib.import_module(mod_name)
    missing = [n for n in want if not hasattr(mod, n)]
    assert not missing, f"{mod_name} missing reference symbols: {missing}"


def test_tensor_method_parity():
    """Every reference tensor_method_func entry exists on Tensor."""
    import paddle_tpu as paddle

    path = os.path.join(REF, "tensor", "__init__.py")
    if not os.path.exists(path):
        pytest.skip("reference tree unavailable")
    src = open(path).read()
    m = re.search(r"tensor_method_func = \[(.*?)\]", src, re.S)
    assert m is not None, "tensor_method_func list not found in reference"
    want = re.findall(r"'([^']+)'", m.group(1)) + \
        re.findall(r'"([^"]+)"', m.group(1))
    assert len(want) > 100, f"parsed only {len(want)} methods — regex " \
                            f"no longer matches the reference format"
    t = paddle.to_tensor(np.ones((2, 2), "float32"))
    missing = [n for n in want if not hasattr(t, n)]
    assert not missing, f"Tensor missing reference methods: {missing}"


def test_new_tensor_methods_work():
    import paddle_tpu as paddle

    t = paddle.to_tensor(np.array([[0.0, 1.0]], "float32"))
    np.testing.assert_allclose(t.sigmoid().numpy(),
                               1 / (1 + np.exp(-t.numpy())), rtol=1e-5)
    x = paddle.to_tensor(np.zeros((2, 3), "float32"))
    x.flatten_()
    assert list(x.shape) == [6]
    e = paddle.to_tensor(np.array([0.5], "float32"))
    e.erfinv_()
    from scipy.special import erfinv as sp_erfinv
    np.testing.assert_allclose(e.numpy(), sp_erfinv([0.5]), rtol=1e-4)


class TestNewSurfaceFunctionality:
    def test_weighted_random_sampler(self):
        from paddle_tpu.io import WeightedRandomSampler

        np.random.seed(0)
        s = WeightedRandomSampler([0.0, 0.0, 1.0], 8, replacement=True)
        idx = list(s)
        assert len(idx) == 8 and all(i == 2 for i in idx)
        with pytest.raises(ValueError):
            WeightedRandomSampler([1.0], 5, replacement=False)

    def test_index_add_inplace(self):
        import paddle_tpu as paddle

        x = paddle.to_tensor(np.zeros((3, 2), "float32"))
        idx = paddle.to_tensor(np.array([0, 2], "int64"))
        v = paddle.to_tensor(np.ones((2, 2), "float32"))
        out = paddle.index_add_(x, idx, 0, v)
        np.testing.assert_allclose(
            x.numpy(), [[1, 1], [0, 0], [1, 1]])
        assert out is x or np.allclose(out.numpy(), x.numpy())

    def test_softmax_mask_fuse_upper_triangle(self):
        import paddle_tpu as paddle
        from paddle_tpu.incubate import softmax_mask_fuse_upper_triangle

        rng = np.random.RandomState(0)
        x = rng.randn(2, 2, 4, 4).astype("float32")
        out = softmax_mask_fuse_upper_triangle(
            paddle.to_tensor(x)).numpy()
        # strictly-upper entries get ~0 probability; rows sum to 1
        assert np.triu(out[0, 0], k=1).max() < 1e-4
        np.testing.assert_allclose(out.sum(-1), 1.0, rtol=1e-5)

    def test_static_gradients_and_compat(self):
        import paddle_tpu as paddle
        import paddle_tpu.static as static

        paddle.enable_static()
        try:
            main, startup = static.Program(), static.Program()
            with static.program_guard(main, startup):
                x = static.data("x", [4, 3], "float32")
                w = static.create_parameter([3, 2], "float32",
                                            name="w_cgrad")
                gv = static.create_global_var([1], 2.0, "float32")
                y = paddle.matmul(x, w) * gv
                loss = paddle.mean(y)
                (g,) = static.gradients(loss, [w])
            exe = static.Executor()
            exe.run(startup)
            rng = np.random.RandomState(0)
            xv = rng.randn(4, 3).astype("float32")
            gval, = exe.run(main, feed={"x": xv}, fetch_list=[g])
            # closed form: d(mean(2*x@w))/dw = 2 * x^T @ ones/(N*M)
            want = 2.0 * xv.T @ np.full((4, 2), 1.0 / 8, "float32")
            np.testing.assert_allclose(gval, want, rtol=1e-4, atol=1e-5)
        finally:
            paddle.disable_static()

    def test_static_save_load_roundtrip(self, tmp_path):
        import paddle_tpu as paddle
        import paddle_tpu.static as static

        paddle.enable_static()
        try:
            main, startup = static.Program(), static.Program()
            with static.program_guard(main, startup):
                x = static.data("x", [2, 3], "float32")
                w = static.create_parameter([3, 2], "float32",
                                            name="w_sv")
                y = paddle.matmul(x, w)
            exe = static.Executor()
            exe.run(startup)
            orig = np.asarray(w._data).copy()
            prefix = str(tmp_path / "m")
            static.save(main, prefix)
            state = static.load_program_state(prefix)
            assert "w_sv" in state
            w._data = np.zeros_like(orig)
            static.set_program_state(main, state)
            np.testing.assert_allclose(np.asarray(w._data), orig)
        finally:
            paddle.disable_static()

    def test_compat_shims_and_hw_raisers(self):
        import paddle_tpu.static as static

        bs = static.BuildStrategy()
        bs.fuse_bn_act_ops = True
        assert bs.fuse_bn_act_ops is True
        with pytest.raises(RuntimeError, match="XPU"):
            static.xpu_places()
        with pytest.raises(RuntimeError, match="IPU"):
            static.IpuStrategy()
        with pytest.raises(NotImplementedError):
            static.WeightNormParamAttr(dim=0)

    def test_vision_image_backend(self, tmp_path):
        import paddle_tpu.vision as V

        assert V.get_image_backend() == "pil"
        with pytest.raises(RuntimeError):
            V.set_image_backend("cv2")
        from PIL import Image

        p = tmp_path / "t.png"
        Image.fromarray(np.zeros((4, 4, 3), "uint8")).save(p)
        img = V.image_load(str(p))
        assert img.size == (4, 4)
