"""Regression tests for the BENCH_r04 crash: a backend that wedges
AFTER the subprocess probe succeeded used to kill bench.py rc=1 from
inside whatever eager op dispatched first (a ``convert_element_type``
on the 1.3B path — which made it LOOK like a dtype regression). The
bench must classify in-process backend-unavailable errors and emit the
structured ``"skipped": true`` record instead."""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestBackendUnavailableClassifier:
    def test_matches_the_r04_error_shape(self):
        sys.path.insert(0, REPO)
        import bench
        e = RuntimeError(
            "Unable to initialize backend 'axon': UNAVAILABLE: TPU "
            "backend setup/compile error (Unavailable). (set "
            "JAX_PLATFORMS='' to automatically choose an available "
            "backend)")
        assert bench._backend_unavailable(e)
        assert bench._backend_unavailable(
            RuntimeError("Unable to initialize backend 'rocm': boom"))

    def test_real_errors_still_raise(self):
        sys.path.insert(0, REPO)
        import bench
        assert not bench._backend_unavailable(
            ValueError("operand dtypes must match"))
        assert not bench._backend_unavailable(
            TypeError("convert_element_type got bad new_dtype"))


@pytest.mark.slow
def test_probe_pass_then_wedge_emits_skip_record():
    """End-to-end: probe says the backend is fine, the in-process first
    op then hits backend-unavailable (simulated with an uninstallable
    JAX_PLATFORMS) — bench must exit 0 with a skipped record, not
    crash."""
    env = dict(os.environ, JAX_PLATFORMS="rocm")
    env.pop("XLA_FLAGS", None)
    code = (
        "import sys; sys.argv = ['bench.py', '--config', 'small', "
        "'--steps', '1', '--windows', '1']\n"
        "import bench\n"
        "bench._probe_backend = lambda **kw: ('tpu', '', "
        "{'attempts': []})\n"
        "raise SystemExit(bench.main())\n")
    r = subprocess.run([sys.executable, "-c", code], cwd=REPO, env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]
    record = json.loads(r.stdout.strip().splitlines()[-1])
    assert record["skipped"] is True
    assert record["metric"] == "backend_unavailable"
    assert "after a successful probe" in record["error"]
