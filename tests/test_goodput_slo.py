"""Goodput ledger + continuous step profiler + SLO burn-rate monitor
(ISSUE 11).

Every window/clock here is INJECTED — the burn-rate math, the goodput
accounting identity, and the straggler detector are all exercised
deterministically; the fleet test drives real HTTP replicas but keeps
its SLO windows wide enough that wall-clock jitter cannot flip the
verdict.
"""
# pdlint: disable=metric_discipline  (tests register synthetic
# families on private registries on purpose)
import json
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import observability, serving
from paddle_tpu.framework.flags import set_flags
from paddle_tpu.observability import goodput, slo, stepprof, tracing
from paddle_tpu.observability.registry import MetricRegistry
from paddle_tpu.serving import fleet


def _get(url, timeout=10):
    opener = urllib.request.build_opener(
        urllib.request.ProxyHandler({}))
    with opener.open(url, timeout=timeout) as r:
        return r.status, r.read().decode()


class _Clock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


@pytest.fixture()
def fresh_defaults():
    """Swap in fresh process-wide singletons so wired code paths
    (TrainStep, CheckpointManager, the engine) record into instances
    this test owns."""
    led_prev = goodput.set_default_ledger(goodput.GoodputLedger())
    prof_prev = stepprof.set_default_profiler(
        stepprof.StepProfiler(min_samples=4))
    mon_prev = slo.set_default_monitor(slo.SLOMonitor())
    yield (goodput.default_ledger(), stepprof.default_profiler(),
           slo.default_monitor())
    goodput.set_default_ledger(led_prev)
    stepprof.set_default_profiler(prof_prev)
    slo.set_default_monitor(mon_prev)


# ============================================================ goodput
class TestGoodputLedger:
    def test_frames_subtract_nested_recordings(self):
        clock = _Clock()
        led = goodput.GoodputLedger(registry=MetricRegistry(),
                                    now=clock)
        led.start()
        led.begin("step")
        clock.advance(2.0)
        led.record("compile", 1.5)   # fired inside the step frame
        led.end()
        rep = led.report()
        assert rep["categories_s"]["step"] == pytest.approx(0.5)
        assert rep["categories_s"]["compile"] == pytest.approx(1.5)

    def test_nested_frames_propagate_elapsed_to_parent(self):
        clock = _Clock()
        led = goodput.GoodputLedger(registry=MetricRegistry(),
                                    now=clock)
        led.begin("step")
        clock.advance(0.25)
        with led.timed("ckpt_save"):
            clock.advance(1.0)
        clock.advance(0.25)
        led.end()
        rep = led.report()
        assert rep["categories_s"]["step"] == pytest.approx(0.5)
        assert rep["categories_s"]["ckpt_save"] == pytest.approx(1.0)

    def test_simulated_timeline_sums_to_wall_clock(self):
        """The acceptance timeline: compile -> steps -> checkpoint ->
        preempt-restore -> replay; categories + idle sum to elapsed
        within 2%."""
        clock = _Clock()
        led = goodput.GoodputLedger(registry=MetricRegistry(),
                                    now=clock)
        led.start()
        with led.timed("compile"):
            clock.advance(8.0)
        for _ in range(20):                       # productive steps
            with led.timed("step"):
                clock.advance(0.5)
        clock.advance(1.0)                        # input stall
        led.record("data_stall", 1.0)
        with led.timed("ckpt_save"):
            clock.advance(2.0)
        clock.advance(0.7)                        # unattributed
        with led.timed("ckpt_restore"):           # preempt-restore
            clock.advance(1.5)
        led.arm_replay(3)
        for _ in range(5):                        # 3 replayed + 2 new
            with led.timed("step"):
                clock.advance(0.5)
        rep = led.report()
        cats = rep["categories_s"]
        assert rep["accounting"]["closes"], rep["accounting"]
        assert sum(cats.values()) == pytest.approx(rep["elapsed_s"])
        assert cats["step"] == pytest.approx(11.0)   # 20 + 2 new
        assert cats["recovery"] == pytest.approx(1.5)  # 3 replayed
        assert cats["compile"] == pytest.approx(8.0)
        assert cats["data_stall"] == pytest.approx(1.0)
        assert cats["idle"] == pytest.approx(0.7)
        assert rep["goodput_fraction"] == pytest.approx(
            11.0 / rep["elapsed_s"], abs=1e-6)

    def test_idle_counter_is_monotone_and_synced(self):
        clock = _Clock()
        reg = MetricRegistry()
        led = goodput.GoodputLedger(registry=reg, now=clock)
        led.start()
        clock.advance(5.0)
        led.report()
        fam = reg.get("paddle_goodput_seconds_total")
        idle1 = fam.labels(category="idle").value
        assert idle1 == pytest.approx(5.0)
        with led.timed("step"):
            clock.advance(1.0)
        led.report()
        assert fam.labels(category="idle").value == \
            pytest.approx(idle1)   # attributed time never shrinks idle
        clock.advance(2.0)
        led.report()
        assert fam.labels(category="idle").value == pytest.approx(7.0)

    def test_overlap_is_surfaced_not_hidden(self):
        """Two threads claiming the same wall second overrun elapsed;
        the report says so instead of silently closing."""
        clock = _Clock()
        led = goodput.GoodputLedger(registry=MetricRegistry(),
                                    now=clock)
        led.start()
        clock.advance(1.0)
        led.record("step", 1.0)
        led.record("data_stall", 1.0)    # overlapping attribution
        rep = led.report()
        assert rep["accounting"]["overlap_s"] == pytest.approx(1.0)
        assert not rep["accounting"]["closes"]

    def test_unknown_category_rejected(self):
        led = goodput.GoodputLedger(registry=MetricRegistry())
        with pytest.raises(ValueError):
            led.record("coffee_break", 1.0)

    def test_goodputz_endpoint(self, fresh_defaults):
        led, _, _ = fresh_defaults
        led.start()
        led.record("step", 1.0)
        srv = observability.TelemetryServer(port=0).start()
        try:
            status, body = _get(srv.url("/goodputz"))
            assert status == 200
            doc = json.loads(body)
            assert doc["goodput"]["categories_s"]["step"] >= 1.0
            assert "steps" in doc
        finally:
            srv.stop()


class TestGoodputWiring:
    def test_train_step_records_step_and_profile(self, fresh_defaults):
        led, prof, _ = fresh_defaults
        import paddle_tpu.nn as nn
        from paddle_tpu.jit import TrainStep
        paddle.seed(0)
        net = nn.Linear(4, 2)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        step = TrainStep(net, lambda o, y: ((o - y) ** 2).mean(), opt)
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        y = paddle.to_tensor(np.zeros((2, 2), np.float32))
        step(x, y)
        step(x, y)
        rep = led.report()
        assert rep["categories_s"]["step"] > 0.0
        envs = prof.envelopes(kind="train")
        assert len(envs) == 2
        assert envs[-1]["wall_ms"] > 0.0

    def test_checkpoint_manager_feeds_ledger_and_replay(
            self, fresh_defaults, tmp_path):
        led, _, _ = fresh_defaults
        from paddle_tpu.elastic import CheckpointManager
        p = paddle.to_tensor(np.arange(4, dtype=np.float32))
        p.name = "w"
        mgr = CheckpointManager(str(tmp_path), parameters={"w": p},
                                async_save=False, health_check=False)
        mgr.save(5, block=True)
        rep = led.report()
        assert rep["categories_s"]["ckpt_save"] > 0.0
        # progress ran ahead of the checkpoint: restore counts the
        # lost steps and arms replay attribution
        mgr._write_progress(8)
        res = mgr.restore_latest()
        assert res is not None and res.steps_lost == 3
        assert led.report()["categories_s"]["ckpt_restore"] > 0.0
        with led.timed("step"):
            pass
        assert led.report()["replay_steps_pending"] == 2
        rep = led.report()
        assert rep["categories_s"]["recovery"] >= 0.0
        mgr.close()

    def test_fit_callback_data_stall_and_step_frames(self):
        clock = _Clock()
        reg = MetricRegistry()
        led = goodput.GoodputLedger(registry=reg, now=clock)
        prof = stepprof.StepProfiler(min_samples=4, registry=reg,
                                     now=clock, wall_ns=lambda: 0)
        cb = observability.TrainingTelemetryCallback(
            registry=reg, now=clock, ledger=led, step_profiler=prof)
        cb.on_train_begin()
        for i in range(3):
            cb.on_train_batch_begin(i)
            clock.advance(0.2)                 # the step itself
            cb.on_train_batch_end(i, {"loss": 0.5})
            clock.advance(0.05)                # the loader gap
        cb.on_train_end()
        rep = led.report()
        assert rep["categories_s"]["step"] == pytest.approx(0.6)
        # two inter-batch gaps (the post-train gap is not a stall)
        assert rep["categories_s"]["data_stall"] == pytest.approx(0.1)
        assert len(prof.envelopes(kind="train")) == 3


# ============================================================ stepprof
class TestStepProfiler:
    def test_ring_is_bounded(self):
        prof = stepprof.StepProfiler(window=8,
                                     registry=MetricRegistry())
        for i in range(50):
            prof.record_step(1.0, kind="k", step=i)
        envs = prof.envelopes(limit=100)
        assert len(envs) == 8
        assert envs[-1]["step"] == 49

    def test_straggler_promotes_error_span(self):
        buf_prev = tracing.set_default_buffer(tracing.SpanBuffer(64))
        try:
            prof = stepprof.StepProfiler(min_samples=8, anomaly_k=4.0,
                                         registry=MetricRegistry())
            for i in range(20):
                prof.record_step(10.0 + (i % 3) * 0.2, kind="train",
                                 step=i)
            env = prof.record_step(200.0, kind="train", step=99)
            assert env["anomaly"]["threshold_ms"] < 200.0
            spans = tracing.default_buffer().snapshot()
            straggler = [s for s in spans
                         if s["name"] == "stepprof::straggler"]
            assert len(straggler) == 1
            assert straggler[0]["status"] == "error"
            assert straggler[0]["attrs"]["step"] == 99
            summary = prof.summary()
            assert summary["kinds"]["train"]["anomalies"] == 1
            assert summary["recent_anomalies"][-1]["step"] == 99
        finally:
            tracing.set_default_buffer(buf_prev)

    def test_baseline_stays_quiet_and_anomalies_do_not_shift_it(self):
        prof = stepprof.StepProfiler(min_samples=8, anomaly_k=6.0,
                                     registry=MetricRegistry())
        for i in range(64):
            prof.record_step(5.0 + (i % 5) * 0.1, kind="d", step=i)
        assert prof.summary()["kinds"]["d"]["anomalies"] == 0
        ewma_before = prof.summary()["kinds"]["d"]["ewma_ms"]
        for _ in range(5):
            prof.record_step(500.0, kind="d")
        # a straggler burst stays anomalous instead of becoming the
        # new normal
        assert prof.summary()["kinds"]["d"]["anomalies"] == 5
        assert prof.summary()["kinds"]["d"]["ewma_ms"] == \
            pytest.approx(ewma_before, rel=0.05)

    def test_kinds_detect_independently(self):
        prof = stepprof.StepProfiler(min_samples=4, anomaly_k=4.0,
                                     registry=MetricRegistry())
        for i in range(10):
            prof.record_step(1.0, kind="train")
            prof.record_step(50.0, kind="decode")
        # 50ms is normal for decode, anomalous for train
        assert "anomaly" not in prof.record_step(50.0, kind="decode")
        assert "anomaly" in prof.record_step(50.0, kind="train")

    def test_decode_engine_records_envelopes(self, fresh_defaults):
        _, prof, _ = fresh_defaults
        from paddle_tpu.models import GPTForCausalLM, gpt_tiny
        paddle.seed(0)
        model = GPTForCausalLM(gpt_tiny(use_flash_attention=False))
        eng = serving.generation.GenerationServer(
            model, name="t_gp_eng", max_batch=2, start=True)
        try:
            toks = eng.generate([1, 2, 3], max_new_tokens=4)
            assert len(toks) == 4
        finally:
            eng.shutdown()
        envs = prof.envelopes(kind="decode")
        assert envs, "decode iterations must drop envelopes"
        assert envs[-1]["occupancy"] >= 1
        assert "kv_pages_used" in envs[-1]


# ============================================================ slo
def _mk_slo(name, metric, clock, threshold=25.0, target=0.99,
            short=10.0, long=40.0, labels=None, reg=None):
    mon = slo.SLOMonitor(registry=reg or MetricRegistry(), now=clock)
    s = slo.LatencySLO(name, metric, threshold_ms=threshold,
                       target_fraction=target, labels=labels,
                       burn_rules=[slo.BurnRule("fast", short, long,
                                                14.4)])
    mon.add(s)
    return mon, s


class TestBurnRateMath:
    def test_fires_on_regression_quiet_at_baseline_recovers(self):
        """The acceptance triad on a real registry histogram with an
        injected clock: quiet -> regression fires within one
        evaluation -> drained window resolves."""
        clock = _Clock(1000.0)
        reg = MetricRegistry()
        hist = reg.histogram("t_slo_lat_ms", "", ("server",))
        mon, _ = _mk_slo("p99", "t_slo_lat_ms", clock, reg=reg)
        alerts = []
        mon.add_alert_sink("test", alerts.append)
        child = hist.labels(server="a")
        mon.evaluate()
        for _ in range(500):
            child.observe(5.0)
        clock.advance(5.0)
        doc = mon.evaluate()
        assert doc["slos"][0]["firing"] == []
        assert alerts == []
        # injected p99 regression: every new sample blows the budget
        for _ in range(100):
            child.observe(400.0)
        clock.advance(5.0)
        doc = mon.evaluate()
        assert doc["slos"][0]["firing"] == ["fast"]
        assert len(alerts) == 1 and alerts[0]["firing"]
        assert alerts[0]["burn_short"] > 14.4
        # regression stops; the short window drains past the bad
        # samples and the alert resolves
        for _ in range(2000):
            child.observe(5.0)
        clock.advance(11.0)
        mon.evaluate()
        clock.advance(35.0)
        doc = mon.evaluate()
        assert doc["slos"][0]["firing"] == []
        assert alerts[-1]["firing"] is False

    def test_both_windows_must_burn(self):
        """A short blip trips the short window but not the long one —
        multi-window alerting exists exactly to not page on it."""
        clock = _Clock(0.0)
        reg = MetricRegistry()
        hist = reg.histogram("t_slo_blip_ms", "", ())
        mon, _ = _mk_slo("p99", "t_slo_blip_ms", clock, reg=reg,
                         short=10.0, long=1000.0)
        child = hist.labels()
        mon.evaluate()               # monitoring starts
        for _ in range(100000):      # long healthy history
            child.observe(1.0)
        clock.advance(990.0)
        mon.evaluate()
        for _ in range(50):          # blip
            child.observe(400.0)
        clock.advance(10.0)
        doc = mon.evaluate()
        w = doc["slos"][0]["windows"]
        assert w["10s"]["burn_rate"] > 14.4
        assert w["1000s"]["burn_rate"] < 14.4
        assert doc["slos"][0]["firing"] == []

    def test_threshold_uses_bucket_bound(self):
        clock = _Clock()
        reg = MetricRegistry()
        hist = reg.histogram("t_slo_eff_ms", "",
                             buckets=(10.0, 50.0, 100.0))
        mon, _ = _mk_slo("p", "t_slo_eff_ms", clock, threshold=60.0,
                         reg=reg)
        mon.evaluate()                 # monitoring starts
        hist.labels().observe(30.0)    # good at the 50ms bound
        hist.labels().observe(55.0)    # between bound and threshold:
        clock.advance(5.0)             # conservatively bad
        doc = mon.evaluate()
        assert doc["slos"][0]["effective_threshold_ms"] == 50.0
        w = doc["slos"][0]["windows"]["10s"]
        assert (w["good"], w["total"]) == (1, 2)

    def test_label_filter_selects_slice(self):
        clock = _Clock()
        reg = MetricRegistry()
        hist = reg.histogram("t_slo_lbl_ms", "", ("server",))
        mon, _ = _mk_slo("p", "t_slo_lbl_ms", clock,
                         labels={"server": "good"}, reg=reg)
        mon.evaluate()
        for _ in range(100):
            hist.labels(server="good").observe(1.0)
            hist.labels(server="evil").observe(500.0)
        clock.advance(5.0)
        doc = mon.evaluate()
        w = doc["slos"][0]["windows"]["10s"]
        assert w["total"] == 100 and w["good"] == 100

    def test_gauges_and_budget(self):
        clock = _Clock()
        reg = MetricRegistry()
        reg.histogram("t_slo_g_ms", "", ()).labels().observe(1.0)
        mon, _ = _mk_slo("pg", "t_slo_g_ms", clock, reg=reg)
        mon.evaluate()
        clock.advance(5.0)
        mon.evaluate()
        burn = reg.get("paddle_slo_burn_rate")
        budget = reg.get("paddle_slo_budget_remaining")
        assert burn.get(slo="pg", window="10s") is not None
        assert budget.labels(slo="pg").value == pytest.approx(1.0)

    def test_alert_carries_exemplar_trace_id(self):
        clock = _Clock()
        reg = MetricRegistry()
        hist = reg.histogram("t_slo_ex_ms", "", ())
        mon, _ = _mk_slo("pex", "t_slo_ex_ms", clock, reg=reg)
        alerts = []
        mon.add_alert_sink("t", alerts.append)
        tracing.clear_exemplars()
        try:
            mon.evaluate()
            trace_id = "ab" * 16
            for _ in range(50):
                hist.labels().observe(300.0)
            tracing.record_exemplar("t_slo_ex_ms", 300.0, trace_id)
            clock.advance(5.0)
            mon.evaluate()
            assert alerts and alerts[0]["exemplar_trace_id"] == trace_id
        finally:
            tracing.clear_exemplars()

    def test_direct_feed_excludes_warmup_samples(self):
        clock = _Clock()
        reg = MetricRegistry()
        mon = slo.SLOMonitor(registry=reg, now=clock)
        mon.add(slo.LatencySLO("d", "t_absent_metric_ms", 10.0, 0.9,
                               windows=(10.0,),
                               burn_rules=[slo.BurnRule(
                                   "fast", 10.0, 10.0, 1.0)]))
        mon.evaluate()
        for _ in range(10):
            mon.observe("d", 500.0, warmup=True)   # excluded
            mon.observe("d", 1.0)
        clock.advance(5.0)
        doc = mon.evaluate()
        w = doc["slos"][0]["windows"]["10s"]
        assert (w["good"], w["total"]) == (10, 10)
        excl = reg.get("paddle_slo_samples_excluded_total")
        assert excl.labels(slo="d").value == 10

    def test_target_fraction_validation(self):
        with pytest.raises(ValueError):
            slo.LatencySLO("bad", "m", 1.0, 1.0)

    def test_merge_sloz_payloads_sums_counts(self):
        def entry(good, total):
            return {"slo": {"name": "s", "target_fraction": 0.9},
                    "windows": {"10s": {"good": good, "total": total,
                                        "bad_fraction": 0.0,
                                        "covered": True,
                                        "burn_rate": 0.0}}}
        merged = slo.merge_sloz_payloads(
            {"process": "router", "slos": [entry(90, 100)]},
            {"r0": {"slos": [entry(50, 100)]},
             "r1": {"slos": [entry(100, 100)]}})
        w = merged["slos"][0]["windows"]["10s"]
        assert (w["good"], w["total"]) == (240, 300)
        assert w["bad_fraction"] == pytest.approx(0.2)
        assert w["burn_rate"] == pytest.approx(2.0)
        assert merged["replicas"] == ["r0", "r1"]

    def test_sloz_endpoint(self, fresh_defaults):
        _, _, mon = fresh_defaults
        mon.add(slo.LatencySLO("end", "paddle_serving_latency_ms",
                               25.0, 0.99, windows=(60.0,),
                               burn_rules=[slo.BurnRule(
                                   "fast", 60.0, 60.0, 14.4)]))
        srv = observability.TelemetryServer(port=0).start()
        try:
            status, body = _get(srv.url("/sloz"))
            assert status == 200
            doc = json.loads(body)
            assert doc["slos"][0]["slo"]["name"] == "end"
        finally:
            srv.stop()


# ===================================================== warmup exclusion
class TestWarmupExclusion:
    @pytest.fixture()
    def predictor(self, tmp_path):
        import paddle_tpu.nn as nn
        from paddle_tpu import inference
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 8), nn.ReLU())
        p = str(tmp_path / "m")
        paddle.jit.save(net, p, input_spec=[
            paddle.static.InputSpec([None, 8], "float32")])
        return inference.create_predictor(inference.Config(p))

    def test_warmup_traffic_never_lands_in_slo_windows(
            self, predictor, fresh_defaults):
        """Regression test at the target_fraction boundary: warmup
        pre-compiles are orders of magnitude over the threshold; ONE
        leaked warmup sample at P-of-N boundary traffic would flip
        the SLO verdict. The exclusion (record_traffic=False, the PR 9
        rule) must hold through the SLO window layer."""
        _, _, mon = fresh_defaults
        name = "t_slo_warm"
        # exactly-at-boundary target: 1 bad in 100 is allowed, 2 are
        # not; a leaked warmup sample is the difference
        s = slo.LatencySLO("warm_p99", "paddle_serving_latency_ms",
                           threshold_ms=1000.0, target_fraction=0.98,
                           labels={"server": name}, windows=(600.0,),
                           burn_rules=[slo.BurnRule(
                               "fast", 600.0, 600.0, 1.0)])
        mon.add(s)
        mon.evaluate()
        srv = serving.InferenceServer(
            predictor, max_batch_size=4, name=name,
            queue_capacity=128, ready_requires_warmup=True,
            start=False)
        n_warm = srv.warmup()          # slow compiles, all excluded
        assert n_warm > 0
        srv.start()
        futs = srv.submit_many([[np.ones((1, 8), np.float32)]
                                for _ in range(100)])
        for f in futs:
            f.result(timeout=60)
        srv.shutdown()
        doc = mon.evaluate()
        w = doc["slos"][0]["windows"]["10m"]
        assert w["total"] == 100, \
            "warmup batches leaked into the SLO sample window"
        assert w["good"] == w["total"]
        assert doc["slos"][0]["firing"] == []


# ============================================================ fleet
class TestFleetSLO:
    def test_two_replica_regression_fires_fast_burn_with_exemplar(
            self, fresh_defaults):
        """The acceptance scenario: a 2-replica fleet, an injected
        latency regression, the fast-burn alert inside one evaluation
        pass, carrying a PR 9 exemplar trace id; the router's /sloz
        aggregates both replicas."""
        _, _, mon = fresh_defaults
        name = "t_slo_fleet"
        bes, apps = [], []
        for _ in range(2):
            be = fleet.StubBackend(device_ms=1.0)
            app = fleet.ReplicaApp(be).start()
            be.warmup()
            bes.append(be)
            apps.append(app)
        set_flags({"FLAGS_trace_sample_rate": 1.0})
        tracing.clear_exemplars()
        router = fleet.FleetRouter(
            {i: app.url for i, app in enumerate(apps)},
            name=name, start=False)
        try:
            router.poll_replicas()
            mon.add(slo.LatencySLO(
                "fleet_p99", "paddle_fleet_request_ms",
                threshold_ms=50.0, target_fraction=0.99,
                labels={"router": name}, windows=(600.0, 1200.0),
                burn_rules=[slo.BurnRule("fast_burn", 600.0, 1200.0,
                                         14.4)]))
            alerts = []
            mon.add_alert_sink("t", alerts.append)
            mon.evaluate()
            for f in router.submit_many([[np.ones((1, 4),
                                          np.float32)]] * 8):
                f.result(timeout=30)
            doc = mon.evaluate()
            assert doc["slos"][0]["firing"] == []
            # inject the regression: both replicas slow to 40x the
            # threshold
            for be in bes:
                be.device_ms = 200.0
            for f in router.submit_many([[np.ones((1, 4),
                                          np.float32)]] * 6):
                f.result(timeout=60)
            doc = mon.evaluate()       # ONE evaluation pass later
            assert doc["slos"][0]["firing"] == ["fast_burn"]
            assert len(alerts) == 1 and alerts[0]["firing"]
            exemplar = alerts[0]["exemplar_trace_id"]
            assert exemplar and len(exemplar) == 32
            # the exemplar is retrievable as a trace
            spans = router.merged_tracez(trace_id=exemplar)
            assert spans["traces"], \
                "exemplar trace id must resolve in /tracez"
        finally:
            set_flags({"FLAGS_trace_sample_rate": 0.0})
            tracing.clear_exemplars()
            router.shutdown()
            for app in apps:
                app.stop()

    def test_router_app_serves_merged_sloz(self, fresh_defaults):
        _, _, mon = fresh_defaults
        be = fleet.StubBackend(device_ms=1.0)
        app = fleet.ReplicaApp(be).start()
        be.warmup()
        router = fleet.FleetRouter({0: app.url}, name="t_slo_http",
                                   start=False)
        router.poll_replicas()
        rapp = fleet.RouterApp(router).start()
        try:
            mon.add(slo.LatencySLO(
                "http_p99", "paddle_fleet_request_ms", 50.0, 0.99,
                labels={"router": "t_slo_http"}, windows=(600.0,),
                burn_rules=[slo.BurnRule("fast", 600.0, 600.0,
                                         14.4)]))
            status, body = _get(rapp.url("/sloz"))
            assert status == 200
            doc = json.loads(body)
            assert doc["replicas"] == ["0"]
            assert any(e["slo"]["name"] == "http_p99"
                       for e in doc["slos"])
        finally:
            rapp.stop()
            router.shutdown()
            app.stop()

    def test_readiness_polling_records_no_slo_samples(
            self, fresh_defaults):
        """Readiness probes are control-plane traffic: polling must
        not mint paddle_fleet_request_ms samples."""
        _, _, mon = fresh_defaults
        be = fleet.StubBackend(device_ms=1.0)
        app = fleet.ReplicaApp(be).start()
        be.warmup()
        router = fleet.FleetRouter({0: app.url}, name="t_slo_ready",
                                   start=False)
        try:
            mon.add(slo.LatencySLO(
                "ready_p99", "paddle_fleet_request_ms", 50.0, 0.99,
                labels={"router": "t_slo_ready"}, windows=(600.0,),
                burn_rules=[slo.BurnRule("fast", 600.0, 600.0,
                                         14.4)]))
            mon.evaluate()
            for _ in range(5):
                router.poll_replicas()
            doc = mon.evaluate()
            assert doc["slos"][0]["windows"]["10m"]["total"] == 0
        finally:
            router.shutdown()
            app.stop()


# ============================================================ misc
class TestBuildInfo:
    def test_build_info_gauge(self):
        from paddle_tpu.observability import runtime
        labels = runtime.install_build_info()
        assert labels["version"] == paddle.__version__
        from paddle_tpu.observability.registry import default_registry
        fam = default_registry().get("paddle_build_info")
        children = fam.collect()
        assert len(children) == 1
        lab, child = children[0]
        assert child.value == 1
        assert lab["jax"] != "unknown"
        assert lab["backend"] == "cpu"
        # idempotent: a re-install never leaves two identities
        runtime.install_build_info()
        assert len(fam.collect()) == 1

    def test_build_info_in_prometheus_text(self):
        from paddle_tpu.observability import prometheus_text, runtime
        runtime.install_build_info()
        text = prometheus_text()
        assert "paddle_build_info{" in text


class TestSloReportTool:
    def test_committed_record_renders(self):
        import os
        import sys
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        from tools import slo_report
        path = slo_report.newest_committed(slo_report.REPO_ROOT)
        doc = slo_report.load_record(path)
        text = slo_report.render_text(doc)
        assert "CLOSES" in text
        assert "goodput" in text
        assert doc["goodput"]["accounting"]["closes"]

    def test_live_scrape_roundtrip(self, fresh_defaults):
        import os
        import sys
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        from tools import slo_report
        led, _, _ = fresh_defaults
        led.start()
        led.record("step", 2.0)
        srv = observability.TelemetryServer(port=0).start()
        try:
            doc = slo_report.fetch_live(srv.url(""))
            assert doc["goodput"]["categories_s"]["step"] >= 2.0
            text = slo_report.render_text(doc)
            assert "goodput" in text
        finally:
            srv.stop()

    def test_goodput_gate_in_perfci(self):
        import os
        import sys
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        from tools import perfci
        report = perfci.run(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        gates = {r["gate"]: r for r in report["results"]}
        assert gates["goodput_accounting"]["status"] == "pass"
        assert gates["goodput_fraction"]["status"] == "pass"
        assert gates["goodput_overhead_pct"]["status"] == "pass"
