"""Static-graph PTQ (round-4 verdict item 8): calibrate on the Program
replay, quantize weights to int8, serve through Predictor.

Reference: python/paddle/static/quantization/post_training_quantization.py.
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import inference
from paddle_tpu.static.quantization import PostTrainingQuantization


def _export_ernie(tmp, bs=2, seq=16):
    from paddle_tpu.models.ernie import (ErnieForSequenceClassification,
                                         ernie_tiny)

    paddle.seed(0)
    cfg = ernie_tiny()
    net = ErnieForSequenceClassification(cfg, num_classes=2)
    net.eval()
    prefix = os.path.join(tmp, "ernie")
    paddle.jit.save(net, prefix, input_spec=[
        paddle.static.InputSpec([bs, seq], "int64")])
    return net, prefix, cfg


class TestPostTrainingQuantization:
    def test_ernie_ptq_serves_within_tolerance(self, tmp_path):
        bs, seq = 2, 16
        net, prefix, cfg = _export_ernie(str(tmp_path), bs, seq)
        rng = np.random.RandomState(0)

        def loader():
            for _ in range(4):
                yield {"feed_0": rng.randint(
                    1, cfg.vocab_size, (bs, seq)).astype("int64")}

        ptq = PostTrainingQuantization(
            model_dir=str(tmp_path), model_filename="ernie.pdmodel",
            params_filename="ernie.pdiparams", data_loader=loader,
            batch_nums=4, algo="abs_max")
        qprefix = ptq.quantize().save_quantized_model(
            os.path.join(str(tmp_path), "q", "ernie_int8"))

        # the artifact really carries int8 weights (deployment payload)
        assert os.path.getsize(qprefix + ".pdiparams") < \
            0.5 * os.path.getsize(prefix + ".pdiparams")
        from paddle_tpu.static.pdmodel import parse_program_desc
        with open(qprefix + ".pdmodel", "rb") as f:
            desc = parse_program_desc(f.read())
        op_types = [op["type"] for op in desc["blocks"][0]["ops"]]
        assert "quantize_linear" in op_types
        assert "dequantize_linear" in op_types

        # quantized serving through the SAME Predictor surface
        x = rng.randint(1, cfg.vocab_size, (bs, seq)).astype("int64")
        cfg_q = inference.Config(qprefix + ".pdmodel",
                                 qprefix + ".pdiparams")
        pred_q = inference.create_predictor(cfg_q)
        out_q = pred_q.run([x])[0]

        want = net(paddle.to_tensor(x)).numpy()
        # int8 tolerance: logits within a few percent of f32
        scale = np.abs(want).max() + 1e-9
        assert np.abs(out_q - want).max() / scale < 0.05, \
            (out_q, want)
        # and quantization actually changed the numbers
        assert not np.allclose(out_q, want, rtol=0, atol=1e-7)

    def test_ptq_cnn_conv_channelwise(self, tmp_path):
        from paddle_tpu.vision.models import LeNet

        paddle.seed(0)
        net = LeNet()
        prefix = os.path.join(str(tmp_path), "lenet")
        paddle.jit.save(net, prefix, input_spec=[
            paddle.static.InputSpec([2, 1, 28, 28], "float32")])
        rng = np.random.RandomState(1)

        def loader():
            for _ in range(3):
                yield [rng.randn(2, 1, 28, 28).astype("float32")]

        ptq = PostTrainingQuantization(
            model_dir=str(tmp_path), model_filename="lenet.pdmodel",
            data_loader=loader, batch_nums=3, algo="avg")
        qprefix = ptq.quantize().save_quantized_model(
            os.path.join(str(tmp_path), "lenet_int8"))
        pred = inference.create_predictor(
            inference.Config(qprefix + ".pdmodel",
                             qprefix + ".pdiparams"))
        x = rng.randn(2, 1, 28, 28).astype("float32")
        out = pred.run([x])[0]
        want = net(paddle.to_tensor(x)).numpy()
        scale = np.abs(want).max() + 1e-9
        assert np.abs(out - want).max() / scale < 0.08

    def test_skip_tensor_list(self, tmp_path):
        net, prefix, cfg = _export_ernie(str(tmp_path))
        rng = np.random.RandomState(0)

        def loader():
            yield {"feed_0": rng.randint(1, cfg.vocab_size,
                                         (2, 16)).astype("int64")}

        ptq = PostTrainingQuantization(
            model_dir=str(tmp_path), model_filename="ernie.pdmodel",
            data_loader=loader, batch_nums=1,
            quantizable_op_type=["matmul_v2"])
        ptq.quantize()
        ops = [o["type"] for o in
               ptq._quantized_desc["blocks"][0]["ops"]]
        assert "dequantize_linear" in ops
