"""Static-graph PTQ (round-4 verdict item 8): calibrate on the Program
replay, quantize weights to int8, serve through Predictor.

Reference: python/paddle/static/quantization/post_training_quantization.py.
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import inference
from paddle_tpu.static.quantization import PostTrainingQuantization


def _export_ernie(tmp, bs=2, seq=16):
    from paddle_tpu.models.ernie import (ErnieForSequenceClassification,
                                         ernie_tiny)

    paddle.seed(0)
    cfg = ernie_tiny()
    net = ErnieForSequenceClassification(cfg, num_classes=2)
    net.eval()
    prefix = os.path.join(tmp, "ernie")
    paddle.jit.save(net, prefix, input_spec=[
        paddle.static.InputSpec([bs, seq], "int64")])
    return net, prefix, cfg


class TestPostTrainingQuantization:
    def test_ernie_ptq_serves_within_tolerance(self, tmp_path):
        bs, seq = 2, 16
        net, prefix, cfg = _export_ernie(str(tmp_path), bs, seq)
        rng = np.random.RandomState(0)

        def loader():
            for _ in range(4):
                yield {"feed_0": rng.randint(
                    1, cfg.vocab_size, (bs, seq)).astype("int64")}

        ptq = PostTrainingQuantization(
            model_dir=str(tmp_path), model_filename="ernie.pdmodel",
            params_filename="ernie.pdiparams", data_loader=loader,
            batch_nums=4, algo="abs_max")
        qprefix = ptq.quantize().save_quantized_model(
            os.path.join(str(tmp_path), "q", "ernie_int8"))

        # the artifact really carries int8 weights (deployment payload)
        assert os.path.getsize(qprefix + ".pdiparams") < \
            0.5 * os.path.getsize(prefix + ".pdiparams")
        from paddle_tpu.static.pdmodel import parse_program_desc
        with open(qprefix + ".pdmodel", "rb") as f:
            desc = parse_program_desc(f.read())
        op_types = [op["type"] for op in desc["blocks"][0]["ops"]]
        assert "quantize_linear" in op_types
        assert "dequantize_linear" in op_types

        # quantized serving through the SAME Predictor surface
        x = rng.randint(1, cfg.vocab_size, (bs, seq)).astype("int64")
        cfg_q = inference.Config(qprefix + ".pdmodel",
                                 qprefix + ".pdiparams")
        pred_q = inference.create_predictor(cfg_q)
        out_q = pred_q.run([x])[0]

        want = net(paddle.to_tensor(x)).numpy()
        # int8 tolerance: logits within a few percent of f32
        scale = np.abs(want).max() + 1e-9
        assert np.abs(out_q - want).max() / scale < 0.05, \
            (out_q, want)
        # and quantization actually changed the numbers
        assert not np.allclose(out_q, want, rtol=0, atol=1e-7)

    def test_ptq_cnn_conv_channelwise(self, tmp_path):
        from paddle_tpu.vision.models import LeNet

        paddle.seed(0)
        net = LeNet()
        prefix = os.path.join(str(tmp_path), "lenet")
        paddle.jit.save(net, prefix, input_spec=[
            paddle.static.InputSpec([2, 1, 28, 28], "float32")])
        rng = np.random.RandomState(1)

        def loader():
            for _ in range(3):
                yield [rng.randn(2, 1, 28, 28).astype("float32")]

        ptq = PostTrainingQuantization(
            model_dir=str(tmp_path), model_filename="lenet.pdmodel",
            data_loader=loader, batch_nums=3, algo="avg")
        qprefix = ptq.quantize().save_quantized_model(
            os.path.join(str(tmp_path), "lenet_int8"))
        pred = inference.create_predictor(
            inference.Config(qprefix + ".pdmodel",
                             qprefix + ".pdiparams"))
        x = rng.randn(2, 1, 28, 28).astype("float32")
        out = pred.run([x])[0]
        want = net(paddle.to_tensor(x)).numpy()
        scale = np.abs(want).max() + 1e-9
        assert np.abs(out - want).max() / scale < 0.08

    def test_skip_tensor_list(self, tmp_path):
        net, prefix, cfg = _export_ernie(str(tmp_path))
        rng = np.random.RandomState(0)

        def loader():
            yield {"feed_0": rng.randint(1, cfg.vocab_size,
                                         (2, 16)).astype("int64")}

        ptq = PostTrainingQuantization(
            model_dir=str(tmp_path), model_filename="ernie.pdmodel",
            data_loader=loader, batch_nums=1,
            quantizable_op_type=["matmul_v2"])
        ptq.quantize()
        ops = [o["type"] for o in
               ptq._quantized_desc["blocks"][0]["ops"]]
        assert "dequantize_linear" in ops


class TestReferenceScaleConvention:
    """Lock in the reference kernel semantics (round-4 advisor high):
    Scale params hold the ABSMAX and dequant divides by
    max_range = 2^(bit_length-1)-1 (quantize_linear_op.cc:39), NOT the
    ONNX scale=absmax/qmax convention. Expected values here are
    hand-computed with the reference formulas so repo-vs-repo agreement
    cannot mask a convention drift."""

    def test_dequantize_linear_matches_reference_kernel(self):
        import jax.numpy as jnp
        from paddle_tpu.static.pdmodel import _CONVERTERS

        # per-channel (quant_axis=0) int8 weights with absmax scales —
        # exactly what a reference onnx_format PTQ export contains
        xq = np.array([[-127, 64, 0], [127, -32, 5]], np.int8)
        scale = np.array([0.5, 2.0], np.float32)  # absmax per row
        zp = np.zeros(2, np.int32)
        out = _CONVERTERS["dequantize_linear"](
            jnp, {"X": [jnp.asarray(xq)], "Scale": [jnp.asarray(scale)],
                  "ZeroPoint": [jnp.asarray(zp)]},
            {"quant_axis": 0, "bit_length": 8})["Y"][0]
        # reference: out = in * scale / max_range, max_range = 127
        want = xq.astype(np.float32) * scale.reshape(2, 1) / 127.0
        np.testing.assert_allclose(np.asarray(out), want, rtol=1e-6)

    def test_quantize_linear_matches_reference_kernel(self):
        import jax.numpy as jnp
        from paddle_tpu.static.pdmodel import _CONVERTERS

        x = np.array([[0.5, -0.25, 0.1], [-0.5, 0.49, 0.0]], np.float32)
        scale = np.array([0.5], np.float32)  # per-tensor absmax
        out = _CONVERTERS["quantize_linear"](
            jnp, {"X": [jnp.asarray(x)], "Scale": [jnp.asarray(scale)],
                  "ZeroPoint": [jnp.asarray(np.zeros(1, np.int32))]},
            {"quant_axis": -1, "bit_length": 8})["Y"][0]
        # reference ClipAndFakeQuant: round(clip(x,-s,s)/s * 127)
        want = np.round(np.clip(x, -0.5, 0.5) / 0.5 * 127.0)
        np.testing.assert_allclose(np.asarray(out), want, rtol=0, atol=0)

    def test_only_observer_passes_through(self):
        """Reference onnx_format exports insert activation q/dq pairs
        with only_observer=True (quantization_pass.py:3261); the kernel
        TensorCopy's the input through (quantize_linear_op.h:154)."""
        import jax.numpy as jnp
        from paddle_tpu.static.pdmodel import _CONVERTERS

        x = np.array([[0.3, -0.7]], np.float32)
        ins = {"X": [jnp.asarray(x)],
               "Scale": [jnp.asarray(np.array([0.7], np.float32))],
               "ZeroPoint": [jnp.asarray(np.zeros(1, np.int32))]}
        attrs = {"quant_axis": -1, "bit_length": 8, "only_observer": True}
        for op in ("quantize_linear", "dequantize_linear"):
            out = _CONVERTERS[op](jnp, ins, attrs)["Y"][0]
            np.testing.assert_array_equal(np.asarray(out), x)

    def test_ptq_writer_stores_absmax_scales(self, tmp_path):
        """A reference runtime loading our artifact divides Scale by
        max_range — so our Scale params must BE the absmax."""
        from paddle_tpu.vision.models import LeNet
        from paddle_tpu.static.pdmodel import (parse_combined_params,
                                               parse_program_desc)

        paddle.seed(0)
        net = LeNet()
        prefix = os.path.join(str(tmp_path), "lenet")
        paddle.jit.save(net, prefix, input_spec=[
            paddle.static.InputSpec([2, 1, 28, 28], "float32")])
        rng = np.random.RandomState(0)
        ptq = PostTrainingQuantization(
            model_dir=str(tmp_path), model_filename="lenet.pdmodel",
            data_loader=lambda: iter(
                [[rng.randn(2, 1, 28, 28).astype("float32")]]),
            batch_nums=1)
        qprefix = ptq.quantize().save_quantized_model(
            os.path.join(str(tmp_path), "lenet_int8"))

        from paddle_tpu.static.pdmodel import PdProgram

        with open(qprefix + ".pdmodel", "rb") as f:
            desc = parse_program_desc(f.read())
        block = desc["blocks"][0]
        with open(qprefix + ".pdiparams", "rb") as f:
            params = parse_combined_params(
                f.read(), PdProgram(desc).persistable_names())
        # float originals under their exported var names
        with open(prefix + ".pdmodel", "rb") as f:
            odesc = parse_program_desc(f.read())
        with open(prefix + ".pdiparams", "rb") as f:
            oparams = parse_combined_params(
                f.read(), PdProgram(odesc).persistable_names())
        # reconstruct each quantized weight by the REFERENCE dequant rule
        # and check it approximates the float original within 1 lsb
        for op in block["ops"]:
            if op["type"] != "dequantize_linear":
                continue
            qname = op["inputs"]["X"][0]
            sname = op["inputs"]["Scale"][0]
            if "@quantized" not in qname:
                continue
            wq = np.asarray(params[qname], np.float32)
            s = np.asarray(params[sname], np.float32)
            axis = op["attrs"]["quant_axis"]
            shape = [1] * wq.ndim
            shape[axis] = s.shape[0]
            wref = wq * s.reshape(shape) / 127.0
            orig = np.asarray(oparams[qname.replace("@quantized", "")])
            lsb = s.reshape(shape) / 127.0
            assert np.all(np.abs(wref - orig) <= lsb * 0.5 + 1e-8), qname
            # the scale itself is the absmax, not absmax/127
            red = tuple(i for i in range(wq.ndim) if i != axis)
            np.testing.assert_allclose(
                s, np.abs(orig).max(axis=red), rtol=1e-5)
        # int8 var metadata: quant outputs declare proto dtype 21
        aq_vars = {v["name"]: v for v in block["vars"]
                   if v["name"].startswith("__ptq_aq")}
        assert aq_vars, "no activation quant vars declared"
        for v in aq_vars.values():
            assert v["type"]["dtype"] == 21, v
