"""AMP autocast / GradScaler, paddle.save/load, DataLoader, jit tests."""
import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


class TestAMP:
    def test_auto_cast_bf16_matmul(self):
        a = paddle.to_tensor(np.random.randn(4, 4).astype("float32"))
        with paddle.amp.auto_cast(dtype="bfloat16"):
            out = paddle.matmul(a, a)
        assert "bfloat16" in str(out.dtype)

    def test_auto_cast_keeps_softmax_fp32(self):
        a = paddle.to_tensor(np.random.randn(4, 4).astype("float32"))
        with paddle.amp.auto_cast(dtype="bfloat16"):
            out = F.softmax(a)
        assert "float32" in str(out.dtype)

    def test_auto_cast_off(self):
        a = paddle.to_tensor(np.random.randn(4, 4).astype("float32"))
        with paddle.amp.auto_cast(enable=False):
            out = paddle.matmul(a, a)
        assert "float32" in str(out.dtype)

    def test_grad_scaler_roundtrip(self):
        m = nn.Linear(4, 4)
        opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=m.parameters())
        scaler = paddle.amp.GradScaler(init_loss_scaling=128.0)
        x = paddle.to_tensor(np.random.randn(2, 4).astype("float32"))
        with paddle.amp.auto_cast():
            loss = m(x).sum()
        scaled = scaler.scale(loss)
        scaled.backward()
        scaler.step(opt)
        scaler.update()
        assert np.isfinite(m.weight.numpy()).all()

    def test_grad_scaler_skips_inf(self):
        m = nn.Linear(2, 2)
        w0 = m.weight.numpy().copy()
        opt = paddle.optimizer.SGD(learning_rate=1.0, parameters=m.parameters())
        scaler = paddle.amp.GradScaler(init_loss_scaling=64.0)
        x = paddle.to_tensor(np.array([[np.inf, 1.0]], "float32"))
        loss = m(x).sum()
        scaler.scale(loss).backward()
        scaler.step(opt)
        scaler.update()
        np.testing.assert_allclose(m.weight.numpy(), w0)  # step skipped


class TestSaveLoad:
    def test_save_load_state_dict(self, tmp_path):
        m = nn.Sequential(nn.Linear(3, 4), nn.ReLU(), nn.Linear(4, 2))
        path = str(tmp_path / "model.pdparams")
        paddle.save(m.state_dict(), path)
        loaded = paddle.load(path)
        m2 = nn.Sequential(nn.Linear(3, 4), nn.ReLU(), nn.Linear(4, 2))
        m2.set_state_dict(loaded)
        for (k1, v1), (k2, v2) in zip(sorted(m.state_dict().items()),
                                      sorted(m2.state_dict().items())):
            np.testing.assert_allclose(v1.numpy(), v2.numpy())

    def test_save_load_optimizer(self, tmp_path):
        m = nn.Linear(3, 3)
        opt = paddle.optimizer.Adam(learning_rate=0.1, parameters=m.parameters())
        m(paddle.to_tensor(np.ones((1, 3), "float32"))).sum().backward()
        opt.step()
        path = str(tmp_path / "opt.pdopt")
        paddle.save(opt.state_dict(), path)
        sd = paddle.load(path)
        opt.set_state_dict(sd)

    def test_save_nested_dict(self, tmp_path):
        obj = {"a": paddle.to_tensor([1.0, 2.0]), "b": {"c": 3}}
        path = str(tmp_path / "obj.pd")
        paddle.save(obj, path)
        back = paddle.load(path)
        np.testing.assert_allclose(np.asarray(back["a"]), [1.0, 2.0])
        assert back["b"]["c"] == 3


class TestDataLoader:
    def test_dataset_and_loader(self):
        from paddle_tpu.io import Dataset, DataLoader

        class Sq(Dataset):
            def __len__(self):
                return 20

            def __getitem__(self, i):
                return np.float32(i), np.float32(i * i)

        dl = DataLoader(Sq(), batch_size=4, shuffle=False, drop_last=False)
        batches = list(dl)
        assert len(batches) == 5
        x, y = batches[0]
        np.testing.assert_allclose(np.asarray(x).reshape(-1), [0, 1, 2, 3])

    def test_loader_shuffle_covers_all(self):
        from paddle_tpu.io import Dataset, DataLoader

        class Ds(Dataset):
            def __len__(self):
                return 10

            def __getitem__(self, i):
                return np.int64(i)

        dl = DataLoader(Ds(), batch_size=2, shuffle=True)
        seen = sorted(int(v) for b in dl for v in np.asarray(b[0] if isinstance(b, (list, tuple)) else b).reshape(-1))
        assert seen == list(range(10))

    def test_tensor_dataset_random_sampler(self):
        from paddle_tpu.io import TensorDataset, DataLoader
        xs = paddle.to_tensor(np.arange(12, dtype="float32").reshape(6, 2))
        ys = paddle.to_tensor(np.arange(6, dtype="int64"))
        ds = TensorDataset([xs, ys])
        assert len(ds) == 6
        dl = DataLoader(ds, batch_size=3)
        n = sum(1 for _ in dl)
        assert n == 2


class TestJit:
    def test_to_static_matches_eager(self):
        m = nn.Sequential(nn.Linear(4, 8), nn.GELU(), nn.Linear(8, 2))
        x = paddle.to_tensor(np.random.randn(3, 4).astype("float32"))
        eager = m(x).numpy()
        sm = paddle.jit.to_static(m)
        static = sm(x).numpy()
        np.testing.assert_allclose(eager, static, rtol=1e-4, atol=1e-5)

    def test_to_static_function(self):
        @paddle.jit.to_static
        def f(a, b):
            return paddle.matmul(a, b) + 1.0

        a = paddle.to_tensor(np.random.randn(2, 3).astype("float32"))
        b = paddle.to_tensor(np.random.randn(3, 2).astype("float32"))
        np.testing.assert_allclose(
            f(a, b).numpy(), a.numpy() @ b.numpy() + 1.0, rtol=1e-4, atol=1e-5)

    def test_train_step_fused(self):
        from paddle_tpu.jit import TrainStep
        m = nn.Linear(4, 2)
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
        step = TrainStep(m, lambda out, y: F.cross_entropy(out, y), opt)
        x = paddle.to_tensor(np.random.randn(8, 4).astype("float32"))
        y = paddle.to_tensor(np.random.randint(0, 2, (8,)).astype("int64"))
        l0 = float(step(x, y).numpy())
        for _ in range(20):
            l = float(step(x, y).numpy())
        assert l < l0

    @pytest.mark.slow
    def test_train_step_amp_o1(self):
        m = nn.Sequential(nn.Linear(4, 16), nn.GELU(), nn.Linear(16, 2))
        opt = paddle.optimizer.AdamW(learning_rate=0.05,
                                     parameters=m.parameters())
        from paddle_tpu.jit import TrainStep
        step = TrainStep(m, lambda o, y: F.cross_entropy(o, y), opt,
                         amp_level="O1")
        x = paddle.to_tensor(np.random.randn(8, 4).astype("float32"))
        y = paddle.to_tensor(np.random.randint(0, 2, (8,)).astype("int64"))
        l0 = float(step(x, y).numpy())
        for _ in range(20):
            l = float(step(x, y).numpy())
        assert l < l0
        # master weights stay f32
        assert all("float32" in str(p.dtype) for p in m.parameters())

    def test_train_step_matches_eager(self):
        xs = np.random.randn(8, 4).astype("float32")
        ys = np.random.randint(0, 2, (8,)).astype("int64")

        def build():
            paddle.seed(42)
            m = nn.Linear(4, 2)
            opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
            return m, opt

        m1, o1 = build()
        for _ in range(3):
            loss = F.cross_entropy(m1(paddle.to_tensor(xs)), paddle.to_tensor(ys))
            loss.backward()
            o1.step()
            o1.clear_grad()

        from paddle_tpu.jit import TrainStep
        m2, o2 = build()
        step = TrainStep(m2, lambda out, y: F.cross_entropy(out, y), o2)
        for _ in range(3):
            step(paddle.to_tensor(xs), paddle.to_tensor(ys))
        np.testing.assert_allclose(m1.weight.numpy(), m2.weight.numpy(),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.slow
class TestVisionModels:
    def test_lenet_forward_backward(self):
        from paddle_tpu.vision.models import LeNet
        net = LeNet()
        x = paddle.to_tensor(np.random.randn(2, 1, 28, 28).astype("float32"))
        out = net(x)
        assert out.shape == [2, 10]
        F.cross_entropy(out, paddle.to_tensor(np.array([1, 2], "int64"))).backward()
        assert net.parameters()[0].grad is not None

    def test_resnet18_forward(self):
        from paddle_tpu.vision.models import resnet18
        net = resnet18(num_classes=10)
        net.eval()
        x = paddle.to_tensor(np.random.randn(1, 3, 32, 32).astype("float32"))
        assert net(x).shape == [1, 10]


class TestUtilsSurface:
    def test_run_check_multidevice(self, capsys):
        import jax
        paddle.utils.run_check()
        out = capsys.readouterr().out
        n = jax.device_count()
        plat = jax.devices()[0].platform
        if n > 1:
            assert f"works well on {n} {plat}s" in out
        assert "installed successfully" in out

    def test_deprecated_and_require_version(self):
        import warnings
        paddle.utils.require_version("0.0.1")
        with pytest.raises(Exception, match="minimum"):
            paddle.utils.require_version("99.0.0")

        @paddle.utils.deprecated(update_to="paddle.x", since="2.0")
        def old():
            return 1
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            assert old() == 1
            assert len(w) == 1 and "paddle.x" in str(w[0].message)

        @paddle.utils.deprecated(level=2)
        def gone():
            return 1
        with pytest.raises(RuntimeError):
            gone()
