"""Reference-format .pdmodel/.pdiparams WRITER (round-4 verdict item 1).

The exporter (static/pdmodel_export.py) traces the serving function to a
jaxpr and translates jax primitives into fluid OpDescs; these tests close
the loop: export -> this repo's own wire decoder -> numerics, plus a
``protoc --decode`` structural check against the reference schema
(/root/reference/paddle/fluid/framework/framework.proto) when available.
"""
import os
import shutil
import subprocess
import tempfile

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.static.pdmodel import (load_pdmodel, parse_combined_params,
                                       parse_program_desc)
from paddle_tpu.static.pdmodel_export import (serialize_params,
                                              serialize_program_desc,
                                              trace_to_pdmodel)

_REF_PROTO = "/root/reference/paddle/fluid/framework/framework.proto"


class TestWireEncoder:
    def test_desc_round_trip(self):
        desc = {"version": 0, "blocks": [{
            "idx": 0, "parent_idx": -1,
            "vars": [{"name": "x", "persistable": False,
                      "is_parameter": False, "stop_gradient": True,
                      "type": {"type": 7, "dtype": 5, "dims": [-1, 4],
                               "lod_level": 0}}],
            "ops": [{"type": "scale", "inputs": {"X": ["x"]},
                     "outputs": {"Out": ["y"]},
                     "attrs": {"scale": 2.0, "bias": 0.5,
                               "bias_after_scale": True,
                               "axes": [0, 2], "name": "s",
                               "big": 2 ** 40, "empty": []}}]}]}
        got = parse_program_desc(serialize_program_desc(desc))
        blk = got["blocks"][0]
        assert blk["vars"][0]["name"] == "x"
        assert blk["vars"][0]["type"]["dims"] == [-1, 4]
        op = blk["ops"][0]
        assert op["type"] == "scale"
        assert op["inputs"]["X"] == ["x"]
        assert op["attrs"]["scale"] == pytest.approx(2.0)
        assert op["attrs"]["bias_after_scale"] is True
        assert op["attrs"]["axes"] == [0, 2]
        assert op["attrs"]["big"] == 2 ** 40
        assert op["attrs"]["empty"] == []

    def test_params_round_trip(self):
        params = {"w": np.arange(12, dtype=np.float32).reshape(3, 4),
                  "ids": np.array([1, -2, 3], dtype=np.int64),
                  "m": np.array([True, False]),
                  "h": np.ones((2, 2), dtype=jnp.bfloat16)}
        data = serialize_params(params)
        got = parse_combined_params(data, sorted(params))
        for k in params:
            np.testing.assert_array_equal(np.asarray(got[k], np.float32),
                                          np.asarray(params[k], np.float32))


class TestJaxprTranslation:
    def _round_trip(self, run, weights, specs, feeds, feed_vals,
                    rtol=1e-5, atol=1e-5):
        model, params = trace_to_pdmodel(run, weights, specs, feeds)
        prog = load_pdmodel(model, params)
        assert prog.missing_ops() == []
        outs = prog.run(dict(zip(feeds, feed_vals)))
        wl = [weights[n] for n in sorted(weights)]
        want = run(wl, *[jnp.asarray(v) for v in feed_vals])
        want = want if isinstance(want, (tuple, list)) else [want]
        for o, r in zip(outs, want):
            np.testing.assert_allclose(np.asarray(o), np.asarray(r),
                                       rtol=rtol, atol=atol)
        return model

    def test_mlp_embedding_layernorm(self):
        def run(wlist, x, ids):
            b, emb, w = wlist
            h = jax.nn.relu(x @ w + b)
            sm = jax.nn.softmax(h, axis=-1)
            e = jnp.take(emb, ids, axis=0)
            mu = jnp.mean(h, -1, keepdims=True)
            ln = (h - mu) / jnp.sqrt(jnp.var(h, -1, keepdims=True) + 1e-5)
            return sm, e, ln * 2.0

        rng = np.random.RandomState(0)
        weights = {"b": rng.randn(16).astype(np.float32),
                   "emb": rng.randn(50, 16).astype(np.float32),
                   "w": rng.randn(8, 16).astype(np.float32)}
        specs = [jax.ShapeDtypeStruct((4, 8), np.float32),
                 jax.ShapeDtypeStruct((4, 3), np.int32)]
        self._round_trip(run, weights, specs, ["x", "ids"],
                         [rng.randn(4, 8).astype(np.float32),
                          rng.randint(0, 50, (4, 3)).astype(np.int32)])

    def test_cnn_pool(self):
        def run(wlist, x):
            cw, = wlist
            h = jax.lax.conv_general_dilated(
                x, cw, (1, 1), [(1, 1), (1, 1)],
                dimension_numbers=("NCHW", "OIHW", "NCHW"))
            h = jax.nn.relu(h)
            h = jax.lax.reduce_window(
                h, -jnp.inf, jax.lax.max, (1, 1, 2, 2), (1, 1, 2, 2),
                [(0, 0), (0, 0), (0, 0), (0, 0)])
            return jnp.mean(h, axis=(2, 3))

        rng = np.random.RandomState(1)
        weights = {"cw": (rng.randn(4, 3, 3, 3) * 0.1).astype(np.float32)}
        specs = [jax.ShapeDtypeStruct((2, 3, 8, 8), np.float32)]
        self._round_trip(run, weights, specs, ["im"],
                         [rng.randn(2, 3, 8, 8).astype(np.float32)])

    def test_attention_block(self):
        # batched dot_general + transpose + masking: the transformer shapes
        def run(wlist, x):
            wq, wk, wv = wlist
            q = x @ wq
            k = x @ wk
            v = x @ wv
            s = jnp.einsum("bsd,btd->bst", q, k) / np.sqrt(16.0)
            mask = jnp.tril(jnp.ones((x.shape[1], x.shape[1])))
            s = jnp.where(mask > 0, s, -1e9)
            return jnp.einsum("bst,btd->bsd", jax.nn.softmax(s, -1), v)

        rng = np.random.RandomState(2)
        weights = {f"w{c}": (rng.randn(16, 16) * 0.2).astype(np.float32)
                   for c in "qkv"}
        weights = {"wq": weights["wq"], "wk": weights["wk"],
                   "wv": weights["wv"]}
        specs = [jax.ShapeDtypeStruct((2, 6, 16), np.float32)]
        self._round_trip(run, weights, specs, ["x"],
                         [rng.randn(2, 6, 16).astype(np.float32)],
                         rtol=1e-4, atol=1e-4)

    def test_protoc_structural_decode(self, tmp_path):
        if shutil.which("protoc") is None or not os.path.exists(_REF_PROTO):
            pytest.skip("protoc or reference framework.proto unavailable")

        def run(wlist, x):
            w, = wlist
            return jax.nn.softmax(x @ w, axis=-1)

        weights = {"w": np.eye(4, dtype=np.float32)}
        specs = [jax.ShapeDtypeStruct((2, 4), np.float32)]
        model, _ = trace_to_pdmodel(run, weights, specs, ["x"])
        p = tmp_path / "m.pdmodel"
        p.write_bytes(model)
        with open(p, "rb") as f:
            res = subprocess.run(
                ["protoc", "--decode=paddle.framework.proto.ProgramDesc",
                 "-I", os.path.dirname(_REF_PROTO), _REF_PROTO],
                stdin=f, capture_output=True)
        assert res.returncode == 0, res.stderr.decode()
        txt = res.stdout.decode()
        # softmax decomposes into exp / reduce_sum / elementwise_div
        assert "matmul_v2" in txt and "reduce_sum" in txt and "exp" in txt
        assert 'parameter: "X"' in txt


class TestDynDimPrimeScreening:
    def test_static_dim_colliding_with_default_prime_stays_static(self):
        """Round-4 advisor low: a genuine static extent that is an exact
        multiple of a sample prime (2*9973=19946) must NOT be written as
        -1 — the prime screen picks a clash-free sample instead."""
        from paddle_tpu.static.pdmodel import parse_program_desc

        class Spec:
            def __init__(self, shape, dtype="float32"):
                self.shape, self.dtype = shape, np.dtype(dtype)

        w = np.random.RandomState(0).randn(19946, 4).astype("float32")

        def run(wlist, ids):
            return jnp.take(wlist[0], ids, axis=0)

        model, params = trace_to_pdmodel(
            run, {"emb": w}, [Spec([None, 8], "int64")], ["ids"])
        desc = parse_program_desc(model)
        dims_by_var = {v["name"]: v["type"]["dims"]
                       for v in desc["blocks"][0]["vars"]}
        assert list(dims_by_var["emb"]) == [19946, 4], dims_by_var["emb"]
        # the dynamic batch dim is still -1 somewhere in the feed var
        feed_dims = [d for v in desc["blocks"][0]["vars"]
                     if not v.get("persistable")
                     for d in v["type"].get("dims", [])]
        assert -1 in feed_dims


class TestStalePdexecRouting:
    def test_explicit_pdmodel_path_skips_pdexec(self, tmp_path):
        paddle.seed(0)
        net = nn.Linear(4, 3)
        prefix = os.path.join(str(tmp_path), "m")
        paddle.jit.save(net, prefix, input_spec=[
            paddle.static.InputSpec([2, 4], "float32")])
        from paddle_tpu.static.io import load_inference_model
        from paddle_tpu.static.pdmodel import PdProgram
        prog, feeds, fetches = load_inference_model(prefix + ".pdmodel")
        # explicit .pdmodel path loads the protobuf program, not the
        # StableHLO twin
        assert isinstance(prog, PdProgram), type(prog)

    def test_stale_pdexec_warns_and_loads_proto(self, tmp_path):
        paddle.seed(0)
        net = nn.Linear(4, 3)
        prefix = os.path.join(str(tmp_path), "m")
        paddle.jit.save(net, prefix, input_spec=[
            paddle.static.InputSpec([2, 4], "float32")])
        # make the .pdexec look stale next to a regenerated .pdmodel
        old = os.path.getmtime(prefix + ".pdexec") - 1000
        os.utime(prefix + ".pdexec", (old, old))
        from paddle_tpu.static.io import load_inference_model
        from paddle_tpu.static.pdmodel import PdProgram
        with pytest.warns(UserWarning, match="OLDER"):
            prog, _, _ = load_inference_model(prefix)
        assert isinstance(prog, PdProgram), type(prog)


class TestFrameworkIntegration:
    def _lenet(self):
        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.conv = nn.Conv2D(1, 4, 3, padding=1)
                self.pool = nn.MaxPool2D(2, 2)
                self.flat = nn.Flatten()
                self.fc = nn.Linear(4 * 14 * 14, 10)

            def forward(self, x):
                h = self.pool(nn.functional.relu(self.conv(x)))
                return nn.functional.softmax(self.fc(self.flat(h)))
        return Net()

    def test_jit_save_emits_reference_format(self, tmp_path):
        paddle.seed(0)
        net = self._lenet()
        prefix = os.path.join(str(tmp_path), "m")
        paddle.jit.save(net, prefix, input_spec=[
            paddle.static.InputSpec([None, 1, 28, 28], "float32")])
        assert os.path.exists(prefix + ".pdmodel")
        assert os.path.exists(prefix + ".pdiparams")
        assert os.path.exists(prefix + ".pdexec")
        # the .pdmodel is a genuine protobuf, not a pickle
        with open(prefix + ".pdmodel", "rb") as f:
            data = f.read()
        assert data[0] == 0x0A
        prog = load_pdmodel(data, open(prefix + ".pdiparams", "rb").read())
        assert prog.missing_ops() == []
        # dynamic batch: serves at extents never seen at export time
        for bs in (2, 5):
            x = np.random.RandomState(bs).randn(
                bs, 1, 28, 28).astype(np.float32)
            out = np.asarray(prog.run({prog.feed_names[0]: x})[0])
            want = net(paddle.to_tensor(x)).numpy()
            np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)

    def test_predictor_serves_proto_pair(self, tmp_path):
        from paddle_tpu import inference

        paddle.seed(1)
        net = self._lenet()
        prefix = os.path.join(str(tmp_path), "m")
        paddle.jit.save(net, prefix, input_spec=[
            paddle.static.InputSpec([2, 1, 28, 28], "float32")])
        # explicit params path routes to the proto pair
        cfg = inference.Config(prefix + ".pdmodel", prefix + ".pdiparams")
        pred = inference.create_predictor(cfg)
        x = np.random.RandomState(0).randn(2, 1, 28, 28).astype(np.float32)
        out = pred.run([x])[0]
        want = net(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)

    def test_static_save_inference_model_round_trip(self, tmp_path):
        paddle.enable_static()
        try:
            import paddle_tpu.static as static

            x = static.data("x", [4, 8], "float32")
            y = paddle.matmul(x, paddle.to_tensor(
                np.random.RandomState(0).randn(8, 4).astype(np.float32)))
            z = nn.functional.relu(y)
            prefix = os.path.join(str(tmp_path), "sm")
            static.save_inference_model(prefix, [x], [z],
                                        executor=static.Executor())
            assert os.path.exists(prefix + ".pdmodel")
            with open(prefix + ".pdmodel", "rb") as f:
                data = f.read()
            assert data[0] == 0x0A
            prog = load_pdmodel(
                data, open(prefix + ".pdiparams", "rb").read())
            xs = np.random.RandomState(1).randn(4, 8).astype(np.float32)
            out = np.asarray(prog.run({"x": xs})[0])
            assert out.shape == (4, 4)
            np.testing.assert_allclose(
                out, np.maximum(
                    xs @ np.random.RandomState(0).randn(8, 4).astype(
                        np.float32), 0), rtol=1e-5, atol=1e-5)
        finally:
            paddle.disable_static()
