"""nn.Layer and layer-zoo tests (reference: python/paddle/nn/layer/)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def t(a, sg=True):
    return paddle.to_tensor(a, stop_gradient=sg)


class TestLayerBase:
    def test_parameters_and_state_dict(self):
        m = nn.Linear(4, 3)
        params = m.parameters()
        assert len(params) == 2
        sd = m.state_dict()
        assert set(sd.keys()) == {"weight", "bias"}
        assert sd["weight"].shape == [4, 3]

    def test_nested_state_dict(self):
        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = nn.Linear(4, 8)
                self.fc2 = nn.Linear(8, 2)

            def forward(self, x):
                return self.fc2(F.relu(self.fc1(x)))

        net = Net()
        sd = net.state_dict()
        assert "fc1.weight" in sd and "fc2.bias" in sd
        x = t(np.random.randn(2, 4).astype("float32"))
        assert net(x).shape == [2, 2]

    def test_set_state_dict(self):
        m1 = nn.Linear(4, 3)
        m2 = nn.Linear(4, 3)
        m2.set_state_dict(m1.state_dict())
        np.testing.assert_allclose(m1.weight.numpy(), m2.weight.numpy())

    def test_train_eval_mode(self):
        m = nn.Dropout(0.5)
        m.eval()
        x = t(np.ones((10, 10), "float32"))
        np.testing.assert_allclose(m(x).numpy(), x.numpy())
        m.train()

    def test_sublayers_named(self):
        net = nn.Sequential(nn.Linear(2, 2), nn.ReLU())
        assert len(list(net.sublayers())) >= 2

    def test_apply_fn(self):
        m = nn.Linear(3, 3)
        m.apply(lambda layer: None)


class TestCommonLayers:
    def test_linear(self):
        m = nn.Linear(5, 7)
        x = np.random.randn(3, 5).astype("float32")
        out = m(t(x))
        expect = x @ m.weight.numpy() + m.bias.numpy()
        np.testing.assert_allclose(out.numpy(), expect, rtol=1e-4, atol=1e-5)

    def test_embedding(self):
        m = nn.Embedding(10, 4)
        idx = t(np.array([[1, 2], [3, 4]], "int64"))
        out = m(idx)
        assert out.shape == [2, 2, 4]
        np.testing.assert_allclose(out.numpy()[0, 0], m.weight.numpy()[1])

    def test_dropout_train(self):
        m = nn.Dropout(0.5)
        m.train()
        x = t(np.ones((100, 100), "float32"))
        y = m(x).numpy()
        frac = (y == 0).mean()
        assert 0.3 < frac < 0.7

    def test_flatten_layer(self):
        m = nn.Flatten()
        x = t(np.random.randn(2, 3, 4).astype("float32"))
        assert m(x).shape == [2, 12]


class TestActivations:
    def test_activations_vs_numpy(self):
        x = np.random.randn(3, 4).astype("float32")
        np.testing.assert_allclose(F.relu(t(x)).numpy(), np.maximum(x, 0))
        np.testing.assert_allclose(
            F.sigmoid(t(x)).numpy(), 1 / (1 + np.exp(-x)), rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(
            F.softmax(t(x), axis=-1).numpy(),
            np.exp(x) / np.exp(x).sum(-1, keepdims=True), rtol=1e-4, atol=1e-5)
        gelu = F.gelu(t(x)).numpy()
        approx = 0.5 * x * (1 + np.tanh(np.sqrt(2 / np.pi) * (x + 0.044715 * x ** 3)))
        np.testing.assert_allclose(gelu, approx, rtol=1e-2, atol=1e-3)
        lrelu = F.leaky_relu(t(x), 0.1).numpy()
        np.testing.assert_allclose(lrelu, np.where(x > 0, x, 0.1 * x), rtol=1e-5)
        np.testing.assert_allclose(F.silu(t(x)).numpy(), x / (1 + np.exp(-x)),
                                   rtol=1e-4, atol=1e-5)

    def test_activation_layers(self):
        x = t(np.random.randn(2, 3).astype("float32"))
        for L in [nn.ReLU(), nn.GELU(), nn.Sigmoid(), nn.Tanh(), nn.Softmax(), nn.Silu()]:
            assert L(x).shape == [2, 3]


class TestConvPool:
    def test_conv2d_shape_and_value(self):
        m = nn.Conv2D(3, 8, 3, padding=1)
        x = t(np.random.randn(2, 3, 16, 16).astype("float32"))
        out = m(x)
        assert out.shape == [2, 8, 16, 16]

    def test_conv2d_vs_manual(self):
        # 1x1 conv equals matmul over channels
        m = nn.Conv2D(4, 6, 1)
        x = np.random.randn(1, 4, 5, 5).astype("float32")
        out = m(t(x)).numpy()
        w = m.weight.numpy().reshape(6, 4)
        expect = np.einsum("oc,bchw->bohw", w, x) + m.bias.numpy().reshape(1, 6, 1, 1)
        np.testing.assert_allclose(out, expect, rtol=1e-3, atol=1e-4)

    def test_conv2d_stride_groups(self):
        m = nn.Conv2D(4, 4, 3, stride=2, padding=1, groups=2)
        x = t(np.random.randn(2, 4, 8, 8).astype("float32"))
        assert m(x).shape == [2, 4, 4, 4]

    def test_maxpool_avgpool(self):
        x = np.random.randn(1, 2, 4, 4).astype("float32")
        mp = nn.MaxPool2D(2, 2)(t(x)).numpy()
        expect = x.reshape(1, 2, 2, 2, 2, 2).max((3, 5))
        np.testing.assert_allclose(mp, expect)
        ap = nn.AvgPool2D(2, 2)(t(x)).numpy()
        np.testing.assert_allclose(ap, x.reshape(1, 2, 2, 2, 2, 2).mean((3, 5)), rtol=1e-5)

    def test_adaptive_avgpool(self):
        x = t(np.random.randn(1, 3, 8, 8).astype("float32"))
        out = nn.AdaptiveAvgPool2D(1)(x)
        assert out.shape == [1, 3, 1, 1]
        np.testing.assert_allclose(out.numpy().reshape(1, 3), x.numpy().mean((2, 3)), rtol=1e-4)


class TestNorm:
    def test_batchnorm_train_stats(self):
        m = nn.BatchNorm2D(3)
        m.train()
        x = np.random.randn(4, 3, 5, 5).astype("float32") * 2 + 1
        out = m(t(x)).numpy()
        np.testing.assert_allclose(out.mean((0, 2, 3)), 0, atol=1e-4)
        np.testing.assert_allclose(out.std((0, 2, 3)), 1, atol=1e-2)

    def test_batchnorm_eval_running_stats(self):
        m = nn.BatchNorm2D(3)
        m.train()
        x = np.random.randn(4, 3, 5, 5).astype("float32")
        for _ in range(5):
            m(t(x))
        m.eval()
        out = m(t(x))
        assert out.shape == [4, 3, 5, 5]

    def test_layernorm(self):
        m = nn.LayerNorm(8)
        x = np.random.randn(2, 4, 8).astype("float32")
        out = m(t(x)).numpy()
        mu = x.mean(-1, keepdims=True)
        sd = x.std(-1, keepdims=True)
        np.testing.assert_allclose(out, (x - mu) / np.sqrt(sd ** 2 + 1e-5), rtol=1e-3, atol=1e-3)

    def test_groupnorm(self):
        m = nn.GroupNorm(2, 4)
        x = t(np.random.randn(2, 4, 3, 3).astype("float32"))
        assert m(x).shape == [2, 4, 3, 3]


class TestLoss:
    def test_cross_entropy(self):
        logits = np.random.randn(4, 5).astype("float32")
        labels = np.array([0, 2, 1, 4], "int64")
        loss = F.cross_entropy(t(logits), t(labels)).numpy()
        # numpy oracle
        e = np.exp(logits - logits.max(1, keepdims=True))
        p = e / e.sum(1, keepdims=True)
        expect = -np.log(p[np.arange(4), labels]).mean()
        np.testing.assert_allclose(loss, expect, rtol=1e-4)

    def test_mse_l1(self):
        a = np.random.randn(3, 4).astype("float32")
        b = np.random.randn(3, 4).astype("float32")
        np.testing.assert_allclose(F.mse_loss(t(a), t(b)).numpy(),
                                   ((a - b) ** 2).mean(), rtol=1e-4)
        np.testing.assert_allclose(F.l1_loss(t(a), t(b)).numpy(),
                                   np.abs(a - b).mean(), rtol=1e-4)

    def test_nll_bce(self):
        p = np.random.rand(4).astype("float32") * 0.8 + 0.1
        y = np.array([1, 0, 1, 0], "float32")
        out = F.binary_cross_entropy(t(p), t(y)).numpy()
        expect = -(y * np.log(p) + (1 - y) * np.log(1 - p)).mean()
        np.testing.assert_allclose(out, expect, rtol=1e-3)

    def test_loss_layers(self):
        logits = t(np.random.randn(4, 5).astype("float32"))
        labels = t(np.array([0, 2, 1, 4], "int64"))
        loss = nn.CrossEntropyLoss()(logits, labels)
        assert loss.shape == [] or loss.shape == [1]


class TestTransformer:
    def test_multihead_attention(self):
        m = nn.MultiHeadAttention(embed_dim=16, num_heads=4)
        x = t(np.random.randn(2, 5, 16).astype("float32"))
        out = m(x, x, x)
        assert out.shape == [2, 5, 16]

    @pytest.mark.slow
    def test_transformer_encoder_layer(self):
        layer = nn.TransformerEncoderLayer(d_model=16, nhead=4, dim_feedforward=32)
        x = t(np.random.randn(2, 5, 16).astype("float32"))
        assert layer(x).shape == [2, 5, 16]

    def test_transformer_encoder(self):
        layer = nn.TransformerEncoderLayer(d_model=16, nhead=4, dim_feedforward=32)
        enc = nn.TransformerEncoder(layer, num_layers=2)
        x = t(np.random.randn(2, 5, 16).astype("float32"))
        assert enc(x).shape == [2, 5, 16]


class TestRNN:
    @pytest.mark.slow
    def test_lstm_gru_shapes(self):
        lstm = nn.LSTM(8, 16)
        x = t(np.random.randn(2, 5, 8).astype("float32"))
        out, (h, c) = lstm(x)
        assert out.shape == [2, 5, 16]
        gru = nn.GRU(8, 16)
        out2, h2 = gru(x)
        assert out2.shape == [2, 5, 16]


class TestTraining:
    @pytest.mark.slow
    def test_mlp_learns_xor(self):
        X = np.array([[0, 0], [0, 1], [1, 0], [1, 1]], "float32")
        Y = np.array([0, 1, 1, 0], "int64")
        net = nn.Sequential(nn.Linear(2, 16), nn.Tanh(), nn.Linear(16, 2))
        opt = paddle.optimizer.Adam(learning_rate=0.05, parameters=net.parameters())
        for _ in range(150):
            logits = net(t(X))
            loss = F.cross_entropy(logits, t(Y))
            loss.backward()
            opt.step()
            opt.clear_grad()
        pred = net(t(X)).numpy().argmax(1)
        assert (pred == Y).all(), pred


class TestIncubateFused:
    """incubate.nn Fused* layers keep the reference API surface
    (fused_transformer.py) while routing compute to plain layers."""

    def test_fused_feedforward_pre_ln_matches_manual(self):
        from paddle_tpu.incubate.nn import FusedFeedForward
        paddle.seed(0)
        ff = FusedFeedForward(8, 32, dropout_rate=0.0, activation="gelu",
                              normalize_before=True)
        x = t(np.random.RandomState(0).randn(2, 4, 8).astype("float32"))
        manual = x + ff.linear2(F.gelu(ff.linear1(ff.norm(x))))
        np.testing.assert_allclose(ff(x).numpy(), manual.numpy(),
                                   rtol=1e-5, atol=1e-5)

    def test_fused_feedforward_post_ln(self):
        from paddle_tpu.incubate.nn import FusedFeedForward
        paddle.seed(0)
        ff = FusedFeedForward(8, 16, dropout_rate=0.0,
                              normalize_before=False)
        x = t(np.ones((2, 3, 8), "float32"))
        manual = ff.norm(x + ff.linear2(F.relu(ff.linear1(x))))
        np.testing.assert_allclose(ff(x).numpy(), manual.numpy(),
                                   rtol=1e-5, atol=1e-5)

    def test_fused_linear_trains(self):
        from paddle_tpu.incubate.nn import FusedLinear
        paddle.seed(0)
        fl = FusedLinear(4, 2)
        x = t(np.ones((3, 4), "float32"))
        fl(x).sum().backward()
        assert fl.weight.grad is not None
        assert fl(x).shape == [3, 2]
        # checkpoint keys match plain Linear (no wrapper prefix)
        assert set(fl.state_dict().keys()) == {"weight", "bias"}

    def test_fused_linear_transpose_weight(self):
        from paddle_tpu.incubate.nn import FusedLinear
        paddle.seed(0)
        fl = FusedLinear(4, 2, transpose_weight=True)
        assert fl.weight.shape == [2, 4]
        x = t(np.random.RandomState(0).randn(3, 4).astype("float32"))
        ref = x.numpy() @ fl.weight.numpy().T + fl.bias.numpy()
        np.testing.assert_allclose(fl(x).numpy(), ref, rtol=1e-5,
                                   atol=1e-5)

    def test_fused_feedforward_ln_attrs_honored(self):
        from paddle_tpu.incubate.nn import FusedFeedForward
        from paddle_tpu import ParamAttr
        from paddle_tpu.nn.initializer import Constant
        ff = FusedFeedForward(
            8, 16, dropout_rate=0.0, normalize_before=True,
            ln1_scale_attr=ParamAttr(initializer=Constant(2.0)))
        np.testing.assert_allclose(ff.norm.weight.numpy(),
                                   np.full(8, 2.0, "float32"))
