"""paddle.incubate.nn.functional + linalg namespace + the four fused
layer classes added in round 5 (reference: python/paddle/incubate/nn/
functional/, python/paddle/linalg.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.incubate.nn as inn
import paddle_tpu.incubate.nn.functional as FF


class TestLinalgNamespace:
    def test_reference_surface_present_and_working(self):
        import paddle_tpu.linalg as L

        for n in ("cholesky", "svd", "qr", "eigh", "pinv", "solve",
                  "lstsq", "norm", "det", "inv", "lu", "cond"):
            assert hasattr(L, n), n
        rng = np.random.RandomState(0)
        a = rng.randn(4, 4).astype("float32")
        spd = a @ a.T + 4 * np.eye(4, dtype="float32")
        c = L.cholesky(paddle.to_tensor(spd)).numpy()
        np.testing.assert_allclose(c @ c.T, spd, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(
            L.inv(paddle.to_tensor(spd)).numpy() @ spd, np.eye(4),
            rtol=1e-3, atol=1e-4)


class TestFusedFunctional:
    def test_fused_matmul_bias_and_linear(self):
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(3, 4).astype("float32"))
        w = paddle.to_tensor(rng.randn(4, 5).astype("float32"))
        b = paddle.to_tensor(rng.randn(5).astype("float32"))
        out = FF.fused_matmul_bias(x, w, b).numpy()
        np.testing.assert_allclose(
            out, x.numpy() @ w.numpy() + b.numpy(), rtol=1e-5)
        wt = paddle.to_tensor(w.numpy().T.copy())
        out2 = FF.fused_linear(x, wt, b, transpose_weight=True).numpy()
        np.testing.assert_allclose(out2, out, rtol=1e-5)

    def test_fused_dropout_add_eval_is_plain_add(self):
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(2, 3).astype("float32"))
        y = paddle.to_tensor(rng.randn(2, 3).astype("float32"))
        out = FF.fused_dropout_add(x, y, p=0.5, training=False).numpy()
        np.testing.assert_allclose(out, x.numpy() + y.numpy(), rtol=1e-6)

    def test_fused_bias_dropout_residual_layer_norm(self):
        rng = np.random.RandomState(0)
        d = 8
        x = paddle.to_tensor(rng.randn(2, 5, d).astype("float32"))
        res = paddle.to_tensor(rng.randn(2, 5, d).astype("float32"))
        bias = paddle.to_tensor(rng.randn(d).astype("float32"))
        out = FF.fused_bias_dropout_residual_layer_norm(
            x, res, bias=bias, dropout_rate=0.0).numpy()
        z = x.numpy() + bias.numpy() + res.numpy()
        mu = z.mean(-1, keepdims=True)
        var = z.var(-1, keepdims=True)
        np.testing.assert_allclose(out, (z - mu) / np.sqrt(var + 1e-5),
                                   rtol=1e-4, atol=1e-5)

    def test_fused_feedforward_matches_layer(self):
        paddle.seed(0)
        layer = inn.FusedFeedForward(8, 16, dropout_rate=0.0,
                                     act_dropout_rate=0.0,
                                     normalize_before=True)
        layer.eval()
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(2, 4, 8).astype("float32"))
        want = layer(x).numpy()
        got = FF.fused_feedforward(
            x, layer.linear1.weight, layer.linear2.weight,
            linear1_bias=layer.linear1.bias,
            linear2_bias=layer.linear2.bias,
            ln1_scale=layer.norm.weight, ln1_bias=layer.norm.bias,
            dropout1_rate=0.0, dropout2_rate=0.0,
            pre_layer_norm=True, training=False).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_fused_multi_head_attention_matches_dense(self):
        rng = np.random.RandomState(0)
        b, s, nh, dh = 2, 5, 2, 4
        d = nh * dh
        x = rng.randn(b, s, d).astype("float32")
        qkv_w = rng.randn(3, nh, dh, d).astype("float32") * 0.3
        qkv_b = rng.randn(3, nh, dh).astype("float32") * 0.05
        lw = rng.randn(d, d).astype("float32") * 0.3
        lb = rng.randn(d).astype("float32") * 0.05
        out = FF.fused_multi_head_attention(
            paddle.to_tensor(x), paddle.to_tensor(qkv_w),
            paddle.to_tensor(lw), qkv_bias=paddle.to_tensor(qkv_b),
            linear_bias=paddle.to_tensor(lb), dropout_rate=0.0,
            attn_dropout_rate=0.0, training=False,
            pre_layer_norm=True).numpy()
        # independent numpy sim (pre-LN, residual, no post-LN)
        mu = x.mean(-1, keepdims=True)
        xv = (x - mu) / np.sqrt(x.var(-1, keepdims=True) + 1e-5)
        qkv = (xv @ qkv_w.reshape(3 * nh * dh, d).T
               + qkv_b.reshape(-1)).reshape(b, s, 3, nh, dh)
        q, k, v = (np.swapaxes(qkv[:, :, j], 1, 2) for j in range(3))
        sc = np.einsum("bhsd,bhtd->bhst", q, k) / np.sqrt(dh)
        p = np.exp(sc - sc.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        o = np.swapaxes(np.einsum("bhst,bhtd->bhsd", p, v),
                        1, 2).reshape(b, s, d)
        want = x + (o @ lw + lb)
        np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)

    def test_fused_multi_transformer_matches_layer(self):
        paddle.seed(0)
        d, nh, dff, L = 8, 2, 16, 2
        layer = inn.FusedMultiTransformer(d, nh, dff, num_layers=L)
        layer.eval()
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(2, 5, d).astype("float32"))
        out = layer(x)
        assert list(out.shape) == [2, 5, d]
        assert np.isfinite(out.numpy()).all()
        # parameters are registered per layer (state_dict round-trips)
        sd = layer.state_dict()
        assert f"qkv_weight_{L - 1}" in sd and "ffn2_bias_0" in sd

    def test_fused_multi_transformer_gradients_flow(self):
        """The functional wraps raw math in a dispatched op — the tape
        must differentiate into the LAYER weights (round-5 review)."""
        paddle.seed(0)
        layer = inn.FusedMultiTransformer(8, 2, 16, num_layers=1)
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(2, 4, 8).astype("float32"))
        out = layer(x)
        loss = (out * out).mean()
        loss.backward()
        got = [(n, p.grad) for n, p in layer.named_parameters()]
        with_grad = [n for n, g in got if g is not None
                     and float(np.abs(np.asarray(g._data)).max()) > 0]
        assert any("qkv_weight" in n for n in with_grad), with_grad
        assert any("ffn1_weight" in n for n in with_grad), with_grad
        assert any("ln_scale" in n for n in with_grad), with_grad

    def test_fused_ec_moe_matches_reference_baseline(self):
        """Independent numpy sim of the op's own baseline
        (test_fused_ec_moe_op.py:85-136)."""
        rng = np.random.RandomState(0)
        b, s, d, f, e = 2, 32, 4, 8, 2
        x = rng.randn(b, s, d).astype("float32")
        gate = rng.randn(b, s, e).astype("float32")
        w0 = (rng.randn(e, d, f) * 0.3).astype("float32")
        b0 = (rng.randn(e, 1, f) * 0.05).astype("float32")
        w1 = (rng.randn(e, f, d) * 0.3).astype("float32")
        b1 = (rng.randn(e, 1, d) * 0.05).astype("float32")
        out = FF.fused_ec_moe(
            paddle.to_tensor(x), paddle.to_tensor(gate),
            paddle.to_tensor(w0), paddle.to_tensor(b0),
            paddle.to_tensor(w1), paddle.to_tensor(b1), "relu").numpy()

        cap = s // 16
        gates = np.exp(gate - gate.max(-1, keepdims=True))
        gates /= gates.sum(-1, keepdims=True)
        want = x.copy()
        for bi in range(b):
            for ei in range(e):
                tok = np.argsort(-gate[bi, :, ei], kind="stable")[:cap]
                sel = x[bi, tok]                          # [cap, d]
                h = np.maximum(sel @ w0[ei] + b0[ei], 0.0)
                h = h @ w1[ei] + b1[ei]
                h = h * gates[bi, tok, ei][:, None]
                np.add.at(want[bi], tok, h)
        np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)

    def test_fused_layers_exist(self):
        for cls in ("FusedMultiTransformer", "FusedEcMoe",
                    "FusedDropoutAdd",
                    "FusedBiasDropoutResidualLayerNorm"):
            assert hasattr(inn, cls), cls
        lay = inn.FusedDropoutAdd(p=0.0)
        x = paddle.to_tensor(np.ones((2, 2), "float32"))
        np.testing.assert_allclose(lay(x, x).numpy(), 2 * np.ones((2, 2)))