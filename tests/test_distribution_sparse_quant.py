"""distribution / sparse / quantization packages — numpy-oracle tests
(reference test analogs: test_distribution_*.py, test_sparse_*.py,
test_quant_*.py under fluid/tests/unittests)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import distribution as D
from paddle_tpu import sparse as S


def _np(t):
    return np.asarray(t.numpy())


class TestDistributions:
    def test_normal_log_prob_oracle(self):
        n = D.Normal(1.5, 2.0)
        v = np.array([0.0, 1.5, 4.0], np.float32)
        lp = _np(n.log_prob(paddle.to_tensor(v)))
        oracle = -((v - 1.5) ** 2) / (2 * 4.0) - np.log(2.0) \
            - 0.5 * np.log(2 * np.pi)
        np.testing.assert_allclose(lp, oracle, rtol=1e-5)

    def test_normal_sampling_moments(self):
        paddle.seed(0)
        n = D.Normal(2.0, 3.0)
        s = _np(n.sample((20000,)))
        assert abs(s.mean() - 2.0) < 0.1
        assert abs(s.std() - 3.0) < 0.1

    def test_uniform_entropy_and_bounds(self):
        u = D.Uniform(1.0, 3.0)
        assert np.isclose(float(u.entropy().numpy()), np.log(2.0))
        paddle.seed(0)
        s = _np(u.sample((1000,)))
        assert s.min() >= 1.0 and s.max() < 3.0
        assert np.isneginf(_np(u.log_prob(paddle.to_tensor(5.0))))

    def test_categorical_log_prob_entropy(self):
        logits = np.log(np.array([0.2, 0.3, 0.5], np.float32))
        c = D.Categorical(logits=logits)
        np.testing.assert_allclose(
            _np(c.log_prob(paddle.to_tensor(np.array([2])))),
            [np.log(0.5)], rtol=1e-5)
        oracle_h = -(0.2 * np.log(0.2) + 0.3 * np.log(0.3)
                     + 0.5 * np.log(0.5))
        np.testing.assert_allclose(float(c.entropy().numpy()), oracle_h,
                                   rtol=1e-5)

    def test_bernoulli(self):
        b = D.Bernoulli(probs=0.7)
        np.testing.assert_allclose(float(b.mean.numpy()), 0.7)
        np.testing.assert_allclose(
            float(b.log_prob(paddle.to_tensor(1.0)).numpy()),
            np.log(0.7), rtol=1e-5)

    def test_beta_dirichlet_moments(self):
        be = D.Beta(2.0, 3.0)
        np.testing.assert_allclose(float(be.mean.numpy()), 0.4, rtol=1e-6)
        d = D.Dirichlet(np.array([1.0, 2.0, 3.0], np.float32))
        np.testing.assert_allclose(_np(d.mean), [1 / 6, 2 / 6, 3 / 6],
                                   rtol=1e-5)

    def test_laplace_gumbel_lognormal(self):
        l = D.Laplace(0.0, 1.0)
        np.testing.assert_allclose(
            float(l.log_prob(paddle.to_tensor(0.0)).numpy()),
            np.log(0.5), rtol=1e-5)
        g = D.Gumbel(0.0, 1.0)
        np.testing.assert_allclose(float(g.mean.numpy()), 0.57721566,
                                   rtol=1e-4)
        ln = D.LogNormal(0.0, 0.5)
        np.testing.assert_allclose(float(ln.mean.numpy()),
                                   np.exp(0.125), rtol=1e-5)
        # TransformedDistribution log_prob: lognormal pdf oracle
        v = 1.7
        lp = float(ln.log_prob(paddle.to_tensor(v)).numpy())
        oracle = -np.log(v * 0.5 * np.sqrt(2 * np.pi)) \
            - (np.log(v)) ** 2 / (2 * 0.25)
        np.testing.assert_allclose(lp, oracle, rtol=1e-4)

    def test_independent_sums_event_dims(self):
        n = D.Normal(np.zeros((3, 4), np.float32),
                     np.ones((3, 4), np.float32))
        ind = D.Independent(n, 1)
        v = paddle.to_tensor(np.zeros((3, 4), np.float32))
        lp = _np(ind.log_prob(v))
        assert lp.shape == (3,)
        np.testing.assert_allclose(lp, _np(n.log_prob(v)).sum(-1),
                                   rtol=1e-6)

    def test_kl_normal_oracle(self):
        kl = float(D.kl_divergence(D.Normal(0.0, 1.0),
                                   D.Normal(1.0, 2.0)).numpy())
        vr = (1 / 2) ** 2
        oracle = 0.5 * (vr + (1 / 2) ** 2 - 1 - np.log(vr))
        np.testing.assert_allclose(kl, oracle, rtol=1e-5)

    def test_kl_registry_dispatch_and_missing(self):
        assert float(D.kl_divergence(D.Bernoulli(probs=0.5),
                                     D.Bernoulli(probs=0.5)).numpy()) == \
            pytest.approx(0.0, abs=1e-6)
        with pytest.raises(NotImplementedError):
            D.kl_divergence(D.Normal(0.0, 1.0), D.Beta(1.0, 1.0))

    def test_multinomial_counts(self):
        paddle.seed(0)
        m = D.Multinomial(20, np.array([0.5, 0.5], np.float32))
        s = _np(m.sample((100,)))
        assert s.shape == (100, 2)
        np.testing.assert_array_equal(s.sum(-1), np.full(100, 20.0))


class TestSparse:
    def _coo(self):
        idx = np.array([[0, 1, 2], [1, 0, 2]])
        vals = np.array([1.0, 2.0, 3.0], np.float32)
        return S.sparse_coo_tensor(idx, vals, shape=[3, 3])

    def test_coo_roundtrip(self):
        sp = self._coo()
        dense = _np(sp.to_dense())
        oracle = np.zeros((3, 3), np.float32)
        oracle[0, 1], oracle[1, 0], oracle[2, 2] = 1, 2, 3
        np.testing.assert_array_equal(dense, oracle)
        assert sp.nnz == 3
        assert S.is_sparse_coo(sp)

    def test_csr_conversion(self):
        csr = self._coo().to_sparse_csr()
        assert S.is_sparse_csr(csr)
        np.testing.assert_array_equal(_np(csr.crows()), [0, 1, 2, 3])
        np.testing.assert_array_equal(_np(csr.to_dense()),
                                      _np(self._coo().to_dense()))

    def test_csr_creation(self):
        csr = S.sparse_csr_tensor([0, 2, 3, 5], [1, 3, 2, 0, 1],
                                  [1., 2., 3., 4., 5.], [3, 4])
        d = _np(csr.to_dense())
        oracle = np.array([[0, 1, 0, 2], [0, 0, 3, 0], [4, 5, 0, 0]],
                          np.float32)
        np.testing.assert_array_equal(d, oracle)

    def test_unary_preserves_pattern(self):
        sp = S.sin(self._coo())
        oracle = np.sin(_np(self._coo().to_dense()))
        np.testing.assert_allclose(_np(sp.to_dense()), oracle, rtol=1e-6)
        assert sp.nnz == 3

    def test_binary_same_pattern(self):
        out = S.add(self._coo(), self._coo())
        np.testing.assert_allclose(_np(out.to_dense()),
                                   2 * _np(self._coo().to_dense()))

    def test_matmul_dense_rhs(self):
        rng = np.random.RandomState(0)
        y = rng.randn(3, 5).astype(np.float32)
        out = _np(S.matmul(self._coo(), y))
        oracle = _np(self._coo().to_dense()) @ y
        np.testing.assert_allclose(out, oracle, rtol=1e-5)

    def test_masked_matmul(self):
        rng = np.random.RandomState(1)
        x = rng.randn(3, 4).astype(np.float32)
        y = rng.randn(4, 3).astype(np.float32)
        mask = self._coo()
        out = S.masked_matmul(paddle.to_tensor(x), paddle.to_tensor(y),
                              mask)
        dense = _np(out.to_dense())
        full = x @ y
        oracle = np.where(_np(mask.to_dense()) != 0, full, 0)
        np.testing.assert_allclose(dense, oracle, rtol=1e-5)

    def test_transpose(self):
        t = S.transpose(self._coo(), [1, 0])
        np.testing.assert_array_equal(_np(t.to_dense()),
                                      _np(self._coo().to_dense()).T)

    def test_sparse_attention(self):
        rng = np.random.RandomState(2)
        q = rng.randn(2, 4, 8).astype(np.float32)
        mask = S.sparse_coo_tensor(
            np.array([[0, 1, 2, 3], [0, 1, 2, 3]]),
            np.ones(4, np.float32), shape=[4, 4])
        out = S.nn.functional.attention(
            paddle.to_tensor(q), paddle.to_tensor(q), paddle.to_tensor(q),
            mask)
        # identity mask -> each position attends only itself -> out == v
        np.testing.assert_allclose(_np(out), q, rtol=1e-5)


class TestQuantization:
    def _model(self):
        class Net(paddle.nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = paddle.nn.Linear(8, 16)
                self.fc2 = paddle.nn.Linear(16, 4)

            def forward(self, x):
                return self.fc2(paddle.nn.functional.relu(self.fc1(x)))
        paddle.seed(0)
        return Net()

    def test_qat_fake_quant_wraps_and_trains(self):
        from paddle_tpu.quantization import (FakeQuanterWithAbsMaxObserver,
                                             QAT, QuantConfig)
        q = QuantConfig(activation=FakeQuanterWithAbsMaxObserver(),
                        weight=FakeQuanterWithAbsMaxObserver())
        model = QAT(q).quantize(self._model())
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(4, 8).astype("float32"))
        y = paddle.to_tensor(rng.randn(4, 4).astype("float32"))
        opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                    parameters=model.parameters())
        losses = []
        for _ in range(10):
            loss = ((model(x) - y) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0]  # STE gradient flows

    def test_fake_quant_rounding_oracle(self):
        from paddle_tpu.quantization import _fake_quant
        import jax.numpy as jnp
        x = jnp.asarray(np.array([0.0, 0.05, -1.0, 0.99], np.float32))
        out = np.asarray(_fake_quant(x, jnp.asarray(1.0), bits=8))
        oracle = np.round(np.clip(np.asarray(x) * 127, -127, 127)) / 127
        np.testing.assert_allclose(out, oracle, rtol=1e-6)

    def test_ptq_observe_then_convert(self):
        from paddle_tpu.quantization import (AbsmaxObserver, PTQ,
                                             QuantConfig)
        q = QuantConfig(activation=AbsmaxObserver(), weight=None)
        model = PTQ(q).quantize(self._model())
        rng = np.random.RandomState(1)
        for _ in range(3):
            model(paddle.to_tensor(rng.randn(4, 8).astype("float32") * 3))
        ptq = PTQ(q)
        ptq.convert(model)
        from paddle_tpu.quantization import _FixedScaleQuant
        fixed = [l for l in model.sublayers()
                 if isinstance(l, _FixedScaleQuant)]
        assert len(fixed) == 2
        assert all(f.scale() > 0 for f in fixed)
        out = model(paddle.to_tensor(rng.randn(4, 8).astype("float32")))
        assert np.isfinite(_np(out)).all()
