"""Higher-order eager autograd + real static-mode autodiff.

Covers the round-2 verdict items: incubate.autograd.forward_grad must
compute a real JVP (was: returned zeros), static.append_backward must
yield fetchable correct grads (was: KeyError facade), optimizer.minimize
must train in static mode, and paddle.grad(create_graph=True) must
support double grad (reference: egr::Grad,
/root/reference/paddle/fluid/eager/backward.cc:404).
"""
import math

import numpy as np
import pytest

import paddle_tpu as paddle


class TestForwardGrad:
    def test_square(self):
        x = paddle.to_tensor([2.0], stop_gradient=False)
        y = x * x
        g = paddle.incubate.autograd.forward_grad(
            y, (x,), (paddle.to_tensor([1.0]),))
        np.testing.assert_allclose(np.asarray(g.numpy()), [4.0], rtol=1e-6)

    def test_chain_matches_finite_differences(self):
        xv = np.random.RandomState(0).randn(5).astype("float32")
        vv = np.random.RandomState(1).randn(5).astype("float32")

        def f(t):
            return paddle.sin(t * t) + paddle.exp(t * 0.1)

        x = paddle.to_tensor(xv, stop_gradient=False)
        tangent = paddle.incubate.autograd.forward_grad(
            f(x), (x,), (paddle.to_tensor(vv),))
        eps = 1e-3
        fd = (np.asarray(f(paddle.to_tensor(xv + eps * vv)).numpy())
              - np.asarray(f(paddle.to_tensor(xv - eps * vv)).numpy())) / (2 * eps)
        np.testing.assert_allclose(np.asarray(tangent.numpy()), fd,
                                   rtol=1e-2, atol=1e-3)

    def test_multi_op_graph_default_seed(self):
        x = paddle.to_tensor([2.0], stop_gradient=False)
        g = paddle.incubate.autograd.forward_grad(paddle.sin(x * x), (x,))
        np.testing.assert_allclose(np.asarray(g.numpy()),
                                   [math.cos(4.0) * 4.0], rtol=1e-5)

    def test_two_inputs(self):
        a = paddle.to_tensor([3.0], stop_gradient=False)
        b = paddle.to_tensor([5.0], stop_gradient=False)
        out = a * b
        g = paddle.incubate.autograd.forward_grad(
            out, (a, b), (paddle.to_tensor([1.0]), paddle.to_tensor([0.0])))
        np.testing.assert_allclose(np.asarray(g.numpy()), [5.0], rtol=1e-6)


class TestCreateGraph:
    def test_double_and_triple_grad(self):
        x = paddle.to_tensor([3.0], stop_gradient=False)
        y = x ** 3
        (g1,) = paddle.grad(y, [x], create_graph=True)
        np.testing.assert_allclose(np.asarray(g1.numpy()), [27.0], rtol=1e-6)
        (g2,) = paddle.grad(g1, [x], create_graph=True)
        np.testing.assert_allclose(np.asarray(g2.numpy()), [18.0], rtol=1e-6)
        (g3,) = paddle.grad(g2, [x])
        np.testing.assert_allclose(np.asarray(g3.numpy()), [6.0], rtol=1e-6)

    def test_gradient_penalty_matches_jax_oracle(self):
        import jax
        import jax.numpy as jnp

        W = np.random.RandomState(0).randn(4, 4).astype("float32")
        xv = np.random.RandomState(1).randn(3, 4).astype("float32")

        def f(x):
            return jnp.tanh(x @ W).sum()

        oracle = jax.grad(lambda x: (jax.grad(f)(x) ** 2).sum())(xv)

        xp = paddle.to_tensor(xv, stop_gradient=False)
        Wp = paddle.to_tensor(W)
        y = paddle.tanh(paddle.matmul(xp, Wp)).sum()
        (gx,) = paddle.grad(y, [xp], create_graph=True)
        penalty = (gx ** 2).sum()
        penalty.backward()
        np.testing.assert_allclose(np.asarray(xp.grad.numpy()),
                                   np.asarray(oracle), rtol=1e-4, atol=1e-5)

    def test_hessian_vector_product(self):
        # HVP via grad-of-(grad·v): the training idiom double grad unlocks.
        import jax
        import jax.numpy as jnp

        xv = np.random.RandomState(2).randn(4).astype("float32")
        vv = np.random.RandomState(3).randn(4).astype("float32")

        def f_j(x):
            return jnp.sum(jnp.sin(x) * x ** 2)

        hvp_oracle = jax.grad(
            lambda x: jnp.vdot(jax.grad(f_j)(x), vv))(xv)

        x = paddle.to_tensor(xv, stop_gradient=False)
        v = paddle.to_tensor(vv)
        y = (paddle.sin(x) * x ** 2).sum()
        (g,) = paddle.grad(y, [x], create_graph=True)
        (hvp,) = paddle.grad((g * v).sum(), [x])
        np.testing.assert_allclose(np.asarray(hvp.numpy()),
                                   np.asarray(hvp_oracle),
                                   rtol=1e-4, atol=1e-5)

    def test_create_graph_uses_record_time_snapshot(self):
        # an in-place rebind of x._data between forward and backward must
        # not change the point the pullback is evaluated at (the
        # TensorWrapper snapshot semantics of the reference)
        x = paddle.to_tensor([3.0], stop_gradient=False)
        y = x * x
        import jax.numpy as jnp
        x._data = jnp.asarray([100.0])  # emulate in-place mutation
        (g,) = paddle.grad(y, [x], create_graph=True)
        np.testing.assert_allclose(np.asarray(g.numpy()), [6.0], rtol=1e-6)

    def test_create_graph_leaf_grad_dtype(self):
        # bf16 upstream cotangent must come back as the leaf's dtype
        x = paddle.to_tensor(np.ones(4, "float32"), stop_gradient=False)
        y = x.astype("bfloat16")
        z = (y * y).sum()
        z.backward()
        assert x.grad is not None
        assert str(x.grad.dtype).endswith("float32") or \
            x.grad._data.dtype == np.float32

    def test_mixed_first_order_still_releases(self):
        # Default path (create_graph=False) must still free the graph.
        x = paddle.to_tensor([2.0], stop_gradient=False)
        y = x * x
        y.backward()
        with pytest.raises(RuntimeError):
            y.backward()


class TestStaticAutodiff:
    def _build(self, opt_factory):
        paddle.enable_static()
        main = paddle.static.Program()
        startup = paddle.static.Program()
        with paddle.static.program_guard(main, startup):
            x = paddle.static.data("x", [8, 1])
            y = paddle.static.data("y", [8, 1])
            lin = paddle.nn.Linear(1, 1)
            pred = lin(x)
            loss = paddle.nn.functional.mse_loss(pred, y)
            pairs = paddle.static.append_backward(loss)
            opt = opt_factory(lin.parameters())
            opt.minimize(loss)
        paddle.disable_static()
        return main, loss, pairs, lin

    def test_linear_regression_converges_sgd(self):
        main, loss, pairs, lin = self._build(
            lambda ps: paddle.optimizer.SGD(learning_rate=0.1, parameters=ps))
        exe = paddle.static.Executor()
        rng = np.random.RandomState(0)
        last = None
        for _ in range(60):
            xv = rng.randn(8, 1).astype("float32")
            yv = (3.0 * xv + 1.0).astype("float32")
            (last,) = exe.run(main, feed={"x": xv, "y": yv},
                              fetch_list=[loss])
        assert float(last) < 1e-3
        w = float(np.asarray(lin.weight.numpy()).ravel()[0])
        b = float(np.asarray(lin.bias.numpy()).ravel()[0])
        assert abs(w - 3.0) < 0.1 and abs(b - 1.0) < 0.1

    def test_linear_regression_converges_adamw(self):
        main, loss, pairs, lin = self._build(
            lambda ps: paddle.optimizer.AdamW(learning_rate=0.1,
                                              parameters=ps))
        exe = paddle.static.Executor()
        rng = np.random.RandomState(0)
        first = None
        losses = []
        for _ in range(150):
            xv = rng.randn(8, 1).astype("float32")
            yv = (3.0 * xv + 1.0).astype("float32")
            (last,) = exe.run(main, feed={"x": xv, "y": yv},
                              fetch_list=[loss])
            losses.append(float(last))
            if first is None:
                first = float(last)
        # AdamW at lr=0.1 oscillates near the optimum; require large
        # improvement and a small recent loss rather than the exact last.
        assert min(losses[-20:]) < 1e-2 and losses[-1] < first / 100

    def test_append_backward_grad_values_correct(self):
        # dL/dW for L = mean((xW + b - y)^2) has closed form; check values.
        paddle.enable_static()
        main = paddle.static.Program()
        with paddle.static.program_guard(main):
            x = paddle.static.data("x", [4, 2])
            y = paddle.static.data("y", [4, 1])
            lin = paddle.nn.Linear(2, 1)
            pred = lin(x)
            loss = paddle.nn.functional.mse_loss(pred, y)
            pairs = paddle.static.append_backward(loss)
        paddle.disable_static()

        xv = np.random.RandomState(0).randn(4, 2).astype("float32")
        yv = np.random.RandomState(1).randn(4, 1).astype("float32")
        exe = paddle.static.Executor()
        fetches = exe.run(main, feed={"x": xv, "y": yv},
                          fetch_list=[loss] + [g for _, g in pairs])
        W = np.asarray(lin.weight.numpy())
        b = np.asarray(lin.bias.numpy())
        pred_np = xv @ W + b
        dW = 2.0 / pred_np.size * xv.T @ (pred_np - yv)
        db = 2.0 / pred_np.size * (pred_np - yv).sum(axis=0)
        np.testing.assert_allclose(fetches[1], dW, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(fetches[2], db, rtol=1e-4, atol=1e-5)

    def test_static_forward_grad(self):
        # reference primapi.forward_grad operates on the static Program;
        # the tangent var must be fetchable through Executor.run
        paddle.enable_static()
        main = paddle.static.Program()
        with paddle.static.program_guard(main):
            x = paddle.static.data("x", [3])
            y = x * x + paddle.sin(x)
            t = paddle.incubate.autograd.forward_grad(y, (x,))
        paddle.disable_static()
        exe = paddle.static.Executor()
        xv = np.array([1.0, 2.0, 3.0], np.float32)
        out = exe.run(main, feed={"x": xv}, fetch_list=[y, t])
        np.testing.assert_allclose(out[1], 2 * xv + np.cos(xv), atol=1e-5)

    def test_static_forward_grad_intermediate_input(self):
        # JVP w.r.t. an INTERMEDIATE var: the producing op must not
        # overwrite the injected primal (would sever the dependency)
        paddle.enable_static()
        main = paddle.static.Program()
        with paddle.static.program_guard(main):
            x = paddle.static.data("x", [3])
            y = x * x
            z = paddle.sin(y)
            t = paddle.incubate.autograd.forward_grad(z, (y,))
        paddle.disable_static()
        exe = paddle.static.Executor()
        xv = np.array([0.5, 1.0, 1.5], np.float32)
        (tv,) = exe.run(main, feed={"x": xv}, fetch_list=[t])
        np.testing.assert_allclose(tv, np.cos(xv * xv), atol=1e-5)

    def test_static_forward_grad_dynamic_batch_and_var_seed(self):
        # default seeds resolve against the FED shape (dynamic batch),
        # and a symbolic var seed takes its run-time value
        paddle.enable_static()
        main = paddle.static.Program()
        with paddle.static.program_guard(main):
            x = paddle.static.data("x", [None, 3])
            v = paddle.static.data("v", [None, 3])
            y = x * x
            t_ones = paddle.incubate.autograd.forward_grad(y, (x,))
            t_var = paddle.incubate.autograd.forward_grad(y, (x,), (v,))
        paddle.disable_static()
        exe = paddle.static.Executor()
        xv = np.random.RandomState(0).randn(4, 3).astype(np.float32)
        vv = np.random.RandomState(1).randn(4, 3).astype(np.float32)
        out = exe.run(main, feed={"x": xv, "v": vv},
                      fetch_list=[t_ones, t_var])
        np.testing.assert_allclose(out[0], 2 * xv, atol=1e-5)
        np.testing.assert_allclose(out[1], 2 * xv * vv, atol=1e-5)

    def test_static_minimize_returns_fetchable_grads(self):
        paddle.enable_static()
        main = paddle.static.Program()
        with paddle.static.program_guard(main):
            x = paddle.static.data("x", [4, 2])
            lin = paddle.nn.Linear(2, 1)
            loss = (lin(x) ** 2).mean()
            opt = paddle.optimizer.SGD(learning_rate=0.1,
                                       parameters=lin.parameters())
            _, pairs = opt.minimize(loss)
        paddle.disable_static()
        exe = paddle.static.Executor()
        xv = np.ones((4, 2), np.float32)
        w = np.asarray(lin.weight.numpy()).copy()
        b = np.asarray(lin.bias.numpy()).copy()
        res = exe.run(main, feed={"x": xv}, fetch_list=[loss, pairs[0][1]])
        # dL/dW for L = mean((xW+b)^2): closed form at step-start params
        pred = xv @ w + b
        dW = 2.0 / pred.size * xv.T @ pred
        np.testing.assert_allclose(res[1], dW, rtol=1e-4, atol=1e-5)

    def test_grad_fetch_without_minimize_does_not_update_params(self):
        paddle.enable_static()
        main = paddle.static.Program()
        with paddle.static.program_guard(main):
            x = paddle.static.data("x", [4, 2])
            lin = paddle.nn.Linear(2, 1)
            loss = lin(x).sum()
            pairs = paddle.static.append_backward(loss)
        paddle.disable_static()
        w_before = np.asarray(lin.weight.numpy()).copy()
        exe = paddle.static.Executor()
        exe.run(main, feed={"x": np.ones((4, 2), "float32")},
                fetch_list=[pairs[0][1]])
        np.testing.assert_array_equal(np.asarray(lin.weight.numpy()),
                                      w_before)
