"""fp16 + dynamic loss scaling fused into the compiled TrainStep.

Reference protocol: GradScaler found_inf / skip-update / incr-decr schedule
(/root/reference/python/paddle/amp/grad_scaler.py:602); here all of it is
in-graph (one XLA program per step).
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.amp import GradScaler
from paddle_tpu.jit import TrainStep

B, D = 8, 16


class Net(paddle.nn.Layer):
    def __init__(self):
        super().__init__()
        self.l1 = paddle.nn.Linear(D, 32)
        self.l2 = paddle.nn.Linear(32, 1)

    def forward(self, x):
        return self.l2(paddle.nn.functional.relu(self.l1(x)))


def _data(seed=0):
    rng = np.random.RandomState(seed)
    return (paddle.to_tensor(rng.randn(B, D).astype("float32")),
            paddle.to_tensor(rng.randn(B, 1).astype("float32")))


def _mse(o, y):
    return ((o - y) ** 2).mean()


def _params(net):
    return {n: np.asarray(p.numpy()) for n, p in net.named_parameters()}


def test_fp16_scaler_trains():
    paddle.seed(0)
    net = Net()
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=net.parameters())
    scaler = GradScaler(init_loss_scaling=2.0 ** 10)
    step = TrainStep(net, _mse, opt, amp_level="O1", amp_dtype="float16",
                     scaler=scaler)
    x, y = _data()
    losses = [float(step(x, y).numpy()) for _ in range(20)]
    assert losses[-1] < losses[0]
    for p in net.parameters():
        assert np.isfinite(np.asarray(p.numpy())).all()
    # no overflow happened; scale unchanged (incr_every default 1000)
    assert scaler.state_dict()["scale"] == 2.0 ** 10


def test_overflow_skips_update_and_decreases_scale():
    paddle.seed(0)
    net = Net()
    opt = paddle.optimizer.SGD(learning_rate=1e-2,
                               parameters=net.parameters())
    # scale so large the f32 scaled loss overflows -> inf grads on step 1
    scaler = GradScaler(init_loss_scaling=1e38, decr_every_n_nan_or_inf=1,
                        decr_ratio=0.5)
    step = TrainStep(net, _mse, opt, amp_level="O1", amp_dtype="float16",
                     scaler=scaler)
    before = _params(net)
    x, y = _data(1)
    step(x, y)
    after = _params(net)
    for n in before:
        np.testing.assert_array_equal(before[n], after[n], err_msg=n)
    sd = scaler.state_dict()
    assert np.isclose(sd["scale"], 0.5e38, rtol=1e-6)  # f32 rounding
    assert bool(np.asarray(scaler._found_inf))
    # keep stepping: scale keeps halving (fp16 cotangents overflow until
    # it drops below ~2**16) and then updates resume
    for _ in range(200):
        step(x, y)
        if any((_params(net)[n] != before[n]).any() for n in before):
            break
    else:
        raise AssertionError("scale never recovered; updates never applied")
    assert scaler.state_dict()["scale"] < 1e5


def test_scale_increases_after_incr_every_good_steps():
    paddle.seed(0)
    net = Net()
    opt = paddle.optimizer.SGD(learning_rate=1e-3,
                               parameters=net.parameters())
    scaler = GradScaler(init_loss_scaling=8.0, incr_every_n_steps=2,
                        incr_ratio=2.0)
    step = TrainStep(net, _mse, opt, amp_level="O1", amp_dtype="float16",
                     scaler=scaler)
    x, y = _data(2)
    step(x, y)
    assert scaler.state_dict()["scale"] == 8.0
    step(x, y)
    assert scaler.state_dict()["scale"] == 16.0
    step(x, y)
    step(x, y)
    assert scaler.state_dict()["scale"] == 32.0


def test_scaler_matches_unscaled_when_no_overflow():
    import jax
    jax.config.update("jax_default_matmul_precision", "highest")
    x, y = _data(3)

    def run(scaler):
        paddle.seed(0)
        net = Net()
        opt = paddle.optimizer.SGD(learning_rate=1e-2,
                                   parameters=net.parameters())
        step = TrainStep(net, _mse, opt, scaler=scaler)  # no AMP: math equal
        return [float(step(x, y).numpy()) for _ in range(5)]

    plain = run(None)
    scaled = run(GradScaler(init_loss_scaling=2.0 ** 8))
    np.testing.assert_allclose(plain, scaled, rtol=1e-5, atol=1e-6)


def test_disabled_scaler_is_inert():
    paddle.seed(0)
    net = Net()
    opt = paddle.optimizer.SGD(learning_rate=1e-2,
                               parameters=net.parameters())
    step = TrainStep(net, _mse, opt, scaler=GradScaler(enable=False))
    x, y = _data(4)
    losses = [float(step(x, y).numpy()) for _ in range(3)]
    assert losses[-1] < losses[0]


def test_scaler_load_state_dict_takes_effect_mid_training():
    paddle.seed(0)
    net = Net()
    opt = paddle.optimizer.SGD(learning_rate=1e-2,
                               parameters=net.parameters())
    scaler = GradScaler(init_loss_scaling=256.0)
    step = TrainStep(net, _mse, opt, scaler=scaler)
    x, y = _data(5)
    step(x, y)
    scaler.load_state_dict({"scale": 1024.0, "incr_count": 0,
                            "decr_count": 0})
    step(x, y)
    assert scaler.state_dict()["scale"] == 1024.0


def test_run_steps_matches_loop():
    """Multi-step scanned TrainStep (run_steps) computes the same params
    and loss as N separate step calls."""
    import jax
    jax.config.update("jax_default_matmul_precision", "highest")
    x, y = _data(7)

    def build():
        paddle.seed(0)
        net = Net()
        opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                    parameters=net.parameters())
        return net, TrainStep(net, _mse, opt)

    n1, s1 = build()
    for _ in range(5):
        l1 = s1(x, y)
    n2, s2 = build()
    l2 = s2.run_steps(5, x, y)
    np.testing.assert_allclose(float(l1.numpy()), float(l2.numpy()),
                               rtol=2e-5)
    for (n, p), (_, q) in zip(n1.named_parameters(), n2.named_parameters()):
        np.testing.assert_allclose(np.asarray(p.numpy()),
                                   np.asarray(q.numpy()), rtol=2e-5,
                                   err_msg=n)
    # optimizer step counter advanced by the full window
    assert s2.optimizer._step_count == 5


def test_run_steps_with_scaler():
    paddle.seed(0)
    net = Net()
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=net.parameters())
    scaler = GradScaler(init_loss_scaling=2.0 ** 8, incr_every_n_steps=4)
    step = TrainStep(net, _mse, opt, scaler=scaler)
    x, y = _data(8)
    step.run_steps(8, x, y)
    # 8 good steps with incr_every=4 -> scale doubled twice
    assert scaler.state_dict()["scale"] == 2.0 ** 10
