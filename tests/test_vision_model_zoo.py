"""Vision model zoo completions: forward shapes, eval-mode determinism,
and one backward pass per family (reference: vision/models/*)."""
import numpy as np
import pytest

pytestmark = pytest.mark.slow

import paddle_tpu as paddle
from paddle_tpu.vision import models as M


def _img(n=1, size=64):
    rng = np.random.RandomState(0)
    return paddle.to_tensor(rng.randn(n, 3, size, size).astype("float32"))


@pytest.mark.parametrize("ctor,size", [
    (lambda: M.resnext50_32x4d(num_classes=10), 64),
    (lambda: M.mobilenet_v1(scale=0.25, num_classes=10), 64),
    (lambda: M.mobilenet_v3_small(scale=0.5, num_classes=10), 64),
    (lambda: M.mobilenet_v3_large(scale=0.35, num_classes=10), 64),
    (lambda: M.shufflenet_v2_x0_25(num_classes=10), 64),
    (lambda: M.shufflenet_v2_swish(num_classes=10), 64),
    (lambda: M.squeezenet1_0(num_classes=10), 64),
    (lambda: M.squeezenet1_1(num_classes=10), 64),
    (lambda: M.densenet121(num_classes=10), 64),
    (lambda: M.inception_v3(num_classes=10), 96),
])
def test_forward_shape(ctor, size):
    net = ctor()
    net.eval()
    out = net(_img(2, size))
    assert list(out.shape) == [2, 10]
    # eval forward is deterministic
    out2 = net(_img(2, size))
    np.testing.assert_allclose(out.numpy(), out2.numpy(), rtol=1e-5)


def test_googlenet_aux_outputs():
    net = M.googlenet(num_classes=10)
    net.eval()
    outs = net(_img(1, 96))
    assert isinstance(outs, list) and len(outs) == 3
    for o in outs:
        assert list(o.shape) == [1, 10]


def test_resnext_grouped_width():
    # resnext bottleneck width: planes*(4/64)*32 = planes*2
    net = M.resnext50_32x4d(num_classes=4)
    convs = [m for m in net.sublayers() if isinstance(m, paddle.nn.Conv2D)]
    grouped = [c for c in convs if getattr(c, "groups", 1) == 32]
    assert grouped, "resnext must contain grouped convolutions"


def test_backward_one_family():
    net = M.mobilenet_v3_small(scale=0.35, num_classes=4)
    net.train()
    x = _img(2, 64)
    y = net(x)
    loss = y.sum()
    loss.backward()
    grads = [p.grad for p in net.parameters() if p.grad is not None]
    assert len(grads) > 10


def test_densenet_channel_growth():
    net = M.densenet121(num_classes=0, with_pool=True)
    net.eval()
    out = net(_img(1, 64))
    # final feature width of densenet121 is 1024
    assert out.shape[1] == 1024


def test_state_dict_roundtrip():
    net = M.shufflenet_v2_x0_25(num_classes=4)
    net.eval()
    x = _img(1, 64)
    ref = net(x).numpy()
    sd = net.state_dict()
    net2 = M.shufflenet_v2_x0_25(num_classes=4)
    net2.set_state_dict(sd)
    net2.eval()
    np.testing.assert_allclose(net2(x).numpy(), ref, rtol=1e-5)
