"""Audio features, geometric ops, ASP, AlexNet/ViT, ERNIE e2e
(BASELINE config 5: sharded training + inference serve)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def _np(t):
    return np.asarray(t.numpy())


class TestAudio:
    def test_spectrogram_parseval_and_shapes(self):
        t = np.linspace(0, 1, 2048, endpoint=False)
        x = np.sin(2 * np.pi * 64 * t).astype(np.float32)
        spec = paddle.audio.Spectrogram(n_fft=256, hop_length=64)(
            paddle.to_tensor(x))
        s = _np(spec)
        assert s.shape[0] == 129
        # energy concentrates at the tone's bin
        assert s.mean(axis=1).argmax() == round(64 * 256 / 2048)

    def test_mel_mfcc_shapes(self):
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(2, 2048).astype("float32"))
        mel = paddle.audio.MelSpectrogram(n_fft=256, n_mels=32)(x)
        assert list(mel.shape)[:2] == [2, 32]
        mfcc = paddle.audio.MFCC(n_fft=256, n_mels=32, n_mfcc=13)(x)
        assert list(mfcc.shape)[:2] == [2, 13]

    def test_fbank_matrix_rows_nonnegative(self):
        from paddle_tpu.audio.functional import compute_fbank_matrix
        fb = _np(compute_fbank_matrix(16000, 512, n_mels=40))
        assert fb.shape == (40, 257)
        assert (fb >= 0).all()
        assert (fb.sum(axis=1) > 0).all()


class TestGeometric:
    def test_send_u_recv_oracle(self):
        x = paddle.to_tensor(np.arange(8, dtype=np.float32).reshape(4, 2))
        si = paddle.to_tensor(np.array([0, 1, 2, 3]))
        di = paddle.to_tensor(np.array([1, 1, 0, 0]))
        out = _np(paddle.geometric.send_u_recv(x, si, di, "sum",
                                               out_size=2))
        np.testing.assert_array_equal(out, [[10, 12], [2, 4]])
        out = _np(paddle.geometric.send_u_recv(x, si, di, "max",
                                               out_size=2))
        np.testing.assert_array_equal(out, [[6, 7], [2, 3]])

    def test_send_ue_recv_and_uv(self):
        x = paddle.to_tensor(np.ones((3, 2), np.float32))
        e = paddle.to_tensor(np.full((3, 2), 2.0, np.float32))
        si = paddle.to_tensor(np.array([0, 1, 2]))
        di = paddle.to_tensor(np.array([0, 0, 1]))
        out = _np(paddle.geometric.send_ue_recv(x, e, si, di, "mul", "sum",
                                                out_size=2))
        np.testing.assert_array_equal(out, [[4, 4], [2, 2]])
        uv = _np(paddle.geometric.send_uv(x, x, si, di, "add"))
        np.testing.assert_array_equal(uv, np.full((3, 2), 2.0))

    def test_segment_ops_grad(self):
        x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(3, 2),
                             stop_gradient=False)
        ids = paddle.to_tensor(np.array([0, 0, 1]))
        out = paddle.geometric.segment_sum(x, ids, num_segments=2)
        out.sum().backward()
        np.testing.assert_array_equal(_np(x.grad), np.ones((3, 2)))


class TestASP:
    def test_prune_then_train_keeps_sparsity(self):
        from paddle_tpu.incubate import asp
        paddle.seed(0)
        net = paddle.nn.Linear(16, 16)
        asp.prune_model(net)
        assert abs(asp.calculate_density(net.weight) - 0.5) < 1e-6
        opt = asp.decorate(paddle.optimizer.SGD(
            learning_rate=0.1, parameters=net.parameters()))
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(4, 16).astype("float32"))
        loss = (net(x) ** 2).mean()
        loss.backward()
        opt.step()
        # mask survives the update
        assert abs(asp.calculate_density(net.weight) - 0.5) < 1e-6

    def test_mask_keeps_largest(self):
        from paddle_tpu.incubate.asp import get_mask_1d
        w = np.array([[1.0, -5.0, 0.1, 3.0]])
        m = get_mask_1d(w, 2, 4)
        np.testing.assert_array_equal(m, [[False, True, False, True]])


class TestVisionExtras:
    @pytest.mark.slow
    def test_alexnet_forward(self):
        paddle.seed(0)
        m = paddle.vision.models.alexnet(num_classes=7)
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(1, 3, 224, 224).astype("float32"))
        assert list(m(x).shape) == [1, 7]

    @pytest.mark.slow
    def test_vit_trains(self):
        paddle.seed(0)
        from paddle_tpu.vision.models import vit_s_16
        m = vit_s_16(num_classes=4, img_size=32, depth=2)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=m.parameters())
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(4, 3, 32, 32).astype("float32"))
        y = paddle.to_tensor(rng.randint(0, 4, (4,)).astype("int64"))
        lossf = paddle.nn.CrossEntropyLoss()
        losses = []
        for _ in range(4):
            loss = lossf(m(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0]


class TestErnieEndToEnd:
    @pytest.mark.slow
    def test_ernie_sharded_train_then_serve(self, tmp_path):
        """BASELINE config 5 shape: ERNIE sharded training (ZeRO axis +
        mp) then an inference artifact served in a fresh process."""
        import subprocess
        import sys
        import jax
        jax.config.update("jax_default_matmul_precision", "highest")
        import paddle_tpu.distributed.fleet as fleet
        from paddle_tpu.distributed.mesh_utils import set_global_mesh
        from paddle_tpu.jit import TrainStep
        from paddle_tpu.models import (ErnieForSequenceClassification,
                                       ernie_tiny)

        paddle.seed(0)
        s = fleet.DistributedStrategy()
        s.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                            "sharding_degree": 2}
        fleet.init(is_collective=True, strategy=s)
        m = ErnieForSequenceClassification(ernie_tiny(), num_classes=3)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=m.parameters())
        lossf = paddle.nn.CrossEntropyLoss()
        step = TrainStep(m, lambda o, y: lossf(o, y), opt)
        rng = np.random.RandomState(0)
        ids = paddle.to_tensor(rng.randint(0, 256, (8, 32)).astype("int64"))
        y = paddle.to_tensor(rng.randint(0, 3, (8,)).astype("int64"))
        l0 = float(step(ids, y).numpy())
        l1 = float(step(ids, y).numpy())
        assert np.isfinite(l1)
        set_global_mesh(None)
        m.to("cpu")  # gather mesh-sharded params for single-device serving

        # export + serve in a fresh process (static inference path)
        from paddle_tpu.static import InputSpec
        prefix = str(tmp_path / "ernie")
        paddle.jit.save(m, prefix,
                        input_spec=[InputSpec([1, 32], "int64")])
        probe = paddle.to_tensor(rng.randint(0, 256, (1, 32))
                                 .astype("int64"))
        expect = _np(m(probe))
        code = f"""
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import sys
sys.path.insert(0, {repr(str(tmp_path))})
import paddle_tpu
from paddle_tpu.inference import Config, create_predictor
cfg = Config({prefix!r} + ".pdmodel")
pred = create_predictor(cfg)
name = pred.get_input_names()[0]
h = pred.get_input_handle(name)
h.copy_from_cpu(np.load({repr(str(tmp_path / 'probe.npy'))}))
pred.run()
out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
np.save({repr(str(tmp_path / 'served.npy'))}, out)
"""
        np.save(tmp_path / "probe.npy", _np(probe))
        r = subprocess.run([sys.executable, "-c", code],
                           cwd="/root/repo", capture_output=True,
                           text=True, timeout=300)
        assert r.returncode == 0, r.stderr[-2000:]
        served = np.load(tmp_path / "served.npy")
        np.testing.assert_allclose(served, expect, rtol=1e-4, atol=1e-4)


def test_asp_mask_survives_trainstep():
    """ASP masks are re-applied inside the COMPILED train step (not just
    eager optimizer.step)."""
    from paddle_tpu.incubate import asp
    from paddle_tpu.jit import TrainStep
    paddle.seed(0)
    net = paddle.nn.Linear(16, 16)
    asp.prune_model(net)
    opt = asp.decorate(paddle.optimizer.Adam(
        learning_rate=1e-2, parameters=net.parameters()))
    step = TrainStep(net, lambda o, y: ((o - y) ** 2).mean(), opt)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(4, 16).astype("float32"))
    y = paddle.to_tensor(rng.randn(4, 16).astype("float32"))
    for _ in range(3):
        step(x, y)
    assert abs(asp.calculate_density(net.weight) - 0.5) < 1e-6


class TestText:
    def test_viterbi_matches_brute_force(self):
        import itertools
        rng = np.random.RandomState(3)
        B, T, N = 2, 5, 3
        pot = rng.randn(B, T, N).astype("float32")
        trans = rng.randn(N, N).astype("float32")
        scores, paths = paddle.text.viterbi_decode(
            paddle.to_tensor(pot), paddle.to_tensor(trans))
        for b in range(B):
            best, bp = -1e30, None
            for seq in itertools.product(range(N), repeat=T):
                s = pot[b, 0, seq[0]] + sum(
                    trans[seq[i - 1], seq[i]] + pot[b, i, seq[i]]
                    for i in range(1, T))
                if s > best:
                    best, bp = s, seq
            assert abs(float(_np(scores)[b]) - best) < 1e-4
            assert tuple(_np(paths)[b]) == bp

    def test_viterbi_decoder_layer_and_lengths(self):
        rng = np.random.RandomState(4)
        pot = paddle.to_tensor(rng.randn(2, 6, 4).astype("float32"))
        trans = paddle.to_tensor(rng.randn(4, 4).astype("float32"))
        dec = paddle.text.ViterbiDecoder(trans)
        lens = paddle.to_tensor(np.array([4, 6], np.int64))
        scores, paths = dec(pot, lens)
        assert list(paths.shape) == [2, 6]
        assert np.isfinite(_np(scores)).all()

    def test_vocab_roundtrip(self):
        v = paddle.text.Vocab(counter={"cat": 5, "dog": 3, "rare": 1},
                              min_freq=2)
        idx = v.to_indices(["cat", "dog", "unseen"])
        assert v.to_tokens(idx[:2]) == ["cat", "dog"]
        assert idx[2] == v.to_indices(v.unk_token)
        assert "cat" in v and "unseen" not in v


class TestOnnxShim:
    def test_export_writes_servable_artifact(self, tmp_path):
        from paddle_tpu.static import InputSpec
        paddle.seed(0)
        net = paddle.nn.Linear(4, 2)
        path = str(tmp_path / "m")
        out = paddle.onnx.export(net, path,
                                 input_spec=[InputSpec([1, 4], "float32")])
        loaded = paddle.jit.load(out)
        x = paddle.to_tensor(np.ones((1, 4), np.float32))
        np.testing.assert_allclose(_np(loaded(x)), _np(net(x)), rtol=1e-5)

    def test_literal_onnx_raises(self, tmp_path):
        with pytest.raises(NotImplementedError, match="paddle2onnx"):
            paddle.onnx.export(paddle.nn.Linear(2, 2),
                               str(tmp_path / "m.onnx"))
