"""Prim decomposition layer (round-4 verdict item 8): orig2prim /
prim2orig / to_prim / enable_prim as VISIBLE static-Program rewrites.

Reference: python/paddle/incubate/autograd/primx.py (orig2prim:702,
prim2orig:727), primrules.py op families. Here each recorded op node is
traced to its jaxpr and spliced back as primitive nodes named after the
reference's *_p set (matmul_p, exp_p, reduce_sum_p, ...)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static
from paddle_tpu.incubate.autograd import (disable_prim, enable_prim,
                                          orig2prim, prim2orig,
                                          prim_enabled, to_prim)


@pytest.fixture(autouse=True)
def _static_mode():
    paddle.enable_static()
    yield
    disable_prim()
    paddle.disable_static()


def _build_mlp_program():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [4, 8], "float32")
        w = paddle.create_parameter([8, 6], "float32", name="w_prim")
        h = paddle.tanh(paddle.matmul(x, w))
        y = paddle.nn.functional.softmax(h)
        loss = paddle.mean(y * y)
    return main, startup, loss


class TestOrig2Prim:
    def test_decomposition_is_visible_and_numerically_identical(self):
        main, startup, loss = _build_mlp_program()
        names_before = [op.name for op in main.ops]
        exe = static.Executor()
        exe.run(startup)
        xv = np.random.RandomState(0).randn(4, 8).astype("float32")
        want = exe.run(main, feed={"x": xv}, fetch_list=[loss])[0]

        orig2prim(main)
        names = [op.name for op in main.ops]
        # every node is a primitive, the program got longer, and the
        # documented families decomposed (softmax -> exp/sum/div chain)
        assert all(n.endswith("_p") for n in names), names
        assert len(names) > len(names_before)
        for expected in ("matmul_p", "tanh_p", "exp_p", "reduce_sum_p",
                         "div_p", "mul_p"):
            assert expected in names, (expected, names)
        got = exe.run(main, feed={"x": xv}, fetch_list=[loss])[0]
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_idempotent(self):
        main, startup, loss = _build_mlp_program()
        orig2prim(main)
        n1 = [op.name for op in main.ops]
        to_prim(main)               # alias, second call is a no-op
        assert [op.name for op in main.ops] == n1

    def test_prim2orig_restores(self):
        main, startup, loss = _build_mlp_program()
        names_before = [op.name for op in main.ops]
        orig2prim(main)
        prim2orig(main)
        assert [op.name for op in main.ops] == names_before

    def test_gelu_decomposes_to_erf_or_tanh_family(self):
        """Reference orig2prim 'gelu' rule (primrules.py:477) decomposes
        into erf- or tanh-approximation primitives."""
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [4, 8], "float32")
            y = paddle.nn.functional.gelu(x)
        orig2prim(main)
        names = [op.name for op in main.ops]
        assert all(n.endswith("_p") for n in names)
        assert any(n in names for n in ("erf_p", "erfc_p", "tanh_p")), \
            names
        assert "mul_p" in names

    def test_decomposed_program_still_trains(self):
        """The verdict's acceptance bar: minimize over the decomposed
        program converges identically to the original."""
        def build_and_train(decompose):
            paddle.seed(0)
            main, startup = static.Program(), static.Program()
            with static.program_guard(main, startup):
                x = static.data("x", [8, 4], "float32")
                lbl = static.data("lbl", [8, 2], "float32")
                w = paddle.create_parameter([4, 2], "float32",
                                            name="w_train")
                pred = paddle.tanh(paddle.matmul(x, w))
                loss = paddle.mean((pred - lbl) ** 2)
                opt = paddle.optimizer.SGD(learning_rate=0.5)
                opt.minimize(loss)
            exe = static.Executor()
            exe.run(startup)
            if decompose:
                orig2prim(main)
                assert all(op.name.endswith("_p") for op in main.ops)
            rng = np.random.RandomState(0)
            xv = rng.randn(8, 4).astype("float32")
            yv = rng.randn(8, 2).astype("float32")
            return [float(exe.run(main, feed={"x": xv, "lbl": yv},
                                  fetch_list=[loss])[0])
                    for _ in range(5)]

        plain = build_and_train(False)
        prim = build_and_train(True)
        assert prim[-1] < prim[0], prim
        np.testing.assert_allclose(prim, plain, rtol=1e-5)

    def test_enable_prim_lowers_at_executor_run(self):
        main, startup, loss = _build_mlp_program()
        exe = static.Executor()
        exe.run(startup)
        assert not prim_enabled()
        enable_prim()
        assert prim_enabled()
        xv = np.random.RandomState(1).randn(4, 8).astype("float32")
        exe.run(main, feed={"x": xv}, fetch_list=[loss])
        # the decomposition is VISIBLE on the program after the run
        assert getattr(main, "_prim_decomposed", False)
        assert all(op.name.endswith("_p") for op in main.ops)
        disable_prim()
        assert not prim_enabled()
