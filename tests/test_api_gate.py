"""API-compatibility + op-registration CI gates (round-4 verdict item 10).

Reference analog: /root/reference/tools/check_api_compatible.py and
check_op_register_type.py. The golden (tests/fixtures/api_golden.json,
regenerated via tools/gen_api_golden.py) locks in every public symbol,
registry op, and pdmodel converter; this gate FAILS when any disappears.
Additions are fine — regenerate the golden to lock them in."""
import importlib
import json
import os

import pytest

GOLDEN = os.path.join(os.path.dirname(__file__), "fixtures",
                      "api_golden.json")


@pytest.fixture(scope="module")
def golden():
    with open(GOLDEN) as f:
        return json.load(f)


def _public_names(mod):
    allv = getattr(mod, "__all__", None)
    if allv:
        return set(allv)
    return {n for n in dir(mod) if not n.startswith("_")}


def test_no_public_symbol_disappeared(golden):
    missing = {}
    for surface, names in golden["surfaces"].items():
        mod = importlib.import_module(surface)
        have = _public_names(mod)
        lost = sorted(set(names) - have)
        if lost:
            missing[surface] = lost
    assert not missing, (
        f"public API symbols disappeared (regenerate the golden via "
        f"tools/gen_api_golden.py ONLY if removal is intentional): "
        f"{missing}")


def test_registry_ops_all_present_and_resolvable(golden):
    from paddle_tpu.ops import registry

    have = set(registry.op_names())
    lost = sorted(set(golden["ops"]) - have)
    assert not lost, f"ops vanished from ops.yaml/registry: {lost}"


def test_registry_impls_importable():
    """Every ops.yaml impl path must import and be callable — the
    op-registration consistency half of the gate (reference
    check_op_register_type.py)."""
    from paddle_tpu.ops import registry

    bad = []
    for name in registry.op_names():
        try:
            fn = registry.resolve(name)
            if not callable(fn):
                bad.append((name, "not callable"))
        except Exception as e:      # noqa: BLE001
            bad.append((name, repr(e)[:80]))
    assert not bad, f"unresolvable registry ops: {bad[:10]}"


def test_pdmodel_converters_all_present(golden):
    from paddle_tpu.static.pdmodel import _CONVERTERS

    lost = sorted(set(golden["converters"]) - set(_CONVERTERS))
    assert not lost, f"pdmodel converters disappeared: {lost}"


def test_golden_is_current_hint():
    """Soft freshness check: new surfaces may exist that the golden does
    not cover yet — not a failure, but keep the golden in sync when
    adding public API (tools/gen_api_golden.py)."""
    with open(GOLDEN) as f:
        golden = json.load(f)
    assert len(golden["surfaces"]) >= 14
    assert len(golden["ops"]) >= 450
    assert len(golden["converters"]) >= 190
